#!/usr/bin/env python
"""Benchmark: ResNet-50/ImageNet-shape training throughput on the local chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "chip": ..., "tflops_per_sec": ..., "mfu": ..., "bound": ...}

vs_baseline is measured against BASELINE.json's north-star target of
10,000 images/sec aggregate on v5e-64 → 156.25 images/sec/chip (the
reference's own published numbers are unrecoverable — BASELINE.md).

MFU and the bottleneck verdict come from XLA's own cost model: the
compiled train step's ``flops`` / ``bytes accessed`` give achieved
TFLOP/s, model-flop utilization against the chip's bf16 peak, and
arithmetic intensity vs the chip's ridge point (peak FLOPs / HBM BW) —
intensity below the ridge means the step is HBM-bandwidth-bound.
Measured numbers and analysis are recorded in PERF_NOTES.md.

Set BENCH_TRACE=<dir> to also capture an XPlane trace of the timed window
(core/profiling.trace) for TensorBoard/Perfetto inspection.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import time

TARGET_PER_CHIP = 10_000 / 64  # BASELINE.json north star on v5e-64

# device_kind → (peak bf16 FLOP/s, HBM bytes/s). Public spec-sheet numbers.
CHIP_PEAKS: dict[str, tuple[float, float]] = {
    "TPU v2": (45e12, 700e9),
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),   # v5e
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),  # v6e / Trillium
    "TPU v6e": (918e12, 1640e9),
}


def bench_resnet50(batch_size: int, steps: int = 20, warmup: int = 3,
                   model_overrides: dict | None = None) -> dict:
    import jax
    import numpy as np

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.core.profiling import trace
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(
        base={
            "name": "bench-resnet50",
            "model": {"name": "resnet50", "num_classes": 1000,
                      "dtype": "bfloat16",
                      # Space-to-depth stem: exact reparametrization of the
                      # 7×7/s2 conv (tests/test_s2d_stem.py), +8% img/s on
                      # v5e — the 3-channel full-res conv wastes MXU lanes
                      # and HBM BW (PERF_NOTES.md). BENCH_NO_S2D=1 reverts.
                      "space_to_depth_stem":
                          os.environ.get("BENCH_NO_S2D", "0")
                          in ("", "0"),
                      **(model_overrides or {})},
            "data": {
                "name": "synthetic_images",
                "global_batch_size": batch_size,
                "image_size": 224,
                "channels": 3,
                # bf16 infeed: the step is HBM-BW-bound (PERF_NOTES.md);
                # halving image bytes is worth ~3% wall-clock.
                "image_dtype": "bfloat16",
            },
            "optimizer": {
                "name": "sgd_momentum",
                "learning_rate": 0.1,
                "weight_decay": 0.0001,
            },
            "train": {"total_steps": 1000},
        }
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    from distributed_tensorflow_framework_tpu.data.pipeline import image_np_dtype

    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((batch_size, 224, 224, 3))
        .astype(image_np_dtype(cfg.data.image_dtype)),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)

    # AOT-compile ONCE; the same executable serves the cost model (flops /
    # HBM bytes per step) AND the warmup/timed loops — a second tracing
    # through the jit cache would double ResNet-50's compile time.
    flops_per_step = bytes_per_step = None
    try:
        compiled = step.lower(state, batch).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_per_step = float(ca.get("flops", 0.0)) or None
        bytes_per_step = float(ca.get("bytes accessed", 0.0)) or None
        step = compiled
    except Exception as e:  # cost model unavailable on some backends
        print(f"bench: cost_analysis unavailable ({type(e).__name__})",
              file=sys.stderr)

    # NOTE: sync via device_get of a VALUE, not block_until_ready — the
    # latter returns early through the axon remote-execution tunnel and
    # inflates throughput ~10x. Fetch a param leaf so the barrier includes
    # the final step's optimizer update, not just its forward pass.
    def sync(s):
        leaf = jax.tree.leaves(s.params)[0]
        jax.device_get(leaf)

    for _ in range(warmup):
        state, metrics = step(state, batch)
    sync(state)
    trace_dir = os.environ.get("BENCH_TRACE")
    ctx = trace(trace_dir) if trace_dir else contextlib.nullcontext()
    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        sync(state)
        dt = time.perf_counter() - t0
    return {
        "images_per_sec": batch_size * steps / dt,
        "sec_per_step": dt / steps,
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
    }


def main() -> int:
    import jax

    n_chips = jax.device_count()
    chip = jax.devices()[0].device_kind
    result = None
    for bs in (256 * n_chips, 128 * n_chips, 64 * n_chips):
        try:
            result = bench_resnet50(bs)
            break
        except Exception as e:  # OOM → retry smaller
            print(f"bench: batch {bs} failed ({type(e).__name__}), retrying",
                  file=sys.stderr)
    if result is None:
        print(json.dumps({"metric": "resnet50_images_per_sec_per_chip",
                          "value": 0.0, "unit": "images/sec/chip",
                          "vs_baseline": 0.0, "chip": chip}))
        return 1

    per_chip = result["images_per_sec"] / n_chips
    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
        "chip": chip,
        "num_chips": n_chips,
    }
    peak = CHIP_PEAKS.get(chip)
    if result["flops_per_step"]:
        achieved = result["flops_per_step"] / result["sec_per_step"] / n_chips
        out["tflops_per_sec"] = round(achieved / 1e12, 2)
        if result["bytes_per_step"]:
            intensity = result["flops_per_step"] / result["bytes_per_step"]
            out["arith_intensity"] = round(intensity, 1)
        if peak:
            peak_flops, hbm_bw = peak
            out["mfu"] = round(achieved / peak_flops, 4)
            if result["bytes_per_step"]:
                ridge = peak_flops / hbm_bw
                out["bound"] = (
                    "hbm_bandwidth" if intensity < ridge else "compute"
                )
                # Fraction of peak HBM bandwidth actually sustained.
                out["hbm_bw_util"] = round(
                    result["bytes_per_step"] / result["sec_per_step"]
                    / n_chips / hbm_bw, 4,
                )
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
