#!/usr/bin/env python
"""Benchmark: ResNet-50/ImageNet-shape training throughput on the local chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N}

vs_baseline is measured against BASELINE.json's north-star target of
10,000 images/sec aggregate on v5e-64 → 156.25 images/sec/chip (the
reference's own published numbers are unrecoverable — BASELINE.md).
"""

from __future__ import annotations

import json
import sys
import time

TARGET_PER_CHIP = 10_000 / 64  # BASELINE.json north star on v5e-64


def bench_resnet50(batch_size: int, steps: int = 20, warmup: int = 3) -> float:
    import jax
    import numpy as np

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(
        base={
            "name": "bench-resnet50",
            "model": {"name": "resnet50", "num_classes": 1000, "dtype": "bfloat16"},
            "data": {
                "name": "synthetic_images",
                "global_batch_size": batch_size,
                "image_size": 224,
                "channels": 3,
                # bf16 infeed: the step is HBM-BW-bound (~95% of v5e peak);
                # halving image bytes is worth ~3% wall-clock.
                "image_dtype": "bfloat16",
            },
            "optimizer": {
                "name": "sgd_momentum",
                "learning_rate": 0.1,
                "weight_decay": 0.0001,
            },
            "train": {"total_steps": 1000},
        }
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    from distributed_tensorflow_framework_tpu.data.pipeline import image_np_dtype

    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((batch_size, 224, 224, 3))
        .astype(image_np_dtype(cfg.data.image_dtype)),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    step = builder.make_train_step(batch)

    # NOTE: sync via device_get of a VALUE, not block_until_ready — the
    # latter returns early through the axon remote-execution tunnel and
    # inflates throughput ~10x. Fetch a param leaf so the barrier includes
    # the final step's optimizer update, not just its forward pass.
    def sync(s):
        leaf = jax.tree.leaves(s.params)[0]
        jax.device_get(leaf)

    for _ in range(warmup):
        state, metrics = step(state, batch)
    sync(state)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch)
    sync(state)
    dt = time.perf_counter() - t0
    return batch_size * steps / dt


def main() -> int:
    import jax

    n_chips = jax.device_count()
    value = None
    for bs in (256 * n_chips, 128 * n_chips, 64 * n_chips):
        try:
            value = bench_resnet50(bs)
            break
        except Exception as e:  # OOM → retry smaller
            print(f"bench: batch {bs} failed ({type(e).__name__}), retrying",
                  file=sys.stderr)
    if value is None:
        print(json.dumps({"metric": "resnet50_images_per_sec_per_chip",
                          "value": 0.0, "unit": "images/sec/chip",
                          "vs_baseline": 0.0}))
        return 1
    per_chip = value / n_chips
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
