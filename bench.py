#!/usr/bin/env python
"""Benchmark: ResNet-50/ImageNet-shape training throughput on the local chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "chip": ..., "tflops_per_sec": ..., "mfu": ..., "bound": ...}

vs_baseline is measured against BASELINE.json's north-star target of
10,000 images/sec aggregate on v5e-64 → 156.25 images/sec/chip (the
reference's own published numbers are unrecoverable — BASELINE.md).

MFU and the bottleneck verdict come from XLA's own cost model: the
compiled train step's ``flops`` / ``bytes accessed`` give achieved
TFLOP/s, model-flop utilization against the chip's bf16 peak, and
arithmetic intensity vs the chip's ridge point (peak FLOPs / HBM BW) —
intensity below the ridge means the step is HBM-bandwidth-bound.
Measured numbers and analysis are recorded in PERF_NOTES.md.

Set BENCH_TRACE=<dir> to also capture an XPlane trace of the timed window
(core/profiling.trace) for TensorBoard/Perfetto inspection.
"""

from __future__ import annotations

import json
import os
import sys

TARGET_PER_CHIP = 10_000 / 64  # BASELINE.json north star on v5e-64

# device_kind → (peak bf16 FLOP/s, HBM bytes/s). Public spec-sheet numbers.
CHIP_PEAKS: dict[str, tuple[float, float]] = {
    "TPU v2": (45e12, 700e9),
    "TPU v3": (123e12, 900e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v5 lite": (197e12, 819e9),   # v5e
    "TPU v5e": (197e12, 819e9),
    "TPU v5p": (459e12, 2765e9),
    "TPU v6 lite": (918e12, 1640e9),  # v6e / Trillium
    "TPU v6e": (918e12, 1640e9),
}


def _compile_and_time(builder, state, batch, steps: int, warmup: int) -> dict:
    """AOT-compile the train step ONCE (the same executable serves the
    XLA cost model AND the timed loop), then measure wall-clock.

    NOTE: sync via device_get of a VALUE, not block_until_ready — the
    latter returns early through the axon remote-execution tunnel and
    inflates throughput ~10x. Fetch a param leaf so the barrier includes
    the final step's optimizer update, not just its forward pass.
    """
    import contextlib
    import time

    import jax

    from distributed_tensorflow_framework_tpu.core.profiling import trace

    step = builder.make_train_step(batch)
    flops_per_step = bytes_per_step = None
    try:
        compiled = step.lower(state, batch).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_per_step = float(ca.get("flops", 0.0)) or None
        bytes_per_step = float(ca.get("bytes accessed", 0.0)) or None
        step = compiled
    except Exception as e:  # cost model unavailable on some backends
        print(f"bench: cost_analysis unavailable ({type(e).__name__})",
              file=sys.stderr)

    def sync(s):
        leaf = jax.tree.leaves(s.params)[0]
        jax.device_get(leaf)

    for _ in range(warmup):
        state, metrics = step(state, batch)
    sync(state)
    trace_dir = os.environ.get("BENCH_TRACE")
    ctx = trace(trace_dir) if trace_dir else contextlib.nullcontext()
    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        sync(state)
        dt = time.perf_counter() - t0
    return {
        "sec_per_step": dt / steps,
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
    }


def bench_resnet50(batch_size: int, steps: int = 20, warmup: int = 3,
                   model_overrides: dict | None = None) -> dict:
    import numpy as np

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(
        base={
            "name": "bench-resnet50",
            "model": {"name": "resnet50", "num_classes": 1000,
                      "dtype": "bfloat16",
                      # Space-to-depth stem: exact reparametrization of the
                      # 7×7/s2 conv (tests/test_s2d_stem.py), +8% img/s on
                      # v5e — the 3-channel full-res conv wastes MXU lanes
                      # and HBM BW (PERF_NOTES.md). BENCH_NO_S2D=1 reverts.
                      "space_to_depth_stem":
                          os.environ.get("BENCH_NO_S2D", "0")
                          in ("", "0"),
                      # Per-block remat: trades idle MXU headroom for HBM
                      # bytes on the BW-bound step (PERF_NOTES.md).
                      "remat":
                          os.environ.get("BENCH_REMAT", "0")
                          not in ("", "0"),
                      **(model_overrides or {})},
            "data": {
                "name": "synthetic_images",
                "global_batch_size": batch_size,
                "image_size": 224,
                "channels": 3,
                # bf16 infeed: the step is HBM-BW-bound (PERF_NOTES.md);
                # halving image bytes is worth ~3% wall-clock.
                "image_dtype": "bfloat16",
            },
            "optimizer": {
                "name": "sgd_momentum",
                "learning_rate": 0.1,
                "weight_decay": 0.0001,
            },
            "train": {"total_steps": 1000},
        }
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    from distributed_tensorflow_framework_tpu.data.pipeline import image_np_dtype

    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((batch_size, 224, 224, 3))
        .astype(image_np_dtype(cfg.data.image_dtype)),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    out = _compile_and_time(builder, state, batch, steps, warmup)
    out["images_per_sec"] = batch_size / out["sec_per_step"]
    return out


def bench_bert(batch_size: int, steps: int = 20, warmup: int = 3,
               *, seq_len: int = 512, attention_impl: str = "pallas",
               remat: bool = False) -> dict:
    """BERT-base MLM train-step throughput — the transformer side of the
    perf story. Measured on v5e it saturates NEITHER roofline (MFU ~27%,
    HBM ~41%): the step is fragmented across medium GEMMs, so the lever
    is fatter per-matmul work, not bandwidth (PERF_NOTES.md round 3).
    Knobs via env in main(): BENCH_ATTN (pallas|xla|ring), BENCH_REMAT=1,
    BENCH_SEQ=<len>, BENCH_BS=<per-chip batch>."""
    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data import get_dataset
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    cfg = load_config(
        base={
            "name": "bench-bert",
            # configs/bert_base_mlm.yaml shapes (BASELINE config 5).
            "model": {"name": "bert", "vocab_size": 30522,
                      "hidden_size": 768, "num_layers": 12, "num_heads": 12,
                      "mlp_dim": 3072, "max_seq_len": seq_len,
                      "dtype": "bfloat16", "attention_impl": attention_impl,
                      "remat": remat},
            "data": {"name": "synthetic_mlm", "global_batch_size": batch_size,
                     "seq_len": seq_len},
            "optimizer": {"name": "adamw", "learning_rate": 1e-4,
                          "weight_decay": 0.01},
            "train": {"total_steps": 1000},
        }
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    host = next(get_dataset(cfg.data))
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    out = _compile_and_time(builder, state, batch, steps, warmup)
    out["examples_per_sec"] = batch_size / out["sec_per_step"]
    out["tokens_per_sec"] = batch_size * seq_len / out["sec_per_step"]
    return out


def _annotate_roofline(out: dict, result: dict, chip: str, n_chips: int) -> None:
    """Achieved TFLOP/s, MFU, arithmetic intensity and the bottleneck
    verdict from the XLA cost model + public chip peaks."""
    peak = CHIP_PEAKS.get(chip)
    if not result["flops_per_step"]:
        return
    achieved = result["flops_per_step"] / result["sec_per_step"] / n_chips
    out["tflops_per_sec"] = round(achieved / 1e12, 2)
    intensity = None
    if result["bytes_per_step"]:
        intensity = result["flops_per_step"] / result["bytes_per_step"]
        out["arith_intensity"] = round(intensity, 1)
    if peak:
        peak_flops, hbm_bw = peak
        out["mfu"] = round(achieved / peak_flops, 4)
        if intensity is not None:
            ridge = peak_flops / hbm_bw
            out["bound"] = "hbm_bandwidth" if intensity < ridge else "compute"
            # Fraction of peak HBM bandwidth actually sustained.
            out["hbm_bw_util"] = round(
                result["bytes_per_step"] / result["sec_per_step"]
                / n_chips / hbm_bw, 4,
            )


def _run_ladder(bench_fn, sizes, failure_metric: str, failure_unit: str):
    """Try batch sizes largest-first (OOM → retry smaller); on total
    failure print the zero-value JSON line and return None."""
    for bs in sizes:
        try:
            return bench_fn(bs)
        except Exception as e:
            print(f"bench: batch {bs} failed ({type(e).__name__}: {e}), "
                  f"retrying", file=sys.stderr)
    import jax

    print(json.dumps({"metric": failure_metric, "value": 0.0,
                      "unit": failure_unit, "vs_baseline": 0.0,
                      "chip": jax.devices()[0].device_kind}))
    return None


def _ladder_override(default: tuple, n_chips: int) -> tuple:
    """BENCH_BS=<per-chip batch> pins the batch ladder to one size."""
    if os.environ.get("BENCH_BS"):
        return (int(os.environ["BENCH_BS"]) * n_chips,)
    return default


def main() -> int:
    import jax

    n_chips = jax.device_count()
    chip = jax.devices()[0].device_kind
    workload = os.environ.get("BENCH_WORKLOAD", "resnet50")

    if workload == "bert":
        # The transformer workload (kept OFF the driver's default path —
        # the ONE default JSON line stays ResNet, the tracked BASELINE
        # metric). Knobs: BENCH_ATTN, BENCH_REMAT, BENCH_SEQ, BENCH_BS.
        seq = int(os.environ.get("BENCH_SEQ", "512"))
        attn = os.environ.get("BENCH_ATTN", "pallas")
        remat = os.environ.get("BENCH_REMAT", "0") not in ("", "0")
        ladder = _ladder_override(
            (64 * n_chips, 32 * n_chips, 16 * n_chips), n_chips)
        result = _run_ladder(
            lambda bs: bench_bert(bs, seq_len=seq, attention_impl=attn,
                                  remat=remat),
            ladder, "bert_base_mlm_examples_per_sec_per_chip",
            "examples/sec/chip")
        if result is None:
            return 1
        out = {
            "metric": "bert_base_mlm_examples_per_sec_per_chip",
            "value": round(result["examples_per_sec"] / n_chips, 2),
            "unit": "examples/sec/chip",
            # No reference-published BERT number exists (BASELINE.md);
            # report the absolute rates and roofline position instead.
            "vs_baseline": 0.0,
            "chip": chip,
            "num_chips": n_chips,
            "seq_len": seq,
            "attention_impl": attn,
            "remat": remat,
            "tokens_per_sec_per_chip": round(
                result["tokens_per_sec"] / n_chips, 1),
        }
        _annotate_roofline(out, result, chip, n_chips)
        print(json.dumps(out))
        return 0

    ladder = _ladder_override(
        (256 * n_chips, 128 * n_chips, 64 * n_chips), n_chips)
    result = _run_ladder(
        bench_resnet50, ladder,
        "resnet50_images_per_sec_per_chip", "images/sec/chip")
    if result is None:
        return 1

    per_chip = result["images_per_sec"] / n_chips
    out = {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
        "chip": chip,
        "num_chips": n_chips,
    }
    _annotate_roofline(out, result, chip, n_chips)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
