#!/usr/bin/env python
"""Benchmark: ResNet-50/ImageNet-shape training throughput on the local chip.

Prints ONE JSON line:
  {"metric": "...", "value": N, "unit": "images/sec/chip", "vs_baseline": N,
   "chip": ..., "tflops_per_sec": ..., "mfu": ..., "bound": ...}

vs_baseline is measured against BASELINE.json's north-star target of
10,000 images/sec aggregate on v5e-64 → 156.25 images/sec/chip (the
reference's own published numbers are unrecoverable — BASELINE.md).

MFU and the bottleneck verdict come from XLA's own cost model: the
compiled train step's ``flops`` / ``bytes accessed`` give achieved
TFLOP/s, model-flop utilization against the chip's bf16 peak, and
arithmetic intensity vs the chip's ridge point (peak FLOPs / HBM BW) —
intensity below the ridge means the step is HBM-bandwidth-bound.
Measured numbers and analysis are recorded in PERF_NOTES.md.

Set BENCH_TRACE=<dir> to also capture an XPlane trace of the timed window
(core/profiling.trace) for TensorBoard/Perfetto inspection; the compiled
HLO text is dumped next to it so scripts/analyze_trace.py can attribute
trace events to source scopes.

Besides the stdout line (the driver contract), every result/failure is
also appended as a schema-versioned telemetry event (core/telemetry,
docs/OBSERVABILITY.md) with per-collective byte counts, joinable with a
training run's events.jsonl by run id. BENCH_JSONL=<path> overrides the
sink (default: <BENCH_TRACE>/bench_events.jsonl, else ./bench_events.jsonl;
BENCH_JSONL=0 disables). BENCH_WAIT=<minutes> arms a bounded backend-init
retry budget (see _init_backend). A backend probe HANG (vs a probe error)
exits 3 with failure_class="probe_hang" in the JSON — chip access
flakiness, not a code regression. BENCH_PROBE_ONLY=1 runs ONLY the
backend probe and exits (0 healthy / 3 hang / 1 error) — the queue
driver's preflight, so a dead chip fails the whole queue once instead of
every workload separately burning its BENCH_WAIT budget (rounds r03–r05
lost hours to exactly that). BENCH_COLLECTIVE=f32|bf16|int8 runs the
collective wire-format A/B instead of a single workload
(_run_collective_ab): f32-wire baseline vs the requested wire format on
the same ladder, reporting the tallied wire-byte ratio and throughput
delta.
"""

from __future__ import annotations

import json
import os
import pathlib
import sys

TARGET_PER_CHIP = 10_000 / 64  # BASELINE.json north star on v5e-64

# Chip peaks and the roofline math moved to core/roofline.py so the
# autotuner's analytic pruner (tools/autotune) and this bench judge
# candidates against the SAME ridge. Re-exported here because the bench
# is the historical home of these names (tests + PERF_NOTES refer to
# bench.CHIP_PEAKS et al.).
from distributed_tensorflow_framework_tpu.core.roofline import (  # noqa: E402,F401
    CHIP_PEAKS,
    GIB,
    RIDGE_FALLBACK_CHIP,
    annotate_roofline as _annotate_roofline,
    chip_hbm_capacity,
)


def _emit_json_line(payload: dict) -> None:
    """The ONE driver-contract JSON line: always stdout, and additionally
    written (whole-file, not append) to the BENCH_OUT=<path> file when
    set. Supervisors (tools/autotune, run_tier1.sh) read the file instead
    of regexing the tail out of warning-polluted stdout — the parse
    failure mode that lost the BENCH_r03–r05 rows. Failure lines land in
    the file too: an empty/missing BENCH_OUT after exit means the process
    died before producing a verdict, which is itself a classification."""
    line = json.dumps(payload)
    print(line)
    out_path = os.environ.get("BENCH_OUT", "").strip()
    if out_path:
        try:
            with open(out_path, "w") as fh:
                fh.write(line + "\n")
        except OSError as e:
            print(f"bench: BENCH_OUT write failed ({e})", file=sys.stderr)


def _check_leaderboard(out: dict, workload: str) -> None:
    """Regression pin against configs/leaderboard.json (dtf-leaderboard/1,
    written by scripts/autotune.py). When the board has an entry for this
    workload, annotate the result row with the pinned incumbent: its
    config digest (re-verified — a board whose digest doesn't match its
    own config dict has been hand-edited and can't be trusted as a pin),
    the score ratio, and a regression flag when this run undershoots the
    incumbent by more than the pinned margin. Annotation only — the exit
    code stays the driver's; the flag is for the queue/tuner to read."""
    board_path = os.environ.get("BENCH_LEADERBOARD", "").strip() or \
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "configs", "leaderboard.json")
    try:
        with open(board_path) as fh:
            board = json.load(fh)
    except (OSError, ValueError):
        return
    entry = (board.get("entries") or {}).get(workload)
    if not isinstance(entry, dict) or not out.get("value"):
        return
    from tools.autotune.leaderboard import config_digest

    digest = entry.get("config_digest")
    digest_ok = (digest == config_digest(entry.get("config") or {}))
    score = float(entry.get("score") or 0.0)
    margin = float(entry.get("regression_margin") or 0.05)
    note = {"incumbent_score": score, "config_digest": digest,
            "digest_ok": digest_ok}
    if score > 0:
        ratio = float(out["value"]) / score
        note["vs_incumbent"] = round(ratio, 4)
        note["regression"] = bool(ratio < 1.0 - margin)
        if note["regression"]:
            print(f"bench: REGRESSION vs leaderboard incumbent for "
                  f"{workload}: {out['value']} vs pinned {score} "
                  f"(margin {margin})", file=sys.stderr)
    if not digest_ok:
        print(f"bench: leaderboard digest mismatch for {workload} — "
              f"the pin was edited outside scripts/autotune.py",
              file=sys.stderr)
    out["leaderboard"] = note


def _compile_and_time(builder, state, batch, steps: int, warmup: int) -> dict:
    """AOT-compile the train step ONCE (the same executable serves the
    XLA cost model AND the timed loop), then measure wall-clock.

    NOTE: sync via device_get of a VALUE, not block_until_ready — the
    latter returns early through the axon remote-execution tunnel and
    inflates throughput ~10x. Fetch a param leaf so the barrier includes
    the final step's optimizer update, not just its forward pass.
    """
    import contextlib
    import time

    import jax

    from distributed_tensorflow_framework_tpu.core.profiling import trace
    from distributed_tensorflow_framework_tpu.parallel import collectives as coll

    from distributed_tensorflow_framework_tpu.core import memstats

    # Drill affordability knobs: the observability drill runs the full
    # bench binary on CPU and only needs the JSON shape, not a stable
    # rate — let it shrink the timed loop without forking the workloads.
    steps = int(os.environ.get("BENCH_STEPS") or steps)
    warmup = int(os.environ.get("BENCH_WARMUP") or warmup)

    step = builder.make_train_step(batch)
    flops_per_step = bytes_per_step = None
    collectives = None
    memory_analysis = None
    trace_dir = os.environ.get("BENCH_TRACE")
    try:
        # Collective byte counters record at JAX *trace* time, and
        # lower() IS the trace (it also populates the jit call cache, so
        # the timed loop below never re-traces) — tally around it and the
        # counts describe every timed step.
        with coll.tally() as tly:
            lowered = step.lower(state, batch)
        collectives = tly.summary()
        compiled = lowered.compile()
        if trace_dir:
            # The optimized-HLO side channel scripts/analyze_trace.py uses
            # for scope attribution (same layout as ProfileHook's dump).
            try:
                os.makedirs(trace_dir, exist_ok=True)
                hlo_path = os.path.join(trace_dir, "train_step.hlo.txt")
                with open(hlo_path, "w") as fh:
                    fh.write(compiled.as_text())
            except Exception as e:
                print(f"bench: HLO dump failed ({type(e).__name__}: {e})",
                      file=sys.stderr)
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, (list, tuple)) else ca
        flops_per_step = float(ca.get("flops", 0.0)) or None
        bytes_per_step = float(ca.get("bytes accessed", 0.0)) or None
        memory_analysis = memstats.compiled_memory_analysis(compiled)
        if memory_analysis is not None:
            # Donation survival on the program actually being timed: the
            # count of input_output_alias entries in the optimized module
            # (tools/graftcheck audits the same number against the state
            # leaf count).
            try:
                from tools.graftcheck.hlo_passes import count_alias_entries
                memory_analysis["donated_alias_entries"] = \
                    count_alias_entries(compiled.as_text())
            except Exception:  # bench must not depend on the lint tooling
                pass
        step = compiled
    except Exception as e:  # cost model unavailable on some backends
        print(f"bench: cost_analysis unavailable ({type(e).__name__})",
              file=sys.stderr)

    def sync(s):
        leaf = jax.tree.leaves(s.params)[0]
        jax.device_get(leaf)

    for _ in range(warmup):
        state, metrics = step(state, batch)
    sync(state)
    ctx = trace(trace_dir) if trace_dir else contextlib.nullcontext()
    with ctx:
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, batch)
        sync(state)
        dt = time.perf_counter() - t0
    # HBM occupancy AFTER the timed loop: arrays are live, so the device
    # peak (or host-RSS fallback on CPU) reflects the workload's real
    # footprint at its largest (core/memstats.py).
    memory = memstats.device_memory_snapshot()
    if memory_analysis:
        memory["analysis"] = memory_analysis
    return {
        "sec_per_step": dt / steps,
        "flops_per_step": flops_per_step,
        "bytes_per_step": bytes_per_step,
        "collectives": collectives,
        "memory": memory,
    }


def _mesh_axes(mesh) -> dict:
    """Mesh tag for bench records: non-trivial axis sizes ({data:1} when
    fully trivial) — so artifacts from different topologies are never read
    as comparable rates (ISSUE 6: throughput at {data:8} vs {fsdp:2,pipe:4}
    is a different experiment, not a regression)."""
    axes = {a: int(s) for a, s in mesh.shape.items() if int(s) > 1}
    return axes or {"data": 1}


def bench_resnet50(batch_size: int, steps: int = 20, warmup: int = 3,
                   model_overrides: dict | None = None,
                   base_overrides: dict | None = None) -> dict:
    """``base_overrides`` merges per top-level section into the base dict
    (the collective A/B uses it to force shard_map + a wire dtype without
    forking the workload definition)."""
    import numpy as np

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    base = {
            "name": "bench-resnet50",
            "model": {"name": "resnet50", "num_classes": 1000,
                      "dtype": "bfloat16",
                      # Space-to-depth stem: exact reparametrization of the
                      # 7×7/s2 conv (tests/test_s2d_stem.py), +8% img/s on
                      # v5e — the 3-channel full-res conv wastes MXU lanes
                      # and HBM BW (PERF_NOTES.md). BENCH_NO_S2D=1 reverts.
                      "space_to_depth_stem":
                          os.environ.get("BENCH_NO_S2D", "0")
                          in ("", "0"),
                      # Per-block remat: trades idle MXU headroom for HBM
                      # bytes on the BW-bound step. BENCH_REMAT=1 → full
                      # replay (measured -13% img/s); BENCH_REMAT=light →
                      # the conv_saved policy (keep conv outputs, replay
                      # only BN/ReLU — the cheap-tail variant). See
                      # PERF_NOTES.md.
                      "remat":
                          os.environ.get("BENCH_REMAT", "0")
                          not in ("", "0"),
                      "remat_policy":
                          "conv_saved"
                          if os.environ.get("BENCH_REMAT") in
                          ("light", "conv", "conv_saved") else "full",
                      **(model_overrides or {})},
            "data": {
                "name": "synthetic_images",
                "num_classes": 1000,
                "global_batch_size": batch_size,
                "image_size": 224,
                "channels": 3,
                # bf16 infeed: the step is HBM-BW-bound (PERF_NOTES.md);
                # halving image bytes is worth ~3% wall-clock.
                "image_dtype": "bfloat16",
            },
            "optimizer": {
                "name": "sgd_momentum",
                "learning_rate": 0.1,
                "weight_decay": 0.0001,
            },
            "train": {"total_steps": 1000},
    }
    for section, override in (base_overrides or {}).items():
        if isinstance(override, dict):
            base[section] = {**base.get(section, {}), **override}
        else:
            base[section] = override
    cfg = load_config(base=base)
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    from distributed_tensorflow_framework_tpu.data.pipeline import image_np_dtype

    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal((batch_size, 224, 224, 3))
        .astype(image_np_dtype(cfg.data.image_dtype)),
        "label": rng.integers(0, 1000, batch_size).astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    out = _compile_and_time(builder, state, batch, steps, warmup)
    out["images_per_sec"] = batch_size / out["sec_per_step"]
    out["mesh_axes"] = _mesh_axes(mesh)
    out["opt_state_bytes_per_chip"] = _opt_state_bytes_per_chip(state)
    return out


def _opt_state_bytes_per_chip(state) -> int:
    """Per-device optimizer-slot footprint, read off the placed shardings.

    Sums prod(shard_shape) x itemsize over every opt_state leaf — the
    number ZeRO weight-update sharding divides by the data x fsdp replica
    count, so the BENCH_ZERO A/B reports the memory win exactly (from the
    arrays' own layouts) rather than estimating it."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(state.opt_state):
        sharding = getattr(leaf, "sharding", None)
        shape = (sharding.shard_shape(leaf.shape)
                 if sharding is not None else getattr(leaf, "shape", ()))
        itemsize = int(getattr(getattr(leaf, "dtype", None), "itemsize", 4))
        total += int(np.prod(shape)) * itemsize
    return total


def bench_inception(batch_size: int, steps: int = 20, warmup: int = 3) -> dict:
    """Inception-v3 train-step throughput — BASELINE config 4's recipe,
    loaded from configs/inception_v3.yaml (one source of truth for the
    hyperparameters) with only the bench-necessary overrides: synthetic
    infeed at the recipe's 299px bf16 shape and the requested batch.
    BENCH_WORKLOAD=inception; BENCH_REMAT=1 for full-replay remat (the
    ResNet-only 'light'/'conv_saved' values are rejected — Inception has
    no conv_saved policy)."""
    import numpy as np

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.data.pipeline import (
        image_np_dtype,
    )
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    remat_env = os.environ.get("BENCH_REMAT", "0")
    if remat_env not in ("", "0", "1"):
        raise ValueError(
            f"BENCH_REMAT={remat_env!r} is ResNet-only (conv_saved policy); "
            f"the inception workload takes BENCH_REMAT=1 (full replay) or "
            f"unset.")
    cfg = load_config(
        pathlib.Path(__file__).parent / "configs" / "inception_v3.yaml",
        overrides=[
            "data.name=synthetic_images",
            f"data.global_batch_size={batch_size}",
            f"model.remat={'true' if remat_env == '1' else 'false'}",
        ],
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    rng = np.random.default_rng(0)
    host = {
        "image": rng.standard_normal(
            (batch_size, cfg.data.image_size, cfg.data.image_size, 3))
        .astype(image_np_dtype(cfg.data.image_dtype)),
        "label": rng.integers(0, cfg.data.num_classes, batch_size)
        .astype(np.int32),
    }
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    out = _compile_and_time(builder, state, batch, steps, warmup)
    out["images_per_sec"] = batch_size / out["sec_per_step"]
    out["mesh_axes"] = _mesh_axes(mesh)
    return out


def _ragged_mlm_batch(batch_size: int, seq_len: int, pack: int) -> dict:
    """Document-realistic synthetic MLM batch for the packing A/B.

    Doc lengths ~ U[s/8, s/2] (mean ≈ 0.31·s — the padding waste packing
    exists to reclaim). ``pack==1``: one doc per row, zero-padded (the
    unpacked baseline). ``pack>1``: ``pack·batch`` docs laid end-to-end by
    the production packer (data/text_mlm.pack_documents) with segment ids
    for block-diagonal attention. Real-token and doc counts ride along so
    the bench can report useful-token throughput, the metric packing
    actually moves (PERF_NOTES.md round 3: "fewer, fatter GEMMs").
    """
    import numpy as np

    from distributed_tensorflow_framework_tpu.data.text_mlm import (
        pack_documents,
    )

    rng = np.random.default_rng(0)
    n_docs = batch_size * max(pack, 1)
    lengths = rng.integers(seq_len // 8, seq_len // 2 + 1, n_docs)
    docs = np.zeros((n_docs, seq_len), np.int32)
    for i, n in enumerate(lengths):
        docs[i, :n] = rng.integers(1000, 30522, n)
    if pack > 1:
        tokens, seg_ids, leftover = pack_documents(docs, batch_size, seq_len)
        docs_in_batch = n_docs - len(leftover)
    else:
        tokens, seg_ids, docs_in_batch = docs, None, n_docs
    mask = (rng.random(tokens.shape) < 0.15) & (tokens != 0)
    batch = {
        "input_ids": np.where(mask, 103, tokens).astype(np.int32),
        "targets": np.where(mask, tokens, -1).astype(np.int32),
        "attention_mask": (tokens != 0).astype(np.int32),
    }
    if seg_ids is not None:
        batch["segment_ids"] = seg_ids
    batch["_real_tokens"] = int((tokens != 0).sum())
    batch["_docs"] = int(docs_in_batch)
    return batch


def bench_bert(batch_size: int, steps: int = 20, warmup: int = 3,
               *, seq_len: int = 512, attention_impl: str = "pallas",
               remat: bool = False, pack: int = 0,
               fused_qkv: bool = False, accum: int = 1,
               pipeline_stages: int = 0, pipeline_schedule: str = "gpipe",
               pipeline_microbatches: int = 0,
               pipeline_virtual_stages: int = 0) -> dict:
    """BERT-base MLM train-step throughput — the transformer side of the
    perf story. Measured on v5e it saturates NEITHER roofline (MFU 17.9%
    base at seq 512): the step is dominated by per-optimizer-step fixed
    overheads plus medium-GEMM fragmentation, so the measured levers are
    grad accumulation (MFU → 32-34%) and fused QKV (+21% at accum 1),
    not bandwidth (PERF_NOTES.md round 5, 2026-08-01 window).
    Knobs via env in main(): BENCH_ATTN (pallas|xla|ring), BENCH_REMAT=1,
    BENCH_SEQ=<len>, BENCH_BS=<per-chip batch>, BENCH_FUSED_QKV=1, BENCH_PACK
    (0 = dense synthetic rows; 1 = ragged docs unpacked — the padding
    baseline; n>1 = same doc distribution packed n-to-1).
    BENCH_PP=<stages> carves a pipe axis off the mesh (data = chips/stages)
    for the pipeline-schedule A/B; BENCH_SCHEDULE (gpipe|1f1b|interleaved),
    BENCH_MICRO=<microbatches>, BENCH_VIRTUAL=<v> pick the schedule
    (docs/DISTRIBUTED.md)."""
    import jax

    from distributed_tensorflow_framework_tpu.core.config import load_config
    from distributed_tensorflow_framework_tpu.core.mesh import create_mesh
    from distributed_tensorflow_framework_tpu.data import get_dataset
    from distributed_tensorflow_framework_tpu.data.infeed import to_global
    from distributed_tensorflow_framework_tpu.train.step import StepBuilder

    mesh_cfg = {}
    if pipeline_stages:
        n = jax.device_count()
        if n % pipeline_stages:
            raise ValueError(
                f"BENCH_PP={pipeline_stages} does not divide the "
                f"{n}-chip slice")
        mesh_cfg = {"mesh": {"data": n // pipeline_stages,
                             "pipe": pipeline_stages}}
    cfg = load_config(
        base={
            "name": "bench-bert",
            **mesh_cfg,
            # configs/bert_base_mlm.yaml shapes (BASELINE config 5).
            "model": {"name": "bert", "vocab_size": 30522,
                      "hidden_size": 768, "num_layers": 12, "num_heads": 12,
                      "mlp_dim": 3072, "max_seq_len": seq_len,
                      "dtype": "bfloat16", "attention_impl": attention_impl,
                      "remat": remat, "fused_qkv": fused_qkv,
                      "pipeline_stages": pipeline_stages,
                      "pipeline_schedule": pipeline_schedule,
                      "pipeline_microbatches": pipeline_microbatches,
                      "pipeline_virtual_stages": pipeline_virtual_stages},
            "data": {"name": "synthetic_mlm", "global_batch_size": batch_size,
                     "seq_len": seq_len},
            "optimizer": {"name": "adamw", "learning_rate": 1e-4,
                          "weight_decay": 0.01},
            # BENCH_ACCUM>1: fatter EFFECTIVE batch at fixed per-micro
            # memory — the VERDICT-r4 fragmentation lever candidate
            # (optimizer + fixed per-step overheads amortize over
            # accum× the tokens; per-micro GEMM shapes unchanged when
            # the ladder is scaled by accum, which main() does).
            "train": {"total_steps": 1000, "grad_accum_steps": accum},
        }
    )
    mesh = create_mesh(cfg.mesh)
    builder = StepBuilder(cfg, mesh)
    if pack:
        host = _ragged_mlm_batch(batch_size, seq_len, pack)
        real_tokens = host.pop("_real_tokens")
        docs = host.pop("_docs")
    else:
        host = next(get_dataset(cfg.data))
        real_tokens = batch_size * seq_len
        docs = batch_size
    batch = to_global(host, mesh)
    state = builder.init_state(0, batch)
    out = _compile_and_time(builder, state, batch, steps, warmup)
    if accum > 1:
        # XLA's cost_analysis counts a lax.scan body ONCE, but the accum
        # scan (train/step.py) runs it `accum` times per optimizer step —
        # verified on-chip 2026-08-01: the raw accum=4 run reported
        # exactly 1/4 the TFLOP/s its wall-clock throughput implied.
        # Scale flops/bytes by the trip count. Residual error: the
        # once-per-step optimizer update is also scaled, over-counting it
        # (accum-1)×. For FLOPs that is <1% (the update is ~10 flops/param
        # vs ~6 TFLOP per BERT-base micro-step). For BYTES it is not
        # negligible (AdamW traffic is ~7 f32 passes over the param tree,
        # ~3 GB for BERT-base — comparable to one micro-step), so for
        # accum runs hbm_bw_util is an UPPER bound and arith_intensity a
        # LOWER bound; the aggregate cost model gives no body/epilogue
        # split to do better with.
        for key in ("flops_per_step", "bytes_per_step"):
            if out.get(key):
                out[key] *= accum
    out["examples_per_sec"] = batch_size / out["sec_per_step"]
    out["tokens_per_sec"] = batch_size * seq_len / out["sec_per_step"]
    out["real_tokens_per_sec"] = real_tokens / out["sec_per_step"]
    out["docs_per_sec"] = docs / out["sec_per_step"]
    out["mesh_axes"] = _mesh_axes(mesh)
    return out


def _pp_bubble(schedule: str, stages: int, micro: int, virtual: int) -> float:
    """Analytic bubble fraction for the bert pp bench (12 BERT-base
    layers fixes the interleaved default v = 12/stages)."""
    from distributed_tensorflow_framework_tpu.parallel import schedule as sched

    v = sched.resolve_virtual(schedule, stages, micro, virtual, 12)
    return sched.bubble_frac(schedule, stages, micro, v)


# _annotate_roofline lives in core/roofline.py now (imported above):
# the tuner's pruning predictor and the bench's measured verdict must
# share one ridge-point implementation or they drift apart.


def _annotate_memory(out: dict, result: dict, chip: str,
                     n_chips: int) -> None:
    """Peak HBM per chip + headroom against the chip's capacity.

    Peak preference order: live device counters (memory_stats peak) →
    the compiled step's static analysis (args+temps+output — works on
    CPU where memory_stats returns nothing) → host RSS. Headroom is
    against CHIP_PEAKS capacity, or host RAM for unknown chips, so the
    number answers "how much bigger a batch/model fits" on any backend.
    """
    import jax

    mem = result.get("memory") or {}
    analysis = mem.get("analysis") or {}
    # Multi-process rows stay comparable across topologies: the process
    # count rides on the row, and the HBM peak below is scoped to THIS
    # host's devices (memory sampling is per-process). Single-process
    # rows keep their exact historical shape — this function stays a
    # no-op when there is nothing to report.
    if int(jax.process_count()) > 1:
        out["process_count"] = int(jax.process_count())
        out["hbm_peak_scope"] = f"host{jax.process_index()}"
    peak = mem.get("peak_bytes_in_use") or 0
    source = mem.get("source_kind", "unknown")
    if source != "device_memory_stats":
        est = analysis.get("peak_bytes_est") or 0
        if est:
            # Static analysis is whole-program; attribute evenly per chip.
            peak, source = est / max(1, n_chips), "memory_analysis"
        elif peak:
            source = "host_rss"
    if not peak:
        return
    out["hbm_peak_bytes_per_chip"] = int(peak)
    out["hbm_peak_source"] = source
    cap = chip_hbm_capacity(chip)
    if cap:
        out["hbm_capacity_bytes_per_chip"] = int(cap)
        out["hbm_headroom_frac"] = round(1.0 - peak / cap, 4)


def _run_ladder(bench_fn, sizes, failure_metric: str, failure_unit: str,
                chip: str, writer=None):
    """Try batch sizes largest-first (OOM → retry smaller); on total
    failure print the zero-value JSON line (with the last error), mirror
    it as a telemetry failure event, and return None."""
    last = "no batch size attempted"
    for bs in sizes:
        try:
            return bench_fn(bs)
        except Exception as e:
            last = f"batch {bs}: {type(e).__name__}: {e}"
            print(f"bench: {last}, retrying", file=sys.stderr)
    fail = {"metric": failure_metric, "value": 0.0, "unit": failure_unit,
            "vs_baseline": 0.0, "chip": chip, "error": last}
    if writer is not None:
        from distributed_tensorflow_framework_tpu.core import telemetry

        fail["run_id"] = writer.run_id
        writer.emit(telemetry.KIND_FAILURE,
                    health={"failure": "bench_ladder", "error": last},
                    metric=failure_metric, chip=chip)
    _emit_json_line(fail)
    return None


def _ladder_override(default: tuple, n_chips: int) -> tuple:
    """BENCH_BS=<per-chip batch> pins the batch ladder to one size."""
    if os.environ.get("BENCH_BS"):
        return (int(os.environ["BENCH_BS"]) * n_chips,)
    return default


class BenchBackendError(RuntimeError):
    """Backend bring-up failure carrying the full probe history, so the
    structured failure line records WHAT was tried, not just the last
    stderr fragment (VERDICT item 2).

    ``failure_class`` separates ``probe_hang`` — the chip tunnel never
    answered, i.e. environment flakiness (stale lease, slice still
    provisioning) — from ``backend_error`` (the probe ran and failed).
    A hang exits the bench with rc 3 instead of 1 so the driver can tell
    "chip access flaked" from "the code under test is broken"
    (the BENCH_r04/r05 re-land trigger, scripts/chip_window_queue.sh)."""

    def __init__(self, message: str, probe_history: list[dict],
                 failure_class: str = "backend_error"):
        super().__init__(message)
        self.probe_history = probe_history
        self.failure_class = failure_class


def _probe_device_count(timeout_s: float) -> tuple[str, object]:
    """One SUBPROCESS probe of ``jax.devices()`` under a hard timeout.

    Returns ``("ok", None)``, ``("error", last_stderr_line)`` for a probe
    that exited nonzero, or ``("hang", <diagnostic>)`` for one that
    outlived the timeout. A timed-out probe is REAPED — SIGKILL to its
    whole process group, then waited — never abandoned: an abandoned
    child holds the exclusive chip client alive, which is precisely what
    wedges every later dial (the BENCH_r05 failure was a 240 s hang
    followed by rc=1 with the probe pid still running). Killing the
    GROUP also takes down any helper the client forked, so nothing keeps
    the remote handshake open after we give up on it.
    """
    import signal
    import subprocess

    # start_new_session: the child leads its own process group, so the
    # timeout path can SIGKILL the whole group without touching us, and
    # an interactive Ctrl-C (group SIGINT) can't kill a healthy probe
    # mid-handshake.
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); "
         "print(len(d), d[0].device_kind, sep='\\t')"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    try:
        _, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass  # exited in the race window / group already gone
        try:
            proc.communicate(timeout=10)  # reap; drain + close the pipes
        except subprocess.TimeoutExpired:
            pass  # kernel will reap it; don't block the retry loop
        return "hang", f"probe exceeded {timeout_s:.0f}s (pid {proc.pid} reaped)"
    if proc.returncode == 0:
        return "ok", None
    return "error", (err.strip().splitlines() or ["no stderr"])[-1]


def _bench_wait_budget_s() -> float:
    """BENCH_WAIT → retry budget in seconds (0 = legacy 3-attempt mode).

    The value is minutes; a non-numeric truthy value (BENCH_WAIT=y) means
    the default hour. Unset/empty/0 keeps the fast-fail behavior."""
    raw = os.environ.get("BENCH_WAIT", "").strip()
    if raw in ("", "0"):
        return 0.0
    try:
        return max(0.0, float(raw) * 60.0)
    except ValueError:
        return 60.0 * 60.0


def _init_backend(attempts: int = 3, probe_timeout_s: float = 240.0, *,
                  wait_budget_s: float | None = None,
                  retry_interval_s: float = 300.0,
                  hang_retry_delay_s: float = 15.0,
                  probe=None, sleep=None, monotonic=None):
    """Bounded, *subprocess-probed* backend bring-up.

    Round 3's perf evidence was erased by a wedged TPU tunnel: a bare
    ``jax.devices()`` in this process would have hung forever and the
    driver recorded a traceback with ``parsed: null`` instead of a
    structured failure line (VERDICT r3 weak #1). A hang cannot be
    recovered in-process (the first backend touch caches forever), so
    each attempt probes ``jax.device_count()`` in a SUBPROCESS under a
    hard timeout; only after a probe succeeds do we touch the backend
    here. Returns (n_chips, device_kind) or raises BenchBackendError
    carrying the per-probe history.

    Two retry regimes:

      * default: ``attempts`` tries with short backoff for fast-failing
        probes — a broken env fails the dial quickly. A HANG is final
        here: without a wait budget there is no basis for deciding how
        long a wedged tunnel is worth waiting on, so the error says how
        to arm one (BENCH_WAIT).
      * BENCH_WAIT=<minutes> (``wait_budget_s``): re-probe every
        ``retry_interval_s`` (5 min) until the budget is spent — for
        dials raced against a slice that is still being provisioned,
        where "wait up to an hour" beats "fail in 15 s". Hangs are
        retried under the same budget as errors: the timed-out probe is
        reaped (its whole process group SIGKILLed and waited, see
        _probe_device_count), so a fresh probe never queues behind a
        zombie chip client, and a slice that comes up 20 minutes late
        still gets its dial. Each probe's timeout is additionally capped
        by the remaining budget so the last probe cannot overshoot it.

    The probe timeout is long (4 min) on purpose: it should only fire on
    a truly dead tunnel, not on a bring-up that is merely slow under
    host CPU load.

    ``probe``/``sleep``/``monotonic`` are injectable for tests.
    """
    import time

    probe = probe or _probe_device_count
    sleep = sleep or time.sleep
    monotonic = monotonic or time.monotonic
    if wait_budget_s is None:
        wait_budget_s = _bench_wait_budget_s()

    history: list[dict] = []
    t0 = monotonic()
    attempt = 0
    while True:
        attempt += 1
        timeout_s = probe_timeout_s
        if wait_budget_s > 0:
            # Never probe past the budget: the final probe gets whatever
            # budget remains (floored so a sliver still gets a real try).
            timeout_s = min(probe_timeout_s,
                            max(30.0, wait_budget_s - (monotonic() - t0)))
        p0 = monotonic()
        outcome, payload = probe(timeout_s)
        history.append({
            "attempt": attempt,
            "t": time.time(),
            "elapsed_s": round(monotonic() - p0, 1),
            "outcome": outcome,
            "error": None if outcome == "ok" else str(payload),
        })
        if outcome == "ok":
            import jax

            return jax.device_count(), jax.devices()[0].device_kind
        if outcome == "hang" and wait_budget_s <= 0:
            raise BenchBackendError(
                f"backend probe hung ({payload}); probe process group "
                f"killed and reaped. The backend is wedged or still "
                f"provisioning — set BENCH_WAIT=<minutes> to keep "
                f"re-probing under a time budget instead of failing "
                f"on the first hang", history, failure_class="probe_hang")
        print(f"bench: backend init attempt {attempt} "
              f"{'hung' if outcome == 'hang' else 'failed'} ({payload})",
              file=sys.stderr)
        if wait_budget_s > 0:
            elapsed = monotonic() - t0
            # A hang already consumed its whole timeout waiting, so it
            # re-probes after only a short settle delay (let the killed
            # group's chip lease lapse); fast failures sleep out the
            # full retry interval.
            wait_s = (hang_retry_delay_s if outcome == "hang"
                      else retry_interval_s)
            if elapsed + wait_s > wait_budget_s:
                raise BenchBackendError(
                    f"backend init {outcome} after {elapsed / 60:.1f} min "
                    f"({attempt} probes, BENCH_WAIT budget "
                    f"{wait_budget_s / 60:.0f} min): {payload}", history,
                    failure_class=("probe_hang" if outcome == "hang"
                                   else "backend_error"))
            sleep(wait_s)
        else:
            if attempt >= attempts:
                raise BenchBackendError(str(payload), history)
            sleep(5 * attempt)


_ROOFLINE_KEYS = ("tflops_per_sec", "mfu", "arith_intensity",
                  "ai_flops_per_byte", "bound", "bound_ridge_source",
                  "hbm_bw_util", "roofline_bound")


def _bench_writer():
    """Telemetry sink for this bench invocation (module docstring)."""
    from distributed_tensorflow_framework_tpu.core import telemetry

    path = os.environ.get("BENCH_JSONL", "").strip()
    if path.lower() in ("0", "off", "none"):
        path = None
    elif not path:
        trace_dir = os.environ.get("BENCH_TRACE")
        path = (os.path.join(trace_dir, "bench_events.jsonl")
                if trace_dir else "bench_events.jsonl")
    return telemetry.TelemetryWriter(
        path, run_id=os.environ.get("BENCH_RUN_ID") or None)


def _emit_bench_result(writer, workload: str, out: dict, result: dict) -> None:
    """Mirror the stdout JSON line as a schema-versioned bench event, with
    the cost-model raw numbers and per-collective byte counts attached."""
    from distributed_tensorflow_framework_tpu.core import telemetry

    metrics = {"value": out["value"], "sec_per_step": result["sec_per_step"]}
    for k in ("flops_per_step", "bytes_per_step"):
        if result.get(k):
            metrics[k] = result[k]
    roofline = {k: out[k] for k in _ROOFLINE_KEYS if k in out} or None
    extra = {k: v for k, v in out.items()
             if k not in metrics and k not in _ROOFLINE_KEYS
             and k != "run_id"}
    writer.emit(telemetry.KIND_BENCH, metrics=metrics, roofline=roofline,
                collectives=result.get("collectives"), workload=workload,
                **extra)
    mem = result.get("memory")
    if mem:
        # The raw snapshot rides as its own KIND_MEMORY event so the
        # bench trace joins the trainer's memory telemetry stream
        # (core/memstats.py, docs/OBSERVABILITY.md) by kind, not by
        # spelunking bench extras.
        mem_metrics = {k: mem[k] for k in
                       ("bytes_in_use", "peak_bytes_in_use", "device_count")
                       if mem.get(k) is not None}
        mem_extra = {k: out[k] for k in
                     ("hbm_peak_bytes_per_chip", "hbm_peak_source",
                      "hbm_capacity_bytes_per_chip", "hbm_headroom_frac")
                     if k in out}
        if mem.get("analysis"):
            mem_extra["analysis"] = mem["analysis"]
        writer.emit(telemetry.KIND_MEMORY, metrics=mem_metrics or None,
                    source="bench", source_kind=mem.get("source_kind"),
                    workload=workload, **mem_extra)


# BENCH_COLLECTIVE value → parallel.collective_dtype knob value.
_COLLECTIVE_MODES = {"f32": "", "bf16": "bfloat16", "int8": "int8"}


def _run_collective_ab(writer, mode: str, n_chips: int, chip: str) -> int:
    """BENCH_COLLECTIVE=f32|bf16|int8 — collective wire-format A/B.

    Runs the ResNet-50 workload TWICE on the same batch ladder under
    ``train.spmd_mode=shard_map`` (the explicit-collective path
    ``parallel.collective_dtype`` applies to — docs/PERFORMANCE.md):
    an f32-wire baseline, then the requested wire format. The JSON line
    reports the tallied wire-byte ratio (baseline/target; trace-time
    counts from parallel/collectives.tally, exact rather than sampled)
    and the throughput delta. ``f32`` runs the baseline once and reports
    ratio 1.0 — the self-calibration dial for the queue.
    """
    metric = "resnet50_collective_wire_ratio"
    unit = "x"
    ladder = _ladder_override(
        (128 * n_chips, 64 * n_chips, 32 * n_chips), n_chips)

    def run(wire: str):
        return _run_ladder(
            lambda bs: bench_resnet50(bs, base_overrides={
                "train": {"spmd_mode": "shard_map"},
                "parallel": {"collective_dtype": wire},
            }),
            ladder, metric, unit, chip, writer=writer)

    baseline = run("")
    if baseline is None:
        return 1
    wire_dtype = _COLLECTIVE_MODES[mode]
    target = run(wire_dtype) if wire_dtype else baseline
    if target is None:
        return 1

    def wire_bytes(result):
        return (result.get("collectives") or {}).get("total_bytes")

    base_b, tgt_b = wire_bytes(baseline), wire_bytes(target)
    ratio = round(base_b / tgt_b, 3) if base_b and tgt_b else None
    base_rate = baseline["images_per_sec"] / n_chips
    tgt_rate = target["images_per_sec"] / n_chips
    out = {
        "metric": metric,
        "value": ratio if ratio is not None else 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "baseline_kind": "f32-wire-self",
        "chip": chip,
        "num_chips": n_chips,
        "mesh_axes": target.get("mesh_axes"),
        "collective_dtype": wire_dtype or "float32",
        "baseline_wire_bytes": base_b,
        "target_wire_bytes": tgt_b,
        "baseline_images_per_sec_per_chip": round(base_rate, 2),
        "target_images_per_sec_per_chip": round(tgt_rate, 2),
        # Relative throughput change from the wire format alone (same
        # ladder, same mesh): +0.04 = 4% faster than the f32 wire.
        "throughput_delta": round(tgt_rate / base_rate - 1.0, 4),
        "run_id": writer.run_id,
    }
    _annotate_roofline(out, target, chip, n_chips)
    _annotate_memory(out, target, chip, n_chips)
    _emit_bench_result(writer, f"resnet50-collective-{mode}", out, target)
    _emit_json_line(out)
    return 0


_ZERO_MODES = ("off", "shard_map")


def _run_zero_ab(writer, mode: str, n_chips: int, chip: str) -> int:
    """BENCH_ZERO=off|shard_map — ZeRO weight-update sharding A/B.

    Runs the ResNet-50 workload TWICE on the same batch ladder under
    ``train.spmd_mode=shard_map``: a replicated-optimizer baseline
    (``optimizer.zero_sharding=off``), then the bucketed reduce-scatter /
    all-gather update path. The JSON line reports the per-chip optimizer
    slot footprint of both arms (read off the placed shardings — the
    memory win is the point of ZeRO-1/2) plus the throughput delta the
    extra collectives cost. ``off`` runs the baseline once and reports
    ratio 1.0 — the self-calibration dial for the queue.
    """
    metric = "resnet50_zero_opt_state_ratio"
    unit = "x"
    ladder = _ladder_override(
        (128 * n_chips, 64 * n_chips, 32 * n_chips), n_chips)

    def run(arm: str):
        return _run_ladder(
            lambda bs: bench_resnet50(bs, base_overrides={
                "train": {"spmd_mode": "shard_map"},
                "optimizer": {"zero_sharding": arm},
            }),
            ladder, metric, unit, chip, writer=writer)

    baseline = run("off")
    if baseline is None:
        return 1
    target = run("shard_map") if mode == "shard_map" else baseline
    if target is None:
        return 1

    base_b = baseline.get("opt_state_bytes_per_chip")
    tgt_b = target.get("opt_state_bytes_per_chip")
    ratio = round(base_b / tgt_b, 3) if base_b and tgt_b else None
    base_rate = baseline["images_per_sec"] / n_chips
    tgt_rate = target["images_per_sec"] / n_chips
    out = {
        "metric": metric,
        "value": ratio if ratio is not None else 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "baseline_kind": "zero-off-self",
        "chip": chip,
        "num_chips": n_chips,
        "mesh_axes": target.get("mesh_axes"),
        "zero_sharding": mode,
        "baseline_opt_state_bytes_per_chip": base_b,
        "target_opt_state_bytes_per_chip": tgt_b,
        "baseline_images_per_sec_per_chip": round(base_rate, 2),
        "target_images_per_sec_per_chip": round(tgt_rate, 2),
        # Relative throughput change from the sharded update alone (same
        # ladder, same mesh): -0.02 = 2% slower than the replicated
        # optimizer. The memory ratio above is what that 2% buys.
        "throughput_delta": round(tgt_rate / base_rate - 1.0, 4),
        "run_id": writer.run_id,
    }
    _annotate_roofline(out, target, chip, n_chips)
    _annotate_memory(out, target, chip, n_chips)
    _emit_bench_result(writer, f"resnet50-zero-{mode}", out, target)
    _emit_json_line(out)
    return 0


# BENCH_PRECISION arm → the `precision:` config block it runs under
# (core/config.py PrecisionConfig). The ladder is CUMULATIVE — each rung
# keeps the previous rungs' levers — because the §13 queue item reads the
# deltas as successive bites out of the same HBM roofline, not as
# independent toggles.
_PRECISION_MODES = {
    "f32": {},
    "bf16": {"activation_dtype": "bf16"},
    "bf16_fused": {"activation_dtype": "bf16", "fused_update": True},
    "bf16_int8": {"activation_dtype": "bf16", "fused_update": True,
                  "matmul_dtype": "int8"},
}


def _run_precision_ab(writer, mode: str, n_chips: int, chip: str) -> int:
    """BENCH_PRECISION=f32|bf16|bf16_fused|bf16_int8 — the precision
    ladder A/B (ISSUE 13 / chip_window_queue.sh §13).

    Runs the ResNet-50 workload TWICE on the same batch ladder under
    ``train.spmd_mode=shard_map`` + ZeRO weight-update sharding (the
    substrate precision.fused_update composes with): an all-f32 compute
    baseline (f32 model dtype, empty ``precision:`` block), then the
    requested rung. The JSON line reports the per-chip peak-HBM ratio
    (baseline/target — the memory the rung buys), both arms'
    ``ai_flops_per_byte`` (the roofline position the rung moves), and the
    throughput delta. ``f32`` runs the baseline once and reports ratio
    1.0 — the self-calibration dial for the queue.
    """
    metric = "resnet50_precision_hbm_peak_ratio"
    unit = "x"
    ladder = _ladder_override(
        (128 * n_chips, 64 * n_chips, 32 * n_chips), n_chips)

    def run(precision: dict):
        return _run_ladder(
            lambda bs: bench_resnet50(bs, base_overrides={
                # f32 model dtype in BOTH arms: the ladder isolates the
                # `precision:` block itself (activation_dtype overrides
                # the model dtype for the target rungs), and the f32
                # infeed keeps the batch bytes constant across arms.
                "model": {"dtype": "float32"},
                "data": {"image_dtype": "float32"},
                "train": {"spmd_mode": "shard_map"},
                "optimizer": {"zero_sharding": "shard_map"},
                "precision": precision,
            }),
            ladder, metric, unit, chip, writer=writer)

    baseline = run(_PRECISION_MODES["f32"])
    if baseline is None:
        return 1
    target = run(_PRECISION_MODES[mode]) if mode != "f32" else baseline
    if target is None:
        return 1

    def peak_of(result):
        probe: dict = {}
        _annotate_memory(probe, result, chip, n_chips)
        return probe.get("hbm_peak_bytes_per_chip")

    base_peak, tgt_peak = peak_of(baseline), peak_of(target)
    ratio = (round(base_peak / tgt_peak, 3)
             if base_peak and tgt_peak else None)
    base_rate = baseline["images_per_sec"] / n_chips
    tgt_rate = target["images_per_sec"] / n_chips
    base_probe: dict = {}
    _annotate_roofline(base_probe, baseline, chip, n_chips)
    out = {
        "metric": metric,
        "value": ratio if ratio is not None else 0.0,
        "unit": unit,
        "vs_baseline": 0.0,
        "baseline_kind": "f32-compute-self",
        "chip": chip,
        "num_chips": n_chips,
        "mesh_axes": target.get("mesh_axes"),
        "precision": dict(_PRECISION_MODES[mode]),
        "baseline_hbm_peak_bytes_per_chip": base_peak,
        "target_hbm_peak_bytes_per_chip": tgt_peak,
        "baseline_ai_flops_per_byte": base_probe.get("ai_flops_per_byte"),
        "baseline_images_per_sec_per_chip": round(base_rate, 2),
        "target_images_per_sec_per_chip": round(tgt_rate, 2),
        # Relative throughput change from the precision rung alone (same
        # ladder, same mesh): +0.10 = 10% faster than all-f32 compute.
        "throughput_delta": round(tgt_rate / base_rate - 1.0, 4),
        "run_id": writer.run_id,
    }
    _annotate_roofline(out, target, chip, n_chips)
    _annotate_memory(out, target, chip, n_chips)
    _emit_bench_result(writer, f"resnet50-precision-{mode}", out, target)
    _emit_json_line(out)
    return 0


def _run(writer) -> int:
    from distributed_tensorflow_framework_tpu.core import telemetry

    workload = os.environ.get("BENCH_WORKLOAD", "resnet50")
    metric = {"bert": "bert_base_mlm_examples_per_sec_per_chip",
              "inception": "inception_v3_images_per_sec_per_chip"}.get(
        workload, "resnet50_images_per_sec_per_chip")
    unit = ("examples/sec/chip" if workload == "bert" else "images/sec/chip")
    writer.emit_run_meta(
        argv=sys.argv, workload=workload,
        bench_env={k: v for k, v in sorted(os.environ.items())
                   if k.startswith("BENCH_")})
    try:
        n_chips, chip = _init_backend()
    except Exception as e:
        # Structured failure line: the driver still gets valid JSON (and
        # the error cause + full probe history) when the environment, not
        # the code, is broken.
        history = list(getattr(e, "probe_history", None) or [])
        for rec in history:
            writer.emit(telemetry.KIND_BENCH_PROBE, t=rec.get("t"),
                        health={k: rec.get(k) for k in
                                ("attempt", "elapsed_s", "outcome", "error")})
        failure_class = getattr(e, "failure_class", "backend_error")
        writer.emit(telemetry.KIND_FAILURE,
                    health={"failure": "backend_init", "error": str(e),
                            "failure_class": failure_class,
                            "num_probes": len(history)})
        fail = {"metric": metric, "value": 0.0, "unit": unit,
                "vs_baseline": 0.0, "error": f"backend init: {e}",
                "failure_class": failure_class,
                "run_id": writer.run_id}
        if history:
            fail["probe_history"] = history
        _emit_json_line(fail)
        if failure_class == "probe_hang":
            # Distinct exit code: a hung probe is chip access flakiness,
            # not a code regression — the driver must not count it
            # against the dial under test (scripts/chip_window_queue.sh
            # re-lands these instead of reverting).
            print("bench: backend probe HANG — chip access flakiness, "
                  "not a code regression (exit 3)", file=sys.stderr)
            return 3
        return 1

    if os.environ.get("BENCH_PROBE_ONLY", "").strip() not in ("", "0"):
        # Preflight mode: the backend-init outcome IS the result. The
        # queue driver runs this once before its first workload — the
        # probe-hang classification (exit 3) happens immediately, up
        # front, instead of once per dial with BENCH_WAIT burned each
        # time.
        out = {"probe_only": True, "chip": chip, "num_chips": n_chips,
               "run_id": writer.run_id}
        writer.emit(telemetry.KIND_BENCH_PROBE,
                    health={"outcome": "ok", "probe_only": True,
                            "chip": chip, "num_chips": n_chips})
        _emit_json_line(out)
        return 0

    coll_mode = os.environ.get("BENCH_COLLECTIVE", "").strip()
    if coll_mode:
        if coll_mode not in _COLLECTIVE_MODES:
            err = (f"BENCH_COLLECTIVE={coll_mode!r} not in "
                   f"{sorted(_COLLECTIVE_MODES)}")
            writer.emit(telemetry.KIND_FAILURE,
                        health={"failure": "bench_config", "error": err})
            _emit_json_line({"metric": metric, "value": 0.0, "unit": unit,
                             "vs_baseline": 0.0, "error": err,
                             "run_id": writer.run_id})
            return 1
        # The A/B owns the whole invocation (always the resnet50
        # workload): one JSON line comparing f32 wire vs the requested
        # format on the same ladder.
        return _run_collective_ab(writer, coll_mode, n_chips, chip)

    zero_mode = os.environ.get("BENCH_ZERO", "").strip()
    if zero_mode:
        if zero_mode not in _ZERO_MODES:
            err = (f"BENCH_ZERO={zero_mode!r} not in "
                   f"{sorted(_ZERO_MODES)}")
            writer.emit(telemetry.KIND_FAILURE,
                        health={"failure": "bench_config", "error": err})
            _emit_json_line({"metric": metric, "value": 0.0, "unit": unit,
                             "vs_baseline": 0.0, "error": err,
                             "run_id": writer.run_id})
            return 1
        # Like BENCH_COLLECTIVE, the A/B owns the invocation: one JSON
        # line comparing replicated vs ZeRO-sharded optimizer state on
        # the same ladder.
        return _run_zero_ab(writer, zero_mode, n_chips, chip)

    precision_mode = os.environ.get("BENCH_PRECISION", "").strip()
    if precision_mode:
        if precision_mode not in _PRECISION_MODES:
            err = (f"BENCH_PRECISION={precision_mode!r} not in "
                   f"{sorted(_PRECISION_MODES)}")
            writer.emit(telemetry.KIND_FAILURE,
                        health={"failure": "bench_config", "error": err})
            _emit_json_line({"metric": metric, "value": 0.0, "unit": unit,
                             "vs_baseline": 0.0, "error": err,
                             "run_id": writer.run_id})
            return 1
        # One JSON line comparing all-f32 compute vs the requested rung
        # of the precision ladder on the same ladder of batch sizes.
        return _run_precision_ab(writer, precision_mode, n_chips, chip)

    if workload == "bert":
        # The transformer workload (kept OFF the driver's default path —
        # the ONE default JSON line stays ResNet, the tracked BASELINE
        # metric). Knobs: BENCH_ATTN, BENCH_REMAT, BENCH_SEQ, BENCH_BS,
        # BENCH_FUSED_QKV, BENCH_PACK.
        seq = int(os.environ.get("BENCH_SEQ", "512"))
        attn = os.environ.get("BENCH_ATTN", "pallas")
        remat = os.environ.get("BENCH_REMAT", "0") not in ("", "0")
        pack = int(os.environ.get("BENCH_PACK", "0"))
        # One (H,3H) projection GEMM per layer instead of three (H,H) —
        # the fragmentation-lever candidate (models/bert.py).
        fused_qkv = os.environ.get("BENCH_FUSED_QKV", "0") not in ("", "0")
        accum = max(1, int(os.environ.get("BENCH_ACCUM", "1")))
        # Pipeline-schedule A/B (docs/DISTRIBUTED.md): BENCH_PP carves a
        # pipe axis; the schedule knobs only mean something with it set.
        pp = int(os.environ.get("BENCH_PP", "0"))
        pp_sched = os.environ.get("BENCH_SCHEDULE", "gpipe")
        pp_micro = int(os.environ.get("BENCH_MICRO", "0"))
        pp_virtual = int(os.environ.get("BENCH_VIRTUAL", "0"))
        ladder = _ladder_override(
            (64 * n_chips, 32 * n_chips, 16 * n_chips), n_chips)
        # Scale the ladder by accum so each micro-step keeps the ladder's
        # GEMM shapes; the effective batch (and examples counted per
        # timed step) grows accum×. NOTE this makes BENCH_BS the per-chip
        # per-MICRO batch when BENCH_ACCUM>1 (global batch =
        # BENCH_BS × n_chips × BENCH_ACCUM) — there is deliberately no
        # way to pin the effective batch while varying accum, because
        # the accum A/B's contract is constant micro-GEMM shapes.
        ladder = tuple(b * accum for b in ladder)
        result = _run_ladder(
            lambda bs: bench_bert(bs, seq_len=seq, attention_impl=attn,
                                  remat=remat, pack=pack,
                                  fused_qkv=fused_qkv, accum=accum,
                                  pipeline_stages=pp,
                                  pipeline_schedule=pp_sched,
                                  pipeline_microbatches=pp_micro,
                                  pipeline_virtual_stages=pp_virtual),
            ladder, metric, unit, chip, writer=writer)
        if result is None:
            return 1
        out = {
            "metric": metric,
            "value": round(result["examples_per_sec"] / n_chips, 2),
            "unit": unit,
            # No reference-published BERT number exists (BASELINE.md);
            # report the absolute rates and roofline position instead.
            "vs_baseline": 0.0,
            "baseline_kind": "none",
            "chip": chip,
            "num_chips": n_chips,
            "mesh_axes": result.get("mesh_axes"),
            "seq_len": seq,
            "attention_impl": attn,
            "remat": remat,
            "pack": pack,
            "grad_accum": accum,
            **({"pipeline_stages": pp,
                "pipeline_schedule": pp_sched,
                "pipeline_microbatches": pp_micro or pp,
                "pipe_bubble_frac": round(_pp_bubble(
                    pp_sched, pp, pp_micro or pp, pp_virtual), 4)}
               if pp else {}),
            "tokens_per_sec_per_chip": round(
                result["tokens_per_sec"] / n_chips, 1),
            # Useful-token/doc throughput: what packing actually moves —
            # position throughput is ~constant at fixed (bs, seq), but
            # packed rows carry ~3x the real tokens (BENCH_PACK doc).
            "real_tokens_per_sec_per_chip": round(
                result["real_tokens_per_sec"] / n_chips, 1),
            "docs_per_sec_per_chip": round(
                result["docs_per_sec"] / n_chips, 2),
            "run_id": writer.run_id,
        }
        _annotate_roofline(out, result, chip, n_chips,
                           accum_scaled=accum > 1)
        _annotate_memory(out, result, chip, n_chips)
        _check_leaderboard(out, workload)
        _emit_bench_result(writer, workload, out, result)
        _emit_json_line(out)
        return 0

    if workload == "inception":
        # BASELINE config 4's model; no published reference number
        # (BASELINE.json publishes none for any workload), so like bert
        # this reports absolute rate + roofline position only.
        ladder = _ladder_override(
            (128 * n_chips, 64 * n_chips, 32 * n_chips), n_chips)
        result = _run_ladder(bench_inception, ladder, metric, unit, chip,
                             writer=writer)
        if result is None:
            return 1
        out = {
            "metric": metric,
            "value": round(result["images_per_sec"] / n_chips, 2),
            "unit": unit,
            "vs_baseline": 0.0,
            "baseline_kind": "none",
            "chip": chip,
            "num_chips": n_chips,
            "mesh_axes": result.get("mesh_axes"),
            "run_id": writer.run_id,
        }
        _annotate_roofline(out, result, chip, n_chips)
        _annotate_memory(out, result, chip, n_chips)
        _check_leaderboard(out, workload)
        _emit_bench_result(writer, workload, out, result)
        _emit_json_line(out)
        return 0

    ladder = _ladder_override(
        (256 * n_chips, 128 * n_chips, 64 * n_chips), n_chips)
    result = _run_ladder(bench_resnet50, ladder, metric, unit, chip,
                         writer=writer)
    if result is None:
        return 1

    per_chip = result["images_per_sec"] / n_chips
    out = {
        "metric": metric,
        "value": round(per_chip, 2),
        "unit": unit,
        "vs_baseline": round(per_chip / TARGET_PER_CHIP, 4),
        # vs_baseline comparator: BASELINE.json publishes no measured
        # reference number (published: {}), so the denominator is the
        # north-star TARGET slice (10k img/s aggregate on v5e-64 →
        # 156.25/chip), NOT a measured reference (VERDICT r4 weak #5).
        "baseline_kind": "north-star-target",
        "baseline_value": TARGET_PER_CHIP,
        "chip": chip,
        "num_chips": n_chips,
        "mesh_axes": result.get("mesh_axes"),
        "run_id": writer.run_id,
    }
    _annotate_roofline(out, result, chip, n_chips)
    _annotate_memory(out, result, chip, n_chips)
    _check_leaderboard(out, workload)
    _emit_bench_result(writer, workload, out, result)
    _emit_json_line(out)
    return 0


def main() -> int:
    writer = _bench_writer()
    try:
        return _run(writer)
    finally:
        writer.close()


if __name__ == "__main__":
    sys.exit(main())
