"""TPU-native distributed training framework.

A from-scratch rebuild of the capabilities of
``Seanforfun/Distributed-Tensorflow-Framework`` (a TF 1.x parameter-server /
worker training template: ClusterSpec launcher, SyncReplicasOptimizer with
NCCL all-reduce, cuDNN conv / fused-BN model builders, tf.data input
pipeline, single ``train.py`` entrypoint) as one idiomatic JAX/XLA SPMD
program.

Reference provenance: the reference mount (``/root/reference``) was empty at
build time; the capability surface is taken from ``SURVEY.md`` /
``BASELINE.json`` (see SURVEY.md §0 for the evidence protocol). Where
docstrings in this package cite the reference they cite the reconstructed
component inventory (SURVEY.md §2 rows), not file:line.

Layout:
  core/      config dataclasses, mesh/runtime init, PRNG discipline, metrics
  parallel/  sharding rules, explicit collectives, shard_map train path,
             ring-attention sequence parallelism
  models/    Flax model zoo: LeNet-5, ResNet-50, Inception-v3, BERT-base
  ops/       Pallas TPU kernels for hot ops (attention, fused loss)
  data/      input pipelines (tf.data TFRecord + synthetic), per-host
             sharding, device infeed
  train/     jitted train/eval steps, LR schedules, hooks, training loop
  ckpt/      Orbax-backed checkpoint/restore of full training state
  cli/       the ``train.py`` entrypoint driving YAML workload configs
"""

__version__ = "0.1.0"

# Canonical mesh axis names used across the framework.
AXIS_DATA = "data"    # data-parallel replicas (reference: worker replicas)
AXIS_FSDP = "fsdp"    # parameter/optimizer sharding (ZeRO-style)
AXIS_MODEL = "model"  # tensor parallelism
AXIS_SEQ = "seq"      # sequence/context parallelism (ring attention)
