"""Checkpoint/resume."""

from distributed_tensorflow_framework_tpu.ckpt.async_saver import (  # noqa: F401
    AsyncSaver,
    AsyncSaverError,
)
from distributed_tensorflow_framework_tpu.ckpt.checkpoint import (  # noqa: F401
    CheckpointManager,
)
from distributed_tensorflow_framework_tpu.ckpt.reshard import (  # noqa: F401
    MeshTopologyError,
)
