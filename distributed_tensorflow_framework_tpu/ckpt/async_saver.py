"""Background checkpoint commit thread — the async half of ckpt/.

PR 2 made every save strictly more expensive (orbax write + per-file
sha256 manifest + fsync + atomic commit) and ran all of it on the
training thread while the device idled. The compiled step is HBM-bound
at ~95% of peak (PERF_NOTES.md), so the host side of the loop is where
wall-clock goes to die — and a checkpoint is the single largest host
stall in steady state.

This module holds the concurrency primitive that takes the save off the
step loop: a single daemon worker thread that executes one *commit job*
(orbax write → manifest hash → fsync → atomic commit, assembled by
ckpt/checkpoint.py) at a time. The training thread pays only the
device→host snapshot; everything durable happens here.

Correctness barriers — the part that must not be clever:

  * **one in flight, ever**: ``submit`` blocks until the previous commit
    has fully landed (manifest written, fsync'd). Overlapping saves
    cannot interleave their orbax step directories or commit manifests
    out of order, and the manager's ``all_steps`` view stays accurate.
  * **drain on exit**: ``wait`` blocks until the in-flight commit lands.
    The trainer's exit paths (final save, SIGTERM graceful preemption →
    rc 83) call it so a process never exits "successfully" with a torn
    step directory on disk.
  * **no silent failure**: an exception in the background job is stored
    and re-raised on the *next* ``submit``/``wait``/``close`` — a failed
    checkpoint must surface on the training thread, not vanish into a
    daemon thread's stderr.

Crash semantics are unchanged from the synchronous pipeline by
construction: a SIGKILL at any point (including one injected by the
``crash_in_save`` fault, which now fires *on this thread*) leaves either
a fully committed step (manifest present) or an uncommitted directory
(no manifest) that restore quarantines — there is no third state,
because the manifest write itself is tmp+fsync+rename (ckpt/manifest.py).

Stdlib-only: threading + time; the jax/orbax work lives in the closures
ckpt/checkpoint.py submits.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable

log = logging.getLogger(__name__)

THREAD_NAME = "dtf-ckpt-saver"


class AsyncSaverError(RuntimeError):
    """A background commit failed; carries the step and original error."""

    def __init__(self, step: int | None, cause: BaseException):
        super().__init__(
            f"background checkpoint save for step {step} failed: "
            f"{type(cause).__name__}: {cause}"
        )
        self.step = step
        self.__cause__ = cause


class AsyncSaver:
    """One background worker executing serialized checkpoint-commit jobs.

    The thread is started lazily on the first ``submit`` and is a daemon:
    a hard crash on the training thread must not hang process exit on a
    half-finished write (the manifest layer makes that write read as
    uncommitted — exactly the crash contract).
    """

    def __init__(self, *, name: str = THREAD_NAME):
        self._name = name
        self._cond = threading.Condition()
        self._job: Callable[[], None] | None = None
        self._job_step: int | None = None
        self._busy = False
        self._error: AsyncSaverError | None = None
        self._closed = False
        self._thread: threading.Thread | None = None
        # Observability counters (tests + telemetry sanity checks).
        self.submitted = 0
        self.completed = 0

    # ------------------------------------------------------------ client --
    def submit(self, job: Callable[[], None], *, step: int | None = None) -> float:
        """Queue one commit job; returns seconds spent blocked waiting for
        the previous commit to land (0.0 when the pipe was idle).

        Serialization contract: at most one job queued-or-running. A
        pending background failure is re-raised here instead of accepting
        more work on top of a broken pipe.
        """
        t0 = time.perf_counter()
        with self._cond:
            if self._closed:
                raise RuntimeError("AsyncSaver is closed")
            self._wait_idle_locked()
            self._raise_pending_locked()
            self._ensure_thread_locked()
            self._job, self._job_step = job, step
            self.submitted += 1
            self._cond.notify_all()
        return time.perf_counter() - t0

    def wait(self) -> None:
        """Barrier: block until no commit is queued or in flight, then
        re-raise any background failure. The exit/preemption flush."""
        with self._cond:
            self._wait_idle_locked()
            self._raise_pending_locked()

    @property
    def idle(self) -> bool:
        with self._cond:
            return self._job is None and not self._busy

    def close(self) -> None:
        """Drain, surface any pending failure, and stop the worker."""
        with self._cond:
            self._wait_idle_locked()
            self._closed = True
            self._cond.notify_all()
            error = self._error
            self._error = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if error is not None:
            raise error

    # ------------------------------------------------------------ worker --
    def _wait_idle_locked(self) -> None:
        while self._job is not None or self._busy:
            self._cond.wait()

    def _raise_pending_locked(self) -> None:
        if self._error is not None:
            error, self._error = self._error, None
            raise error

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name=self._name, daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            with self._cond:
                while self._job is None and not self._closed:
                    self._cond.wait()
                if self._job is None and self._closed:
                    return
                job, step = self._job, self._job_step
                self._job, self._job_step = None, None
                self._busy = True
            try:
                job()
            except BaseException as e:  # surface on the training thread
                log.error("background checkpoint save for step %s failed",
                          step, exc_info=True)
                with self._cond:
                    # Keep the FIRST failure if several pile up before a
                    # barrier runs (the first is the root cause).
                    if self._error is None:
                        self._error = AsyncSaverError(step, e)
            finally:
                with self._cond:
                    self._busy = False
                    self.completed += 1
                    self._cond.notify_all()
