"""Orbax-backed checkpointing of the full training state.

Replaces the reference's ``tf.train.Saver`` under MonitoredTrainingSession
(SURVEY.md §5 "Checkpoint / resume": chief-only writes, global_step-suffixed
files, latest-checkpoint auto-restore) with Orbax:

  * step-numbered directories + ``latest_step()`` resolution,
  * async saves (device→host copy happens synchronously, disk write in the
    background — the train loop doesn't stall),
  * saves MORE than the reference: params, BN stats, optimizer state, step,
    RNG key AND the data-iterator position, so resume is exact
    (SURVEY.md §7 hard part 3 — tested by tests/test_ckpt.py).

All processes call save/restore (Orbax coordinates internally; process 0
writes metadata) — the multi-host analogue of "chief writes".
"""

from __future__ import annotations

import logging
import os
from typing import Any

import jax
import orbax.checkpoint as ocp

from distributed_tensorflow_framework_tpu.core.config import CheckpointConfig
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset
from distributed_tensorflow_framework_tpu.train.state import TrainState

log = logging.getLogger(__name__)


def _pack(state: TrainState) -> Any:
    """Make the state orbax-serializable (typed PRNG keys → raw key data)."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _unpack(raw: Any, like: TrainState) -> TrainState:
    impl = jax.random.key_impl(like.rng)
    return raw.replace(rng=jax.random.wrap_key_data(raw.rng, impl=impl))


class CheckpointManager:
    def __init__(self, config: CheckpointConfig, *, is_chief: bool = True):
        if not config.directory:
            raise ValueError("CheckpointConfig.directory must be set")
        self.config = config
        self.is_chief = is_chief
        path = os.path.abspath(config.directory)
        os.makedirs(path, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                enable_async_checkpointing=config.async_save,
            ),
        )

    def save(self, step: int, state: TrainState, *,
             dataset_state: dict | None = None, force: bool = False) -> bool:
        """``dataset_state`` must be the iterator snapshot aligned with
        ``step`` (see data/infeed.py) — NOT the live dataset's state, which
        the prefetcher has advanced past the training step."""
        if step in self._mgr.all_steps():
            return False  # already saved (e.g. final save on an interval step)
        args = {"state": ocp.args.StandardSave(_pack(state))}
        if dataset_state is not None:
            args["data_iter"] = ocp.args.JsonSave(dataset_state)
        saved = self._mgr.save(step, args=ocp.args.Composite(**args), force=force)
        if saved and self.is_chief:
            log.info("Saved checkpoint at step %d", step)
        return saved

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template: TrainState, *,
                dataset: HostDataset | None = None,
                step: int | None = None) -> TrainState | None:
        """Restore into the template's shardings; None if no checkpoint."""
        step = step if step is not None else self.latest_step()
        if step is None:
            return None
        args = {"state": ocp.args.StandardRestore(_pack(template))}
        if dataset is not None:
            args["data_iter"] = ocp.args.JsonRestore()
        restored = self._mgr.restore(step, args=ocp.args.Composite(**args))
        if dataset is not None and restored.get("data_iter") is not None:
            dataset.restore(restored["data_iter"])
        return _unpack(restored["state"], template)

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
