"""Orbax-backed checkpointing of the full training state.

Replaces the reference's ``tf.train.Saver`` under MonitoredTrainingSession
(SURVEY.md §5 "Checkpoint / resume": chief-only writes, global_step-suffixed
files, latest-checkpoint auto-restore) with Orbax:

  * step-numbered directories + ``latest_step()`` resolution,
  * async save pipeline (``checkpoint.async_save``, docs/PERFORMANCE.md):
    at a save step the training thread pays only a device→host snapshot
    of the TrainState; a background saver thread (ckpt/async_saver.py)
    then performs the orbax write, the manifest hashing, the fsync and
    the atomic commit — the loop never stalls on disk. A new save waits
    for the previous commit, and every exit path drains the in-flight
    commit before the process returns (``wait_until_finished``).
    ``async_save=false`` runs the identical commit sequence inline on
    the training thread (the sync fallback — also the path multi-host
    sharded saves use, since the snapshot is a full host copy).
  * saves MORE than the reference: params, BN stats, optimizer state, step,
    RNG key AND the data-iterator position, so resume is exact
    (SURVEY.md §7 hard part 3 — tested by tests/test_ckpt.py).

All processes call save/restore (Orbax coordinates internally; process 0
writes metadata) — the multi-host analogue of "chief writes".

Integrity layer (docs/RESILIENCE.md): after every committed save the chief
hashes the step directory into a ``manifest.json`` commit record
(ckpt/manifest.py — write-to-tmp + fsync + atomic rename). ``latest_step``
and ``all_steps`` only report manifested steps, restore re-hashes before
reading, and a torn/corrupt step is quarantined (renamed ``<step>.corrupt``)
with automatic fallback to the newest verified older step — a SIGKILL
racing a save can cost at most one checkpoint interval, never the run.
The async pipeline preserves that contract bit-for-bit: the commit
sequence is the same code, merely executed on the saver thread, so a kill
at any point still leaves either a manifested step or an uncommitted
directory restore refuses. Quarantine/rename decisions are chief-only;
non-chief processes follow the shared filesystem state.

Per-save telemetry (``ckpt_save`` events): ``ckpt_save_blocked_ms`` is the
wall time the TRAINING thread spent inside ``save`` (wait-for-previous +
snapshot); ``ckpt_save_total_ms`` is submit→commit-landed. Async saves
show blocked ≪ total; the sync fallback shows blocked == total.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from distributed_tensorflow_framework_tpu.ckpt import manifest as mf
from distributed_tensorflow_framework_tpu.ckpt import reshard
from distributed_tensorflow_framework_tpu.ckpt.async_saver import AsyncSaver
from distributed_tensorflow_framework_tpu.core import faults, telemetry
from distributed_tensorflow_framework_tpu.core.config import CheckpointConfig
from distributed_tensorflow_framework_tpu.parallel import zero
from distributed_tensorflow_framework_tpu.data import shard as data_shard
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset
from distributed_tensorflow_framework_tpu.train.state import TrainState

log = logging.getLogger(__name__)


def _pack(state: TrainState) -> Any:
    """Make the state orbax-serializable (typed PRNG keys → raw key data)."""
    return state.replace(rng=jax.random.key_data(state.rng))


def _unpack(raw: Any, like: TrainState) -> TrainState:
    impl = jax.random.key_impl(like.rng)
    return raw.replace(rng=jax.random.wrap_key_data(raw.rng, impl=impl))


def _param_key_names(tree: Any) -> set[str]:
    """Every dict-key name appearing anywhere in a params pytree."""
    names: set[str] = set()
    for path, _ in jax.tree_util.tree_flatten_with_path(tree)[0]:
        for p in path:
            k = getattr(p, "key", None)
            if isinstance(k, str):
                names.add(k)
    return names


def _attention_layout(key_names: set[str]) -> str | None:
    """'fused' / 'unfused' QKV projection layout, or None if the tree has
    no attention projections at all (conv nets). The fused module stores
    one ``attn/qkv`` kernel; unfused stores ``attn/{query,key,value}``
    (models/bert.py) — require the full triple so a stray generic 'key'
    entry can't misclassify."""
    if "qkv" in key_names:
        return "fused"
    if {"query", "key", "value"} <= key_names:
        return "unfused"
    return None


class CheckpointManager:
    def __init__(self, config: CheckpointConfig, *, is_chief: bool = True,
                 telemetry_writer: telemetry.TelemetryWriter | None = None,
                 mesh=None, process_count: int | None = None):
        """``mesh``/``process_count`` identify the topology this manager
        saves under (recorded in every manifest commit record,
        ckpt/reshard.py); when omitted they are derived from the state's
        own shardings at save time."""
        if not config.directory:
            raise ValueError("CheckpointConfig.directory must be set")
        self.config = config
        self.is_chief = is_chief
        self._mesh = mesh
        self._process_count = process_count
        self._telemetry = telemetry_writer
        path = self._path = os.path.abspath(config.directory)
        os.makedirs(path, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=config.max_to_keep,
                # Orbax's own async layer stays OFF either way: asynchrony
                # is owned by ckpt/async_saver.py, whose worker runs the
                # WHOLE commit sequence (orbax write + manifest + fsync)
                # so the integrity manifest always hashes a finished
                # directory — no deferred-manifest bookkeeping.
                enable_async_checkpointing=False,
            ),
        )
        self._saver = AsyncSaver() if config.async_save else None
        # Exactly-once data plumbing (data/shard.py): the Trainer wires in
        # the live infeed's watermark() and the dataset's repartition
        # capability so every manifest commit record can describe the
        # saved iterator state, and the restore gate knows whether an
        # N→M host refit may repartition it.
        self._watermark_source = None
        self._data_repartition = data_shard.REPARTITION_NONE
        self._data_resume_strict = True

    def set_data_sources(self, *, watermark_source=None,
                         repartition: str | None = None,
                         resume_strict: bool | None = None) -> None:
        """Wire the data plane into save/restore commit records.

        ``watermark_source`` is the live infeed's ``watermark()`` (batches
        prefetched ahead at save time — telemetry only); ``repartition``
        the dataset's capability tag; ``resume_strict`` the
        ``data.resume_strict`` knob gating the restore-time digest /
        host-count checks. The Trainer calls this before restore (tag +
        strictness) and again at train start (watermark), clearing the
        watermark source in its shutdown path — a dead infeed's queue
        must not be polled by a final save.
        """
        self._watermark_source = watermark_source
        if repartition is not None:
            self._data_repartition = repartition
        if resume_strict is not None:
            self._data_resume_strict = bool(resume_strict)

    def _emit(self, kind: str, **fields: Any) -> None:
        if self._telemetry is not None:
            self._telemetry.emit(kind, **fields)

    # ----------------------------------------------------- commit records --
    def _drain(self) -> None:
        """Barrier on the in-flight background commit (no-op when sync or
        idle). Every read of the step listing and every new save funnels
        through here, so directory views are never taken mid-commit and a
        background failure surfaces on the training thread."""
        if self._saver is not None:
            self._saver.wait()

    def _write_and_commit(self, step: int, packed_state: Any,
                          dataset_state: dict | None, *, force: bool,
                          t_begin: float, blocked_s: float | None,
                          topology: dict | None = None,
                          watermark: int = 0) -> bool:
        """The full durable commit sequence — orbax write, fault points,
        manifest hash + fsync + atomic rename, telemetry. Runs on the
        saver thread (async) or inline (sync fallback); identical either
        way, which is what keeps the crash/quarantine drills bit-exact
        across the ``async_save`` knob."""
        args = {"state": ocp.args.StandardSave(packed_state)}
        if dataset_state is not None:
            args["data_iter"] = ocp.args.JsonSave(dataset_state)
        saved = self._mgr.save(step, args=ocp.args.Composite(**args),
                               force=force)
        if not saved:
            return False
        step_dir = os.path.join(self._path, str(step))
        if self.is_chief and os.path.isdir(step_dir) \
                and mf.read_manifest(step_dir) is None:
            # A crash_in_save fault here leaves a written directory with
            # NO manifest — exactly the torn-"latest" artifact the restore
            # path must refuse (docs/RESILIENCE.md drill). In async mode
            # it fires on the saver thread (SIGKILL still takes the whole
            # process — core/faults.py).
            faults.fire("ckpt_in_save", step=step)
            extra: dict = {}
            if topology:
                extra[reshard.MESH_RECORD_KEY] = topology
            if dataset_state is not None:
                # Data-state commit record (data/shard.py): sha256 of the
                # saved iterator state + repartition capability + prefetch
                # watermark, living in the SAME manifest as the weight
                # hashes — "where was the data stream?" shares the
                # integrity contract with "which bytes are the weights?".
                extra[data_shard.DATA_RECORD_KEY] = data_shard.data_state_record(
                    dataset_state,
                    process_count=(self._process_count
                                   if self._process_count is not None
                                   else jax.process_count()),
                    repartition=self._data_repartition,
                    watermark=watermark)
            mf.write_manifest(step_dir, step, extra=extra or None)
            for fault in faults.fire("ckpt_committed", step=step):
                if fault.kind == "corrupt_ckpt":
                    faults.corrupt_checkpoint_dir(step_dir)
        total_ms = (time.perf_counter() - t_begin) * 1e3
        blocked_ms = total_ms if blocked_s is None else blocked_s * 1e3
        self._emit(
            telemetry.KIND_CKPT_SAVE, step=step,
            metrics={"ckpt_save_blocked_ms": round(blocked_ms, 3),
                     "ckpt_save_total_ms": round(total_ms, 3)},
            async_save=self._saver is not None,
        )
        if self.is_chief:
            log.info("Saved checkpoint at step %d (%s, blocked %.0f ms / "
                     "total %.0f ms)", step,
                     "async" if self._saver is not None else "sync",
                     blocked_ms, total_ms)
        return saved

    def save(self, step: int, state: TrainState, *,
             dataset_state: dict | None = None, force: bool = False) -> bool:
        """``dataset_state`` must be the iterator snapshot aligned with
        ``step`` (see data/infeed.py) — NOT the live dataset's state, which
        the prefetcher has advanced past the training step.

        Async mode returns as soon as the snapshot is queued; the True
        return means "accepted for commit", and any commit failure is
        re-raised at the next save/barrier (ckpt/async_saver.py)."""
        t0 = time.perf_counter()
        self._drain()  # a new save waits for the previous commit
        if step in self._mgr.all_steps():
            return False  # already saved (e.g. final save on an interval step)
        # Topology record for the manifest (ckpt/reshard.py): computed from
        # the LIVE sharded state, before any device→host snapshot (the host
        # copy no longer carries NamedShardings).
        topology = reshard.state_topology(
            state, mesh=self._mesh, process_count=self._process_count)
        # Prefetch watermark at the moment of save (the training thread —
        # the same instant the snapshot pairs with), not at commit time on
        # the saver thread, when the producer has run further ahead.
        watermark = 0
        if self._watermark_source is not None and dataset_state is not None:
            try:
                watermark = int(self._watermark_source())
            except Exception:
                log.warning("infeed watermark probe failed", exc_info=True)
        if self._saver is None:
            return self._write_and_commit(
                step, _pack(state), dataset_state, force=force,
                t_begin=t0, blocked_s=None, topology=topology,
                watermark=watermark)
        # Async: the training thread pays only the device→host snapshot.
        # device_get also syncs on the step that produced `state`, so the
        # snapshot is taken at a well-defined step boundary; the loop may
        # donate/overwrite the device buffers freely afterwards.
        host_state = jax.device_get(_pack(state))
        # The iterator snapshot is a small JSON-able dict the trainer
        # rebinds each step; deep-copy via JSON so a hook mutating its
        # live dict can never tear the queued snapshot.
        ds_state = (None if dataset_state is None
                    else json.loads(json.dumps(dataset_state)))
        blocked_s = time.perf_counter() - t0
        self._saver.submit(
            lambda: self._write_and_commit(
                step, host_state, ds_state, force=force,
                t_begin=t0, blocked_s=blocked_s, topology=topology,
                watermark=watermark),
            step=step)
        return True

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return max(steps) if steps else None

    def all_steps(self) -> list[int]:
        """Steps with a complete, COMMITTED checkpoint: saved by Orbax and
        carrying an integrity manifest (post max_to_keep GC). A directory
        Orbax lists but the manifest layer never committed — a save torn by
        a kill — is excluded here and quarantined at restore time.

        Back-compat: a directory with checkpoints but no manifests anywhere
        predates the integrity layer; its steps are trusted as-is (with a
        warning) rather than bricking every pre-manifest run.
        """
        self._drain()
        orbax_steps = sorted(self._mgr.all_steps())
        committed = set(mf.committed_steps(self._path))
        if not committed and orbax_steps:
            log.warning(
                "checkpoint directory %s has no integrity manifests "
                "(pre-manifest checkpoints?) — steps %s are trusted "
                "unverified", self._path, orbax_steps,
            )
            return orbax_steps
        return [s for s in orbax_steps if s in committed]

    def restore(self, template: TrainState, *,
                dataset: HostDataset | None = None,
                step: int | None = None) -> TrainState | None:
        """Restore into the template's shardings; None if no checkpoint.

        Tolerates ``optimizer.ema_decay`` being toggled across a resume:
        the stored tree's ``ema_params`` entry ({} vs params-shaped) may
        not match the template's. On mismatch the restore is retried with
        the opposite EMA shape and reconciled — EMA re-seeded from the
        restored params when newly enabled, dropped when newly disabled —
        instead of failing mid-experiment on a template/tree mismatch.
        """
        if step is not None:
            # Explicitly requested snapshot: fail loudly on corruption (the
            # caller pinned THIS step; silently reading another would be the
            # exact fallback restore_step exists to prevent).
            errors = self._verify(step)
            if errors:
                raise ValueError(
                    f"checkpoint step {step} in {self._path} failed "
                    f"integrity verification: {'; '.join(errors)}"
                )
        else:
            step = self._verified_latest()
        if step is None:
            return None
        self._check_attention_layout(step, template)
        # Topology gate (ckpt/reshard.py): same mesh → normal restore;
        # different mesh → typed MeshTopologyError unless
        # checkpoint.allow_reshard, in which case orbax restores into the
        # template's (new-mesh) shardings and the plan is validated +
        # telemetered below. Runs AFTER integrity verification — a torn
        # step must quarantine, not "reshard".
        saved_manifest = mf.read_manifest(
            os.path.join(self._path, str(step))) or {}
        saved_topo = saved_manifest.get(reshard.MESH_RECORD_KEY)
        reshard_plan = reshard.check_restore_topology(
            saved_topo, template, allow_reshard=self.config.allow_reshard,
            directory=self._path, step=step)

        want_ema = bool(jax.tree.leaves(template.ema_params))
        want_res = bool(jax.tree.leaves(template.collective_residual))
        n_want = (jax.tree.leaves(template.collective_residual)[0].shape[0]
                  if want_res else 0)

        def _residual_read_tmpl() -> Any:
            """Template subtree for READING a stored shaped residual: the
            concrete (sharded) template when the replica count matches,
            else host-side ShapeDtypeStructs at the STORED shape — folded
            onto the new replica rows or dropped after the read."""
            axes = (saved_topo or {}).get("axes") or {}
            if not axes:
                raise ValueError(
                    f"checkpoint step {step} in {self._path} stores a "
                    f"collective_residual but its manifest has no mesh "
                    f"topology record — cannot derive the stored replica "
                    f"dimension to fold/drop it"
                )
            n_saved = int(axes.get("data", 1)) * int(axes.get("fsdp", 1))
            if want_res and n_saved == n_want:
                return template.collective_residual
            return jax.tree.map(
                lambda p: jax.ShapeDtypeStruct((n_saved,) + p.shape,
                                               jnp.float32),
                template.params)

        # ZeRO-stacked optimizer slots (parallel/zero.py): detected
        # structurally from the template — (n, ceil(size/n)) rows per
        # param-mirroring slot. A cross-mesh restore must READ them at
        # the STORED row grid and refold host-side (the row count is the
        # data×fsdp replica count, exactly like the EF residual above).
        zero_rows = zero.stacked_rows(template.opt_state, template.params)

        def _zero_saved_rows() -> int | None:
            axes = (saved_topo or {}).get("axes") or {}
            if not axes:
                return None
            return int(axes.get("data", 1)) * int(axes.get("fsdp", 1))

        def _zero_read_tmpl() -> Any:
            n_saved = _zero_saved_rows()
            if n_saved is None:
                raise ValueError(
                    f"checkpoint step {step} in {self._path} is being "
                    f"resharded with ZeRO-stacked optimizer state but its "
                    f"manifest has no mesh topology record — cannot derive "
                    f"the stored shard grid"
                )
            if n_saved == zero_rows:
                return template.opt_state

            def tmpl(slot, param):
                if param is None or getattr(slot, "ndim", 0) != 2:
                    return slot
                size = int(math.prod(param.shape)) if param.shape else 1
                return jax.ShapeDtypeStruct(
                    (n_saved, -(-size // n_saved)), slot.dtype)

            return zero.map_slots(tmpl, template.opt_state, template.params)

        def tmpl_for(stored_ema: bool, stored_res: str) -> TrainState:
            """Restore template matching the stored tree's EMA and
            error-feedback-residual presence."""
            t = template
            if want_ema and not stored_ema:
                log.warning(
                    "Checkpoint at step %d has no EMA params (ema_decay "
                    "enabled after it was saved) — will re-seed EMA from "
                    "the restored params", step,
                )
                t = t.replace(ema_params={})
            if stored_ema and not want_ema:
                # Stored EMA must be read into a params-shaped template and
                # discarded below (orbax's Standard handler has no partial
                # restore) — a one-time params-sized I/O cost on the rare
                # disable-EMA-mid-experiment resume. Leaves are only a
                # restore template, so aliasing params is fine.
                log.warning(
                    "Checkpoint at step %d carries EMA params but ema_decay "
                    "is now disabled — dropping them", step,
                )
                t = t.replace(ema_params=template.params)
            if stored_res == "shaped":
                if not want_res:
                    log.warning(
                        "Checkpoint at step %d carries a collective "
                        "error-feedback residual but quantized collectives "
                        "are now off — dropping it", step,
                    )
                t = t.replace(collective_residual=_residual_read_tmpl())
            else:
                if want_res:
                    log.warning(
                        "Checkpoint at step %d has no collective residual "
                        "(quantized collectives enabled after it was saved) "
                        "— starting from a zero residual", step,
                    )
                t = t.replace(collective_residual={})
            if reshard_plan is not None and zero_rows:
                t = t.replace(opt_state=_zero_read_tmpl())
            return t

        def attempt(t: TrainState, *, legacy: bool):
            item = _pack(t)
            if legacy:
                # Pre-residual checkpoint: flax dataclasses serialize as
                # dicts, so restore into the historical six-key dict and
                # rebuild the TrainState afterwards.
                item = {
                    "step": item.step, "params": item.params,
                    "batch_stats": item.batch_stats,
                    "opt_state": item.opt_state, "rng": item.rng,
                    "ema_params": item.ema_params,
                }
            args = {"state": ocp.args.StandardRestore(item)}
            if dataset is not None:
                args["data_iter"] = ocp.args.JsonRestore()
            return self._mgr.restore(step, args=ocp.args.Composite(**args)), \
                item

        stored_ema = self._stored_has_ema(step, default=want_ema)
        stored_res = self._stored_residual_presence(
            step, default="shaped" if want_res else "empty")
        ema_flipped = res_flipped = False
        while True:
            tmpl = tmpl_for(stored_ema, stored_res)
            try:
                restored, item = attempt(tmpl,
                                         legacy=(stored_res == "missing"))
                break
            except ValueError as e:
                # Fallbacks for when a metadata probe misjudged (the JSON
                # layout is orbax-private and may change): a tree-structure
                # mismatch naming the field means the stored presence is
                # the opposite of what we assumed — flip and retry, once
                # per field.
                msg = str(e)
                if "ema_params" in msg and not ema_flipped:
                    log.warning(
                        "EMA-presence probe disagreed with the stored tree "
                        "(%s); retrying restore with the flipped EMA "
                        "template", e,
                    )
                    ema_flipped, stored_ema = True, not stored_ema
                    continue
                if "collective_residual" in msg and not res_flipped:
                    log.warning(
                        "residual-presence probe disagreed with the stored "
                        "tree (%s); retrying restore with the flipped "
                        "residual template", e,
                    )
                    res_flipped = True
                    stored_res = ("empty" if stored_res == "shaped"
                                  else "shaped")
                    continue
                if "opt_state" in msg or "Ranks do not match" in msg:
                    # A slot-shape (or tensorstore rank — the stacked
                    # (n, chunk) layout differs in RANK from the param
                    # shape, and that error carries no tree path)
                    # mismatch here is the ZeRO layout
                    # toggled (or re-gridded without a reshard plan)
                    # across a resume — name the knob instead of leaking
                    # an orbax tree error.
                    raise ValueError(
                        f"checkpoint step {step} in {self._path} stores an "
                        f"optimizer state whose slot layout does not match "
                        f"this run's: toggling optimizer.zero_sharding "
                        f"between 'shard_map' and another mode (or "
                        f"precision.fused_update, which regroups the slots "
                        f"per ZeRO bucket) across a resume is unsupported "
                        f"(replicated, ZeRO-stacked and per-bucket slot "
                        f"layouts are incompatible) — restore with the "
                        f"settings the checkpoint was saved under ({e})"
                    ) from e
                raise
        if reshard_plan is not None:
            # Cross-mesh load succeeded mechanically; confirm it moved
            # bytes without reshaping them, then record the reshard in the
            # run's event stream (analyze_trace.py surfaces it).
            leaf_count = reshard.validate_restored(
                item, restored["state"], step=step)
            self._emit(
                telemetry.KIND_CKPT_RESHARDED, step=step,
                from_axes=reshard_plan["from_axes"],
                to_axes=reshard_plan["to_axes"],
                leaf_count=leaf_count,
                from_spec_digest=reshard_plan["from_spec_digest"],
                to_spec_digest=reshard_plan["to_spec_digest"],
                respec_agreement=reshard_plan["respec_agreement"],
            )
            log.warning(
                "restored checkpoint step %d RESHARDED %s -> %s "
                "(%d leaves validated)", step,
                reshard.describe_axes(reshard_plan["from_axes"]),
                reshard.describe_axes(reshard_plan["to_axes"]), leaf_count,
            )
        raw = restored["state"]
        if stored_res == "missing":
            # Legacy dict (pre-residual) → TrainState; collective_residual
            # takes its {} default and is reconciled below.
            raw = TrainState(**raw)
        state = _unpack(raw, tmpl)
        if reshard_plan is not None and zero_rows:
            n_saved = _zero_saved_rows()
            if n_saved != zero_rows:
                refolded = reshard.refold_zero_opt_state(
                    state.opt_state, template.params, zero_rows)
                state = state.replace(opt_state=jax.tree.map(
                    lambda f, t: (jax.device_put(f, t.sharding)
                                  if hasattr(t, "sharding") else f),
                    refolded, template.opt_state))
                log.warning(
                    "ZeRO optimizer state re-gridded %d -> %d shard rows "
                    "(padding truncated and re-derived) across the "
                    "reshard", n_saved, zero_rows,
                )
        if want_res and stored_res == "shaped":
            n_saved = jax.tree.leaves(state.collective_residual)[0].shape[0]
            if n_saved != n_want:
                folded = reshard.fold_residual(
                    state.collective_residual, n_want)
                state = state.replace(collective_residual=jax.tree.map(
                    lambda f, t: jax.device_put(f, t.sharding),
                    folded, template.collective_residual))
                log.warning(
                    "collective_residual folded %d -> %d replica rows "
                    "(sum-preserving) across the reshard", n_saved, n_want,
                )
        elif want_res:
            state = state.replace(
                collective_residual=template.collective_residual)
        elif jax.tree.leaves(state.collective_residual):
            state = state.replace(collective_residual={})
        if want_ema and not stored_ema:
            # Real copies, not aliases: params and ema_params both live in
            # the donated TrainState — aliased buffers would be donated
            # twice in the first train step.
            state = state.replace(ema_params=jax.tree.map(jnp.copy, state.params))
        elif stored_ema and not want_ema:
            state = state.replace(ema_params={})
        if dataset is not None and restored.get("data_iter") is not None:
            # Data-state restore gate (data/shard.py): digest-check the
            # restored iterator state against its manifest commit record
            # and decide whether a host-count change may repartition it.
            # Runs BEFORE the state reaches the dataset, so a failed gate
            # leaves the dataset untouched at its initial state.
            data_plan = data_shard.check_restore_data(
                saved_manifest.get(data_shard.DATA_RECORD_KEY),
                restored["data_iter"],
                process_count=(self._process_count
                               if self._process_count is not None
                               else jax.process_count()),
                resume_strict=self._data_resume_strict)
            if data_plan is not None:
                self._emit(telemetry.KIND_DATA_STATE, step=step,
                           plan=data_plan)
                if data_plan["action"] != "resume":
                    log.warning(
                        "data state restored at step %d: %s (%s -> %s "
                        "hosts)", step, data_plan["action"],
                        data_plan.get("from_processes"),
                        data_plan.get("to_processes"))
            dataset.restore(restored["data_iter"])
        return state

    # ------------------------------------------------ integrity / fallback --
    def _verify(self, step: int) -> list[str]:
        """Integrity errors for one step ([] = safe to restore)."""
        step_dir = os.path.join(self._path, str(step))
        manifest = mf.read_manifest(step_dir)
        if manifest is None:
            if not mf.committed_steps(self._path):
                # Pre-manifest directory: nothing to verify against.
                log.warning(
                    "restoring step %d without integrity verification "
                    "(no manifests in %s)", step, self._path,
                )
                return []
            return ["no committed manifest (save did not complete)"]
        if not self.config.verify_restore:
            return []  # manifest presence (commit record) is still required
        return mf.verify_step_dir(step_dir, manifest)

    def _verified_latest(self) -> int | None:
        """Newest step that passes verification, quarantining every newer
        step that does not — the automatic-fallback half of the integrity
        contract. Returns None when no restorable checkpoint remains."""
        self._drain()
        candidates = sorted(self._mgr.all_steps(), reverse=True)
        if not candidates:
            return None
        if not mf.committed_steps(self._path):
            return candidates[0]  # legacy store; _verify logs the warning
        newest = candidates[0]
        quarantined = False
        chosen = None
        for s in candidates:
            errors = self._verify(s)
            if not errors:
                chosen = s
                break
            log.error(
                "checkpoint step %d in %s is corrupt/torn: %s",
                s, self._path, "; ".join(errors[:3]),
            )
            reason = ("uncommitted save" if "no committed manifest" in errors[0]
                      else "integrity verification failed")
            if self.is_chief:
                mf.quarantine(self._path, s, reason, errors)
                quarantined = True
            self._emit(
                telemetry.KIND_CKPT_QUARANTINED, step=s,
                health={"reason": reason, "errors": "; ".join(errors[:3]),
                        "directory": self._path},
            )
        if quarantined:
            # Orbax caches its step listing; the renames just invalidated it.
            try:
                self._mgr.reload()
            except Exception:
                log.warning("orbax manager reload after quarantine failed",
                            exc_info=True)
        if chosen is not None and chosen != newest:
            log.warning(
                "restore falling back from corrupt step %d to verified "
                "step %d", newest, chosen,
            )
            self._emit(
                telemetry.KIND_RESTORE_FALLBACK, step=chosen,
                health={"from_step": newest, "to_step": chosen,
                        "directory": self._path},
            )
        return chosen

    def _stored_has_ema(self, step: int, *, default: bool) -> bool:
        """Whether the stored state tree carries EMA param leaves.

        Reads the step's PyTree ``_METADATA`` JSON directly (the manager's
        ``item_metadata`` returns nothing before the item registry is
        populated). A state saved with EMA disabled stores a single
        empty-Dict marker at ``('ema_params',)``; real EMA state stores
        nested array entries ``('ema_params', <module>, ...)``.
        """
        import json

        path = os.path.join(self._path, str(step), "state", "_METADATA")
        try:
            with open(path) as fh:
                tree_meta = json.load(fh).get("tree_metadata", {})
        except Exception as e:  # probe is best-effort; restore() retries
            log.warning("EMA-presence probe failed reading %s (%s) — "
                        "assuming template shape", path, e)
            return default
        for entry in tree_meta.values():
            keys = entry.get("key_metadata") or []
            if keys and keys[0].get("key") == "ema_params" and len(keys) > 1:
                return True
        return False

    def _stored_residual_presence(self, step: int, *, default: str) -> str:
        """Whether the stored tree carries a collective_residual subtree:
        ``"missing"`` (pre-residual checkpoint — no such key), ``"empty"``
        (the {} marker: quantized collectives were off) or ``"shaped"``
        (per-replica residual arrays). Same best-effort ``_METADATA``
        probe as ``_stored_has_ema``; ``default`` on unreadable metadata.
        """
        import json

        path = os.path.join(self._path, str(step), "state", "_METADATA")
        try:
            with open(path) as fh:
                tree_meta = json.load(fh).get("tree_metadata", {})
        except Exception as e:
            log.warning("residual-presence probe failed reading %s (%s) — "
                        "assuming template shape", path, e)
            return default
        found = False
        for entry in tree_meta.values():
            keys = entry.get("key_metadata") or []
            if keys and keys[0].get("key") == "collective_residual":
                found = True
                if len(keys) > 1:
                    return "shaped"
        return "empty" if found else "missing"

    def _stored_param_key_names(self, step: int) -> set[str] | None:
        """Dict-key names under the stored tree's ``params`` subtree, from
        the step's PyTree ``_METADATA`` JSON; None when unreadable (the
        probe is best-effort, like ``_stored_has_ema``)."""
        import json

        path = os.path.join(self._path, str(step), "state", "_METADATA")
        try:
            with open(path) as fh:
                tree_meta = json.load(fh).get("tree_metadata", {})
        except Exception:
            return None
        names: set[str] = set()
        for entry in tree_meta.values():
            keys = [k.get("key") for k in (entry.get("key_metadata") or [])]
            if keys and keys[0] == "params":
                names.update(k for k in keys[1:] if isinstance(k, str))
        return names or None

    def _check_attention_layout(self, step: int, template: TrainState) -> None:
        """Fail fast, with the fix named, when the stored params use the
        opposite ``model.fused_qkv`` layout from the restore template.

        Without this the mismatch surfaces as an opaque Orbax tree-structure
        error deep inside StandardRestore ('user-provided restore item and
        on-disk value metadata tree structures do not match'), long after
        the config change that caused it.
        """
        stored_keys = self._stored_param_key_names(step)
        if stored_keys is None:
            return
        stored = _attention_layout(stored_keys)
        want = _attention_layout(_param_key_names(template.params))
        if stored is None or want is None or stored == want:
            return
        raise ValueError(
            f"Checkpoint at step {step} in {self._path} stores "
            f"{stored} attention projections but the model is configured "
            f"for {want} (model.fused_qkv="
            f"{'true' if want == 'fused' else 'false'}). Set model."
            f"fused_qkv to match the checkpoint, or transplant the params "
            f"— the fused kernel is stack([query, key, value], axis=1) of "
            f"the unfused kernels, see tests/test_models.py::"
            f"test_fused_qkv_transplant_parity and docs/MIGRATING.md."
        )

    def wait_until_finished(self) -> None:
        """The exit/preemption barrier: returns only once every accepted
        save has durably committed (manifest written + fsync'd). Called by
        CheckpointHook.on_end so normal completion AND the SIGTERM
        graceful-preempt path (rc 83) never exit with a commit in flight."""
        self._drain()
        self._mgr.wait_until_finished()

    def close(self) -> None:
        try:
            self._drain()
        finally:
            if self._saver is not None:
                try:
                    self._saver.close()
                except Exception:
                    log.warning("async saver close failed", exc_info=True)
            self._mgr.close()
