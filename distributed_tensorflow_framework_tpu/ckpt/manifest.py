"""Checkpoint integrity manifests — the commit/verify half of resilience.

Orbax's own commit protocol (write to a tmp dir, rename to ``<step>/``)
protects against a *partially renamed* checkpoint, but nothing on disk says
"every byte of this checkpoint is the byte that was written": a SIGKILL
racing the final flushes, a truncated copy, or plain bit-rot leaves a
directory that LOOKS committed and poisons every relaunch through
auto-restore (ISSUE 2; the TF systems paper treats checkpoint recovery as
the core fault-tolerance primitive, so a torn "latest" is the single worst
artifact a failure can leave behind).

This module adds an explicit commit marker with content hashes:

  * after a save finishes, ``write_manifest(step_dir)`` hashes every file
    under the step directory (sha256 + byte size) and commits
    ``manifest.json`` via write-to-tmp + fsync + atomic rename — the
    manifest IS the commit record; a step directory without one is
    uncommitted;
  * at restore, ``verify_step_dir`` re-hashes and reports every missing /
    truncated / mutated file;
  * corrupt or uncommitted steps are quarantined by renaming the directory
    to ``<step>.corrupt`` (``quarantine``) so ``latest_step()`` scans and
    relaunches never see them again, while the evidence stays on disk for
    post-mortems.

Storage-format note: with Orbax's OCDBT layout the hash unit is the storage
*file*, not the logical array — per-array attribution is impossible at this
layer, but torn/corrupt detection (the recovery-correctness property) only
needs file-level integrity.

Everything here is stdlib-only on purpose: the supervisor
(scripts/train_resilient.py) uses ``latest_committed_step`` to measure
checkpoint progress between relaunches without touching JAX or Orbax.

Threading note (async pipeline, ckpt/async_saver.py): with
``checkpoint.async_save`` on, ``write_manifest`` runs on the background
saver thread, immediately after the orbax write for that step finishes
on the same thread. Nothing here is shared mutable state — every function
is a pure function of the directory passed in — and the manager
serializes commits (one in flight, ever), so directory-level views
(``step_dirs``, ``committed_steps``) stay race-free as long as readers go
through the manager's drain barrier first.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time

log = logging.getLogger(__name__)

MANIFEST_NAME = "manifest.json"
MANIFEST_SCHEMA = "dtf-ckpt-manifest/1"
CORRUPT_SUFFIX = ".corrupt"
_HASH_CHUNK = 1 << 20


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(_HASH_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def iter_payload_files(step_dir: str):
    """Relative paths of every payload file under a step directory (the
    manifest itself and quarantine records are not payload)."""
    for root, _dirs, files in os.walk(step_dir):
        for name in sorted(files):
            rel = os.path.relpath(os.path.join(root, name), step_dir)
            if rel in (MANIFEST_NAME, "quarantine.json"):
                continue
            yield rel


def build_manifest(step_dir: str, step: int,
                   extra: dict | None = None) -> dict:
    """``extra`` merges additional commit-record fields (e.g. the saver's
    mesh topology, ckpt/reshard.py, and the data-state record,
    data/shard.py) without touching the reserved keys — readers of legacy
    manifests simply see them absent."""
    files = {}
    for rel in iter_payload_files(step_dir):
        path = os.path.join(step_dir, rel)
        files[rel] = {
            "sha256": file_sha256(path),
            "bytes": os.path.getsize(path),
        }
    manifest = {
        "schema": MANIFEST_SCHEMA,
        "step": int(step),
        "created_t": time.time(),
        "file_count": len(files),
        "files": files,
    }
    for key, value in (extra or {}).items():
        if key in manifest:
            raise ValueError(f"extra manifest field {key!r} is reserved")
        manifest[key] = value
    return manifest


def write_manifest(step_dir: str, step: int,
                   extra: dict | None = None) -> str:
    """Hash the step directory and atomically commit its manifest.

    tmp + fsync + rename, then fsync the directory so the rename itself is
    durable — the same discipline a SIGKILL-mid-save must not be able to
    break (a kill before the rename leaves NO manifest → the step reads as
    uncommitted, never as half-committed).
    """
    manifest = build_manifest(step_dir, step, extra)
    path = os.path.join(step_dir, MANIFEST_NAME)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(manifest, fh, indent=1)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dir_fd = os.open(step_dir, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    return path


def read_manifest(step_dir: str) -> dict | None:
    """The step's manifest, or None when absent/unreadable (uncommitted)."""
    try:
        with open(os.path.join(step_dir, MANIFEST_NAME)) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if manifest.get("schema") != MANIFEST_SCHEMA:
        return None
    return manifest


def verify_step_dir(step_dir: str, manifest: dict | None = None) -> list[str]:
    """Integrity errors for one step directory ([] = verified).

    Detects missing files, size changes (truncation — the torn-write
    signature) and content mutation (hash mismatch). Extra files are
    tolerated: Orbax may add per-process metadata a chief-written manifest
    did not see, and extra bytes cannot corrupt a restore.
    """
    manifest = manifest if manifest is not None else read_manifest(step_dir)
    if manifest is None:
        return ["no committed manifest (save did not complete)"]
    errors: list[str] = []
    for rel, meta in manifest.get("files", {}).items():
        path = os.path.join(step_dir, rel)
        if not os.path.isfile(path):
            errors.append(f"missing file {rel}")
            continue
        size = os.path.getsize(path)
        if size != meta.get("bytes"):
            errors.append(
                f"truncated/resized file {rel}: {size} bytes, "
                f"manifest says {meta.get('bytes')}"
            )
            continue
        if file_sha256(path) != meta.get("sha256"):
            errors.append(f"content hash mismatch for {rel}")
    return errors


def quarantine(root: str, step: int, reason: str,
               errors: list[str] | None = None) -> str | None:
    """Rename ``<root>/<step>`` to ``<root>/<step>.corrupt`` (suffixing
    ``.N`` if a previous quarantine of the same step exists) and drop a
    ``quarantine.json`` record inside. Returns the new path, or None when
    the step directory has already vanished."""
    src = os.path.join(root, str(step))
    if not os.path.isdir(src):
        return None
    dst = src + CORRUPT_SUFFIX
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = f"{src}{CORRUPT_SUFFIX}.{n}"
    os.replace(src, dst)
    record = {
        "step": int(step),
        "reason": reason,
        "errors": list(errors or []),
        "t": time.time(),
        "pid": os.getpid(),
    }
    try:
        with open(os.path.join(dst, "quarantine.json"), "w") as fh:
            json.dump(record, fh, indent=1)
    except OSError:  # quarantine must not fail because the record could not
        pass         # be written — the rename already did the real work
    log.warning("quarantined checkpoint step %d -> %s (%s)", step, dst, reason)
    return dst


def step_dirs(root: str) -> dict[int, str]:
    """step -> absolute path for every non-quarantined step directory."""
    out: dict[int, str] = {}
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        if name.isdigit() and os.path.isdir(os.path.join(root, name)):
            out[int(name)] = os.path.join(root, name)
    return out


def committed_steps(root: str) -> list[int]:
    """Steps whose directory carries a committed manifest, ascending."""
    return sorted(
        step for step, path in step_dirs(root).items()
        if read_manifest(path) is not None
    )


def latest_committed_step(root: str) -> int | None:
    """Newest committed step — the supervisor's checkpoint-progress probe
    (no JAX/Orbax import; safe to call from the relaunch loop)."""
    steps = committed_steps(root)
    return steps[-1] if steps else None
