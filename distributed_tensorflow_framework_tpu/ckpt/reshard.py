"""Cross-topology checkpoint restore — make checkpoints mesh-independent.

PR 5 proved checkpoints move freely across pipeline SCHEDULES; this module
makes them move across mesh TOPOLOGIES (ROADMAP item 4, TF-Replicator's
researcher-facing elasticity): a state saved under ``{data:8}`` restores
onto ``{data:4}`` or ``{fsdp:2, pipe:4}`` — the "survive losing a slice"
half of the resilience ladder (docs/RESILIENCE.md).

How a reshard actually happens: orbax's ``StandardRestore`` already loads
into whatever shardings the restore *template* carries, and the trainer
builds its template with ``StepBuilder.init_state`` — partition specs
re-derived by ``parallel/sharding.infer_param_specs`` against the CURRENT
mesh. So the mechanical scatter/gather is host-side respecification the
storage layer performs for free; what was missing, and what this module
owns, is the *contract* around it:

  * ``state_topology`` — the mesh descriptor (ordered axis sizes, device
    and process counts, a sha256 digest of every leaf's partition spec)
    the CheckpointManager records in the manifest commit record at save;
  * ``check_restore_topology`` — the restore-time gate: same axes →
    normal restore; different axes with ``checkpoint.allow_reshard`` off
    → a typed :class:`MeshTopologyError` naming saved vs requested mesh
    and the knob (instead of an opaque orbax sharding failure); with the
    knob on → a reshard plan the manager executes and telemeters
    (``ckpt_resharded``). Legacy manifests without a topology record
    restore with a one-line warning — pre-elastic stores must not brick;
  * ``validate_restored`` — leaf-by-leaf GLOBAL-shape validation after a
    cross-mesh load: resharding redistributes bytes, it must never
    reshape them.

Nothing here touches the PR-2 integrity contract (verify/quarantine/
fallback run before any topology check sees the step) or the PR-3 async
save path (the topology record is computed from the live sharded state
BEFORE the device→host snapshot, then rides the ordinary manifest commit).

The DATA plane has a parallel gate: data/shard.py writes a data-state
record (``DATA_RECORD_KEY``) into the same manifest commit record, and
its ``check_restore_data`` plays for the sample stream the role
``check_restore_topology`` plays for the parameter state — same-count →
resume, refit → repartition plan or a typed refusal.
"""

from __future__ import annotations

import hashlib
import logging
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from distributed_tensorflow_framework_tpu.core.mesh import MESH_AXES
from distributed_tensorflow_framework_tpu.parallel.sharding import (
    infer_param_specs,
)

log = logging.getLogger(__name__)

# Manifest commit-record field carrying the saver's topology (manifest.py
# ``extra``): absent in legacy manifests, which restore with a warning.
MESH_RECORD_KEY = "mesh"


class MeshTopologyError(ValueError):
    """Restore refused: the checkpoint was saved under a different mesh.

    Raised instead of letting orbax fail deep inside ``StandardRestore``
    with a sharding/layout error that names neither mesh. Carries both
    descriptors and names the knob (``checkpoint.allow_reshard``) that
    turns the refusal into a reshard. ``hint`` lets a caller that holds
    a more specific knob append its own one-liner — the serving export
    path names ``serve.allow_reshard`` (serve/export.py), since telling
    an inference operator to flip a checkpoint.* training knob sends
    them to the wrong config block.
    """

    def __init__(self, saved_axes: dict, requested_axes: dict, *,
                 directory: str, step: int, hint: str | None = None):
        self.saved_axes = dict(saved_axes)
        self.requested_axes = dict(requested_axes)
        self.directory = directory
        self.step = step
        self.hint = hint
        super().__init__(
            f"Checkpoint at step {step} in {directory} was saved under "
            f"mesh {describe_axes(saved_axes)} but the run is configured "
            f"for mesh {describe_axes(requested_axes)}. Set "
            f"checkpoint.allow_reshard=true to reshard the state onto the "
            f"new mesh (partition specs are re-derived against it), or "
            f"restore on matching hardware. docs/RESILIENCE.md 'losing a "
            f"slice' covers the elastic-supervisor path that does this "
            f"automatically." + (f" {hint}" if hint else "")
        )


def describe_axes(axes: dict) -> str:
    """Compact human form: {'data': 8, 'fsdp': 1, ...} -> ``{data:8}``."""
    parts = [f"{a}:{int(axes[a])}" for a in MESH_AXES
             if a in axes and int(axes[a]) != 1]
    parts += [f"{a}:{int(v)}" for a, v in axes.items()
              if a not in MESH_AXES and int(v) != 1]
    return "{" + ", ".join(parts) + "}" if parts else "{1 device}"


def normalize_axes(axes: dict) -> dict[str, int]:
    """Canonical ordered axis dict, missing axes filled with 1 — so a
    record written before a new axis name existed still compares equal to
    a mesh where that axis has size 1."""
    out = {a: int(axes.get(a, 1)) for a in MESH_AXES}
    for a, v in axes.items():
        if a not in MESH_AXES:
            out[a] = int(v)
    return out


def axes_equal(a: dict | None, b: dict | None) -> bool:
    if a is None or b is None:
        return False
    return normalize_axes(a) == normalize_axes(b)


def state_mesh(state: Any) -> Mesh | None:
    """The mesh the state's arrays live on (first NamedSharding leaf)."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return sharding.mesh
    return None


def spec_digest(state: Any) -> str:
    """sha256 over every leaf's (tree path, partition spec) — a compact
    fingerprint of the full sharding layout. Same axes + same digest means
    the restore is layout-identical; same axes + different digest (e.g.
    ``train.shard_opt_state`` toggled) still restores — orbax respecifies
    within a mesh — so the digest is recorded for forensics, not gated on.
    """
    h = hashlib.sha256()
    leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    for path, leaf in leaves:
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        h.update(f"{jax.tree_util.keystr(path)}={spec}\n".encode())
    return h.hexdigest()


def state_topology(state: Any, *, mesh: Mesh | None = None,
                   process_count: int | None = None) -> dict | None:
    """The manifest topology record for a (sharded) state, or None when
    no leaf carries a NamedSharding (nothing meaningful to record)."""
    mesh = mesh if mesh is not None else state_mesh(state)
    if mesh is None:
        return None
    return {
        "axes": {a: int(s) for a, s in mesh.shape.items()},
        "device_count": int(mesh.devices.size),
        "process_count": int(
            jax.process_count() if process_count is None else process_count),
        "spec_digest": spec_digest(state),
    }


def plan_reshard(saved: dict, template: Any, *, step: int) -> dict:
    """The reshard plan/record for telemetry: saved vs target axes, leaf
    count, target spec digest, and how many param leaves the target
    template agrees with a fresh ``infer_param_specs`` derivation on (an
    informational cross-check that the template really is the canonical
    sharding for the new mesh — spmd-mode templates that intentionally
    deviate, e.g. shard_map's all-replicated specs, just score low)."""
    mesh = state_mesh(template)
    target = state_topology(template, mesh=mesh) or {}
    match = total = 0
    if mesh is not None:
        derived = infer_param_specs(template.params, mesh)
        spec_leaves = jax.tree.leaves(
            derived, is_leaf=lambda x: isinstance(x, PartitionSpec))
        for spec, leaf in zip(spec_leaves, jax.tree.leaves(template.params)):
            total += 1
            sharding = getattr(leaf, "sharding", None)
            if isinstance(sharding, NamedSharding) and sharding.spec == spec:
                match += 1
    return {
        "step": int(step),
        "from_axes": dict(saved.get("axes") or {}),
        "to_axes": dict(target.get("axes") or {}),
        "from_spec_digest": saved.get("spec_digest"),
        "to_spec_digest": target.get("spec_digest"),
        "leaf_count": len(jax.tree.leaves(template)),
        "respec_agreement": f"{match}/{total}",
    }


def check_restore_topology(saved: dict | None, template: Any, *,
                           allow_reshard: bool, directory: str,
                           step: int) -> dict | None:
    """The restore-time topology gate.

    Returns None for a same-mesh (or legacy, unrecorded) restore, a
    reshard plan dict when the meshes differ and ``allow_reshard`` is on,
    and raises :class:`MeshTopologyError` when they differ with the knob
    off.
    """
    if not saved or not saved.get("axes"):
        log.warning(
            "checkpoint step %d in %s has no mesh topology record (saved "
            "before the elastic layer) — restoring without a topology "
            "check", step, directory,
        )
        return None
    target = state_topology(template)
    if target is None or axes_equal(saved["axes"], target["axes"]):
        if target is not None and \
                saved.get("spec_digest") not in (None, target["spec_digest"]):
            log.info(
                "checkpoint step %d: same mesh, different partition-spec "
                "digest (sharding knobs changed) — orbax respecifies "
                "within the mesh", step,
            )
        return None
    if not allow_reshard:
        raise MeshTopologyError(
            saved["axes"], target["axes"], directory=directory, step=step)
    plan = plan_reshard(saved, template, step=step)
    log.warning(
        "resharding checkpoint step %d: %s -> %s (%d leaves, "
        "respec agreement %s)", step,
        describe_axes(plan["from_axes"]), describe_axes(plan["to_axes"]),
        plan["leaf_count"], plan["respec_agreement"],
    )
    return plan


def fold_residual(tree: Any, n_new: int) -> Any:
    """Fold a stored ``(n_old, *shape)`` error-feedback residual
    (train/state.TrainState.collective_residual) onto ``n_new`` replica
    rows, preserving each leaf's column sum Σ_i r_i — the quantity error
    feedback owes the optimizer (parallel/collectives.py): the mean
    gradient trajectory is unchanged by HOW the total residual is
    distributed over replicas, only by losing part of it.

    Even shrinks (``n_old % n_new == 0``) sum ``k = n_old/n_new``
    consecutive rows per new row; any other topology change collapses the
    total into row 0 and restarts the remaining replicas from a zero
    residual.
    """

    def fold(leaf):
        n_old = leaf.shape[0]
        if n_old == n_new:
            return leaf
        if n_old % n_new == 0:
            k = n_old // n_new
            return leaf.reshape((n_new, k) + leaf.shape[1:]).sum(axis=1)
        total = jnp.sum(leaf, axis=0, keepdims=True)
        pad = jnp.zeros((n_new - 1,) + leaf.shape[1:], leaf.dtype)
        return jnp.concatenate([total, pad], axis=0)

    return jax.tree.map(fold, tree)


def refold_zero_opt_state(stored: Any, params: Any, n_new: int) -> Any:
    """Re-chunk ZeRO-stacked optimizer slots for a new replica count.

    The shard_map ZeRO path (parallel/zero.py) stores each slot as
    ``(n_old, ceil(size/n_old))`` — flattened param values zero-padded to
    the row grid. A cross-mesh restore must re-grid to
    ``(n_new, ceil(size/n_new))``: flatten, TRUNCATE to the true element
    count (dropping the old grid's padding), re-pad for the new grid.
    The padding is provably inert — padded grad AND param positions are
    exactly zero, so every optax rule we allow under ZeRO produces a
    zero update there (rmsprop's ``initial_scale=1.0`` slot refolds to 0
    in pad cells, which only affects those same zero-update cells).

    ``params`` pairs slots to their true sizes via
    :func:`parallel.zero.map_slots`; non-mirroring leaves (optax step
    counters) pass through untouched.
    """
    from distributed_tensorflow_framework_tpu.parallel import zero

    def refold(slot, param):
        if param is None or getattr(slot, "ndim", 0) != 2:
            return slot
        size = int(math.prod(param.shape)) if param.shape else 1
        chunk = -(-size // n_new)
        if tuple(slot.shape) == (n_new, chunk):
            return slot
        flat = jnp.asarray(slot).reshape(-1)[:size]
        pad = n_new * chunk - size
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(n_new, chunk)

    return zero.map_slots(refold, stored, params)


def validate_restored(template: Any, restored: Any, *, step: int) -> int:
    """Leaf-by-leaf global-shape validation after a cross-mesh restore.

    Resharding moves bytes between devices; the GLOBAL array a leaf
    represents must be identical. Any shape/dtype drift here means the
    checkpoint does not actually hold this model's state — fail with the
    offending paths named instead of letting a reshaped leaf poison the
    run. Returns the validated leaf count.
    """
    t_leaves, t_def = jax.tree_util.tree_flatten_with_path(template)
    r_leaves, r_def = jax.tree_util.tree_flatten_with_path(restored)
    if t_def != r_def:
        raise ValueError(
            f"resharded restore at step {step} returned a different tree "
            f"structure than the template: {t_def} vs {r_def}"
        )
    errors = []
    for (path, t), (_, r) in zip(t_leaves, r_leaves):
        t_shape = getattr(t, "shape", None)
        r_shape = getattr(r, "shape", None)
        if t_shape != r_shape:
            errors.append(
                f"{jax.tree_util.keystr(path)}: template {t_shape} vs "
                f"restored {r_shape}"
            )
        elif getattr(t, "dtype", None) != getattr(r, "dtype", None):
            errors.append(
                f"{jax.tree_util.keystr(path)}: template dtype "
                f"{getattr(t, 'dtype', None)} vs restored "
                f"{getattr(r, 'dtype', None)}"
            )
    if errors:
        raise ValueError(
            f"resharded restore at step {step} changed global leaf "
            f"shapes ({len(errors)} of {len(t_leaves)}): "
            + "; ".join(errors[:5])
        )
    return len(t_leaves)
