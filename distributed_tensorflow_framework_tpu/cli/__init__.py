"""Command-line entrypoints."""
