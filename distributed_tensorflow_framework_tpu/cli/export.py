"""``python -m distributed_tensorflow_framework_tpu.cli.export`` — freeze
a trained checkpoint into a serving artifact.

    python -m distributed_tensorflow_framework_tpu.cli.export \
        --config configs/lenet_mnist.yaml --output /runs/lenet_artifact \
        [--step 900] [--set serve.allow_reshard=true]

The config names the training run (``checkpoint.directory``) and the
serving mesh (``serve.data``); a checkpoint saved under a different mesh
needs ``serve.allow_reshard`` (the error says so). docs/SERVING.md
covers the artifact layout.
"""

from __future__ import annotations

import argparse
import logging
import sys

from distributed_tensorflow_framework_tpu.cli.train import (
    _honor_platform_env,
)
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.metrics import setup_logging


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", type=str, default=None, help="YAML config path")
    p.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="key.path=value", help="config override (repeatable)")
    p.add_argument("--output", type=str, required=True,
                   help="artifact directory to create (must not exist "
                        "non-empty — artifacts are immutable)")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step to export (default: latest "
                        "committed)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    setup_logging()
    _honor_platform_env()
    args = parse_args(argv)
    config = load_config(args.config, overrides=list(args.overrides))
    from distributed_tensorflow_framework_tpu.serve.export import (
        export_checkpoint,
    )

    path = export_checkpoint(config, args.output, step=args.step)
    logging.getLogger(__name__).info("artifact ready: %s", path)
    print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
