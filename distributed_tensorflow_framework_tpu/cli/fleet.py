"""``python -m distributed_tensorflow_framework_tpu.cli.fleet`` — stand up
a health-aware serving fleet: N replica engines behind one router.

    python -m distributed_tensorflow_framework_tpu.cli.fleet \
        --artifact /runs/lenet_artifact \
        [--set serve.fleet_replicas=3 --set serve.port=8000]

Each replica is a ``cli/serve.py`` subprocess on an ephemeral port with
its own log dir (``<log_dir>/r{i}/``); the router (serve/fleet.py)
load-balances ``POST /predict`` across them with hedged retries, ejects
and readmits them on health, restarts dead ones through the supervision
machinery, walks ``POST /reload`` across the fleet one drained replica
at a time, and — with ``serve.fleet_autoscale=true`` — grows/shrinks
the replica set from live pressure (serve/autoscale.py), enforcing the
``X-DTF-Tenant`` QoS contract at the front door. The router's resolved endpoint lands in
``<log_dir>/endpoint.json`` — same contract as the single server, so
scripts/load_gen.py points at a fleet unchanged.

SIGTERM drains the router first (stop admission) and then SIGTERMs every
replica, whose own graceful drain finishes queued work — the whole tree
exits 0 on a clean preemption.

The router process itself never imports jax: replica subprocesses own
the accelerators, the parent is pure stdlib plumbing.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import subprocess
import sys

from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.metrics import setup_logging

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--artifact", type=str, default=None,
                   help="artifact directory from cli/export.py (overrides "
                        "serve.artifact_dir)")
    p.add_argument("--config", type=str, default=None,
                   help="optional YAML config (serve.* block)")
    p.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="key.path=value", help="config override (repeatable)")
    p.add_argument("--replicas", type=int, default=None,
                   help="fleet size (overrides serve.fleet_replicas)")
    return p.parse_args(argv)


def make_replica_launcher(artifact_dir: str, log_dir: str,
                          overrides: list[str]):
    """Build the launcher serve/fleet.py uses for first launch AND every
    supervised restart: spawn ``cli.serve`` on an ephemeral port without
    blocking on readiness (the router's prober admits the replica once
    its endpoint.json appears and /healthz answers)."""

    def launch(index: int):
        replica_dir = os.path.join(log_dir, f"r{index}")
        os.makedirs(replica_dir, exist_ok=True)
        endpoint_path = os.path.join(replica_dir, "endpoint.json")
        # A stale endpoint.json from the previous incarnation would make
        # the prober probe a dead port forever — remove it first.
        try:
            os.remove(endpoint_path)
        except FileNotFoundError:
            pass
        cmd = [
            sys.executable, "-m",
            "distributed_tensorflow_framework_tpu.cli.serve",
            "--artifact", artifact_dir,
            "--set", "serve.port=0",
            "--set", f"serve.log_dir={replica_dir}",
        ]
        for override in overrides:
            cmd.extend(["--set", override])
        env = dict(os.environ)
        env["DTF_REPLICA_ID"] = f"r{index}"
        # Chaos faults target the ROUTER's fleet_chaos/fleet_reload
        # points, not the replicas' own in-process points — a replica
        # inheriting DTF_FAULTS would double-fire the drill.
        env.pop("DTF_FAULTS", None)
        env.pop("DTF_FAULTS_STATE", None)
        log.info("launching replica r%d: %s", index, " ".join(cmd))
        out = open(os.path.join(replica_dir, "stdout.log"), "ab")
        try:
            proc = subprocess.Popen(
                cmd, stdout=out, stderr=subprocess.STDOUT, env=env)
        finally:
            out.close()  # the child holds its own dup of the fd
        return proc, endpoint_path

    return launch


def main(argv=None) -> int:
    setup_logging()
    args = parse_args(argv)
    config = load_config(args.config, overrides=list(args.overrides))
    srv = config.serve
    artifact_dir = args.artifact or srv.artifact_dir
    if not artifact_dir:
        log.error("no artifact: pass --artifact or set serve.artifact_dir")
        return 2
    replicas = args.replicas if args.replicas is not None \
        else srv.fleet_replicas

    from distributed_tensorflow_framework_tpu.core import telemetry, tracing
    from distributed_tensorflow_framework_tpu.serve.fleet import FleetRouter

    log_dir = srv.log_dir or os.path.join(artifact_dir, "fleet_logs")
    os.makedirs(log_dir, exist_ok=True)
    writer = telemetry.TelemetryWriter(
        os.path.join(log_dir, "events.jsonl"))
    writer.emit_run_meta(
        argv=list(argv if argv is not None else sys.argv),
        config=config.name, role="fleet", artifact=artifact_dir,
        replicas=replicas)

    # Replica serve.* knobs ride through verbatim; router-only knobs
    # (host/port/log_dir, the fleet_* control loop, tenant_* QoS — all
    # enforced at the front door, never inside a replica) are overridden
    # per replica by the launcher or simply withheld.
    passthrough = [o for o in args.overrides
                   if not o.startswith(("serve.port=", "serve.host=",
                                        "serve.log_dir=",
                                        "serve.fleet_",
                                        "serve.tenant_"))]
    launcher = make_replica_launcher(
        os.path.abspath(artifact_dir), log_dir, passthrough)
    # Router-side flight recorder: ring of recent route/attempt/eject
    # telemetry, dumped when the prober observes a replica die (and on
    # SIGUSR1) so the fault's causal neighborhood survives the crash.
    recorder = tracing.FlightRecorder(
        config.trace.ring_size,
        dump_dir=config.trace.dump_dir or log_dir).attach(writer)
    recorder.install_sigusr1()
    router = FleetRouter(srv, telemetry_writer=writer, launcher=launcher,
                         trace_enabled=config.trace.enabled,
                         flight_recorder=recorder)
    router.spawn_replicas(replicas)
    router.start()
    if not router.wait_ready(min_replicas=1, timeout=180.0):
        log.error("no replica became healthy within 180s — aborting")
        router.shutdown("startup failed")
        writer.close()
        return 3
    endpoint = {
        "url": f"http://{router.host}:{router.port}",
        "host": router.host, "port": router.port, "pid": os.getpid(),
        "artifact": os.path.abspath(artifact_dir),
        "events": os.path.join(log_dir, "events.jsonl"),
        "replicas": replicas, "role": "fleet",
    }
    with open(os.path.join(log_dir, "endpoint.json"), "w") as fh:
        json.dump(endpoint, fh, indent=2)
        fh.write("\n")
    router.install_sigterm_drain()
    try:
        router.serve_forever()
    finally:
        writer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
