"""``python -m distributed_tensorflow_framework_tpu.cli.serve`` — stand up
the batched-inference server on an exported artifact.

    python -m distributed_tensorflow_framework_tpu.cli.serve \
        --artifact /runs/lenet_artifact \
        [--set serve.port=8000 --set serve.max_batch_size=16 \
         --set serve.seq_buckets=[32,64,128]]

Everything about the standing engine is a ``serve.*`` knob (the model
itself comes from the artifact, so ``--config`` is optional and only
consulted for the serve block). The process serves until SIGTERM, then
drains in-flight requests and exits 0 — the same graceful-preemption
contract the trainer honors. The resolved endpoint (ephemeral ports
included) is written to ``<log_dir>/endpoint.json`` for tooling like
scripts/load_gen.py.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from distributed_tensorflow_framework_tpu.cli.train import (
    _honor_platform_env,
)
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.metrics import setup_logging

log = logging.getLogger(__name__)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--artifact", type=str, default=None,
                   help="artifact directory from cli/export.py (overrides "
                        "serve.artifact_dir)")
    p.add_argument("--config", type=str, default=None,
                   help="optional YAML config (serve.* block)")
    p.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="key.path=value", help="config override (repeatable)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    setup_logging()
    _honor_platform_env()
    args = parse_args(argv)
    config = load_config(args.config, overrides=list(args.overrides))
    srv = config.serve
    artifact_dir = args.artifact or srv.artifact_dir
    if not artifact_dir:
        log.error("no artifact: pass --artifact or set serve.artifact_dir")
        return 2

    from distributed_tensorflow_framework_tpu.core import telemetry, tracing
    from distributed_tensorflow_framework_tpu.serve.engine import (
        InferenceEngine,
    )
    from distributed_tensorflow_framework_tpu.serve.export import (
        load_artifact,
    )
    from distributed_tensorflow_framework_tpu.serve.server import (
        ServingServer,
    )

    artifact = load_artifact(artifact_dir)
    log_dir = srv.log_dir or os.path.join(artifact_dir, "serve_logs")
    os.makedirs(log_dir, exist_ok=True)
    writer = telemetry.TelemetryWriter(
        os.path.join(log_dir, "events.jsonl"))
    writer.emit_run_meta(
        argv=list(argv if argv is not None else sys.argv),
        config=config.name, role="serve", artifact=artifact_dir,
        model=artifact.model_config.name, step=artifact.step)
    engine = InferenceEngine(artifact, srv, telemetry_writer=writer,
                             trace_enabled=config.trace.enabled)
    decode_engine = None
    if config.decode.enabled:
        from distributed_tensorflow_framework_tpu.models import (
            decode_support_reason,
        )
        from distributed_tensorflow_framework_tpu.serve.decode import (
            DecodeEngine,
        )

        reason = (None if artifact.task == "mlm"
                  else f"artifact task {artifact.task!r} has no vocabulary")
        reason = reason or decode_support_reason(artifact.model_config)
        if reason is not None:
            # decode.enabled on an unsupported artifact is a config error,
            # not a silent downgrade: fail before binding the port.
            log.error("decode.enabled but artifact cannot decode: %s",
                      reason)
            return 2
        decode_engine = DecodeEngine(
            artifact, config.decode, srv,
            mesh=engine.mesh, telemetry_writer=writer)
    # Flight recorder on the replica: ring of recent telemetry (spans
    # included), dumped on SIGUSR1 or by the fleet router observing this
    # process die (docs/OBSERVABILITY.md "Tracing and flight recorder").
    recorder = tracing.FlightRecorder(
        config.trace.ring_size,
        dump_dir=config.trace.dump_dir or log_dir,
        tracer=engine.tracer).attach(writer)
    recorder.install_sigusr1()
    server = ServingServer(engine, srv, decode_engine=decode_engine,
                           telemetry_writer=writer)
    # The resolved endpoint record: with serve.port=0 the OS picked the
    # port, so tooling polls this file instead of guessing.
    endpoint = {
        "url": f"http://{server.host}:{server.port}",
        "host": server.host, "port": server.port, "pid": os.getpid(),
        "artifact": os.path.abspath(artifact_dir),
        "events": os.path.join(log_dir, "events.jsonl"),
    }
    with open(os.path.join(log_dir, "endpoint.json"), "w") as fh:
        json.dump(endpoint, fh, indent=2)
        fh.write("\n")
    server.install_sigterm_drain()
    try:
        server.serve_forever()
    finally:
        writer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
