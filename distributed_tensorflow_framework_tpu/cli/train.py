"""``train.py`` — the single entrypoint (SURVEY.md §2 row 1 / §3.1).

The reference's train.py parses role flags (--job_name, --task_index,
--ps_hosts, --worker_hosts) and dispatches PS vs worker; here every process
runs the same program:

    python train.py --config configs/lenet_mnist.yaml \
        [--set train.total_steps=100 --set mesh.data=8] [--eval-only]

Multi-host jobs launch the identical command on every host (topology is
discovered, not configured).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys

from distributed_tensorflow_framework_tpu.core import supervision
from distributed_tensorflow_framework_tpu.core.config import load_config
from distributed_tensorflow_framework_tpu.core.metrics import setup_logging


def _honor_platform_env() -> None:
    """Restore stock JAX semantics for the JAX_PLATFORMS env var.

    Some images pin the platform via ``jax.config`` in sitecustomize,
    which silently beats the env var — a launcher that sets
    ``JAX_PLATFORMS=cpu`` (e.g. scripts/launch_local_cluster.py spawning
    virtual-CPU workers) would otherwise end up on the pinned backend
    with the wrong device count. Re-assert the env var through
    jax.config BEFORE any backend query; unset/empty leaves the default
    untouched.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    if plat and plat.split(",")[0] == "cpu":
        # Rendezvous-timeout defaults for virtual-device CPU runs — see
        # core/platform.py (tests/conftest.py applies the same policy).
        from distributed_tensorflow_framework_tpu.core.platform import (
            with_cpu_collective_timeouts,
        )

        os.environ["XLA_FLAGS"] = with_cpu_collective_timeouts(
            os.environ.get("XLA_FLAGS", ""))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--config", type=str, default=None, help="YAML config path")
    p.add_argument(
        "--set",
        dest="overrides",
        action="append",
        default=[],
        metavar="key.path=value",
        help="config override (repeatable)",
    )
    p.add_argument("--eval-only", action="store_true",
                   help="restore latest checkpoint and evaluate")
    p.add_argument("--print-config", action="store_true",
                   help="print the resolved config (YAML + --set overrides "
                        "+ defaults) as YAML and exit without touching "
                        "devices — the debugging aid for multi-host runs "
                        "where every host must resolve identically")
    return p.parse_args(argv)


def main(argv=None) -> int:
    setup_logging()
    _honor_platform_env()
    args = parse_args(argv)
    overrides = list(args.overrides)
    # Elastic refit (core/supervision.py): the supervisor passes the
    # fitted mesh / rescaled batch through the environment because the
    # child command line may be opaque to it (e.g. a `python -c` driver
    # with a hardcoded argv). Env overrides append AFTER the CLI's so
    # the refit wins.
    elastic = os.environ.get(supervision.ELASTIC_OVERRIDES_ENV, "")
    if elastic:
        extra = [e.strip() for e in elastic.split(",") if e.strip()]
        logging.getLogger(__name__).warning(
            "applying elastic overrides from %s: %s",
            supervision.ELASTIC_OVERRIDES_ENV, " ".join(extra),
        )
        overrides += extra
    config = load_config(args.config, overrides=overrides)
    if args.print_config:
        import yaml

        print(yaml.safe_dump(config.to_dict(), sort_keys=False))
        return 0
    if config.train.compilation_cache_dir:
        # Before the Trainer touches a backend: cached executables from the
        # previous attempt turn the relaunch recompile into a disk read
        # (the startup telemetry event shows the delta).
        from distributed_tensorflow_framework_tpu.core.platform import (
            enable_compilation_cache,
        )

        if enable_compilation_cache(config.train.compilation_cache_dir):
            logging.getLogger(__name__).info(
                "persistent XLA compilation cache: %s",
                config.train.compilation_cache_dir,
            )
        else:
            logging.getLogger(__name__).warning(
                "this jax build lacks the persistent compilation cache — "
                "continuing uncached"
            )
    from distributed_tensorflow_framework_tpu.core.mesh import MeshSizeError
    from distributed_tensorflow_framework_tpu.train import Trainer

    try:
        trainer = Trainer(config)
        trainer.build()
    except MeshSizeError as e:
        # The configured mesh no longer fits the visible device set —
        # a slice was lost (or regained). Leave a device report for the
        # supervisor and exit the distinct elastic rc: the supervisor
        # refits the mesh axes (supervision.fit_axis_sizes), rescales
        # the batch, and relaunches with checkpoint.allow_reshard on —
        # WITHOUT consuming a restart-budget attempt (rc contract in
        # scripts/train_resilient.py; docs/RESILIENCE.md).
        logging.getLogger(__name__).error(
            "mesh does not fit the visible device set — exiting rc=%d "
            "for an elastic refit: %s", supervision.ELASTIC_RESHARD_RC, e,
        )
        if config.checkpoint.directory:
            supervision.write_device_report(
                config.checkpoint.directory,
                visible_devices=e.available,
                needed=e.needed,
                mesh=e.sizes,
            )
        return supervision.ELASTIC_RESHARD_RC
    if args.eval_only:
        results = trainer.evaluate()
        logging.getLogger(__name__).info("eval results: %s", results)
        return 0
    # Graceful preemption (docs/RESILIENCE.md): SIGTERM lets the loop
    # finish its in-flight step and save a checkpoint, then the process
    # exits GRACEFUL_PREEMPT_RC — the supervisor relaunches immediately
    # without consuming an attempt. A second SIGTERM kills outright.
    # trainer.train() only returns after the checkpoint manager's exit
    # barrier, so with async_save on the rc-83 exit below can never race
    # an in-flight background commit.
    supervision.install_sigterm_handler()
    try:
        final = trainer.train()
    except Exception as e:
        from distributed_tensorflow_framework_tpu.train.anomaly import (
            PersistentAnomalyError)

        if isinstance(e, PersistentAnomalyError):
            # The in-process recovery ladder is exhausted: this is a
            # poisoned data region or deterministic numeric bug, not a
            # transient. The distinct rc lets the supervisor classify it
            # WITHOUT feeding the crash-loop breaker (relaunching into the
            # same region would burn the whole budget for nothing).
            logging.getLogger(__name__).error(
                "persistent anomaly — escalating with rc=%d: %s "
                "(provenance: %s)",
                supervision.ANOMALY_ESCALATION_RC, e, e.provenance,
            )
            return supervision.ANOMALY_ESCALATION_RC
        raise
    if trainer.preempted:
        logging.getLogger(__name__).warning(
            "preempted gracefully at step %d (checkpoint saved: %s) — "
            "exiting rc=%d for immediate relaunch",
            trainer.host_step, bool(trainer.config.checkpoint.directory),
            supervision.GRACEFUL_PREEMPT_RC,
        )
        return supervision.GRACEFUL_PREEMPT_RC
    if trainer.config.train.eval_steps > 0:
        results = trainer.evaluate(step=trainer.host_step)
        logging.getLogger(__name__).info("final eval: %s", results)
    logging.getLogger(__name__).info("final train metrics: %s", final)
    return 0


if __name__ == "__main__":
    sys.exit(main())
