"""Core runtime: configuration, mesh construction, PRNG, metrics."""

from distributed_tensorflow_framework_tpu.core.config import (  # noqa: F401
    CheckpointConfig,
    DataConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainConfig,
    load_config,
)
from distributed_tensorflow_framework_tpu.core.mesh import (  # noqa: F401
    MeshRuntime,
    create_mesh,
    initialize_runtime,
)
from distributed_tensorflow_framework_tpu.core.prng import (  # noqa: F401
    fold_in_step,
    host_rng,
    make_root_key,
)
