"""Gang lifecycle for the multi-process runtime — the pure library half
of the cluster supervisor (``scripts/train_cluster.py``).

``jax.distributed`` gangs fail as a unit: one worker crash or hang wedges
every collective in the job, so recovery decisions are *cluster*-level —
who is stale, who failed to rejoin, what mesh still fits the survivors,
and when it is safe for anyone to exit.  This module holds those
decisions as small, stdlib-only, thread-free functions so the supervisor
script stays a poll loop and tier-1 tests can drive every branch without
spawning a gang:

* ``heartbeat_name`` / ``heartbeat_path`` — the per-worker heartbeat
  file contract shared with ``train/loop.py`` (``heartbeat-p<i>.json``
  when the gang has more than one process, the legacy single-process
  ``heartbeat.json`` otherwise).
* ``worker_env`` — the ``jax.distributed`` discovery env for one worker
  (coordinator address / process id / virtual-device mask), also used by
  ``scripts/launch_local_cluster.py``.
* ``GangBreaker`` — crash-loop breaking keyed on (worker, failure
  signature): one flaky host trips its own breaker instead of burning
  the shared attempt budget, wrapping
  :class:`core.supervision.CrashLoopBreaker` per process id.
* ``decide_rejoin`` — which workers failed to rejoin the gang within
  ``cluster.rejoin_timeout_s`` while their peers did.
* ``decide_refit`` — the gang-level rc-84 path: fit the mesh to the
  surviving process count via :func:`core.supervision.fit_axis_sizes`
  and preserve the effective batch via ``rescale_for_devices``.
* ``exit_barrier`` — coordinator-led exit barrier: no worker returns
  from training until the chief's async-checkpoint commit record for
  the final step is durable in the manifest.

Everything importable without JAX — the supervisor process must stay
light enough to relaunch children in a tight loop.
"""

from __future__ import annotations

import dataclasses
import importlib.util
import os
import time

from distributed_tensorflow_framework_tpu.core import supervision, tracing

# Single-process runs keep the legacy name so scripts/train_resilient.py
# and every existing drill stay untouched.
SINGLE_HEARTBEAT_NAME = "heartbeat.json"


class ClusterSpecError(ValueError):
    """A gang cannot be formed from the requested parameters — e.g. a
    worker index outside the process count or a mesh no surviving
    subset of devices can satisfy."""


class ExitBarrierTimeoutError(RuntimeError):
    """The exit barrier timed out: the manifest never showed a durable
    commit record for the final step within
    ``cluster.exit_barrier_timeout_s``.  Exiting anyway would let this
    host drop its shard of an in-flight async save, so the barrier
    raises instead of returning."""


# ---------------------------------------------------------------------------
# Heartbeat file contract
# ---------------------------------------------------------------------------

def heartbeat_name(process_index: int, process_count: int) -> str:
    """Per-worker heartbeat filename inside the checkpoint directory.

    Every member of a multi-process gang (chief included) writes its own
    ``heartbeat-p<i>.json`` so the supervisor can tell a hung worker from
    a hung gang; single-process runs keep ``heartbeat.json``.
    """
    if process_count <= 1:
        return SINGLE_HEARTBEAT_NAME
    if not 0 <= process_index < process_count:
        raise ClusterSpecError(
            f"process_index {process_index} outside gang of {process_count}")
    return f"heartbeat-p{process_index}.json"


def heartbeat_path(ckpt_dir: str, process_index: int,
                   process_count: int) -> str:
    """Absolute path of one worker's heartbeat file."""
    return os.path.join(ckpt_dir, heartbeat_name(process_index, process_count))


# ---------------------------------------------------------------------------
# Worker environment (the jax.distributed discovery path)
# ---------------------------------------------------------------------------

# The jax.distributed discovery triple, as PUBLIC names: data/shard.py
# derives each host's deterministic shard assignment from the same env
# the gang supervisor writes (worker_env below), so data-shard identity
# and gang identity cannot drift apart.
ENV_COORDINATOR = "JAX_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "JAX_NUM_PROCESSES"
ENV_PROCESS_ID = "JAX_PROCESS_ID"

_DISCOVERY_VARS = (ENV_COORDINATOR, ENV_NUM_PROCESSES, ENV_PROCESS_ID)


def worker_env(
    base_env: dict[str, str],
    *,
    coordinator_port: int,
    num_processes: int,
    process_id: int,
    devices_per_proc: int,
    coordinator_host: str = "127.0.0.1",
    trace_ctx: str | None = None,
) -> dict[str, str]:
    """Environment for one gang worker on the local discovery path.

    Sets the ``jax.distributed`` discovery triple, forces the CPU
    platform (this is the localhost simulation path) and masks
    ``devices_per_proc`` virtual devices per process.  A gang refit down
    to one process strips the discovery vars entirely so the survivor
    initializes as a plain single-process run.

    ``trace_ctx`` is an encoded :class:`core.tracing.SpanContext` (the
    supervisor's attempt span): it rides ``DTF_TRACE_CTX`` so every
    worker's ``worker.run`` span parents on the same attempt and the
    whole gang stitches into one trace tree.  ``None`` leaves whatever
    ``base_env`` carried untouched (the supervisor usually injects the
    var into the shared base env once per attempt).
    """
    if not 0 <= process_id < num_processes:
        raise ClusterSpecError(
            f"process_id {process_id} outside gang of {num_processes}")
    env = dict(base_env)
    if num_processes > 1:
        env[ENV_COORDINATOR] = f"{coordinator_host}:{coordinator_port}"
        env[ENV_NUM_PROCESSES] = str(num_processes)
        env[ENV_PROCESS_ID] = str(process_id)
    else:
        for key in _DISCOVERY_VARS:
            env.pop(key, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = supervision.mask_host_device_count(
        env.get("XLA_FLAGS", ""), devices_per_proc)
    if trace_ctx is not None:
        env[tracing.TRACE_CTX_ENV] = trace_ctx
    return env


# ---------------------------------------------------------------------------
# Gang capability probe
# ---------------------------------------------------------------------------

# Failure signatures of a backend that can FORM a gang (coordinator
# handshake succeeds, device discovery works) but cannot COMPILE a
# computation spanning processes.  jaxlib's stock CPU backend is the
# canonical case: jax.distributed.initialize() succeeds and every worker
# sees the global device count, then the first jit over a global array
# raises INVALID_ARGUMENT.
GANG_UNSUPPORTED_SIGNS = (
    "multiprocess computations aren't implemented",
    "multi-process computations are not supported",
    "collectives are not implemented",
)

# One worker of the probe gang: init distributed from the discovery env
# (same triple worker_env sets) and run the smallest computation that
# actually spans processes — a jit'd sum over a globally-sharded array.
# jax_platforms is forced via jax.config, not the env var, because a
# sitecustomize that sets it through jax.config at interpreter start
# beats the env var (see tests/conftest.py).
_PROBE_WORKER = """\
import os
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
    process_id=int(os.environ["JAX_PROCESS_ID"]),
)
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
devices = np.array(jax.devices())
mesh = Mesh(devices, ("d",))
arr = jax.make_array_from_callback(
    (devices.size,), NamedSharding(mesh, PartitionSpec("d")),
    lambda idx: np.ones((1,), np.float32))
total = jax.jit(lambda a: a.sum(),
                out_shardings=NamedSharding(mesh, PartitionSpec()))(arr)
assert float(total) == devices.size, float(total)
print("GANG_PROBE_OK", flush=True)
"""


def is_gang_unsupported(detail: str) -> bool:
    """Does a probe failure match the known this-backend-cannot-do-gangs
    signatures (vs. an environmental flake worth investigating)?"""
    low = detail.lower()
    return any(sign in low for sign in GANG_UNSUPPORTED_SIGNS)


def probe_gang(
    *,
    procs: int = 2,
    devices_per_proc: int = 1,
    timeout_s: float = 120.0,
) -> tuple[bool, str]:
    """Can this host run a REAL ``procs``-process ``jax.distributed``
    gang with a cross-process computation?  Returns ``(ok, detail)``.

    The gang drills (tests/test_cluster_drill.py) and the two-host-sim
    bench arm (scripts/chip_window_queue.sh §15) gate on this: stub-level
    supervisor behavior is tier-1-tested without JAX, but end-to-end
    drills need a backend whose compiler accepts multi-process programs,
    which stock CPU jaxlib does not (see GANG_UNSUPPORTED_SIGNS).
    """
    import socket
    import subprocess
    import sys

    with socket.socket() as sock:
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
    workers = []
    for i in range(procs):
        env = worker_env(
            dict(os.environ), coordinator_port=port, num_processes=procs,
            process_id=i, devices_per_proc=devices_per_proc)
        # num_processes == 1 strips the discovery triple (the refit
        # path); the probe worker needs it either way.
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env["JAX_NUM_PROCESSES"] = str(procs)
        env["JAX_PROCESS_ID"] = str(i)
        workers.append(subprocess.Popen(
            [sys.executable, "-c", _PROBE_WORKER],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env))
    outs = []
    ok = True
    try:
        for proc in workers:
            try:
                out, _ = proc.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                proc.kill()
                out, _ = proc.communicate()
                out = (out or "") + "\n[probe timeout]"
            outs.append(out or "")
            ok = ok and proc.returncode == 0 and "GANG_PROBE_OK" in out
    finally:
        for proc in workers:
            if proc.poll() is None:
                proc.kill()
    return ok, "\n".join(outs)[-4000:]


# ---------------------------------------------------------------------------
# Crash-loop breaking, keyed per worker
# ---------------------------------------------------------------------------

class GangBreaker:
    """Crash-loop breaker keyed on (worker, failure signature).

    One :class:`supervision.CrashLoopBreaker` per process id: worker 3
    segfaulting at the same step every attempt trips after ``threshold``
    repeats, while unrelated failures on other workers keep their own
    streaks — a single flaky host cannot burn the gang's attempt budget
    by alternating with healthy-worker noise.
    """

    def __init__(self, threshold: int = 2):
        self.threshold = threshold
        self._per_worker: dict[int, supervision.CrashLoopBreaker] = {}

    def record(
        self,
        worker: int,
        *,
        rc: int,
        last_step: int | None,
        ckpt_step: int | None,
        hung: bool = False,
        transient: bool = False,
    ) -> bool:
        """Register one failed attempt attributed to ``worker``; True =
        that worker's failure is a deterministic crash loop — stop."""
        breaker = self._per_worker.setdefault(
            worker, supervision.CrashLoopBreaker(self.threshold))
        return breaker.record(rc=rc, last_step=last_step,
                              ckpt_step=ckpt_step, hung=hung,
                              transient=transient)

    def report(self, worker: int) -> dict:
        """Post-mortem for one worker's breaker, tagged with its id."""
        breaker = self._per_worker.get(worker)
        out = breaker.report() if breaker else {
            "verdict": "no_failures_recorded"}
        out["process_id"] = worker
        return out


# ---------------------------------------------------------------------------
# Rejoin watchdog
# ---------------------------------------------------------------------------

def decide_rejoin(
    ages: dict[int, float | None],
    *,
    elapsed_s: float,
    rejoin_timeout_s: float,
) -> list[int]:
    """Which workers failed to rejoin the gang and should be dropped.

    ``ages`` maps process id → heartbeat age (None = never beat this
    attempt, pid-scoped).  A worker is overdue only when the rejoin
    window has elapsed, it has no heartbeat, and at least one peer
    *does* — if nobody has joined yet the gang is still booting (or the
    coordinator itself is stuck) and dropping members would shrink a
    healthy mesh for no reason.  ``rejoin_timeout_s <= 0`` disables the
    watchdog.
    """
    if rejoin_timeout_s <= 0 or elapsed_s <= rejoin_timeout_s:
        return []
    if not any(age is not None for age in ages.values()):
        return []
    return sorted(w for w, age in ages.items() if age is None)


# ---------------------------------------------------------------------------
# Gang-level elastic refit (the rc-84 ladder, across processes)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class GangRefit:
    """Outcome of refitting the mesh to a smaller surviving gang."""

    process_count: int          # surviving processes to relaunch
    n_devices: int              # total devices across the survivors
    sizes: dict[str, int]       # fitted mesh axis sizes
    global_batch: int           # rescaled global batch
    grad_accum: int             # rescaled grad-accum factor
    batch_preserved: bool       # effective batch held constant?
    overrides: list[str]        # key.path=value overrides for the child


def decide_refit(
    sizes: dict[str, int],
    global_batch: int,
    grad_accum: int,
    *,
    process_count: int,
    devices_per_proc: int,
) -> GangRefit:
    """Fit the mesh to ``process_count`` surviving workers.

    The same ``fit_axis_sizes``/``rescale_for_devices`` path the
    single-process rc-84 ladder uses, applied to the gang's total device
    count: non-data axes shrink to divisors, the data axis absorbs the
    rest, and the per-device batch is held constant by moving the
    difference into grad accumulation so the *effective* batch — and the
    optimizer trajectory — survive the shrink.
    """
    if process_count < 1:
        raise ClusterSpecError("cannot refit a gang to zero processes")
    n_devices = process_count * devices_per_proc
    try:
        fitted = supervision.fit_axis_sizes(sizes, n_devices)
    except ValueError as e:
        raise ClusterSpecError(
            f"no mesh over {n_devices} devices satisfies {sizes}: {e}"
        ) from e
    old_dp = sizes.get("data", 1)
    new_dp = fitted.get("data", 1)
    if old_dp > 0:
        new_batch, new_accum, preserved = supervision.rescale_for_devices(
            global_batch, grad_accum, old_dp, new_dp)
    else:  # data was -1 (infer): per-device batch is unknowable here
        new_batch, new_accum, preserved = global_batch, grad_accum, False
    overrides = [f"mesh.{axis}={size}" for axis, size in fitted.items()]
    overrides.append("checkpoint.allow_reshard=true")
    if preserved:
        overrides.append(f"data.global_batch_size={new_batch}")
        overrides.append(f"train.grad_accum_steps={new_accum}")
    return GangRefit(
        process_count=process_count,
        n_devices=n_devices,
        sizes=fitted,
        global_batch=new_batch,
        grad_accum=new_accum,
        batch_preserved=preserved,
        overrides=overrides,
    )


# ---------------------------------------------------------------------------
# Coordinator-led exit barrier
# ---------------------------------------------------------------------------

_manifest_module = None


def _load_manifest_module():
    """Import ckpt/manifest.py by file path so the barrier (and the
    supervisor that shares this helper) never pulls JAX/Orbax through
    the package ``__init__``."""
    global _manifest_module
    if _manifest_module is None:
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "ckpt", "manifest.py")
        spec = importlib.util.spec_from_file_location("_dtf_manifest", path)
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        _manifest_module = module
    return _manifest_module


def latest_committed_step(ckpt_dir: str) -> int | None:
    """Newest committed checkpoint step, read without importing JAX."""
    return _load_manifest_module().latest_committed_step(ckpt_dir)


def exit_barrier(
    ckpt_dir: str,
    *,
    step: int,
    timeout_s: float,
    poll_s: float = 0.5,
    is_chief: bool = False,
    latest_step_fn=None,
    sleep=time.sleep,
    clock=time.monotonic,
) -> int:
    """Block until the final checkpoint's commit record is durable.

    Async checkpointing lets training finish while shards are still in
    flight; in a gang, a worker that exits early tears down the
    coordinator and can strand every peer's commit.  The barrier closes
    that window: the chief confirms its own manifest commit record for
    ``step`` (written after every host's shard landed), and survivors
    poll the same record — nobody returns until the save is durable for
    everyone.  Returns the committed step observed (which may exceed
    ``step`` after an elastic resume).  Raises
    :class:`ExitBarrierTimeoutError` on timeout rather than silently
    exiting with a half-committed save.

    ``latest_step_fn``/``sleep``/``clock`` are test seams.
    """
    read_step = latest_step_fn or latest_committed_step
    deadline = clock() + max(0.0, timeout_s)
    while True:
        committed = read_step(ckpt_dir)
        if committed is not None and committed >= step:
            return committed
        if clock() >= deadline:
            role = "chief" if is_chief else "worker"
            raise ExitBarrierTimeoutError(
                f"exit barrier timed out after {timeout_s:.1f}s: {role} "
                f"waited for commit record of step {step} in {ckpt_dir} "
                f"but the manifest shows "
                f"{'nothing committed' if committed is None else committed}")
        sleep(poll_s)
