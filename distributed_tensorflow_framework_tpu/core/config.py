"""Typed configuration system.

Replaces the reference's ``tf.app.flags`` global FLAGS (SURVEY.md §2 row 11:
cluster topology, model, dataset paths, hparams all as process-global flags)
with typed dataclasses loaded from YAML plus ``key=value`` CLI overrides.

Unlike the reference there are no cluster-topology flags (``--ps_hosts``,
``--worker_hosts``, ``--job_name``, ``--task_index``): the SPMD runtime
discovers the slice topology from JAX, and the only topology knob the user
holds is the logical mesh shape (`MeshConfig`).
"""

from __future__ import annotations

import dataclasses
import logging
import pathlib
import re
from dataclasses import dataclass, field
from typing import Any

import yaml

log = logging.getLogger(__name__)


def _fields(cls) -> dict[str, dataclasses.Field]:
    return {f.name: f for f in dataclasses.fields(cls)}


def _build(cls, data: dict[str, Any]):
    """Construct a (possibly nested) config dataclass from a plain dict."""
    if data is None:
        data = {}
    kwargs = {}
    fields = _fields(cls)
    unknown = set(data) - set(fields)
    if unknown:
        raise ValueError(
            f"Unknown key(s) {sorted(unknown)} for {cls.__name__}; "
            f"valid keys: {sorted(fields)}"
        )
    types = getattr(cls, "__field_types__", {})
    for name, f in fields.items():
        if name not in data:
            continue
        value = data[name]
        target = _dataclass_in(types.get(name, f.type))
        if target is not None and isinstance(value, dict):
            value = _build(target, value)
        kwargs[name] = value
    return cls(**kwargs)


def _dataclass_in(tp) -> type | None:
    """Return the dataclass inside ``tp`` (handles Optional[...] unions)."""
    import typing

    if dataclasses.is_dataclass(tp):
        return tp
    for arg in typing.get_args(tp):
        if dataclasses.is_dataclass(arg):
            return arg
    return None


def _annotate_types(cls):
    """Resolve concrete field types once (handles string annotations)."""
    import typing

    cls.__field_types__ = typing.get_type_hints(cls)
    return cls


def config_dataclass(cls):
    return _annotate_types(dataclass(cls))


@config_dataclass
class MeshConfig:
    """Logical device mesh. Axis sizes of 1 collapse that axis.

    ``data`` is the data-parallel axis (the reference's worker-replica count,
    SURVEY.md §2 row 3); ``fsdp`` shards params/optimizer state ZeRO-style;
    ``expert`` is expert parallelism (MoE experts sharded, all_to_all
    dispatch — the batch is also sharded over it, so it doubles as extra
    data parallelism for the dense params); ``pipe`` is pipeline parallelism
    (layer stages, microbatched); ``model`` is tensor parallelism; ``seq``
    is sequence/context parallelism for ring attention. -1 for ``data``
    means "all remaining devices".
    """

    data: int = -1
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    model: int = 1
    seq: int = 1

    def axis_sizes(self) -> dict[str, int]:
        return {"data": self.data, "fsdp": self.fsdp, "expert": self.expert,
                "pipe": self.pipe, "model": self.model, "seq": self.seq}


@config_dataclass
class OptimizerConfig:
    name: str = "sgd_momentum"  # sgd_momentum | adam | adamw | lars | rmsprop
    learning_rate: float = 0.1
    warmup_steps: int = 0
    schedule: str = "constant"  # constant | cosine | staircase | linear
    # staircase: multiply lr by `decay_factor` at each boundary (in steps).
    boundaries: list[int] = field(default_factory=list)
    decay_factor: float = 0.1
    momentum: float = 0.9
    nesterov: bool = False
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    # RMSProp second-moment decay (the reference's Inception recipe family
    # is RMSProp decay=0.9, momentum=0.9, eps=1.0 — set eps accordingly
    # when using name=rmsprop for recipe fidelity).
    rms_decay: float = 0.9
    weight_decay: float = 0.0
    grad_clip_norm: float = 0.0  # 0 disables
    # Exponential moving average of params (0 disables). Uses the
    # tf.train.ExponentialMovingAverage warmup schedule
    # min(decay, (1+step)/(10+step)); eval reads the averaged params
    # unless train.eval_use_ema is false.
    ema_decay: float = 0.0
    # ZeRO-1/2 cross-replica weight-update sharding (PAPERS.md "Automatic
    # Cross-Replica Sharding of Weight Update"). Params stay REPLICATED
    # (pure-DP reference semantics); the optimizer state and the weight
    # update itself are sharded 1/n over the data(+fsdp) replicas:
    #   "off"       — replicated optimizer state, monolithic all-reduce.
    #   "jit"       — passive jit-spec sharding of the slot tensors over
    #                 the fsdp axis; XLA inserts the collectives. Requires
    #                 mesh.fsdp > 1 and train.spmd_mode="jit".
    #   "shard_map" — explicit ZeRO path (parallel/zero.py): bucketed
    #                 reduce-scatter of grads in reverse-layer order
    #                 (overlaps backward compute), per-replica optax
    #                 update on 1/n of the flattened weights, updates
    #                 all-gathered (wire format via
    #                 parallel.collective_dtype). Requires
    #                 train.spmd_mode="shard_map".
    zero_sharding: str = "off"  # off | jit | shard_map
    # Bucket size for the shard_map reduce-scatter, in MiB of f32
    # gradient. Smaller buckets → more collectives hidden behind backward
    # (overlap_frac_est = (B-1)/B) but more per-collective latency.
    zero_bucket_mb: float = 4.0
    # DEPRECATED — use zero_sharding="jit". Folded in by load_config with
    # a warning (conflicting settings of both are rejected).
    shard_opt_state: bool = False


@config_dataclass
class ModelConfig:
    name: str = "lenet5"  # lenet5 | resnet50 | inception_v3 | bert
    num_classes: int = 10
    # BatchNorm statistic scope: "global" computes stats over the full
    # (sharded) batch — XLA inserts the cross-replica reduction; "per_replica"
    # matches the reference's per-GPU BN via shard_map (SURVEY.md §7 hard
    # part 2).
    bn_cross_replica: bool = True
    dtype: str = "bfloat16"     # compute dtype; params stay float32
    # ResNet ImageNet-stem only: space-to-depth input transform — replaces
    # the 7×7/s2 conv with an exactly-equivalent 4×4/s1 conv on a
    # (H/2,W/2,12) regrouped input. Avoids the MXU-wasting 3-channel conv
    # and the full-res activation's HBM round-trip (the step is
    # HBM-BW-bound; see PERF_NOTES.md). Changes stem param shape, so
    # checkpoints are not interchangeable with the conv7 stem.
    space_to_depth_stem: bool = False
    # BERT-family knobs.
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_seq_len: int = 512
    dropout_rate: float = 0.1
    # Attention implementation: "xla" (dot-product, XLA-fused) or
    # "pallas" (fused flash-attention kernel, ops/flash_attention.py) or
    # "ring" (sequence-parallel ring attention over the seq mesh axis).
    attention_impl: str = "xla"
    # Fuse the q/k/v projections into one (H, 3H) GEMM (bert models):
    # fewer, fatter MXU calls on a GEMM-fragmentation-bound step;
    # column-block-exact vs the separate projections (parity-tested).
    # Changes the parameter tree (qkv/kernel replaces query|key|value), so
    # checkpoints are not interchangeable across this flag.
    fused_qkv: bool = False
    # Mixture-of-Experts (models/moe.py): 0 = dense FFN everywhere; >0 =
    # every `moe_every`-th encoder layer uses an expert-parallel MoE FFN
    # routed top-`expert_topk` with per-group capacity `capacity_factor`.
    num_experts: int = 0
    moe_every: int = 2
    expert_topk: int = 2
    capacity_factor: float = 1.25
    # "sorted" (argsort+gather dispatch, O(B·E·C) tables — the scalable
    # default) or "dense" (one-hot einsum dispatch, the parity reference).
    moe_dispatch: str = "sorted"
    # Router z-loss (ST-MoE): penalizes mean(logsumexp(router logits)^2),
    # shrinking logit magnitudes so routing stays near-uniform early —
    # the measured round-5 failure mode is a seed-dependent router-
    # collapse basin (docs/DISTRIBUTED.md "Operating note"). RELATIVE
    # weight: the trainer multiplies the whole MoE aux output (balance
    # aux + moe_zloss_weight * zloss) by train.moe_aux_weight, so with
    # the 0.01 default, moe_zloss_weight=0.1 lands on ST-MoE's canonical
    # 1e-3 absolute z weight. 0 disables (default — bit-identical to
    # pre-knob behavior).
    moe_zloss_weight: float = 0.0
    # Pipeline parallelism (parallel/pipeline.py): >1 splits the encoder
    # stack into this many stages over the `pipe` mesh axis (must equal the
    # mesh's pipe size) with microbatched GPipe scheduling.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 0  # 0 → defaults to pipeline_stages
    # Stage schedule (parallel/schedule.py):
    #   "gpipe"       — circular fill-drain, backward from autodiff.
    #                   Bubble (S-1)/(M+S-1); activation residency O(M+S).
    #   "1f1b"        — hand-built one-forward-one-backward backward with
    #                   per-stage recompute: same analytic bubble as
    #                   gpipe, activation residency O(S) — the MEMORY
    #                   schedule (buys more microbatches at a fixed
    #                   activation budget, ~one extra forward of
    #                   recompute in the backward pass).
    #   "interleaved" — v virtual stages per device, round-robin layer
    #                   assignment: bubble (S-1)/(v·M+S-1) — the
    #                   THROUGHPUT schedule. Needs microbatches % stages
    #                   == 0 and num_layers % (stages·v) == 0.
    # Default "gpipe": zero behavior change for existing runs; the param
    # tree is schedule-independent, so checkpoints are interchangeable
    # across schedules.
    pipeline_schedule: str = "gpipe"
    # Virtual stages per device for pipeline_schedule="interleaved".
    # 0 → defaults to num_layers // pipeline_stages (one layer per
    # virtual chunk — the maximal bubble cut). Must be 0/1 for the other
    # schedules.
    pipeline_virtual_stages: int = 0
    # Rematerialize transformer encoder layers in the backward pass
    # (jax.checkpoint via nn.remat): trades ~30% more FLOPs for O(layers)
    # less activation memory — the lever for long-context / big-model
    # fits. Supported for the bert models (numerics parity tested); other
    # model families reject it rather than silently ignore it.
    remat: bool = False
    # What the remat blocks may keep from the forward pass:
    #   "full"       — save nothing; replay the whole block (max memory
    #                  savings, full recompute cost — measured -13% img/s
    #                  on the HBM-bound ResNet-50 step, PERF_NOTES.md).
    #   "conv_saved" — save conv outputs (jax.ad_checkpoint name tag in
    #                  layers.ConvBN), replay only the BN/ReLU/residual
    #                  tail — near-zero recompute flops for roughly half
    #                  the activation bytes. ResNet only.
    remat_policy: str = "full"


@config_dataclass
class DataConfig:
    name: str = "synthetic_images"  # mnist | cifar10 | imagenet | text_mlm | synthetic_*
    data_dir: str = ""
    # Global batch size across all replicas (the reference exposed per-worker
    # batch; global is the SPMD-native unit — per-host share is derived).
    global_batch_size: int = 64
    image_size: int = 28
    channels: int = 1
    # Label range of the records; must not exceed the model head
    # (load_config cross-checks, and every reader path validates per
    # batch). load_config defaults this to 1000 for name="imagenet".
    num_classes: int = 10
    # Dtype images are fed to the device in. "bfloat16" halves infeed HBM
    # traffic — the ResNet-50 train step is HBM-bandwidth-bound on v5e
    # (~95% of peak BW at bs 256/chip; see bench.py), so this is a real
    # throughput lever. Augmentation math stays float32 host-side.
    image_dtype: str = "float32"
    shuffle_buffer: int = 10_000
    prefetch: int = 2
    # Run the host pipeline pull + device transfer on a producer thread so
    # decode/augment work overlaps device steps (data/infeed.py). The
    # batch/snapshot pairing and order are identical to the synchronous
    # prefetcher; disable when debugging host-side pipeline errors (they
    # surface with a cleaner stack synchronously). NOTE: not implicated
    # in the XLA:CPU rendezvous freezes on oversubscribed virtual-device
    # hosts — an 8-device MoE run froze with async_infeed=false too; see
    # core/platform.py for that failure class and the bounded-terminate +
    # checkpoint-restart mitigation.
    async_infeed: bool = True
    seed: int = 0
    # text / MLM
    seq_len: int = 128
    mask_prob: float = 0.15
    vocab_size: int = 30522  # must match ModelConfig.vocab_size
    # Sequence packing (MLM train path): each batch consumes pack_factor
    # raw record batches and lays the documents end-to-end with per-row
    # segment ids (block-diagonal attention, data/packing.pack_documents)
    # — more useful tokens per step when documents are shorter than
    # seq_len. 1 = off. Train-only; eval streams stay unpacked.
    pack_factor: int = 1
    # native C++ record reader (ops/native) when available
    use_native_reader: bool = False
    # How each host slices the shared epoch permutation (data/shard.py).
    # "block": host h takes the h-th contiguous host-batch rows of every
    # global batch — the consumed prefix is host-count-INVARIANT, so a
    # resumed data state survives an N→M elastic refit with no sample
    # replayed or dropped (docs/RESILIENCE.md "Exactly-once data").
    # "stride": the legacy perm[h::P] layout — kept for bit-exact
    # continuation of old runs; NOT repartitionable across a host-count
    # change. Single-process runs are identical under both.
    shard_mode: str = "block"
    # Restore-time data-state gate (data/shard.check_restore_data): when
    # True, a restored iterator state that fails its manifest sha256 or
    # hits a host-count change it cannot repartition raises DataShardError.
    # False downgrades both to warnings and resumes anyway (samples may
    # replay or drop) — the escape hatch for salvaging a run.
    resume_strict: bool = True


@config_dataclass
class CheckpointConfig:
    directory: str = ""
    save_interval_steps: int = 1000
    max_to_keep: int = 3
    # Commit checkpoints (orbax write + manifest hash + fsync) on a
    # background saver thread (ckpt/async_saver.py): the step loop pays
    # only a device→host snapshot of the train state. False = fully
    # synchronous save on the training thread — required for multi-host
    # sharded state (the snapshot path assumes fully-addressable arrays)
    # and useful when debugging save failures (clean stacks). Either way
    # the manifest commit record and crash semantics are identical.
    async_save: bool = True
    restore: bool = True  # auto-restore latest on startup (MonitoredTrainingSession contract)
    # Re-hash every file against the step's integrity manifest before
    # restoring (ckpt/manifest.py); corrupt/torn steps are quarantined with
    # automatic fallback to the newest verified older step. Disabling skips
    # the hashing (huge checkpoints on trusted storage) but still requires
    # the manifest commit record, so torn SAVES are caught either way.
    verify_restore: bool = True
    # Restore a SPECIFIC saved step instead of the latest (-1 = latest) —
    # the Saver's restore-any-checkpoint capability, e.g. to branch an
    # experiment off an earlier snapshot. Fails loudly if the step was
    # never saved (or was GC'd by max_to_keep).
    restore_step: int = -1
    # Allow restoring a checkpoint saved under a DIFFERENT mesh topology:
    # partition specs are re-derived against the current mesh and the
    # state is resharded on load (ckpt/reshard.py, docs/RESILIENCE.md
    # "losing a slice"). Off by default so an accidental mesh.* change
    # fails fast with MeshTopologyError instead of silently rescattering
    # a production run; the elastic supervisor turns it on when it
    # shrinks/grows the mesh (scripts/train_resilient.py, rc 84).
    allow_reshard: bool = False


@config_dataclass
class TrainConfig:
    total_steps: int = 100
    log_interval: int = 10
    # Backpressure on async step dispatch: at most this many steps may be
    # in flight on the device queue; the host then syncs on the OLDEST
    # pending step (a scalar device_get — the axon-safe sync) before
    # dispatching the next. Without a bound the host runs ahead by a full
    # log_interval (observed: 250 queued multi-device programs, 35 s
    # metric drains, and amplified XLA:CPU collective-rendezvous freezes
    # on oversubscribed virtual-device hosts). Default 8: measured safe
    # over 7000 MoE-mesh steps, while depth 64 froze the dp+pp CPU mesh
    # at its first cross-data all-reduce 3/3 times (64 queued pipelined
    # programs starve the 1-thread XLA:CPU pool's rendezvous — round-5
    # RESULTS.md). Deep queues buy nothing on real TPU either (the
    # device runs one program at a time; ~2 in flight already hides
    # host latency). 0 = unbounded.
    dispatch_ahead: int = 8
    eval_interval: int = 0        # 0 disables mid-training eval
    # Batches per MID-TRAINING eval firing, and the fallback length for
    # infinite (synthetic) eval streams. The final eval and --eval-only
    # always walk the FULL validation set when the stream is finite
    # (exact-eval contract); 0 disables the final eval entirely.
    eval_steps: int = 10
    seed: int = 42
    # "jit" = pjit-style automatic partitioning; "shard_map" = explicit
    # per-replica code with hand-placed collectives (the closer analogue of
    # the reference's SyncReplicasOptimizer + NCCL pipeline).
    spmd_mode: str = "jit"
    # DEPRECATED — use parallel.collective_dtype, which covers the fsdp
    # gather/scatter wires too. load_config maps this onto it with a
    # warning and rejects conflicting settings of both.
    grad_allreduce_dtype: str = ""
    # Accumulation for the compressed all-reduce: "float32" (default)
    # reduce-scatters in f32 (exact adds, 6/8 of f32 bytes, one
    # n-independent rounding — the accuracy-safe choice for n≫8 DCN);
    # "wire" reduces in the wire dtype itself (4/8 of f32 bytes, log2(n)
    # narrow adds). See parallel/collectives.allreduce_gradients.
    grad_allreduce_accum: str = "float32"
    nan_guard: bool = True
    label_smoothing: float = 0.0
    eval_use_ema: bool = True  # only meaningful with optimizer.ema_decay>0
    # Weight of the MoE load-balancing aux loss (Switch Transformer uses 0.01).
    moe_aux_weight: float = 0.01
    # Gradient accumulation: split each global batch into this many
    # microbatches, scan fwd/bwd accumulating grads, apply once. The
    # accumulated gradient equals the full-batch gradient exactly. BN
    # caveat: running stats are EMA-updated once per *microbatch* (k
    # updates per optimizer step from microbatch statistics), so the
    # effective BN momentum is momentum**k and BN-model trajectories
    # differ slightly from the accum=1 step — only BN-free models get
    # bitwise full-batch parity (tests/test_grad_accum.py).
    grad_accum_steps: int = 1
    # XPlane trace capture over steps [profile_start, profile_stop);
    # 0/0 disables (SURVEY.md §5 tracing).
    profile_start: int = 0
    profile_stop: int = 0
    # Persistent XLA compilation cache directory ("" = off). Shrinks the
    # relaunch → first-step latency a supervisor pays on every preemption
    # (the KIND_STARTUP telemetry event measures it). Default OFF: on the
    # CPU test backend, reloading cached executables that embed pallas
    # interpret-mode host callbacks SIGABRTs (stale callback pointers —
    # see pytest.ini); safe on real TPU backends and for XLA-attention
    # configs. Applied by cli/train.py via platform.enable_compilation_cache
    # BEFORE the first backend use.
    compilation_cache_dir: str = ""
    # Goodput ledger (core/goodput.py): cumulative KIND_GOODPUT snapshots
    # at most this often (checked at metric-fetch steps; the final rollup
    # always fires). 0 emits at every fetch.
    goodput_interval_s: float = 30.0
    # HBM sampling (core/memstats.py): periodic KIND_MEMORY
    # device.memory_stats() samples, same cadence contract.
    memory_interval_s: float = 60.0
    # Also capture compiled.memory_analysis() of the train step (one
    # extra lowering+compile when profiling isn't already doing one —
    # that cost is why it defaults off; the profile-window path captures
    # it for free).
    memory_analysis: bool = False


@config_dataclass
class ResilienceConfig:
    """In-process recovery ladder (train/anomaly.py, docs/RESILIENCE.md).

    The ladder runs at metric-fetch steps (train.log_interval cadence —
    metrics are already on host there, so detection costs no extra device
    syncs): classify the step, and on an anomaly restore the last good
    in-memory snapshot, skip the offending data, and resume. Only after
    ``max_rollbacks`` consecutive failed recoveries does the process
    escalate to the supervisor with ``ANOMALY_ESCALATION_RC``.
    """

    # Master switch for detection + in-memory rollback. Off, anomalies go
    # straight to the PR 2 path: NaNGuardHook abort → supervisor relaunch.
    rollback: bool = True
    # Device→host state snapshot cadence/retention for the rollback ring.
    # Snapshots are taken at CLEAN metric-fetch steps, so the effective
    # cadence is max(snapshot_interval_steps, train.log_interval).
    snapshot_interval_steps: int = 100
    snapshot_depth: int = 2
    # Consecutive rollbacks (no clean fetch between them) before the
    # ladder declares the anomaly persistent and escalates.
    max_rollbacks: int = 3
    # Loss-spike detector: flag when the loss sits more than this many
    # EWMA standard deviations above its running mean (0 disables). The
    # EWMA needs min_observations clean fetches before it can fire.
    loss_spike_zscore: float = 10.0
    loss_ewma_beta: float = 0.95
    min_observations: int = 5
    # Hard grad-norm ceiling (0 disables): a finite but exploding
    # grad_norm metric is anomalous even before the loss moves.
    grad_norm_max: float = 0.0
    # After a rollback, linearly re-warm the learning rate over this many
    # steps (0 disables). Costs one train-step recompile per rollback —
    # still far cheaper than the relaunch+restore+recompile it replaces.
    lr_rewarmup_steps: int = 0
    # Infeed watchdog (data/infeed.py): deadline on each next(batch) pull
    # in seconds (0 disables). On InfeedStallError the loop retries with
    # exponential backoff up to infeed_retries times before escalating.
    infeed_deadline_s: float = 0.0
    infeed_retries: int = 3
    infeed_backoff_s: float = 0.5


@config_dataclass
class ClusterConfig:
    """Gang supervision knobs for the multi-process runtime
    (core/cluster.py, scripts/train_cluster.py, docs/RESILIENCE.md
    "Gang supervision"). All of these matter only when
    jax.process_count() > 1; single-process runs ignore them.
    """

    # After a gang (re)launch, a worker that produces no heartbeat within
    # this window while at least one peer has → dropped from the gang and
    # the mesh is refit to the survivors (gang-level rc-84, no attempt
    # consumed). 0 disables the rejoin watchdog: the supervisor waits
    # forever (or until the heartbeat-staleness watchdog fires).
    rejoin_timeout_s: float = 0.0
    # Coordinator-led exit barrier: at the end of training every worker
    # blocks until the chief's manifest commit record for the final step
    # is durable, polling every exit_barrier_poll_s, raising
    # ExitBarrierTimeoutError past exit_barrier_timeout_s.
    exit_barrier_timeout_s: float = 120.0
    exit_barrier_poll_s: float = 0.5
    # Per-worker heartbeat cadence (heartbeat-p<i>.json) — the supervisor's
    # staleness watchdog budget must exceed this.
    heartbeat_interval_s: float = 10.0


@config_dataclass
class ParallelConfig:
    """Collective wire-format knobs (parallel/collectives.py,
    docs/PERFORMANCE.md "Quantized collectives")."""

    # Wire dtype for the explicit collectives (shard_map mode only):
    #   ""         — full-precision wires (bit-identical to pre-knob runs);
    #   "bfloat16" — narrow the gradient all-reduce and fsdp gathers to
    #                bf16 (f32 accumulation per train.grad_allreduce_accum);
    #   "int8"     — EQuARX block-scaled int8 (per-block max-abs scales,
    #                f32 accumulation of dequantized partials, ~3.9× fewer
    #                wire bytes than f32) with a per-leaf error-feedback
    #                residual carried in the training state.
    # Subsumes the deprecated train.grad_allreduce_dtype, which mapped the
    # same compression onto the gradient all-reduce only.
    collective_dtype: str = ""
    # Elements per quantization block for collective_dtype="int8". One f32
    # scale rides the wire per block (~1.6% overhead at 256). Smaller
    # blocks track magnitude variation more tightly at more overhead.
    collective_block_size: int = 256
    # Carry the int8 compression error forward in a per-leaf residual
    # (TrainState.collective_residual) and re-inject it into the next
    # step's gradients — compensated, not accumulated. Disable only for
    # A/B measurement of the raw quantization error.
    error_feedback: bool = True


@config_dataclass
class PrecisionConfig:
    """Memory-traffic reduction pack (docs/PERFORMANCE.md "Flipping the
    bound"): three composable levers against the HBM roofline, each
    verifiable on the CPU mesh via the graftcheck trace/HLO audits."""

    # Activation/compute dtype policy threaded through the model zoo:
    #   ""     — defer to model.dtype (bit-identical to pre-knob runs);
    #   "f32"  — force f32 compute everywhere (the A/B control arm);
    #   "bf16" — bf16 compute casts at module boundaries with f32 master
    #            params, f32 logits/loss head preserved (the
    #            jaxpr-f32-upcast pass audits that only the justified
    #            head widens back up).
    activation_dtype: str = ""
    # Forward-matmul operand quantization for the dense/conv paths
    # (models/layers.py): "" = matmuls run at the activation dtype;
    # "int8" = block-scaled int8 operands (the parallel/quantization.py
    # EQuARX codecs, DEFAULT_BLOCK_SIZE elements per f32 scale) with s32
    # MXU accumulation and per-block f32 rescale. Classifier/logits
    # heads stay full-precision. On CPU this is bit-exact emulation of
    # the TPU int8 MXU path; error is bounded per element by the same
    # maxabs/254 contract the collective codecs pin.
    matmul_dtype: str = ""
    # Fuse the optax apply into the backward's bucketed reverse-layer
    # walk (parallel/zero.py fused_update_walk): each param shard is
    # read-modified-written once while hot instead of a separate
    # whole-tree optimizer pass re-reading every parameter. Requires
    # optimizer.zero_sharding="shard_map" (the walk IS the bucketed
    # reduce-scatter / shard-update / update-all-gather path); composes
    # with parallel.collective_dtype (int8 + error feedback) and
    # train.grad_accum_steps. Optimizer slots are stored per bucket
    # (tuple of per-bucket optax states) — same bytes, different
    # grouping; toggling across a resume is rejected like zero_sharding.
    fused_update: bool = False
    # Selective rematerialization policy mapped onto
    # jax.checkpoint_policies for the remat-capable models and the
    # pipeline stages:
    #   "none"          — defer to model.remat/model.remat_policy;
    #   "dots_saveable" — save matmul outputs, replay the cheap
    #                     elementwise tail (recompute ≈ free, roughly
    #                     half the activation bytes);
    #   "save_nothing"  — save only block inputs, replay everything
    #                     (max memory savings, max recompute — the
    #                     long-context fit lever).
    # Needs model.remat=true (pipeline stages excepted) and conflicts
    # with resnet's model.remat_policy="conv_saved" spelling.
    remat_policy: str = "none"


@config_dataclass
class ServeConfig:
    """Standing batched-inference engine (serve/, docs/SERVING.md).

    The serving mesh is DATA-PARALLEL ONLY by design: a serving replica is
    the deployment unit and params are replicated across it (multi-stage
    pipelined serving is the 1F1B slot-table follow-up, ROADMAP item 3).
    """

    # Frozen artifact directory written by cli/export.py (serve/export.py).
    artifact_dir: str = ""
    # HTTP front end (serve/server.py). port=0 binds an ephemeral port
    # (tests / local probing); cli/serve.py writes the resolved endpoint
    # to <log_dir>/endpoint.json either way.
    host: str = "127.0.0.1"
    port: int = 8000
    # Devices in the serving mesh (-1 = all visible). Unlike mesh.data
    # this may be SMALLER than the visible device count — serving takes
    # the first `data` devices, so a training-mesh checkpoint restores
    # onto a 1-device engine on an 8-device host.
    data: int = 1
    # Dynamic batching admission: close a batch at max_batch_size rows,
    # or max_wait_ms after the FIRST queued request arrived — the
    # latency/fill tradeoff dial.
    max_batch_size: int = 8
    max_wait_ms: float = 5.0
    # Padding buckets for variable-length (MLM) requests: ascending seq
    # lengths a batch is padded up to ([] = one bucket at the model's
    # max_seq_len). Together with the power-of-two row buckets this
    # bounds XLA recompiles to len(seq_buckets) x len(row buckets).
    seq_buckets: list[int] = field(default_factory=list)
    # Admission bound on queued requests: beyond this depth submit()
    # fails fast (HTTP 503) instead of growing latency without bound.
    queue_capacity: int = 1024
    # Export-side: freeze the EMA params when the checkpoint carries them
    # (matches the trainer's eval_use_ema eval contract).
    use_ema: bool = True
    # Gate for restoring a TRAINING-mesh checkpoint onto the serving
    # mesh. Off, a topology mismatch raises the typed MeshTopologyError
    # naming this knob — the same deliberate gate as
    # checkpoint.allow_reshard, scoped to the serve path.
    allow_reshard: bool = False
    # Graceful SIGTERM drain budget (mirrors the supervisor's preemption
    # contract, core/supervision.py): stop admitting, finish every
    # in-flight request within this budget, flush telemetry, exit 0.
    drain_timeout_s: float = 30.0
    # Cadence of the KIND_SERVE_QUEUE / KIND_SERVE_LATENCY gauge events.
    report_interval_s: float = 10.0
    # Telemetry logdir ("" = <artifact_dir>/serve_logs).
    log_dir: str = ""

    # ---- Fleet router (serve/fleet.py, cli/fleet.py) ----
    # Replica engines the router fronts (each a cli/serve.py subprocess).
    fleet_replicas: int = 3
    # End-to-end deadline for one proxied /predict, spanning every retry.
    fleet_deadline_s: float = 30.0
    # Per-attempt cap (the hedge window): an attempt that has not
    # answered within this budget is abandoned and the request re-issued
    # on a DIFFERENT replica while deadline budget remains.
    fleet_attempt_timeout_s: float = 10.0
    # Bounded retry count after the first attempt; each retry lands on a
    # different replica (POST /predict is idempotent — POST /reload and
    # anything else is proxied at most once).
    fleet_retries: int = 2
    # Backoff between retry attempts (doubles per attempt).
    fleet_retry_backoff_ms: float = 25.0
    # Consecutive proxy/probe failures before a replica is ejected into
    # the circuit-breaker probing state.
    fleet_eject_failures: int = 3
    # A replica whose last good /healthz is older than this is ejected
    # (stale health = not routable, even if the TCP port still accepts).
    fleet_healthz_stale_s: float = 10.0
    # Background prober cadence: healthz polls of admitted replicas,
    # probe/readmit of ejected ones, restart of dead ones.
    fleet_probe_interval_s: float = 0.5
    # Retry-After seconds returned with a 503 when every admitted
    # replica is saturated (shed, never queue unboundedly).
    fleet_shed_retry_after_s: float = 1.0
    # Per-replica restart budget (supervision backoff applies between
    # attempts; the crash-loop breaker can stop earlier).
    fleet_max_restarts: int = 8

    # ---- Autoscaler (serve/autoscale.py, driven from the prober tick) ----
    # Master switch for the closed control loop. Off (default), the fleet
    # stays at the fixed fleet_replicas count — exactly the PR 14 behavior.
    fleet_autoscale: bool = False
    # Hard bounds on live (non-retired, non-given-up) replicas. The
    # autoscaler never drains below min or spawns above max, no matter
    # what the pressure signal says.
    fleet_min_replicas: int = 1
    fleet_max_replicas: int = 8
    # Hysteresis band on fleet pressure (0..1-ish utilization: queued +
    # in-flight + chaos-injected synthetic load over admitted capacity).
    # Scale up at/above the up threshold, down at/below the down
    # threshold; the gap between them is what keeps the loop from
    # flapping on a noisy signal. A shed since the last decision forces
    # pressure to at least the up threshold (shedding IS saturation).
    fleet_scale_up_threshold: float = 0.75
    fleet_scale_down_threshold: float = 0.25
    # Minimum seconds between scaling actions (either direction), so one
    # spike produces a measured ramp instead of a thundering spawn herd.
    fleet_scale_cooldown_s: float = 30.0

    # ---- Multi-tenant QoS at the router (X-DTF-Tenant header) ----
    # Priority class assumed when a request carries no tenant header.
    # Known classes, best-first: "high", "default", "batch".
    tenant_default_class: str = "default"
    # Queue slots per replica reserved per priority step: a class that is
    # p steps below "high" may only claim a replica whose load is under
    # queue_capacity - p * reserve. Under exact-capacity load this sheds
    # batch strictly before default before high. 0 = classless routing.
    tenant_priority_reserve: int = 1
    # Per-tenant token-bucket quota: sustained requests/second and burst
    # capacity. Breach = HTTP 429 with Retry-After at the router, before
    # a replica slot is ever claimed. rps 0.0 = quotas off (default).
    tenant_quota_rps: float = 0.0
    # Bucket depth; 0 = ceil(tenant_quota_rps), minimum 1.
    tenant_quota_burst: int = 0


@config_dataclass
class DecodeConfig:
    """Autoregressive decode engine (serve/decode.py, docs/SERVING.md
    "Autoregressive decode"): prefill/decode split with a paged KV cache
    and continuous batching over the serving mesh."""

    # Master switch: cli/serve.py stands a DecodeEngine next to the
    # single-shot engine (POST /generate) only when enabled AND the
    # artifact's task supports decode (mlm/bert family).
    enabled: bool = False
    # "continuous" admits/retires streams at EVERY token (freed slots
    # refill from the queue mid-flight); "static" joins only at batch
    # boundaries — the whole batch must finish before the next group is
    # admitted. Static exists as the A/B control arm: mixed-length
    # streams idle its slots, which is exactly what continuous fixes.
    scheduler: str = "continuous"
    # Tokens per KV page. Pages are the cache's allocation unit: a
    # stream holds ceil(tokens / page_size) pages and grows one page at
    # a time as decode crosses each boundary.
    page_size: int = 16
    # Physical pages in the pool (page 0 is a reserved scratch page, so
    # num_pages - 1 are allocatable). Total resident-token capacity per
    # replica = (num_pages - 1) * page_size.
    num_pages: int = 64
    # Concurrent streams in the in-flight decode batch. The row ladder
    # is the power-of-two ladder over dp multiples up to this cap, the
    # same discipline as serve.max_batch_size.
    max_streams: int = 8
    # Ceiling on prompt + generated tokens per stream. 0 = the model's
    # max_seq_len (position-embedding capacity bounds it either way).
    max_len: int = 0
    # Server-side cap on requested new tokens per stream.
    max_new_tokens: int = 64
    # Page-table width buckets (pages per stream a table is padded up
    # to, ascending). [] = power-of-two ladder up to ceil(max_len /
    # page_size). Together with the row ladder this bounds decode-step
    # recompiles to |page_buckets| x |row ladder|.
    page_buckets: list = field(default_factory=list)
    # Prompt-length padding buckets for the prefill forward (ascending).
    # [] = one bucket at max_len. Prefill compiles are bounded to
    # |prompt_buckets| x |page_buckets| (prefill always runs one row).
    prompt_buckets: list = field(default_factory=list)
    # KV page storage dtype: "float32" (exact) or "int8" (EQuARX-style
    # block-scaled pages via parallel/quantization.py — ~4x more
    # resident streams per replica, per-token logits pinned within a
    # quantization bound of the f32 path rather than bitwise).
    kv_dtype: str = "float32"
    # Streaming granularity: a stream's tokens are buffered scheduler-
    # side and delivered every this-many decode steps (the FIRST token
    # and the finish summary always flush immediately, so TTFT is
    # unaffected). 1 = deliver every token as it lands. Raising it
    # trades up to (interval - 1) steps of in-stream latency for far
    # fewer consumer wakeups — on hosts where clients, handlers and the
    # scheduler share cores, per-token wakeups steal enough CPU from
    # the step loop to show up in tokens/s.
    stream_interval: int = 1


@config_dataclass
class TraceConfig:
    """Distributed tracing + flight recorder (core/tracing.py,
    docs/OBSERVABILITY.md "Tracing and flight recorder")."""

    # Master switch for span emission (KIND_SPAN events) and the
    # per-process flight recorder. Off, propagation headers/env are
    # still accepted and forwarded but no spans are recorded.
    enabled: bool = True
    # Flight-recorder ring capacity: the last N telemetry events (spans
    # included) kept in memory per process for the flightrec-<pid>.json
    # dump. Sized so a fault's causal neighborhood survives a few
    # hundred ms of peak serve-path event rate.
    ring_size: int = 512
    # Directory for flight-recorder dumps ("" = the DTF_TRACE_DIR env
    # var, falling back to the process's telemetry log directory).
    dump_dir: str = ""


@config_dataclass
class AutotuneConfig:
    """Goodput-driven autotuner (scripts/autotune.py, tools/autotune,
    docs/PERFORMANCE.md "Autotuning")."""

    # Roofline pruning tolerance: a candidate whose PREDICTED rate is
    # more than this fraction below the incumbent's on the binding
    # resource is skipped without spending a run (the prediction is
    # logged + journaled either way). 0 disables the tolerance (any
    # predicted loss prunes); keep it wide enough to absorb model error.
    prune_margin: float = 0.05
    # Cap on RUN (not pruned/resumed) trials per window; 0 = unbounded.
    max_trials: int = 0
    # Trial journal path (dtf-autotune-journal/1 JSONL). "" =
    # <out_dir>/autotune_journal.jsonl. The journal is the resume
    # contract: settled trials never re-run after a killed window.
    journal_path: str = ""
    # Where best_<workload>.yaml + leaderboard.json land.
    out_dir: str = "configs"
    # BENCH_WAIT minutes forwarded to each supervised child (0 = don't
    # set; the child's own default applies).
    bench_wait_min: float = 0.0
    # Regression tolerance written into the leaderboard entry: bench.py
    # flags a headline run this fraction below the pinned incumbent.
    regression_margin: float = 0.05


@config_dataclass
class ExperimentConfig:
    name: str = "experiment"
    mesh: MeshConfig = field(default_factory=MeshConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    data: DataConfig = field(default_factory=DataConfig)
    eval_data: DataConfig | None = None
    optimizer: OptimizerConfig = field(default_factory=OptimizerConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    checkpoint: CheckpointConfig = field(default_factory=CheckpointConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    precision: PrecisionConfig = field(default_factory=PrecisionConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    decode: DecodeConfig = field(default_factory=DecodeConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    autotune: AutotuneConfig = field(default_factory=AutotuneConfig)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


def _set_by_path(data: dict, dotted: str, value: Any) -> None:
    keys = dotted.split(".")
    node = data
    for k in keys[:-1]:
        node = node.setdefault(k, {})
        if not isinstance(node, dict):
            raise ValueError(f"Override path {dotted!r} collides with non-dict")
    node[keys[-1]] = value


_SCI_NOTATION = re.compile(r"^[+-]?(\d+\.?\d*|\.\d+)[eE][+-]?\d+$")


def _parse_scalar(text: str) -> Any:
    value = yaml.safe_load(text)
    # YAML 1.1 reads "1e-3" (no decimal point) as a *string*; CLI overrides
    # mean numbers when they look like numbers. Coerce ONLY the
    # scientific-notation shapes YAML misses — a bare float() would also
    # swallow intended strings like "nan", "inf" or "1_000".
    if isinstance(value, str) and _SCI_NOTATION.match(value):
        return float(value)
    return value


def load_config(
    path: str | pathlib.Path | None = None,
    overrides: list[str] | None = None,
    base: dict[str, Any] | None = None,
) -> ExperimentConfig:
    """Load an ExperimentConfig from YAML with ``a.b.c=value`` overrides."""
    data: dict[str, Any] = dict(base or {})
    if path is not None:
        with open(path) as fh:
            loaded = yaml.safe_load(fh) or {}
        if not isinstance(loaded, dict):
            raise ValueError(f"Config file {path} must contain a mapping")
        data.update(loaded)
    for item in overrides or []:
        if "=" not in item:
            raise ValueError(f"Override {item!r} must look like key.path=value")
        key, _, raw = item.partition("=")
        _set_by_path(data, key.strip(), _parse_scalar(raw.strip()))
    # ImageNet's label space is 1000 classes; the DataConfig-wide default
    # of 10 predates the label-range guards and would abort real ImageNet
    # data on the first record past label 10. Applied on the raw dict so
    # an explicit num_classes always wins.
    for section in ("data", "eval_data"):
        sec = data.get(section)
        if (isinstance(sec, dict) and sec.get("name") == "imagenet"
                and "num_classes" not in sec):
            sec["num_classes"] = 1000
    cfg = _build(ExperimentConfig, data)
    # Deprecation shim: train.grad_allreduce_dtype predates the quantized
    # collective layer and named only the gradient all-reduce wire; it maps
    # onto parallel.collective_dtype (which also covers the fsdp
    # gather/scatter wires). Conflicting settings of both are rejected
    # rather than silently picking one.
    if cfg.train.grad_allreduce_dtype:
        if (cfg.parallel.collective_dtype
                and cfg.parallel.collective_dtype
                != cfg.train.grad_allreduce_dtype):
            raise ValueError(
                f"train.grad_allreduce_dtype="
                f"{cfg.train.grad_allreduce_dtype!r} conflicts with "
                f"parallel.collective_dtype="
                f"{cfg.parallel.collective_dtype!r}; set only "
                f"parallel.collective_dtype (the old knob is deprecated)"
            )
        if not cfg.parallel.collective_dtype:
            log.warning(
                "train.grad_allreduce_dtype is deprecated — mapping it to "
                "parallel.collective_dtype=%r (docs/MIGRATING.md)",
                cfg.train.grad_allreduce_dtype,
            )
            cfg.parallel.collective_dtype = cfg.train.grad_allreduce_dtype
    # Deprecation shim: optimizer.shard_opt_state predates the explicit
    # ZeRO path and named only the passive jit-spec variant; it maps onto
    # optimizer.zero_sharding="jit". Conflicting settings of both are
    # rejected rather than silently picking one (same contract as the
    # grad_allreduce_dtype shim above).
    if cfg.optimizer.shard_opt_state:
        if cfg.optimizer.zero_sharding not in ("off", "jit"):
            raise ValueError(
                "optimizer.shard_opt_state=true conflicts with "
                f"optimizer.zero_sharding={cfg.optimizer.zero_sharding!r}; "
                "set only optimizer.zero_sharding (the old knob is "
                "deprecated)"
            )
        if cfg.optimizer.zero_sharding == "off":
            log.warning(
                "optimizer.shard_opt_state is deprecated — mapping it to "
                "optimizer.zero_sharding='jit' (docs/MIGRATING.md)",
            )
            cfg.optimizer.zero_sharding = "jit"
    if cfg.optimizer.zero_sharding not in ("off", "jit", "shard_map"):
        raise ValueError(
            "optimizer.zero_sharding must be 'off', 'jit' or 'shard_map', "
            f"got {cfg.optimizer.zero_sharding!r}"
        )
    if cfg.optimizer.zero_bucket_mb <= 0:
        raise ValueError(
            "optimizer.zero_bucket_mb must be > 0, got "
            f"{cfg.optimizer.zero_bucket_mb}"
        )
    if cfg.parallel.collective_dtype not in ("", "bfloat16", "int8"):
        raise ValueError(
            "parallel.collective_dtype must be '', 'bfloat16' or 'int8', "
            f"got {cfg.parallel.collective_dtype!r}"
        )
    if cfg.parallel.collective_block_size < 1:
        raise ValueError(
            "parallel.collective_block_size must be >= 1, got "
            f"{cfg.parallel.collective_block_size}"
        )
    if cfg.precision.activation_dtype not in ("", "f32", "bf16"):
        raise ValueError(
            "precision.activation_dtype must be '', 'f32' or 'bf16', got "
            f"{cfg.precision.activation_dtype!r}"
        )
    if cfg.precision.matmul_dtype not in ("", "int8"):
        raise ValueError(
            "precision.matmul_dtype must be '' or 'int8', got "
            f"{cfg.precision.matmul_dtype!r}"
        )
    if cfg.precision.remat_policy not in ("none", "dots_saveable",
                                          "save_nothing"):
        raise ValueError(
            "precision.remat_policy must be 'none', 'dots_saveable' or "
            f"'save_nothing', got {cfg.precision.remat_policy!r}"
        )
    if (cfg.precision.fused_update
            and cfg.optimizer.zero_sharding != "shard_map"):
        raise ValueError(
            "precision.fused_update=true fuses the optax apply into the "
            "ZeRO bucketed reverse-layer walk and therefore requires "
            "optimizer.zero_sharding='shard_map', got "
            f"{cfg.optimizer.zero_sharding!r}"
        )
    if cfg.model.pipeline_schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            "model.pipeline_schedule must be 'gpipe', '1f1b' or "
            f"'interleaved', got {cfg.model.pipeline_schedule!r}"
        )
    res = cfg.resilience
    if res.snapshot_depth < 1:
        raise ValueError(
            f"resilience.snapshot_depth must be >= 1, got {res.snapshot_depth}"
        )
    if res.max_rollbacks < 1:
        raise ValueError(
            f"resilience.max_rollbacks must be >= 1, got {res.max_rollbacks}"
        )
    if not 0.0 < res.loss_ewma_beta < 1.0:
        raise ValueError(
            "resilience.loss_ewma_beta must be in (0, 1), got "
            f"{res.loss_ewma_beta}"
        )
    clu = cfg.cluster
    if clu.rejoin_timeout_s < 0:
        raise ValueError(
            f"cluster.rejoin_timeout_s must be >= 0, got "
            f"{clu.rejoin_timeout_s}"
        )
    if clu.exit_barrier_timeout_s <= 0:
        raise ValueError(
            "cluster.exit_barrier_timeout_s must be > 0, got "
            f"{clu.exit_barrier_timeout_s}"
        )
    if clu.exit_barrier_poll_s <= 0:
        raise ValueError(
            f"cluster.exit_barrier_poll_s must be > 0, got "
            f"{clu.exit_barrier_poll_s}"
        )
    if clu.heartbeat_interval_s <= 0:
        raise ValueError(
            "cluster.heartbeat_interval_s must be > 0, got "
            f"{clu.heartbeat_interval_s}"
        )
    if cfg.trace.ring_size < 1:
        raise ValueError(
            f"trace.ring_size must be >= 1, got {cfg.trace.ring_size}"
        )
    srv = cfg.serve
    if srv.max_batch_size < 1:
        raise ValueError(
            f"serve.max_batch_size must be >= 1, got {srv.max_batch_size}"
        )
    if srv.max_wait_ms < 0:
        raise ValueError(
            f"serve.max_wait_ms must be >= 0, got {srv.max_wait_ms}"
        )
    if srv.queue_capacity < 1:
        raise ValueError(
            f"serve.queue_capacity must be >= 1, got {srv.queue_capacity}"
        )
    if srv.fleet_min_replicas < 1:
        raise ValueError(
            "serve.fleet_min_replicas must be >= 1, got "
            f"{srv.fleet_min_replicas}"
        )
    if srv.fleet_max_replicas < srv.fleet_min_replicas:
        raise ValueError(
            f"serve.fleet_max_replicas={srv.fleet_max_replicas} must be >= "
            f"serve.fleet_min_replicas={srv.fleet_min_replicas}"
        )
    if not (0.0 < srv.fleet_scale_down_threshold
            < srv.fleet_scale_up_threshold):
        raise ValueError(
            "serve autoscaler hysteresis requires 0 < "
            f"fleet_scale_down_threshold={srv.fleet_scale_down_threshold} < "
            f"fleet_scale_up_threshold={srv.fleet_scale_up_threshold} — a "
            f"degenerate or inverted band makes the control loop flap"
        )
    if srv.fleet_scale_cooldown_s < 0:
        raise ValueError(
            "serve.fleet_scale_cooldown_s must be >= 0, got "
            f"{srv.fleet_scale_cooldown_s}"
        )
    if srv.tenant_priority_reserve < 0:
        raise ValueError(
            "serve.tenant_priority_reserve must be >= 0, got "
            f"{srv.tenant_priority_reserve}"
        )
    if srv.tenant_priority_reserve and (
            2 * srv.tenant_priority_reserve >= srv.queue_capacity):
        raise ValueError(
            f"serve.tenant_priority_reserve={srv.tenant_priority_reserve} "
            f"leaves no claimable capacity for the lowest priority class "
            f"(2*reserve >= queue_capacity={srv.queue_capacity}) — batch "
            f"traffic would shed even on an idle fleet"
        )
    if srv.tenant_quota_rps < 0:
        raise ValueError(
            f"serve.tenant_quota_rps must be >= 0, got "
            f"{srv.tenant_quota_rps}"
        )
    if srv.tenant_quota_burst < 0:
        raise ValueError(
            f"serve.tenant_quota_burst must be >= 0, got "
            f"{srv.tenant_quota_burst}"
        )
    if srv.seq_buckets:
        if (any(int(b) < 1 for b in srv.seq_buckets)
                or list(srv.seq_buckets) != sorted(set(srv.seq_buckets))):
            raise ValueError(
                "serve.seq_buckets must be strictly ascending positive "
                f"sequence lengths, got {srv.seq_buckets} — each request "
                f"is padded up to the smallest bucket that fits it"
            )
        if srv.seq_buckets[-1] > cfg.model.max_seq_len:
            raise ValueError(
                f"serve.seq_buckets max {srv.seq_buckets[-1]} exceeds "
                f"model.max_seq_len={cfg.model.max_seq_len} — the model "
                f"cannot embed positions past its trained length"
            )
    dec = cfg.decode
    if dec.scheduler not in ("continuous", "static"):
        raise ValueError(
            f"decode.scheduler must be 'continuous' or 'static', got "
            f"{dec.scheduler!r}"
        )
    if dec.kv_dtype not in ("float32", "int8"):
        raise ValueError(
            f"decode.kv_dtype must be 'float32' or 'int8', got "
            f"{dec.kv_dtype!r}"
        )
    if dec.page_size < 1:
        raise ValueError(
            f"decode.page_size must be >= 1, got {dec.page_size}"
        )
    if dec.stream_interval < 1:
        raise ValueError(
            f"decode.stream_interval must be >= 1, got "
            f"{dec.stream_interval}"
        )
    if dec.num_pages < 2:
        raise ValueError(
            f"decode.num_pages must be >= 2 (page 0 is the reserved "
            f"scratch page), got {dec.num_pages}"
        )
    if dec.max_streams < 1:
        raise ValueError(
            f"decode.max_streams must be >= 1, got {dec.max_streams}"
        )
    if dec.max_new_tokens < 1:
        raise ValueError(
            f"decode.max_new_tokens must be >= 1, got {dec.max_new_tokens}"
        )
    if dec.max_len < 0:
        raise ValueError(
            f"decode.max_len must be >= 0 (0 = model.max_seq_len), got "
            f"{dec.max_len}"
        )
    if dec.max_len > cfg.model.max_seq_len:
        raise ValueError(
            f"decode.max_len={dec.max_len} exceeds model.max_seq_len="
            f"{cfg.model.max_seq_len} — the model cannot embed positions "
            f"past its trained length"
        )
    for knob, buckets in (("decode.page_buckets", dec.page_buckets),
                          ("decode.prompt_buckets", dec.prompt_buckets)):
        if buckets and (
                any(int(b) < 1 for b in buckets)
                or list(buckets) != sorted(set(buckets))):
            raise ValueError(
                f"{knob} must be strictly ascending positive values, got "
                f"{buckets}"
            )
    # Head-vs-labels cross-check for the built-in classification datasets:
    # a label outside the head's range turns the loss metric into NaN
    # through the integer-label CE gather (fill semantics) while grads
    # stay finite — the NaN guard kills the run without naming the cause.
    # Only data > model is fatal (a wider head than the label range is
    # wasteful but valid); eval_data feeds the same head.
    for role, dc in (("data", cfg.data), ("eval_data", cfg.eval_data)):
        if dc is None:
            continue
        if dc.shard_mode not in ("block", "stride"):
            raise ValueError(
                f"{role}.shard_mode must be 'block' or 'stride', got "
                f"{dc.shard_mode!r}"
            )
        if (dc.name in ("mnist", "cifar10", "imagenet", "synthetic_images")
                and dc.num_classes > cfg.model.num_classes):
            raise ValueError(
                f"{role}.num_classes={dc.num_classes} > "
                f"model.num_classes={cfg.model.num_classes} for "
                f"classification dataset {dc.name!r} — out-of-range labels "
                f"poison the loss metric with NaN; widen the model head or "
                f"fix {role}.num_classes"
            )
    tune = cfg.autotune
    if not (0.0 <= tune.prune_margin < 1.0):
        raise ValueError(
            f"autotune.prune_margin must be in [0, 1), got "
            f"{tune.prune_margin} — it is the fraction of predicted loss "
            f"the pruner tolerates before skipping a candidate"
        )
    if tune.max_trials < 0:
        raise ValueError(
            f"autotune.max_trials must be >= 0 (0 = unbounded), got "
            f"{tune.max_trials}"
        )
    if tune.bench_wait_min < 0:
        raise ValueError(
            f"autotune.bench_wait_min must be >= 0 (0 = don't set "
            f"BENCH_WAIT), got {tune.bench_wait_min}"
        )
    if not (0.0 <= tune.regression_margin < 1.0):
        raise ValueError(
            f"autotune.regression_margin must be in [0, 1), got "
            f"{tune.regression_margin}"
        )
    return cfg
