"""Deterministic fault injection — make every failure mode reproducible.

The recovery contract (kill → relaunch → resume, SURVEY.md §5) is only as
good as its worst untested path, and real infrastructure faults (preempted
workers, wedged infeed threads, torn checkpoint writes) arrive on nobody's
schedule. This registry turns them into config: a comma-separated spec in
the ``DTF_FAULTS`` env var names fault points threaded through the train
loop, the checkpoint manager and the host data pipeline, so CI can drill
SIGKILL-mid-save or a stalled input pipeline on CPU, on demand
(docs/RESILIENCE.md).

Spec syntax (``DTF_FAULTS=crash_at_step:120,stall_infeed:30s``):

  crash_at_step:N    SIGKILL this process right before step N runs — the
                     hard preemption drill (no cleanup, no atexit).
  crash_in_save:N    SIGKILL between the step-N checkpoint's data write and
                     its manifest commit — leaves an uncommitted directory
                     that restore must skip (ckpt/manifest.py).
  corrupt_ckpt:WHAT  after the next checkpoint commits, truncate its largest
                     payload file — a committed-but-torn checkpoint that
                     restore must detect by hash, quarantine, and fall back
                     from. WHAT is a free-form label (e.g. ``params``)
                     recorded for the logs; with OCDBT storage the
                     corruption unit is a file, not a named array.
  stall_infeed:S     one ``next(dataset)`` call sleeps S seconds (suffix
                     ``s`` optional) — the hung-input drill the heartbeat
                     watchdog must catch. ``0`` means "hang forever"
                     (6 hours, far past any staleness budget). An optional
                     third field (``stall_infeed:3s:4``) stalls the Nth
                     pull of the process instead of the first — the train
                     loop's infeed watchdog drill needs the stall INSIDE
                     the step loop, past the build-time sample-batch peek
                     (pull ordinals are 1-based; the peek is pull 1).
  nan_grads:N        step N's batch is poisoned to NaN (the train loop
                     applies it to floating-point inputs), so the loss and
                     gradients go non-finite and the NaN guard's provenance
                     path fires end-to-end.
  loss_spike:N       step N's floating-point inputs are scaled by a large
                     FINITE factor, so the loss/grad-norm jump without
                     going non-finite — the EWMA z-score detector's drill
                     (train/anomaly.py).
  repeat_nan:N:K     like nan_grads but poisons EVERY step in [N, N+K):
                     after a rollback the replayed region is poisoned
                     again, so max_rollbacks consecutive recoveries fail
                     and the escalation rung (ANOMALY_ESCALATION_RC)
                     fires. Fires up to K times; with DTF_FAULTS_STATE it
                     is disarmed entirely after the first firing records.
  drop_devices:N:S   before the supervisor's Sth relaunch (1-based attempt
                     ordinal; default 1), shrink the child's visible
                     device set to N devices — the "lost a slice" drill.
                     Fired by scripts/train_resilient.py at its
                     ``relaunch`` point, never inside the trainer; the
                     supervisor masks the child's host-device count and
                     the child's mesh construction then fails with a
                     typed MeshSizeError → exit code 84 → elastic refit
                     (core/supervision.py). N may also be LARGER than the
                     current count: growth drills take the same path.
  kill_replica:N:T   SIGKILL serving replica N (0-based) at the fleet
                     prober's Tth chaos tick (1-based; default 1) — the
                     replica-death drill. Fired by serve/fleet.py at its
                     ``fleet_chaos`` point and applied by the router
                     (kill the child, watch the circuit breaker eject it
                     and supervision restart + readmit it). The chaos
                     clock starts once the whole fleet has been admitted,
                     so T is relative to readiness, not replica boot.
  stall_replica:N:S  SIGSTOP serving replica N for S seconds (then
                     SIGCONT) — the wedged-replica drill: the process is
                     alive, the port accepts, nothing answers. ``0``
                     means "stopped forever". The router's hedged
                     per-attempt timeout must route around it and the
                     stale-healthz breaker must eject it.
  spike:F:S          synthetic traffic spike: for S seconds after the
                     first fleet chaos tick, the router's autoscaler
                     sees F extra queued requests per admitted replica
                     on top of real load — the deterministic stand-in
                     for a client-side load ramp (scale-up must engage,
                     bounded by fleet_max_replicas, and real traffic is
                     never touched). Fired by serve/fleet.py at its
                     ``fleet_chaos`` point.
  tenant_stampede:T  low-priority stampede at chaos tick T (optional
                     duration ``tenant_stampede:T:4s``, default 5s):
                     synthetic batch-class load saturates every
                     replica's unreserved queue slots, so batch/default
                     admission sheds (503 + Retry-After) while the
                     tenant_priority_reserve headroom keeps high-class
                     traffic flowing — the QoS-under-saturation drill.
  corrupt_reload     before the next rolling reload begins, truncate the
                     largest payload file of the NEW artifact — every
                     replica's manifest verification must reject the
                     swap (HTTP 409) and keep serving the old weights.
                     Fired by serve/fleet.py at its ``fleet_reload``
                     point; the arg is a free-form label for the logs.
  kill_worker:W:T    SIGKILL gang worker W (0-based process id) at the
                     cluster supervisor's Tth chaos tick (1-based;
                     default 1) — the worker-death drill. Fired by
                     scripts/train_cluster.py at its ``gang_chaos``
                     point; the supervisor kills the child and must
                     then SIGTERM the survivors (chief force-saves) and
                     relaunch the whole gang. The chaos clock starts
                     once every worker has heartbeated, so T is
                     relative to gang readiness, not boot.
  stall_worker:W:S   SIGSTOP gang worker W for S seconds (then SIGCONT)
                     — the wedged-worker drill: the process is alive
                     but its heartbeat goes stale and every peer is
                     blocked in a collective. ``0`` means "stopped
                     forever". The supervisor's per-worker watchdog
                     must catch the stale heartbeat and coordinate a
                     gang restart.
  drop_worker:W:T    SIGKILL gang worker W at chaos tick T (1-based;
                     default 1) and mark it PERMANENTLY lost — the
                     shrunk-pod drill: the supervisor must refit the
                     mesh to the surviving process count (gang-level
                     rc-84) and relaunch smaller without consuming an
                     attempt.
  corrupt_shard:K:P  poison host K's Pth dataset pull (1-based; default
                     1) to NaN — the bad-shard drill: ONE host's infeed
                     yields garbage, the global batch assembled from it
                     goes non-finite, and the NaN guard's provenance
                     path must name the step. Fired at the data
                     pipeline's ``data_chaos`` point; only the process
                     whose shard index is K applies it (the ``worker=``
                     filter below keeps other hosts from consuming the
                     one-shot fault). Like ``stall_infeed``, pull 1 is
                     the build-time sample-batch peek.
  skew_shard:K:S     host K's next dataset pull sleeps S seconds
                     (suffix ``s`` optional; ``0`` = forever) — the
                     straggler-shard drill: one host's infeed falls
                     behind, every peer blocks at the collective, and
                     the infeed watchdog / heartbeat ladder must catch
                     it. Same ``data_chaos`` point and worker filter
                     as corrupt_shard.

Faults fire at most once per process. When ``DTF_FAULTS_STATE`` names a
file, firings are also recorded there (before executing — a crash fault
must not re-fire on relaunch) so a supervised kill → relaunch → resume
drill injects each fault exactly once across the whole run.

Thread model: fault points are not confined to the main thread — with the
async checkpoint pipeline (``checkpoint.async_save``, ckpt/async_saver.py)
the ``ckpt_in_save``/``ckpt_committed`` points fire on the background
saver thread, and ``infeed`` fires on the async-infeed producer thread.
``fire`` is therefore serialized by a process-wide lock (matching,
recording and executing are atomic — two threads can never double-fire
one fault), the diagnostic names the firing thread, and the crash kinds
use ``os.kill(SIGKILL)``, which takes down the whole process regardless
of which thread calls it — exactly the semantics the drills need.

Stdlib-only by design: the module is imported by the data pipeline and the
supervisor, and an inactive plan (the default) costs one set lookup per
fault point.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time
from dataclasses import dataclass, field

log = logging.getLogger(__name__)

ENV_VAR = "DTF_FAULTS"
STATE_ENV_VAR = "DTF_FAULTS_STATE"

# Fault kind -> the fault point it fires at. Points are the hook names the
# framework threads through its layers:
#   step_begin      train/loop.py, before dispatching each step
#   infeed          data/pipeline.py, each HostDataset.__next__
#   ckpt_in_save    ckpt/checkpoint.py, after data write / before manifest
#   ckpt_committed  ckpt/checkpoint.py, after the manifest commit
#   relaunch        scripts/train_resilient.py, before launching attempt N
#                   (`step` carries the 1-based attempt ordinal)
#   fleet_chaos     serve/fleet.py, each prober/supervision tick (`step`
#                   carries the 1-based tick ordinal); the router applies
#                   the returned faults to its replica subprocesses
#   fleet_reload    serve/fleet.py, before a rolling reload begins (the
#                   router corrupts the NEW artifact so every replica's
#                   verification must reject the swap)
#   gang_chaos      scripts/train_cluster.py, each supervisor tick once the
#                   whole gang has heartbeated (`step` carries the 1-based
#                   tick ordinal); the supervisor applies the returned
#                   faults to its worker subprocesses
#   data_chaos      data/pipeline.py, each HostDataset pull (`step` carries
#                   the 1-based pull ordinal, `worker` the host's shard
#                   index) — per-host data faults (corrupt_shard,
#                   skew_shard) applied by the pulling host itself
KIND_POINTS = {
    "crash_at_step": "step_begin",
    "nan_grads": "step_begin",
    "loss_spike": "step_begin",
    "repeat_nan": "step_begin",
    "stall_infeed": "infeed",
    "crash_in_save": "ckpt_in_save",
    "corrupt_ckpt": "ckpt_committed",
    "drop_devices": "relaunch",
    "kill_replica": "fleet_chaos",
    "stall_replica": "fleet_chaos",
    "spike": "fleet_chaos",
    "tenant_stampede": "fleet_chaos",
    "corrupt_reload": "fleet_reload",
    "kill_worker": "gang_chaos",
    "stall_worker": "gang_chaos",
    "drop_worker": "gang_chaos",
    "corrupt_shard": "data_chaos",
    "skew_shard": "data_chaos",
}
_STEP_KINDS = ("crash_at_step", "crash_in_save", "nan_grads", "loss_spike")
_STALL_FOREVER_S = 6 * 3600.0


@dataclass
class Fault:
    kind: str
    arg: str = ""
    step: int | None = None
    seconds: float | None = None
    # drop_devices: the device count the child set is masked to.
    devices: int | None = None
    # kill_replica / stall_replica: the 0-based replica index targeted.
    replica: int | None = None
    # kill_worker / stall_worker / drop_worker: the 0-based gang process
    # id. corrupt_shard / skew_shard: the 0-based host shard index the
    # fault targets (matched against the `worker=` the data pipeline
    # passes to `fire`, so only that host consumes the fault).
    worker: int | None = None
    # spike: synthetic queued requests per admitted replica added to the
    # autoscaler's pressure signal while the window is open.
    factor: float | None = None
    # A fault may fire at `count` distinct steps ([step, step+count) —
    # repeat_nan); it is spent once `fires` reaches it.
    count: int = 1
    fires: int = 0
    fired: bool = False

    @property
    def point(self) -> str:
        return KIND_POINTS[self.kind]

    @property
    def fault_id(self) -> str:
        return f"{self.kind}:{self.arg}" if self.arg else self.kind

    def matches(self, point: str, step: int | None,
                worker: int | None = None) -> bool:
        if self.fired or point != self.point:
            return False
        if self.step is not None:
            if step is None or not (
                    self.step <= step < self.step + self.count):
                return False
        # Worker filtering only applies when the CALL SITE identifies
        # itself (data_chaos passes the pulling host's shard index): a
        # non-matching host must not match — and so not consume — another
        # host's one-shot fault. Points that don't pass `worker` (e.g.
        # gang_chaos, where the supervisor applies the fault TO a worker)
        # keep the old match-any behaviour.
        if (self.worker is not None and worker is not None
                and self.worker != worker):
            return False
        return True


def _parse_one(entry: str) -> Fault:
    kind, _, arg = entry.partition(":")
    kind, arg = kind.strip(), arg.strip()
    if kind not in KIND_POINTS:
        raise ValueError(
            f"unknown fault kind {kind!r} in {ENV_VAR} entry {entry!r}; "
            f"known kinds: {sorted(KIND_POINTS)}"
        )
    fault = Fault(kind=kind, arg=arg)
    if kind in _STEP_KINDS:
        try:
            fault.step = int(arg)
        except ValueError:
            raise ValueError(
                f"fault {kind!r} needs an integer step, got {arg!r}"
            ) from None
        if fault.step < 1:
            raise ValueError(f"fault {kind!r} step must be >= 1, got {arg!r}")
    elif kind == "repeat_nan":
        head, _, tail = arg.partition(":")
        try:
            fault.step, fault.count = int(head), int(tail)
        except ValueError:
            raise ValueError(
                f"fault repeat_nan needs start:count (e.g. repeat_nan:30:5), "
                f"got {arg!r}"
            ) from None
        if fault.step < 1 or fault.count < 1:
            raise ValueError(
                f"fault repeat_nan needs step >= 1 and count >= 1, got {arg!r}"
            )
    elif kind == "drop_devices":
        head, _, tail = arg.partition(":")
        try:
            fault.devices = int(head)
            fault.step = int(tail) if tail else 1
        except ValueError:
            raise ValueError(
                f"fault drop_devices needs devices[:attempt] (e.g. "
                f"drop_devices:4:2), got {arg!r}"
            ) from None
        if fault.devices < 1 or fault.step < 1:
            raise ValueError(
                f"fault drop_devices needs devices >= 1 and attempt >= 1, "
                f"got {arg!r}"
            )
    elif kind == "kill_replica":
        head, _, tail = arg.partition(":")
        try:
            fault.replica = int(head)
            fault.step = int(tail) if tail else 1
        except ValueError:
            raise ValueError(
                f"fault kill_replica needs replica[:tick] (e.g. "
                f"kill_replica:1:3), got {arg!r}"
            ) from None
        if fault.replica < 0 or fault.step < 1:
            raise ValueError(
                f"fault kill_replica needs replica >= 0 and tick >= 1, "
                f"got {arg!r}"
            )
    elif kind == "stall_replica":
        head, _, tail = arg.partition(":")
        raw = tail[:-1] if tail.endswith("s") else tail
        try:
            fault.replica = int(head)
            fault.seconds = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(
                f"fault stall_replica needs replica:seconds (e.g. "
                f"stall_replica:0:10s), got {arg!r}"
            ) from None
        if fault.replica < 0:
            raise ValueError(
                f"fault stall_replica replica must be >= 0, got {arg!r}"
            )
        if fault.seconds == 0.0:
            fault.seconds = _STALL_FOREVER_S
        fault.step = 1  # first prober tick, like kill_replica's default
    elif kind == "spike":
        head, _, tail = arg.partition(":")
        raw = tail[:-1] if tail.endswith("s") else tail
        try:
            fault.factor = float(head)
            fault.seconds = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(
                f"fault spike needs factor:seconds (e.g. spike:6:8s), "
                f"got {arg!r}"
            ) from None
        if fault.factor <= 0:
            raise ValueError(
                f"fault spike factor must be > 0, got {arg!r}"
            )
        if fault.seconds <= 0:
            raise ValueError(
                f"fault spike needs a positive duration, got {arg!r}"
            )
        fault.step = 1  # first chaos tick: the spike starts at readiness
    elif kind == "tenant_stampede":
        head, _, tail = arg.partition(":")
        raw = tail[:-1] if tail.endswith("s") else tail
        try:
            fault.step = int(head)
            fault.seconds = float(raw) if raw else 5.0
        except ValueError:
            raise ValueError(
                f"fault tenant_stampede needs tick[:seconds] (e.g. "
                f"tenant_stampede:3:4s), got {arg!r}"
            ) from None
        if fault.step < 1:
            raise ValueError(
                f"fault tenant_stampede tick must be >= 1, got {arg!r}"
            )
        if fault.seconds <= 0:
            raise ValueError(
                f"fault tenant_stampede needs a positive duration, "
                f"got {arg!r}"
            )
    elif kind in ("kill_worker", "drop_worker"):
        head, _, tail = arg.partition(":")
        try:
            fault.worker = int(head)
            fault.step = int(tail) if tail else 1
        except ValueError:
            raise ValueError(
                f"fault {kind} needs worker[:tick] (e.g. "
                f"{kind}:1:3), got {arg!r}"
            ) from None
        if fault.worker < 0 or fault.step < 1:
            raise ValueError(
                f"fault {kind} needs worker >= 0 and tick >= 1, "
                f"got {arg!r}"
            )
    elif kind == "stall_worker":
        head, _, tail = arg.partition(":")
        raw = tail[:-1] if tail.endswith("s") else tail
        try:
            fault.worker = int(head)
            fault.seconds = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(
                f"fault stall_worker needs worker:seconds (e.g. "
                f"stall_worker:1:10s), got {arg!r}"
            ) from None
        if fault.worker < 0:
            raise ValueError(
                f"fault stall_worker worker must be >= 0, got {arg!r}"
            )
        if fault.seconds == 0.0:
            fault.seconds = _STALL_FOREVER_S
        fault.step = 1  # first supervisor tick, like kill_worker's default
    elif kind == "corrupt_shard":
        head, _, tail = arg.partition(":")
        try:
            fault.worker = int(head)
            fault.step = int(tail) if tail else 1
        except ValueError:
            raise ValueError(
                f"fault corrupt_shard needs host[:pull] (e.g. "
                f"corrupt_shard:1:3), got {arg!r}"
            ) from None
        if fault.worker < 0 or fault.step < 1:
            raise ValueError(
                f"fault corrupt_shard needs host >= 0 and pull >= 1, "
                f"got {arg!r}"
            )
    elif kind == "skew_shard":
        head, _, tail = arg.partition(":")
        raw = tail[:-1] if tail.endswith("s") else tail
        try:
            fault.worker = int(head)
            fault.seconds = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(
                f"fault skew_shard needs host:seconds (e.g. "
                f"skew_shard:1:10s), got {arg!r}"
            ) from None
        if fault.worker < 0:
            raise ValueError(
                f"fault skew_shard host must be >= 0, got {arg!r}"
            )
        if fault.seconds == 0.0:
            fault.seconds = _STALL_FOREVER_S
        # No pull ordinal: the skew starts at host K's next pull (the
        # fault is one-shot, so "next" means "first after arming").
    elif kind == "stall_infeed":
        dur, _, ordinal = arg.partition(":")
        raw = dur[:-1] if dur.endswith("s") else dur
        try:
            fault.seconds = float(raw) if raw else 0.0
        except ValueError:
            raise ValueError(
                f"fault stall_infeed needs a duration (e.g. 30s), got {arg!r}"
            ) from None
        if fault.seconds == 0.0:
            fault.seconds = _STALL_FOREVER_S
        if ordinal:
            # stall the Nth dataset pull (matched against the pull ordinal
            # the data pipeline passes as `step`); without it, the first.
            try:
                fault.step = int(ordinal)
            except ValueError:
                raise ValueError(
                    f"fault stall_infeed ordinal must be an integer "
                    f"(e.g. stall_infeed:3s:4), got {arg!r}"
                ) from None
            if fault.step < 1:
                raise ValueError(
                    f"fault stall_infeed ordinal must be >= 1, got {arg!r}"
                )
    return fault


@dataclass
class FaultPlan:
    """A parsed fault spec plus per-run fired-state tracking."""

    faults: list[Fault] = field(default_factory=list)
    state_path: str | None = None

    @classmethod
    def parse(cls, spec: str, *, state_path: str | None = None) -> "FaultPlan":
        faults = [
            _parse_one(entry)
            for entry in (e.strip() for e in spec.split(","))
            if entry
        ]
        plan = cls(faults=faults, state_path=state_path)
        plan._mark_already_fired()
        return plan

    @classmethod
    def from_env(cls, env: dict | None = None) -> "FaultPlan":
        env = os.environ if env is None else env
        return cls.parse(
            env.get(ENV_VAR, ""), state_path=env.get(STATE_ENV_VAR) or None
        )

    @property
    def active(self) -> bool:
        return bool(self.faults)

    # -- cross-process once-only state -----------------------------------
    def _fired_ids(self) -> set[str]:
        if not self.state_path or not os.path.exists(self.state_path):
            return set()
        try:
            with open(self.state_path) as fh:
                return set(json.load(fh))
        except (OSError, json.JSONDecodeError):
            return set()

    def _mark_already_fired(self) -> None:
        fired = self._fired_ids()
        for f in self.faults:
            if f.fault_id in fired:
                f.fired = True

    def _record_fired(self, fault: Fault) -> None:
        fault.fires += 1
        fault.fired = fault.fires >= fault.count
        if not self.state_path:
            return
        ids = self._fired_ids() | {fault.fault_id}
        tmp = f"{self.state_path}.{os.getpid()}.tmp"
        # fsync before the crash faults execute: the record must survive
        # the SIGKILL it is about to cause, or the fault re-fires forever.
        with open(tmp, "w") as fh:
            json.dump(sorted(ids), fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.state_path)

    # -- firing ----------------------------------------------------------
    def fire(self, point: str, *, step: int | None = None,
             worker: int | None = None) -> list[Fault]:
        """Execute self-contained faults matching this point (crash, stall)
        and return the caller-handled ones (nan_grads, corrupt_ckpt) so the
        call site applies them with its own context. ``worker`` lets a
        call site that IS a specific worker (the data pipeline's
        data_chaos point) claim only faults targeted at it. Thread-safe:
        the match→record→execute sequence runs under the plan lock, so the
        background saver thread and the training thread can never both
        claim the same fault."""
        matched: list[Fault] = []
        with _FIRE_LOCK:  # match + record atomically; execute after release
            for fault in self.faults:
                if not fault.matches(point, step, worker):
                    continue
                self._record_fired(fault)
                matched.append(fault)
        handled: list[Fault] = []
        for fault in matched:
            print(
                f"DTF_FAULTS: firing {fault.fault_id} at point "
                f"{point!r} (step={step}, "
                f"thread={threading.current_thread().name})",
                file=sys.stderr, flush=True,
            )
            if fault.kind in ("crash_at_step", "crash_in_save"):
                # SIGKILL the PROCESS (not the thread): fired from the
                # async saver thread this still models a machine-level
                # kill racing the commit sequence.
                os.kill(os.getpid(), signal.SIGKILL)
                os._exit(137)  # unreachable on POSIX; belt-and-braces
            elif fault.kind == "stall_infeed":
                # The long sleep happens OUTSIDE the lock: a stalled
                # infeed thread must not also wedge every other thread's
                # fault points.
                time.sleep(fault.seconds or 0.0)
            else:
                handled.append(fault)
        return handled


# -- process-wide plan ----------------------------------------------------
# Serializes fire() across threads (training loop, async checkpoint saver,
# async infeed producer) — see the thread-model note in the module docs.
_FIRE_LOCK = threading.Lock()
_plan: FaultPlan | None = None


def active_plan() -> FaultPlan:
    global _plan
    if _plan is None:
        _plan = FaultPlan.from_env()
        if _plan.active:
            log.warning(
                "fault injection ACTIVE: %s",
                ", ".join(f.fault_id for f in _plan.faults),
            )
    return _plan


def install(plan: FaultPlan | str | None) -> FaultPlan:
    """Set (or, with None, clear back to env-lazy) the process fault plan —
    the test seam; production configuration is the DTF_FAULTS env var."""
    global _plan
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return active_plan()


def fire(point: str, *, step: int | None = None,
         worker: int | None = None) -> list[Fault]:
    """Fire the process plan at a fault point; cheap no-op when inactive."""
    plan = active_plan()
    if not plan.active:
        return []
    return plan.fire(point, step=step, worker=worker)


def corrupt_checkpoint_dir(step_dir: str) -> str | None:
    """Truncate the largest payload file in a committed step directory to
    half its size — a committed-but-torn checkpoint (the corrupt_ckpt
    fault's effect; also used directly by tests). Returns the path, or
    None when there is nothing to corrupt."""
    from distributed_tensorflow_framework_tpu.ckpt import manifest as mf

    best, best_size = None, -1
    for rel in mf.iter_payload_files(step_dir):
        path = os.path.join(step_dir, rel)
        size = os.path.getsize(path)
        if size > best_size:
            best, best_size = path, size
    if best is None:
        return None
    with open(best, "r+b") as fh:
        fh.truncate(best_size // 2)
    log.warning(
        "corrupt_ckpt fault: truncated %s from %d to %d bytes",
        best, best_size, best_size // 2,
    )
    return best
