"""Goodput ledger: classify every wall-clock second of a training run.

BENCH_r02 pinned the step loop at MFU 0.31, but MFU only describes the
seconds the accelerator was actually stepping. Once the resilience
ladder is in play — supervisor relaunches, rollbacks, infeed stalls,
checkpoint-blocked time — a run's *goodput* (the fraction of wall-clock
that became training progress) can be far below its per-step MFU, and
nothing measured it. This module is the accountant:

  * ``GoodputLedger`` lives in the Trainer, absorbs ``StepTimer`` phase
    totals (core/profiling.py) each metrics fetch, listens on the
    ``TelemetryWriter`` for ``ckpt_save`` blocked-ms emitted from the
    async saver thread, and classifies everything else by explicit
    ``add()``/``timed()`` calls. It emits periodic ``KIND_GOODPUT``
    events plus a ``final=True`` rollup at loop exit.
  * ``stitch_attempts`` joins the per-attempt ledgers of a supervised
    run (one ``run_id`` per process) into one cross-attempt table whose
    buckets — including the restart gaps BETWEEN attempts, classified
    from the sibling ``supervisor_events.jsonl`` — sum to the measured
    wall-clock span. Gang runs add a dimension: each worker's stream
    (``events.jsonl`` / ``events-p<i>.jsonl``) carries ``process_id``
    on its goodput events, and stitching a list of streams groups
    attempts by (run id, process id) into a ``per_host`` section whose
    every host-table still sums to that host's own measured span.
    ``format_goodput_table`` renders it (scripts/analyze_trace.py
    prints it per run directory).

Bucket definitions (seconds of host wall time; docs/OBSERVABILITY.md):

  step_compute   dispatch + backpressure phases: the loop was driving
                 the accelerator (the PRODUCTIVE bucket)
  recompile      first dispatch of a program (initial jit) and the
                 dispatch after a rollback rebuild
  infeed_wait    blocking on ``next(batch)`` — includes infeed-watchdog
                 retry sleeps, which fire inside the infeed phase
  metrics_fetch  device→host fetch of logged metrics
  ckpt_blocked   training thread blocked inside save() (joined from
                 ``ckpt_save`` events' ``ckpt_save_blocked_ms``)
  rollback       anomaly handling: snapshot restore + LR-rewarmup
                 rebuild inside ``_maybe_recover``
  startup        trainer construction → first loop iteration (restore +
                 input build; the first compile lands in ``recompile``)
  other          residual: wall since ledger start minus every bucket
                 above (hooks, logging, eval, exit barrier)
  restart_gap    stitch-time only: wall between one attempt's last
                 ledger event and the next attempt's start
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Iterator, Mapping

from distributed_tensorflow_framework_tpu.core import telemetry

# StepTimer phase name -> ledger bucket.
PHASE_BUCKETS = {
    "dispatch": "step_compute",
    "backpressure": "step_compute",
    "compile": "recompile",
    "infeed": "infeed_wait",
    "metrics_fetch": "metrics_fetch",
}

PRODUCTIVE_BUCKETS = ("step_compute",)

# Display order for tables; unknown buckets append after these.
BUCKET_ORDER = (
    "step_compute", "recompile", "infeed_wait", "metrics_fetch",
    "ckpt_blocked", "rollback", "startup", "other", "restart_gap",
)


class GoodputLedger:
    """Per-process wall-clock accountant feeding ``KIND_GOODPUT``.

    Thread-safe: ``ckpt_save`` observations arrive from the async saver
    thread while the training thread absorbs phases. The ledger's clock
    starts at construction, or at ``t0_perf`` when given — the Trainer
    passes its ``__init__``-entry timestamp so the runtime/dataset build
    that precedes the telemetry writer's existence is INSIDE the
    ledger's wall (the ``startup`` bucket charges exactly that span;
    without the backdate those seconds would overflow the wall and the
    residual ``other`` would clamp dishonestly at zero).
    """

    def __init__(self, writer: telemetry.TelemetryWriter | None = None,
                 *, interval_s: float = 30.0, t0_perf: float | None = None,
                 process_id: int | None = None):
        self._writer = writer
        self._interval_s = float(interval_s)
        # Gang runs stamp the owning process id on every KIND_GOODPUT
        # event so stitch_attempts can group per host without joining
        # run_meta across files; single-process runs leave it off.
        self._process_id = process_id
        self._lock = threading.Lock()
        now = time.perf_counter()
        self._t0 = now if t0_perf is None else float(t0_perf)
        self.t0_wall = time.time() - (now - self._t0)
        self._buckets: dict[str, float] = {}
        self._counters: dict[str, int] = {}
        self._last_emit = self._t0
        if writer is not None:
            writer.add_listener(self._observe)

    # -- accumulation ----------------------------------------------------

    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self._t0

    def add(self, bucket: str, seconds: float) -> None:
        if seconds <= 0.0:
            return
        with self._lock:
            self._buckets[bucket] = self._buckets.get(bucket, 0.0) + seconds

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    @contextlib.contextmanager
    def timed(self, bucket: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(bucket, time.perf_counter() - t0)

    def absorb_phases(self, totals: Mapping[str, float]) -> None:
        """Fold a ``StepTimer.totals`` dict in (call BEFORE its reset).

        Unknown phase names land in their own bucket rather than being
        dropped — a new phase must never silently vanish from the
        accounting.
        """
        for phase, seconds in totals.items():
            self.add(PHASE_BUCKETS.get(phase, phase), float(seconds))

    def _observe(self, ev: Mapping[str, Any]) -> None:
        """TelemetryWriter listener: join sibling streams in-process."""
        kind = ev.get("kind")
        if kind == telemetry.KIND_CKPT_SAVE:
            m = ev.get("metrics") or {}
            self.add("ckpt_blocked",
                     float(m.get("ckpt_save_blocked_ms", 0.0)) / 1e3)
            self.count("ckpt_saves")
        elif kind == telemetry.KIND_INFEED_STALL:
            # Stall time is already inside infeed_wait (the watchdog
            # retries within the infeed phase); only tally the incident.
            self.count("infeed_stalls")
        elif kind == telemetry.KIND_ROLLBACK:
            self.count("rollbacks")
        elif kind == telemetry.KIND_BATCH_SKIPPED:
            self.count("batches_skipped",
                       int((ev.get("health") or {}).get("batches", 1) or 1))
        elif kind == telemetry.KIND_SERVE_RECOMPILE:
            m = ev.get("metrics") or {}
            self.add("recompile", float(m.get("compile_ms", 0.0)) / 1e3)
            self.count("recompiles")
        elif kind == telemetry.KIND_DATA_STATE:
            # Restore-gate verdicts (data/shard.py): how many times this
            # attempt resumed a saved data stream, and how many of those
            # were N→M repartitions — the restart classification the
            # stitched cross-attempt ledger rolls up.
            self.count("data_restores")
            plan = (ev.get("extra") or {}).get("plan") or {}
            if plan.get("action") == "repartition":
                self.count("data_repartitions")

    # -- snapshots & emission --------------------------------------------

    def snapshot(self) -> dict:
        """Point-in-time ledger: buckets + residual ``other`` summing to
        ``wall_s``, and the productive fraction of that wall."""
        with self._lock:
            buckets = dict(self._buckets)
            counters = dict(self._counters)
        wall = self.wall_s
        other = wall - sum(buckets.values())
        buckets["other"] = max(0.0, other)
        productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE_BUCKETS)
        return {
            "wall_s": wall,
            "goodput_frac": (productive / wall) if wall > 0 else 0.0,
            "buckets": {b: round(s, 4) for b, s in buckets.items()},
            "counters": counters,
        }

    def _emit(self, step: int | None, final: bool) -> dict | None:
        if self._writer is None:
            return None
        snap = self.snapshot()
        extra: dict[str, Any] = {}
        if self._process_id is not None:
            extra["process_id"] = self._process_id
        return self._writer.emit(
            telemetry.KIND_GOODPUT,
            step=step,
            metrics={"wall_s": round(snap["wall_s"], 4),
                     "goodput_frac": round(snap["goodput_frac"], 4)},
            buckets=snap["buckets"],
            counters=snap["counters"],
            t0=self.t0_wall,
            final=final,
            **extra,
        )

    def maybe_emit(self, step: int | None = None) -> dict | None:
        """Periodic cumulative snapshot — cheap enough for every metrics
        fetch; a SIGKILLed attempt's last one is its ledger of record."""
        now = time.perf_counter()
        if now - self._last_emit < self._interval_s:
            return None
        self._last_emit = now
        return self._emit(step, final=False)

    def finalize(self, step: int | None = None) -> dict | None:
        """End-of-run rollup (``final=True`` supersedes periodic ones)."""
        return self._emit(step, final=True)


# -- cross-attempt stitching (read side) ---------------------------------


def _stitch_host(attempts: list[dict], classifications: list[str]) -> dict:
    """Stitch ONE host's time-ordered attempts: sum buckets/counters,
    classify the restart gaps between coverage windows, and close the
    books so buckets (gaps included) sum to that host's measured span."""
    buckets: dict[str, float] = {}
    counters: dict[str, int] = {}
    gaps: list[dict] = []
    for i, att in enumerate(attempts):
        for b, s in att["buckets"].items():
            buckets[b] = buckets.get(b, 0.0) + float(s)
        for c, n in att["counters"].items():
            counters[c] = counters.get(c, 0) + int(n)
        if i + 1 < len(attempts):
            gap = attempts[i + 1]["t0"] - (att["t0"] + att["wall_s"])
            cls = (classifications[i] if i < len(classifications)
                   else "unknown")
            gaps.append({"after_attempt": i + 1, "seconds": max(0.0, gap),
                         "classification": cls})
    restart_gap = sum(g["seconds"] for g in gaps)
    if restart_gap:
        buckets["restart_gap"] = restart_gap
    span = sum(a["wall_s"] for a in attempts) + restart_gap
    productive = sum(buckets.get(b, 0.0) for b in PRODUCTIVE_BUCKETS)
    return {
        "attempts": [
            {"run_id": a["run_id"], "wall_s": a["wall_s"],
             "goodput_frac": a["goodput_frac"], "final": a["final"]}
            for a in attempts
        ],
        "wall_s": span,
        "buckets": buckets,
        "counters": counters,
        "restart_gaps": gaps,
        "goodput_frac": (productive / span) if span > 0 else 0.0,
    }


def stitch_attempts(events_path,
                    supervisor_path: str | None = None) -> dict | None:
    """Join per-attempt ``KIND_GOODPUT`` ledgers into one run table.

    Each supervised attempt is a separate process with its own run_id
    and ledger; its last (preferably final) goodput event covers the
    interval ``[t0, t0 + wall_s]``. The wall between one attempt's
    coverage end and the next attempt's ``t0`` is the ``restart_gap`` —
    supervisor backoff + relaunch + the next process's pre-ledger
    import time — classified, when ``supervisor_events.jsonl`` sits
    next to the (first) events file, by the exit classification of the
    attempt that ended each gap.

    ``events_path`` may be a single path or a list of per-worker
    streams from a gang run (``events.jsonl`` plus the non-chief
    workers' ``events-p<i>.jsonl``). Snapshots are grouped by (run id,
    ``process_id`` extra); with more than one host the result gains a
    ``per_host`` section — one stitched table per process id, each
    summing to its OWN measured span, all sharing the gang-level gap
    classifications — while the top-level table stays the chief's
    timeline (host 0), keeping the single-stream shape. Returns None
    when no stream has goodput events (e.g. a serve log).
    """
    paths = [events_path] if isinstance(events_path, str) else list(events_path)
    if not paths:
        return None
    by_key: dict[tuple[int, str], dict] = {}
    for path in paths:
        for ev in telemetry.read_events(
                path, kind=telemetry.KIND_GOODPUT, strict=False):
            extra = ev.get("extra") or {}
            m = ev.get("metrics") or {}
            host = int(extra.get("process_id") or 0)
            snap = {
                "run_id": ev.get("run_id"),
                "process_id": host,
                "t0": float(extra.get("t0") or ev.get("t") or 0.0),
                "wall_s": float(m.get("wall_s") or 0.0),
                "goodput_frac": m.get("goodput_frac"),
                "buckets": dict(extra.get("buckets") or {}),
                "counters": dict(extra.get("counters") or {}),
                "final": bool(extra.get("final")),
            }
            key = (host, snap["run_id"])
            prev = by_key.get(key)
            if prev is None or not prev["final"] or snap["final"]:
                by_key[key] = snap
    if not by_key:
        return None

    classifications: list[str] = []
    if supervisor_path is None:
        supervisor_path = os.path.join(
            os.path.dirname(os.path.abspath(paths[0])),
            "supervisor_events.jsonl")
    if os.path.exists(supervisor_path):
        for ev in telemetry.read_events(
                supervisor_path, kind=telemetry.KIND_SUPERVISOR_ATTEMPT,
                strict=False):
            classifications.append(
                str((ev.get("extra") or {}).get("classification", "unknown")))

    by_host: dict[int, list[dict]] = {}
    for snap in by_key.values():
        by_host.setdefault(snap["process_id"], []).append(snap)
    stitched = {
        host: _stitch_host(sorted(atts, key=lambda s: s["t0"]),
                           classifications)
        for host, atts in by_host.items()
    }
    # The chief's timeline is the run's timeline: its attempts bound the
    # span the supervisor actually managed.
    primary = stitched[min(stitched)]
    out = dict(primary)
    out["supervisor_events"] = (supervisor_path
                                if os.path.exists(supervisor_path) else None)
    if len(stitched) > 1:
        out["per_host"] = {
            str(host): stitched[host] for host in sorted(stitched)
        }
    return out


def format_goodput_table(g: Mapping[str, Any]) -> str:
    """Render a stitched ledger: one row per bucket, % of measured wall
    (rows sum to ~100% by construction — ``other`` is the residual)."""
    span = float(g.get("wall_s") or 0.0)
    buckets = dict(g.get("buckets") or {})
    ordered = [b for b in BUCKET_ORDER if b in buckets]
    ordered += sorted(b for b in buckets if b not in BUCKET_ORDER)
    n_att = len(g.get("attempts") or [])
    lines = [
        f"goodput ledger: {n_att} attempt(s), "
        f"{span:.1f} s measured wall-clock",
        f"  {'bucket':<14} {'seconds':>10} {'%':>7}",
    ]
    for b in ordered:
        s = float(buckets[b])
        pct = 100.0 * s / span if span > 0 else 0.0
        lines.append(f"  {b:<14} {s:>10.2f} {pct:>6.1f}%")
    total = sum(float(buckets[b]) for b in ordered)
    total_pct = 100.0 * total / span if span > 0 else 0.0
    lines.append(f"  {'TOTAL':<14} {total:>10.2f} {total_pct:>6.1f}%")
    frac = g.get("goodput_frac")
    if frac is not None:
        lines.append(
            f"  goodput: {100.0 * float(frac):.1f}% of wall-clock was "
            f"productive step compute")
    for gap in g.get("restart_gaps") or []:
        lines.append(
            f"  restart gap after attempt {gap['after_attempt']}: "
            f"{gap['seconds']:.1f} s ({gap['classification']})")
    per_host = g.get("per_host") or {}
    for host in sorted(per_host, key=lambda h: int(h)):
        h = per_host[host]
        hf = h.get("goodput_frac")
        hg = sum(x["seconds"] for x in h.get("restart_gaps") or [])
        lines.append(
            f"  host {host}: {float(h.get('wall_s') or 0.0):.1f} s span, "
            f"{100.0 * float(hf or 0.0):.1f}% goodput, "
            f"{len(h.get('attempts') or [])} attempt(s), "
            f"{hg:.1f} s restart gap")
    return "\n".join(lines)
