"""HBM memory telemetry: where the bytes behind ``hbm_bw_util 0.94`` live.

ROADMAP item 5 (attack the HBM roofline) needs a before/after
instrument for every byte-moving experiment. Two complementary sources,
both emitted as ``KIND_MEMORY`` events:

  * ``device_memory_snapshot`` — the allocator's live view
    (``device.memory_stats()``: bytes_in_use / peak_bytes_in_use per
    device). TPU/GPU runtimes expose it; the CPU backend returns None,
    so the snapshot falls back to process RSS (``resource.getrusage``)
    with ``source_kind`` saying which ruler was used — CPU CI exercises
    the full pipeline, chips report real HBM.
  * ``compiled_memory_analysis`` — XLA's static budget for one program
    (``compiled.memory_analysis()``: argument/output/temp/generated-code
    bytes). One-shot per compile, works on every backend, and is the
    number remat/donation experiments move directly.

``MemoryMonitor`` owns the cadence: periodic ``maybe_sample`` from the
train loop and serve reporter, ``capture_compiled`` when a lowered step
is at hand, and a no-emit ``snapshot()`` for /healthz.
"""

from __future__ import annotations

import resource
import time
from typing import Any

from distributed_tensorflow_framework_tpu.core import telemetry

# CompiledMemoryStats attribute -> analysis dict key (bytes).
_ANALYSIS_FIELDS = {
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "temp_size_in_bytes": "temp_bytes",
    "generated_code_size_in_bytes": "generated_code_bytes",
    "alias_size_in_bytes": "alias_bytes",
}


def host_rss_bytes() -> tuple[int, int]:
    """(current, peak) resident-set bytes of this process.

    ``ru_maxrss`` is KiB on Linux; the current figure comes from
    /proc/self/statm when available, else the peak stands in for both.
    """
    peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    current = peak
    try:
        with open("/proc/self/statm") as fh:
            pages = int(fh.read().split()[1])
        import os
        current = pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    return current, peak


def device_memory_snapshot(devices=None) -> dict:
    """One sample across ``devices`` (default: ``jax.devices()``).

    Returns per-chip MAXIMA in the top-level fields — the binding
    constraint on an SPMD program is its worst chip, and that is the
    number bench.py holds against the chip's HBM capacity:

      {"device_count", "bytes_in_use", "peak_bytes_in_use",
       "source_kind": "device_memory_stats" | "host_rss",
       "devices": [{"id", "kind", "bytes_in_use", "peak_bytes_in_use"}]}
    """
    if devices is None:
        import jax
        devices = jax.devices()
    per_device = []
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # backend without allocator stats
            stats = None
        if stats:
            per_device.append({
                "id": getattr(d, "id", None),
                "kind": getattr(d, "device_kind", "?"),
                "bytes_in_use": int(stats.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(
                    stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))),
            })
    if per_device:
        return {
            "device_count": len(devices),
            "bytes_in_use": max(d["bytes_in_use"] for d in per_device),
            "peak_bytes_in_use": max(
                d["peak_bytes_in_use"] for d in per_device),
            "source_kind": "device_memory_stats",
            "devices": per_device,
        }
    current, peak = host_rss_bytes()
    return {
        "device_count": len(devices),
        "bytes_in_use": current,
        "peak_bytes_in_use": peak,
        "source_kind": "host_rss",
        "devices": [],
    }


def compiled_memory_analysis(compiled) -> dict | None:
    """XLA's static memory budget for one compiled program, or None.

    ``peak_bytes_est`` is the classic XLA program-footprint sum
    (arguments + outputs + temps + generated code) — nonzero on every
    backend, including CPU, which is what keeps the bench acceptance
    check meaningful off-chip.
    """
    analysis_fn = getattr(compiled, "memory_analysis", None)
    if analysis_fn is None:
        return None
    try:
        stats = analysis_fn()
    except Exception:
        return None
    if stats is None:
        return None
    out: dict[str, int] = {}
    for attr, key in _ANALYSIS_FIELDS.items():
        v = getattr(stats, attr, None)
        if v is not None:
            out[key] = int(v)
    if not out:
        return None
    out["peak_bytes_est"] = (
        out.get("argument_bytes", 0) + out.get("output_bytes", 0)
        + out.get("temp_bytes", 0) + out.get("generated_code_bytes", 0))
    return out


class MemoryMonitor:
    """Cadenced ``KIND_MEMORY`` emitter for one process.

    ``source`` tags who is sampling ("train", "serve", "bench") so a
    joined events file keeps the streams apart. Per-device rows ride in
    the event only up to ``max_device_rows`` — megapod runs must not
    turn every sample into a kilobyte of JSON.
    """

    def __init__(self, writer: telemetry.TelemetryWriter | None = None,
                 *, interval_s: float = 60.0, source: str = "train",
                 devices=None, max_device_rows: int = 16):
        self._writer = writer
        self._interval_s = float(interval_s)
        self._source = source
        self._devices = devices
        self._max_device_rows = max_device_rows
        self._last_sample = time.perf_counter()
        self._last_snapshot: dict | None = None

    def snapshot(self) -> dict:
        """Fresh sample, no emission (the /healthz path)."""
        snap = device_memory_snapshot(self._devices)
        self._last_snapshot = snap
        return snap

    def sample(self, step: int | None = None, *,
               final: bool = False) -> dict:
        """Sample and emit one ``KIND_MEMORY`` event."""
        snap = self.snapshot()
        self._last_sample = time.perf_counter()
        if self._writer is not None:
            self._writer.emit(
                telemetry.KIND_MEMORY,
                step=step,
                metrics={
                    "bytes_in_use": snap["bytes_in_use"],
                    "peak_bytes_in_use": snap["peak_bytes_in_use"],
                    "device_count": snap["device_count"],
                },
                source=self._source,
                source_kind=snap["source_kind"],
                devices=snap["devices"][: self._max_device_rows] or None,
                final=final,
            )
        return snap

    def maybe_sample(self, step: int | None = None) -> dict | None:
        if time.perf_counter() - self._last_sample < self._interval_s:
            return None
        return self.sample(step)

    def capture_compiled(self, compiled, *, step: int | None = None,
                         label: str = "train_step") -> dict | None:
        """One-shot static-budget capture of a compiled program."""
        analysis = compiled_memory_analysis(compiled)
        if analysis is None:
            return None
        if self._writer is not None:
            self._writer.emit(
                telemetry.KIND_MEMORY,
                step=step,
                metrics={"peak_bytes_est": analysis["peak_bytes_est"]},
                source=self._source,
                source_kind="memory_analysis",
                program=label,
                analysis=analysis,
            )
        return analysis
