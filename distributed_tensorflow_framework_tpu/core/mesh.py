"""Device-mesh construction and distributed runtime initialization.

Replaces the reference's L1 cluster runtime (SURVEY.md §2 rows 1–2:
``tf.train.ClusterSpec`` + ``tf.train.Server`` per-role launcher and
``replica_device_setter`` variable placement). There is no parameter-server
role: every host runs the same SPMD program, parameters live wherever the
sharding rules put them (replicated, or sharded over the ``fsdp`` axis), and
the "cluster spec" collapses to one logical `jax.sharding.Mesh`.

Collectives emitted against this mesh ride ICI within a slice and DCN across
slices — the TPU-native equivalent of the reference's grpc PS transport +
NCCL all-reduce (SURVEY.md §2 native rows).
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.config import MeshConfig

log = logging.getLogger(__name__)

# Axis order matters: data outermost so data-parallel replicas land on
# distinct slices/hosts first, model/seq innermost so tensor- and
# sequence-parallel collectives ride the fastest ICI links; expert/pipe sit
# between (all_to_all and stage ppermute traffic is lighter than TP
# all_reduce but heavier than DP grad reduction per step).
MESH_AXES = ("data", "fsdp", "expert", "pipe", "seq", "model")


class MeshSizeError(ValueError):
    """The configured mesh does not fit the visible device set.

    Typed (vs a bare ValueError) so cli/train.py can map it to the
    supervisor's elastic-reshard exit code (``ELASTIC_RESHARD_RC`` = 84,
    core/supervision.py): when a slice drops out between relaunches this
    is a topology change to adapt to, not a crash to back off from.
    """

    def __init__(self, sizes: dict[str, int], needed: int, available: int):
        self.sizes = dict(sizes)
        self.needed = int(needed)
        self.available = int(available)
        super().__init__(
            f"Mesh {self.sizes} needs {self.needed} devices but "
            f"{self.available} are available"
        )


def initialize_distributed() -> None:
    """Initialize multi-host JAX if a cluster environment is detected.

    The reference required the user to pass ``--ps_hosts/--worker_hosts/
    --job_name/--task_index`` to every process; here multi-host discovery is
    automatic (TPU metadata / cluster env vars), and single-host runs skip
    initialization entirely.
    """
    # NOTE: must not touch jax.process_count()/devices() here — any backend
    # query initializes XLA, after which jax.distributed.initialize raises.
    if _distributed_initialized():
        return
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    num_procs = os.environ.get("JAX_NUM_PROCESSES")
    if coord and num_procs:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(num_procs),
            process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
        )


def _distributed_initialized() -> bool:
    """``jax.distributed.is_initialized`` without requiring it to exist —
    jax < 0.5 has no public probe, but the private global_state.client is
    the exact value the public API later wrapped."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # pragma: no cover - private API moved
        return False


def _resolve_axis_sizes(config: MeshConfig, n: int) -> dict[str, int]:
    """Fill the single -1 axis and validate the product against n."""
    sizes = config.axis_sizes()
    fixed = {k: v for k, v in sizes.items() if v != -1}
    fixed_prod = int(np.prod(list(fixed.values()))) if fixed else 1
    free = [k for k, v in sizes.items() if v == -1]
    if len(free) > 1:
        raise ValueError(f"At most one mesh axis may be -1, got {free}")
    if free:
        if n % fixed_prod:
            raise MeshSizeError(sizes, fixed_prod, n)
        sizes[free[0]] = n // fixed_prod
    total = int(np.prod(list(sizes.values())))
    if total != n:
        raise MeshSizeError(sizes, total, n)
    return sizes


def fit_mesh(
    config: MeshConfig | dict[str, int], n_devices: int
) -> dict[str, int]:
    """Largest valid axis sizes fitting ``n_devices`` — the elastic
    supervisor's mesh-rewrite primitive. Non-``data`` axes only shrink to
    divisors of their configured size (preserving divisibility of stage/
    shard splits), ``data`` absorbs the rest; axis ORDER is MESH_AXES.
    Pure arithmetic delegated to core/supervision.fit_axis_sizes so the
    jax-free supervisor computes the identical answer."""
    from distributed_tensorflow_framework_tpu.core import supervision

    sizes = config.axis_sizes() if isinstance(config, MeshConfig) else config
    return supervision.fit_axis_sizes(dict(sizes), n_devices)


def hybrid_mesh_shapes(
    sizes: dict[str, int], num_slices: int
) -> tuple[dict[str, int], dict[str, int]]:
    """Split logical axis sizes into (per-slice ICI, cross-slice DCN) parts.

    Multislice placement policy: outer axes span slices first — ``data``
    (one grad all-reduce per step tolerates DCN latency), then ``fsdp``
    (for FSDP-dominant layouts), and so on down MESH_AXES order — while
    everything still fitting intra-slice stays on ICI. The slice count
    must factor into the axis sizes walked in that order.
    """
    import math

    ici = dict(sizes)
    dcn = {a: 1 for a in sizes}
    remaining = num_slices
    for axis in MESH_AXES:
        if remaining == 1:
            break
        f = math.gcd(ici[axis], remaining)
        if f > 1:
            dcn[axis] = f
            ici[axis] //= f
            remaining //= f
    if remaining != 1:
        raise ValueError(
            f"slice count {num_slices} does not factor into the mesh axes "
            f"{sizes} (walked in {MESH_AXES} order) — no DCN-spanning "
            f"layout exists"
        )
    return ici, dcn


def create_mesh(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build the logical mesh from a MeshConfig over available devices.

    Axes with size 1 are kept in the mesh (size-1 axes are free) so that
    sharding rules can always name all canonical axes regardless of the
    physical topology. On a multislice TPU deployment (devices report
    distinct ``slice_index``), the mesh is built hybrid: ``data`` replicas
    span slices over DCN, every other axis stays within a slice on ICI.
    """
    config = config or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    sizes = _resolve_axis_sizes(config, n)
    shape = tuple(sizes[a] for a in MESH_AXES)

    slice_ids = {getattr(d, "slice_index", 0) for d in devs}
    if len(slice_ids) > 1:
        from jax.experimental import mesh_utils

        ici, dcn = hybrid_mesh_shapes(sizes, len(slice_ids))
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici[a] for a in MESH_AXES),
            tuple(dcn[a] for a in MESH_AXES),
            devices=devs,
        )
        return Mesh(dev_array, MESH_AXES)

    dev_array = np.asarray(devs).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def batch_spec(mesh: Mesh) -> P:
    """PartitionSpec sharding the leading batch dim over the data-like axes.

    ``expert`` participates: for MoE runs the batch is sharded over it too
    (it acts as extra data parallelism for the dense params; the MoE
    dispatch einsum moves tokens expert-ward via all_to_all). ``pipe``/
    ``seq``/``model`` never shard the batch dim.
    """
    del mesh
    return P(("data", "fsdp", "expert"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@dataclasses.dataclass
class MeshRuntime:
    """The process's view of the SPMD runtime (replaces ClusterSpec+Server)."""

    mesh: Mesh
    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_chief(self) -> bool:
        """Process 0 — the reference's "chief" worker. It owns checkpoint
        writes and summary logging (SURVEY.md §2 row 10)."""
        return self.process_index == 0

    @property
    def data_parallel_size(self) -> int:
        return (
            self.mesh.shape["data"]
            * self.mesh.shape["fsdp"]
            * self.mesh.shape["expert"]
        )

    def describe(self) -> str:
        return (
            f"process {self.process_index}/{self.process_count}, "
            f"{self.local_device_count} local / {self.global_device_count} "
            f"global devices, mesh {dict(self.mesh.shape)}"
        )


def initialize_runtime(
    config: MeshConfig | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> MeshRuntime:
    initialize_distributed()
    mesh = create_mesh(config, devices=devices)
    rt = MeshRuntime(
        mesh=mesh,
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=jax.local_device_count(),
        global_device_count=jax.device_count(),
    )
    log.info("Mesh runtime: %s", rt.describe())
    return rt
