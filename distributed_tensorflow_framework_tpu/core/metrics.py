"""Metrics, logging and throughput accounting.

Replaces the reference's ``tf.summary`` + ``SummarySaverHook`` + console
``tf.logging`` stack (SURVEY.md §5 "Metrics / logging"): a MetricWriter that
fans out to the console and, when available, a TensorBoard event file
(written through TF's summary writer — TF is in the image for tf.data), plus
a ThroughputMeter tracking the BASELINE.json north-star metric
(images/sec and images/sec/chip).

The on-disk record is the versioned telemetry schema (core/telemetry.py):
``events.jsonl`` in the logdir, one ``dtf-telemetry/1`` event per write,
with phase timings, throughput and collective byte counters split into
their schema fields rather than flattened into one ad-hoc dict.
"""

from __future__ import annotations

import logging
import math
import os
import random
import time
from typing import Any, Mapping

from distributed_tensorflow_framework_tpu.core import telemetry

log = logging.getLogger("dtf_tpu")


def setup_logging(level: int = logging.INFO) -> None:
    if not logging.getLogger().handlers:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
        )


class MetricWriter:
    """Console + optional TensorBoard + optional JSONL metric sink.

    Only the chief process writes console/TensorBoard summaries
    (reference contract: chief owns summaries, SURVEY.md §2 row 10). In
    a multi-process gang every worker additionally keeps a telemetry
    stream of its own — the chief's at ``events.jsonl``, worker i's at
    ``events-p<i>.jsonl`` — so per-host goodput/heartbeat evidence
    survives a worker death and ``stitch_attempts`` can join them by
    run id + process_id. Single-process non-chief construction stays a
    full no-op writer.
    """

    def __init__(
        self,
        logdir: str | None = None,
        *,
        is_chief: bool = True,
        jsonl: bool = True,
        run_id: str | None = None,
        process_index: int = 0,
        process_count: int = 1,
    ):
        self._enabled = is_chief
        self._tb = None
        telemetry_path = None
        if logdir and jsonl and (is_chief or process_count > 1):
            name = ("events.jsonl" if is_chief
                    else f"events-p{process_index}.jsonl")
            telemetry_path = os.path.join(logdir, name)
        self.telemetry = telemetry.TelemetryWriter(
            telemetry_path,
            run_id=run_id,
            is_chief=is_chief or process_count > 1,
        )
        self.run_id = self.telemetry.run_id
        if not self._enabled:
            return
        if logdir:
            os.makedirs(logdir, exist_ok=True)
            try:
                import tensorflow as tf  # noqa: PLC0415 — optional heavy dep

                self._tb = tf.summary.create_file_writer(logdir)
            except Exception:  # pragma: no cover - TF missing/broken
                log.warning("TensorBoard writer unavailable; console only")

    def write(
        self,
        step: int,
        values: Mapping[str, Any],
        *,
        kind: str = telemetry.KIND_TRAIN_STEP,
        collectives: Mapping[str, Any] | None = None,
    ) -> None:
        if not self._enabled:
            return
        scalars = {k: _to_scalar(v) for k, v in values.items()}
        msg = " ".join(f"{k}={_fmt(v)}" for k, v in scalars.items())
        log.info("step %d: %s", step, msg)
        if self._tb is not None:
            import tensorflow as tf  # noqa: PLC0415

            with self._tb.as_default():
                for k, v in scalars.items():
                    if isinstance(v, (int, float)):
                        tf.summary.scalar(k, v, step=step)
                self._tb.flush()
        metrics, phases, throughput = telemetry.split_metrics(scalars)
        self.telemetry.emit(
            kind,
            step=step,
            metrics=metrics or None,
            phases=phases or None,
            throughput=throughput or None,
            collectives=collectives,
        )

    def close(self) -> None:
        self.telemetry.close()


def _to_scalar(v: Any) -> Any:
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            return str(v)
    return v


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


class PercentileReservoir:
    """Streaming p50/p90/p99 over a bounded uniform sample (Vitter's
    algorithm R).

    The serving path (serve/engine.py) and the load generator
    (scripts/load_gen.py) both need tail-latency percentiles over an
    unbounded request stream without keeping every observation; a
    capacity-bounded reservoir holds a uniform random sample of the
    stream, so the nearest-rank percentile over the sample is an
    estimate of the stream percentile with O(capacity) memory. Under
    ``capacity`` observations the sample IS the stream and the
    percentiles are exact. Seeded — same stream, same sample — so SLO
    rollups are reproducible.

    Not thread-safe; callers serialize (the engine adds from its single
    batcher thread, the load generator under its results lock).
    """

    def __init__(self, capacity: int = 4096, *, seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._seed = seed
        self._rng = random.Random(seed)
        self._values: list[float] = []
        self._count = 0
        self._sum = 0.0

    @property
    def count(self) -> int:
        """Observations seen (not the retained sample size)."""
        return self._count

    def add(self, value: float) -> None:
        v = float(value)
        self._count += 1
        self._sum += v
        if len(self._values) < self.capacity:
            self._values.append(v)
        else:
            j = self._rng.randrange(self._count)
            if j < self.capacity:
                self._values[j] = v

    def percentile(self, p: float) -> float | None:
        """Nearest-rank percentile over the retained sample; None when
        empty. ``p`` is in [0, 100]."""
        if not 0.0 <= p <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {p}")
        if not self._values:
            return None
        ordered = sorted(self._values)
        rank = max(1, math.ceil(p / 100.0 * len(ordered)))
        return ordered[rank - 1]

    def summary(self) -> dict[str, Any]:
        """The SLO rollup shape the serve telemetry emits: count, mean,
        p50/p90/p99 (None when no observations)."""
        ordered = sorted(self._values)

        def at(p: float) -> float | None:
            if not ordered:
                return None
            return ordered[max(1, math.ceil(p / 100.0 * len(ordered))) - 1]

        return {
            "count": self._count,
            "mean": (self._sum / self._count) if self._count else None,
            "p50": at(50.0),
            "p90": at(90.0),
            "p99": at(99.0),
        }

    def reset(self) -> None:
        self._rng = random.Random(self._seed)
        self._values = []
        self._count = 0
        self._sum = 0.0


class ThroughputMeter:
    """Tracks examples/sec over a sliding window of steps.

    ``examples/sec/chip`` is the tracked BASELINE.json metric; the chip count
    is the global device count so multi-host numbers are comparable.
    """

    def __init__(self, num_chips: int):
        self.num_chips = max(1, num_chips)
        self._t0: float | None = None
        self._examples = 0

    def start(self) -> None:
        self._t0 = time.perf_counter()
        self._examples = 0

    def update(self, batch_examples: int) -> None:
        if self._t0 is None:
            self.start()
        self._examples += batch_examples

    def rates(self) -> dict[str, float]:
        if self._t0 is None or self._examples == 0:
            return {"examples_per_sec": 0.0, "examples_per_sec_per_chip": 0.0}
        dt = max(time.perf_counter() - self._t0, 1e-9)
        eps = self._examples / dt
        return {
            "examples_per_sec": eps,
            "examples_per_sec_per_chip": eps / self.num_chips,
        }

    def reset(self) -> None:
        self.start()
