"""Host-platform environment policy shared by the CLI and the test harness.

Pure string helpers only — this module must stay importable before JAX
initializes a backend (XLA_FLAGS is consumed at first backend init, so
callers mutate os.environ with these helpers first).
"""

from __future__ import annotations

# XLA:CPU aborts a collective whose participants don't all reach the
# rendezvous within ~40 s (`rendezvous.cc` termination timeout). On small
# hosts running N virtual devices (N threads time-sharing few cores) the
# default trips mid-training — observed repeatedly on 8-device MoE
# runs on a 1-core VM. These defaults keep transient scheduling stalls
# from aborting short runs; anything the user already put in XLA_FLAGS
# wins. KNOWN LIMIT: some long-run freezes are NOT transient — a
# participant blocks permanently at an all-reduce with zero CPU load
# (intermittent; reproduced with async AND sync infeed). For those, the
# working recipe is the opposite tuning: a LOW terminate timeout (e.g.
# 240 s) plus frequent checkpoints and a relaunch loop, so the
# framework's auto-restore turns each freeze into a bounded restart —
# fault recovery doing its job rather than a hang.
CPU_COLLECTIVE_TIMEOUT_FLAGS: tuple[tuple[str, int], ...] = (
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", 120),
    ("xla_cpu_collective_call_terminate_timeout_seconds", 1200),
)


FAST_FAIL_COLLECTIVE_FLAGS: tuple[tuple[str, int], ...] = (
    # The retry-loop tuning (scripts/train_resilient.py): fast death +
    # relaunch beats a 20-minute hang when auto-restore is standing by.
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", 60),
    ("xla_cpu_collective_call_terminate_timeout_seconds", 240),
)


def with_cpu_collective_timeouts(flags: str, table=None) -> str:
    """Append rendezvous-timeout flags to an XLA_FLAGS string, skipping
    any flag the caller already set. ``table`` defaults to the
    long-run-tolerant values; pass FAST_FAIL_COLLECTIVE_FLAGS for the
    relaunch-loop tuning."""
    for name, value in (table or CPU_COLLECTIVE_TIMEOUT_FLAGS):
        if name not in flags:
            flags += f" --{name}={value}"
    return flags.strip()
