"""Host-platform environment policy shared by the CLI and the test harness.

Pure string helpers only — this module must stay importable before JAX
initializes a backend (XLA_FLAGS is consumed at first backend init, so
callers mutate os.environ with these helpers first).
"""

from __future__ import annotations

# XLA:CPU aborts a collective whose participants don't all reach the
# rendezvous within ~40 s (`rendezvous.cc` termination timeout). On small
# hosts running N virtual devices (N threads time-sharing few cores) the
# default trips mid-training — observed repeatedly on 8-device MoE
# runs on a 1-core VM. These defaults keep transient scheduling stalls
# from aborting short runs; anything the user already put in XLA_FLAGS
# wins. KNOWN LIMIT: some long-run freezes are NOT transient — a
# participant blocks permanently at an all-reduce with zero CPU load
# (intermittent; reproduced with async AND sync infeed). For those, the
# working recipe is the opposite tuning: a LOW terminate timeout (e.g.
# 240 s) plus frequent checkpoints and a relaunch loop, so the
# framework's auto-restore turns each freeze into a bounded restart —
# fault recovery doing its job rather than a hang.
CPU_COLLECTIVE_TIMEOUT_FLAGS: tuple[tuple[str, int], ...] = (
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", 120),
    ("xla_cpu_collective_call_terminate_timeout_seconds", 1200),
)


FAST_FAIL_COLLECTIVE_FLAGS: tuple[tuple[str, int], ...] = (
    # The retry-loop tuning (scripts/train_resilient.py): fast death +
    # relaunch beats a 20-minute hang when auto-restore is standing by.
    ("xla_cpu_collective_call_warn_stuck_timeout_seconds", 60),
    ("xla_cpu_collective_call_terminate_timeout_seconds", 240),
)


def xla_flag_supported(name: str) -> bool:
    """Whether this jaxlib's XLA knows flag ``name``.

    XLA *hard-aborts the process* at first backend init on any unknown
    flag in XLA_FLAGS (``parse_flags_from_env.cc``) — observed killing
    every test in the suite when a jaxlib upgrade dropped the
    ``xla_cpu_collective_call_*`` timeout flags. Registered flag names
    are compiled into the xla_extension binary as plain strings, so a
    substring probe of the shared object is a reliable, cheap (mmap'd)
    check that never needs to initialize a backend. Unknown layouts
    (no .so found) fail open: the flag is assumed supported, matching
    the old unconditional behavior.

    The scan MUST be ``mmap.find`` (C memmem over the mapping): ``in``
    against an mmap falls back to byte-wise sequence iteration — ~10 s
    of interpreter time per probe on a 264 MiB binary, and never a
    match for a multi-byte needle. Results are memoized per process;
    supervisor relaunch loops call this on every start.
    """
    cached = _FLAG_SUPPORTED.get(name)
    if cached is None:
        blob = _xla_binary_flag_blob()
        if len(blob) == 0:  # no .so located: fail open
            cached = True
        else:
            cached = blob.find(name.encode()) >= 0
        _FLAG_SUPPORTED[name] = cached
    return cached


_FLAG_SUPPORTED: dict[str, bool] = {}


_XLA_BINARY_BLOB = None  # bytes | mmap.mmap once probed


def _xla_binary_flag_blob():
    global _XLA_BINARY_BLOB
    if _XLA_BINARY_BLOB is None:
        import mmap
        import pathlib

        blob = b""
        try:
            import jaxlib

            root = pathlib.Path(jaxlib.__file__).parent
            so = next(root.glob("**/xla_extension*.so"), None)
            if so is not None:
                with open(so, "rb") as fh:
                    # mmap: the binary is hundreds of MB; don't copy it.
                    blob = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        except Exception:
            blob = b""
        _XLA_BINARY_BLOB = blob
    return _XLA_BINARY_BLOB


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir`` so a
    relaunched process reuses the previous run's compiled executables
    instead of re-lowering + re-compiling the train step — the dominant
    share of restart → first-step latency (the ``startup`` telemetry
    event measures it; docs/PERFORMANCE.md has numbers).

    Must run before the first backend use (jax.config updates after
    compilation has started don't retroactively cache). Returns True when
    the cache was enabled, False when this jax build lacks the knobs (old
    releases) — callers log and continue uncached rather than fail.

    CAVEAT (why the config knob defaults off): executables that embed
    host callbacks — pallas INTERPRET-mode kernels on the CPU backend —
    SIGABRT when reloaded from cache in a fresh process (the serialized
    executable holds dead callback pointers; see pytest.ini). Real TPU
    backends compile pallas to Mosaic, which caches fine.
    """
    if not cache_dir:
        return False
    import os as _os

    import jax

    _os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except AttributeError:
        return False
    # Cache everything, immediately: the defaults skip "fast" compiles
    # (min time 1 s) and small programs, which on the CPU test backend is
    # most of them — useless for measuring the restart win.
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, value)
        except AttributeError:
            pass  # older jax: keep its defaults
    return True


def with_cpu_collective_timeouts(flags: str, table=None) -> str:
    """Append rendezvous-timeout flags to an XLA_FLAGS string, skipping
    any flag the caller already set and any flag this jaxlib's XLA does
    not register (an unknown flag aborts the process — see
    ``xla_flag_supported``). ``table`` defaults to the
    long-run-tolerant values; pass FAST_FAIL_COLLECTIVE_FLAGS for the
    relaunch-loop tuning."""
    for name, value in (table or CPU_COLLECTIVE_TIMEOUT_FLAGS):
        if name not in flags and xla_flag_supported(name):
            flags += f" --{name}={value}"
    return flags.strip()
