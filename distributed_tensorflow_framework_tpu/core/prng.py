"""PRNG discipline — the single source of randomness derivation.

The reference seeds ``tf.set_random_seed`` globally and relies on per-op
graph seeds (SURVEY.md §4 "input-pipeline determinism"). Here every
random stream derives from the experiment seed through ONE of two
documented paths:

**Device side** (jax keys; traced inside jit):

  root key (experiment seed)
    ├─ for_role(ROLE_INIT / ROLE_DROPOUT)   per subsystem
    └─ fold_in_step(step)                    per training step

Device-side keys are never host-dependent so the SPMD program is
identical on every host.

**Host side** (numpy generators; data pipelines): ``host_rng(seed, role,
*context)`` seeds ``np.random.default_rng`` with the full derivation
tuple. Context integers are stream coordinates (epoch, batch index,
process index). Rules:

  * include ``process_index`` iff the stream is host-local (per-host
    synthetic data, per-example augmentation) — NEVER for decisions that
    must agree across hosts (the epoch shuffle permutation all hosts
    stride-index into);
  * include the batch/epoch counters the resume snapshot records, so a
    restored pipeline re-derives identical randomness (resume exactness,
    SURVEY.md §7 hard part 3).
"""

from __future__ import annotations

import jax
import numpy as np

ROLE_INIT = 0     # parameter init (device)
ROLE_DROPOUT = 1  # dropout / stochastic layers (device)
ROLE_DATA = 2     # data stream content + order (host)
ROLE_MASK = 3     # MLM dynamic masking (host)
ROLE_AUGMENT = 4  # per-example augmentation (host)


def make_root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def for_role(root: jax.Array, role: int) -> jax.Array:
    return jax.random.fold_in(root, role)


def fold_in_step(key: jax.Array, step) -> jax.Array:
    return jax.random.fold_in(key, step)


def host_rng(seed: int, role: int, *context: int) -> np.random.Generator:
    """Host-side generator for data pipelines (see module docstring)."""
    return np.random.default_rng((seed, role, *context))
