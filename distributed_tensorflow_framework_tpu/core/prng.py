"""PRNG discipline.

The reference seeds ``tf.set_random_seed`` globally and relies on per-op
graph seeds (SURVEY.md §4 "input-pipeline determinism"). JAX keys are
explicit; the framework's discipline is:

  root key (experiment seed)
    ├─ fold_in(ROLE_*)            per subsystem (init / dropout / data)
    ├─ fold_in(step)              per training step
    └─ fold_in(process_index)     only for host-local streams (data feed)

Device-side keys are never host-dependent so that the SPMD program is
identical on every host.
"""

from __future__ import annotations

import jax

ROLE_INIT = 0
ROLE_DROPOUT = 1
ROLE_DATA = 2
ROLE_MASK = 3  # MLM masking


def make_root_key(seed: int) -> jax.Array:
    return jax.random.key(seed)


def for_role(root: jax.Array, role: int) -> jax.Array:
    return jax.random.fold_in(root, role)


def fold_in_step(key: jax.Array, step) -> jax.Array:
    return jax.random.fold_in(key, step)


def split_for_hosts(key: jax.Array, process_index: int) -> jax.Array:
    """Host-local stream (data pipelines only — never device compute)."""
    return jax.random.fold_in(key, process_index)
