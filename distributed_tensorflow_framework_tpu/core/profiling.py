"""Tracing / profiling (SURVEY.md §5 "Tracing / profiling").

The reference's option here is TF1 ``tf.RunMetadata`` + timeline JSON /
``tf.profiler``; the TPU-native equivalents are XPlane traces viewable in
TensorBoard/Perfetto plus lightweight step annotations:

  * ``trace(logdir)``       — context manager around a window of steps
                              (``jax.profiler.start_trace``/``stop_trace``);
                              bench.py wraps its timed window in it
  * ``annotate(name)``      — named region inside a traced window
                              (``jax.profiler.TraceAnnotation``); the
                              Trainer annotates every ``train_step`` dispatch
  * ``StepTimer``           — host-side per-phase wall timing (infeed /
                              dispatch / metrics_fetch), reported as
                              ``time_*_ms`` in the Trainer's logged metrics

Step-window traces during training: ``--set train.profile_start=N
--set train.profile_stop=M`` via ProfileHook (train/hooks.py).
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture an XPlane trace for everything inside the block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region visible in the trace viewer."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Accumulates host-side wall time per named phase."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def means(self) -> dict[str, float]:
        return {
            f"time_{k}_ms": 1000.0 * v / max(self.counts[k], 1)
            for k, v in self.totals.items()
        }

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()
