"""Roofline model: chip peaks, ridge points, and the step-time predictor.

Factored out of bench.py so the bound verdict and the analytic step-time
lower bound are one implementation shared by the bench (annotating
measured rows) and the autotuner (pruning candidate configs BEFORE
spending a chip run — tools/autotune). Stdlib-only: nothing here touches
jax, so the CPU-side tuner harness can import it without a backend.

The model is the classic two-resource roofline. A step that must move
``B`` bytes through HBM and execute ``F`` flops cannot finish faster
than ``max(F / peak_flops, B / hbm_bw)`` per chip; whichever term is
larger is the *binding resource* ("compute" vs "hbm_bandwidth"), and the
crossover sits at the ridge point ``peak_flops / hbm_bw`` (v5e:
197e12 / 819e9 ≈ 240 FLOP/byte — PERF_NOTES.md round 2 measured the
ResNet-50 step at 78.7 FLOP/byte, firmly HBM-bound).

Traffic inputs come from the artifacts the repo already measures
(docs/PERFORMANCE.md "The bench as the measurement instrument"):
the compiled step's ``memory_analysis`` footprint (argument + output +
temp bytes), the CollectiveTally's wire bytes, and
``opt_state_bytes_per_chip`` — see :func:`traffic_bytes`.
"""

from __future__ import annotations

import dataclasses
import os

GIB = 1024 ** 3

# device_kind → (peak bf16 FLOP/s, HBM bytes/s, HBM capacity bytes/chip).
# Public spec-sheet numbers.
CHIP_PEAKS: dict[str, tuple[float, float, float]] = {
    "TPU v2": (45e12, 700e9, 8 * GIB),
    "TPU v3": (123e12, 900e9, 16 * GIB),
    "TPU v4": (275e12, 1228e9, 32 * GIB),
    "TPU v5 lite": (197e12, 819e9, 16 * GIB),   # v5e
    "TPU v5e": (197e12, 819e9, 16 * GIB),
    "TPU v5p": (459e12, 2765e9, 95 * GIB),
    "TPU v6 lite": (918e12, 1640e9, 32 * GIB),  # v6e / Trillium
    "TPU v6e": (918e12, 1640e9, 32 * GIB),
}

# Ridge-point fallback for backends absent from CHIP_PEAKS (the CPU
# harness): the bound verdict is about the PROGRAM's position relative
# to a roofline, and the v5e ridge (peak_flops/hbm_bw ≈ 240 flops/byte,
# the fleet's deploy target) is the reference every row is read against
# — tagged with bound_ridge_source so a fallback verdict is never
# mistaken for a measured-chip one.
RIDGE_FALLBACK_CHIP = "TPU v5e"


def chip_hbm_capacity(chip: str) -> float | None:
    """Per-chip HBM capacity, or host RAM when the chip isn't in the
    table (the CPU backend: headroom against physical memory is still a
    meaningful ceiling for the compiled step's working set)."""
    peak = CHIP_PEAKS.get(chip)
    if peak:
        return peak[2]
    try:
        return float(os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE"))
    except (ValueError, OSError, AttributeError):
        return None


def ridge_point(chip: str) -> tuple[float, str] | None:
    """(ridge FLOP/byte, source chip) for ``chip``, falling back to the
    RIDGE_FALLBACK_CHIP reference when the chip isn't in CHIP_PEAKS.
    Returns None only if the fallback itself were removed from the table."""
    source = chip if chip in CHIP_PEAKS else RIDGE_FALLBACK_CHIP
    peak = CHIP_PEAKS.get(source)
    if not peak:
        return None
    peak_flops, hbm_bw = peak[:2]
    return peak_flops / hbm_bw, source


def traffic_bytes(memory_analysis: dict | None, wire_bytes: float = 0.0,
                  opt_state_bytes: float = 0.0) -> float:
    """HBM + interconnect bytes/step from the measured artifacts.

    ``memory_analysis`` is the compiled step's cost breakdown
    (core/memstats.compiled_memory_analysis): argument + output + temp
    bytes is the compiled footprint one execution streams. ``wire_bytes``
    is the CollectiveTally grand total for the step. ``opt_state_bytes``
    (bench's opt_state_bytes_per_chip) covers callers whose footprint was
    taken on a forward/backward program only — a compiled WHOLE step
    already carries the optimizer state in its argument bytes, so pass 0
    there or the state is counted twice.
    """
    analysis = memory_analysis or {}
    footprint = sum(int(analysis.get(f) or 0) for f in
                    ("argument_bytes", "output_bytes", "temp_bytes"))
    return float(footprint) + float(wire_bytes) + float(opt_state_bytes)


@dataclasses.dataclass
class RooflinePrediction:
    """Analytic step-time lower bound and the binding-resource verdict.

    ``sec_per_step`` is ``max(sec_compute, sec_hbm)`` — the roofline
    says the step can't beat the slower resource. ``bound`` names that
    resource; ``ridge_source`` records which chip's ridge judged it
    (``"<chip> (fallback)"`` when CHIP_PEAKS had no entry for the chip,
    mirroring bench.py's bound_ridge_source tag).
    """

    chip: str
    flops_per_step: float
    bytes_per_step: float
    intensity: float | None
    ridge: float
    ridge_source: str
    sec_compute: float
    sec_hbm: float
    sec_per_step: float
    bound: str


def predict(chip: str, flops_per_step: float, bytes_per_step: float,
            n_chips: int = 1) -> RooflinePrediction:
    """Predict the per-step time floor for a program on ``chip``.

    Inputs are WHOLE-program flops and bytes (use :func:`traffic_bytes`
    to assemble bytes from footprint + wire + opt state); the work is
    assumed evenly divided across ``n_chips``. Unknown chips are judged
    against the RIDGE_FALLBACK_CHIP roofline and tagged.
    """
    n = max(1, int(n_chips))
    source = chip if chip in CHIP_PEAKS else RIDGE_FALLBACK_CHIP
    peak_flops, hbm_bw = CHIP_PEAKS[source][:2]
    ridge = peak_flops / hbm_bw
    sec_compute = flops_per_step / n / peak_flops
    sec_hbm = bytes_per_step / n / hbm_bw
    intensity = (flops_per_step / bytes_per_step) if bytes_per_step else None
    if intensity is not None:
        bound = "hbm_bandwidth" if intensity < ridge else "compute"
    else:
        bound = "compute"
    ridge_source = source if source == chip else f"{source} (fallback)"
    return RooflinePrediction(
        chip=chip, flops_per_step=float(flops_per_step),
        bytes_per_step=float(bytes_per_step), intensity=intensity,
        ridge=ridge, ridge_source=ridge_source, sec_compute=sec_compute,
        sec_hbm=sec_hbm, sec_per_step=max(sec_compute, sec_hbm),
        bound=bound)


def annotate_roofline(out: dict, result: dict, chip: str, n_chips: int,
                      *, accum_scaled: bool = False) -> None:
    """Achieved TFLOP/s, MFU, arithmetic intensity and the bottleneck
    verdict from the XLA cost model + public chip peaks (the bench row
    annotator, moved here from bench.py so the tuner's predictor and the
    bench's measured verdict share one ridge).

    Two intensity numbers ride every row that can compute them:
    ``arith_intensity`` (cost-model flops / cost-model bytes accessed —
    counts every HBM touch, fusion-aware) and ``ai_flops_per_byte``
    (cost-model flops / (memory_analysis arg+out+temp footprint + the
    CollectiveTally's wire bytes)). The second is the one the precision
    levers move: activation-width and fused-update changes shrink the
    compiled footprint and the wire, so the ratio climbing toward the
    ridge is the "flipping the bound" claim in one column
    (docs/PERFORMANCE.md).

    ``accum_scaled``: the flops/bytes were multiplied by the accum trip
    count (bench_bert) and the once-per-step optimizer traffic got scaled
    with them, so hbm_bw_util is an UPPER bound and arith_intensity a
    LOWER bound. Tag the output so accum and non-accum artifacts are not
    read as directly comparable roofline positions.
    """
    peak = CHIP_PEAKS.get(chip)
    if not result["flops_per_step"]:
        return
    if accum_scaled:
        out["roofline_bound"] = "accum-scaled-upper"
    achieved = result["flops_per_step"] / result["sec_per_step"] / n_chips
    out["tflops_per_sec"] = round(achieved / 1e12, 2)
    intensity = None
    if result["bytes_per_step"]:
        intensity = result["flops_per_step"] / result["bytes_per_step"]
        out["arith_intensity"] = round(intensity, 1)
    wire = (result.get("collectives") or {}).get("total_bytes") or 0
    ai = None
    footprint_plus_wire = traffic_bytes(
        (result.get("memory") or {}).get("analysis"), wire)
    if footprint_plus_wire > wire:  # a footprint was actually present
        ai = result["flops_per_step"] / footprint_plus_wire
        out["ai_flops_per_byte"] = round(ai, 1)
    if peak:
        peak_flops, hbm_bw = peak[:2]
        out["mfu"] = round(achieved / peak_flops, 4)
        if intensity is not None:
            ridge = peak_flops / hbm_bw
            out["bound"] = "hbm_bandwidth" if intensity < ridge else "compute"
            # Fraction of peak HBM bandwidth actually sustained.
            out["hbm_bw_util"] = round(
                result["bytes_per_step"] / result["sec_per_step"]
                / n_chips / hbm_bw, 4,
            )
    if "bound" not in out:
        # Every row carries a verdict: on unknown backends (or when the
        # cost model's byte count is absent) fall back to the reference
        # ridge and the best intensity available, tagged as a fallback.
        best = intensity if intensity is not None else ai
        if best is not None:
            ref = ridge_point("")  # forces the fallback reference
            if ref is not None:
                ridge, source = ref
                out["bound"] = ("hbm_bandwidth" if best < ridge
                                else "compute")
                out["bound_ridge_source"] = f"{source} (fallback)"
