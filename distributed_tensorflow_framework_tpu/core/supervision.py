"""Self-healing supervision primitives: preemption, backoff, crash-loop.

The policy half of the resilience subsystem (docs/RESILIENCE.md). The
mechanisms live where the state lives — heartbeats in train/hooks.py,
integrity manifests in ckpt/manifest.py, fault injection in core/faults.py
— while this module holds the pure decision logic the supervisor
(scripts/train_resilient.py) and the trainer share:

  * the graceful-preemption contract: a SIGTERM'd trainer finishes its
    in-flight step, saves a checkpoint, and exits ``GRACEFUL_PREEMPT_RC``
    so the supervisor relaunches immediately without consuming an attempt
    (preemption is scheduling, not failure);
  * exponential backoff with jitter between relaunches (TF-Replicator-style
    supervised workers: a crashing fleet must not relaunch in lockstep);
  * the crash-loop breaker: a deterministic crash (same exit, same step,
    no checkpoint progress, attempt after attempt) is a bug, and retrying
    a bug converts one failure into ``max_attempts`` identical failures —
    stop instead, with a structured report;
  * heartbeat staleness reading, pid-scoped so a relaunched child is never
    condemned by its predecessor's stale file;
  * the elastic-reshard contract: a child that finds the visible device
    set no longer matches its configured mesh exits
    ``ELASTIC_RESHARD_RC`` after writing a device report; the supervisor
    fits the largest valid mesh onto what remains (``fit_axis_sizes``),
    re-scales batch/grad-accum so the effective batch is preserved
    (``rescale_for_devices``) and relaunches — losing a slice is
    scheduling, not failure (docs/RESILIENCE.md "losing a slice").

Stdlib-only so the supervisor's decision loop is unit-testable without a
device runtime.
"""

from __future__ import annotations

import json
import logging
import os
import random
import signal
import time

log = logging.getLogger(__name__)

# Exit code the trainer uses for "SIGTERM honored: step finished, checkpoint
# saved, relaunch me whenever". Distinct from 143 (SIGTERM death = operator
# cancellation, never relaunched) and from any shell 128+N signal code.
GRACEFUL_PREEMPT_RC = 83

# Exit code for "the in-process recovery ladder gave up": the anomaly
# detector fired max_rollbacks consecutive times and every in-memory
# rollback landed back on a bad step (train/anomaly.py). Distinct from a
# plain crash so the supervisor can classify it — a persistent anomaly
# (e.g. a poisoned data region) usually clears on relaunch because the
# restored checkpoint + skipped batches take a different path through the
# data, so it must not feed the crash-loop breaker's deterministic-bug
# streak.
ANOMALY_ESCALATION_RC = 85

# Exit code for "the visible device set no longer matches the configured
# mesh": the trainer could not even build its mesh because devices
# disappeared (or came back) between attempts. Distinct from a crash so
# the supervisor can classify it as a TOPOLOGY change — it refits the mesh
# (fit_axis_sizes), rewrites the child's config and relaunches without
# feeding the crash-loop breaker or consuming an attempt: losing a slice
# is infrastructure scheduling, exactly like graceful preemption.
ELASTIC_RESHARD_RC = 84

# Mirror of core/mesh.MESH_AXES (that module imports jax; this one must
# stay stdlib-importable for the supervisor). test_reshard.py pins the two
# tuples equal so they cannot drift.
MESH_AXIS_ORDER = ("data", "fsdp", "expert", "pipe", "seq", "model")

# Filename of the device report an rc-84 child leaves in the checkpoint
# directory (cli/train.py) — the supervisor's per-attempt probe of the
# visible device set, readable without importing jax.
DEVICE_REPORT_NAME = "devices.json"

# Env var carrying the supervisor's refit to the relaunched child as
# comma-separated ``key.path=value`` config overrides (applied by
# cli/train.py AFTER its own --set overrides, so the refit wins even when
# the child command line hardcodes mesh sizes).
ELASTIC_OVERRIDES_ENV = "DTF_ELASTIC_OVERRIDES"

_preempt_requested = False
_handler_installed = False


def preemption_requested() -> bool:
    return _preempt_requested


def reset_preemption() -> None:
    """Clear the flag (tests; also a relaunch-in-process harness)."""
    global _preempt_requested
    _preempt_requested = False


def install_sigterm_handler() -> bool:
    """Arm graceful preemption: the first SIGTERM sets a flag the train
    loop polls at step boundaries; a second SIGTERM restores the default
    disposition so a stuck shutdown can still be killed with plain TERM.
    Returns False (and arms nothing) outside the main thread or where
    SIGTERM does not exist — callers proceed without graceful handling.
    """
    global _handler_installed
    if _handler_installed:
        return True

    def _on_sigterm(signum, frame):
        global _preempt_requested
        if _preempt_requested:  # second TERM: operator means it
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        _preempt_requested = True
        log.warning(
            "SIGTERM received — graceful preemption armed: finishing the "
            "in-flight step, saving a checkpoint, exiting rc=%d",
            GRACEFUL_PREEMPT_RC,
        )

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, AttributeError, OSError):
        return False
    _handler_installed = True
    return True


def backoff_seconds(
    failure_index: int,
    *,
    base: float = 5.0,
    cap: float = 120.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
) -> float:
    """Sleep before relaunch ``failure_index`` (1-based): capped exponential
    ``base * 2^(i-1)`` with ±``jitter`` fractional randomization."""
    if base <= 0:
        return 0.0
    delay = min(cap, base * (2.0 ** max(0, failure_index - 1)))
    if jitter > 0:
        r = rng or random
        delay *= 1.0 + r.uniform(-jitter, jitter)
    return max(0.0, delay)


def read_heartbeat(path: str) -> dict | None:
    """The heartbeat record, or None when absent/torn. Writers commit via
    atomic rename (train/hooks.HeartbeatHook), so a partial read here means
    a non-conforming writer — treated as no heartbeat."""
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    return record if isinstance(record, dict) else None


def heartbeat_age_s(
    path: str, *, pid: int | None = None, now: float | None = None
) -> float | None:
    """Seconds since the child's last heartbeat, or None when no heartbeat
    from that child exists yet. ``pid`` scopes the check to the CURRENT
    child: a predecessor's leftover file reads as "no heartbeat yet", not
    as instant staleness."""
    record = read_heartbeat(path)
    if record is None:
        return None
    if pid is not None and record.get("pid") not in (None, pid):
        return None
    t = record.get("t")
    if not isinstance(t, (int, float)):
        try:
            t = os.path.getmtime(path)
        except OSError:
            return None
    return max(0.0, (time.time() if now is None else now) - float(t))


# -- elastic resharding (rc 84) -------------------------------------------
def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def fit_axis_sizes(sizes: dict[str, int], n_devices: int) -> dict[str, int]:
    """Largest valid mesh over ``n_devices`` preserving axis order and
    divisibility.

    Every non-``data`` axis keeps its original size or shrinks to a
    divisor of it (a ``pipe:4`` stage split or ``fsdp`` shard count that
    divided the model still divides it), while ``data`` absorbs whatever
    remains — it may shrink OR grow, matching its "all remaining devices"
    semantics. All ``n_devices`` are always used (the all-ones fallback
    makes ``data = n`` feasible for any n). Among feasible meshes the one
    keeping the most non-data structure wins: maximize the non-data
    product, tie-break toward preserving the innermost axes (model-ward),
    whose sizes are baked into the model config (tensor-parallel degree,
    pipeline stages).
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    sizes = {a: (1 if v == -1 else int(v)) for a, v in sizes.items()}
    for a, v in sizes.items():
        if v < 1:
            raise ValueError(f"axis {a!r} has invalid size {v}")
    non_data = [a for a in MESH_AXIS_ORDER if a != "data" and a in sizes]
    best: tuple | None = None
    best_fit: dict[str, int] | None = None

    def search(i: int, chosen: dict[str, int], prod: int) -> None:
        nonlocal best, best_fit
        if i == len(non_data):
            if n_devices % prod:
                return
            fit = dict(sizes)
            fit.update(chosen)
            if "data" in sizes:
                fit["data"] = n_devices // prod
            elif prod != n_devices:
                return
            # Innermost-first preference: reversed MESH_AXIS_ORDER puts
            # model/seq sizes earliest in the tie-break tuple.
            key = (prod, tuple(chosen[a] for a in reversed(non_data)))
            if best is None or key > best:
                best, best_fit = key, fit
            return
        axis = non_data[i]
        for d in _divisors(sizes[axis]):
            if prod * d <= n_devices:
                search(i + 1, {**chosen, axis: d}, prod * d)

    search(0, {}, 1)
    if best_fit is None:
        raise ValueError(
            f"no mesh over {n_devices} devices fits axis sizes {sizes} "
            f"(non-data axes cannot shrink to a divisor combination "
            f"dividing {n_devices})"
        )
    return best_fit


def rescale_for_devices(
    global_batch: int, grad_accum: int, old_dp: int, new_dp: int
) -> tuple[int, int, bool]:
    """(new_global_batch, new_grad_accum, effective_preserved) for a
    data-parallel resize ``old_dp -> new_dp``.

    Policy: keep the PER-DEVICE batch constant (the shrunken mesh must not
    OOM; the grown mesh should not under-fill) and move the difference
    into grad accumulation, so the effective batch
    ``global_batch * grad_accum`` — and with it the LR schedule — is
    unchanged. When the per-device-preserving rescale is not integral,
    fall back to keeping ``global_batch`` (effective batch still
    preserved, per-device size changes); when even that is not divisible
    by ``new_dp``, return the inputs unchanged with ``False`` — the
    caller warns and lets config validation decide.
    """
    if old_dp == new_dp or old_dp < 1 or new_dp < 1:
        return global_batch, grad_accum, old_dp == new_dp
    if global_batch % old_dp == 0 and (grad_accum * old_dp) % new_dp == 0:
        return (global_batch * new_dp // old_dp,
                grad_accum * old_dp // new_dp, True)
    if global_batch % new_dp == 0:
        return global_batch, grad_accum, True
    return global_batch, grad_accum, False


def write_device_report(ckpt_dir: str, *, visible_devices: int,
                        needed: int, mesh: dict) -> str:
    """Commit the rc-84 child's device report (atomic rename, so the
    supervisor never reads a torn one). Creates the directory if the run
    died before its first checkpoint."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(ckpt_dir, DEVICE_REPORT_NAME)
    record = {
        "visible_devices": int(visible_devices),
        "needed": int(needed),
        "mesh": {a: int(v) for a, v in (mesh or {}).items()},
        "t": time.time(),
        "pid": os.getpid(),
    }
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(record, fh)
    os.replace(tmp, path)
    return path


def read_device_report(ckpt_dir: str) -> dict | None:
    """The rc-84 child's device report, or None when absent/torn."""
    try:
        with open(os.path.join(ckpt_dir, DEVICE_REPORT_NAME)) as fh:
            report = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    return report if isinstance(report, dict) else None


def mask_host_device_count(xla_flags: str, n: int) -> str:
    """XLA_FLAGS with the virtual-CPU device count forced to ``n`` — how
    the ``drop_devices`` fault makes a CPU drill lose a slice (on real
    TPUs devices drop by themselves; this is the injectable stand-in)."""
    import re

    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in xla_flags:
        return re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, xla_flags)
    return (xla_flags + " " + flag).strip()


class CrashLoopBreaker:
    """Distinguish deterministic crashes from transient infrastructure.

    Each failed attempt is recorded with its exit code, the child's last
    completed step (heartbeat) and the newest committed checkpoint step.
    ``threshold`` consecutive attempts with the SAME signature and NO
    progress on either step counter trip the breaker: the crash will
    reproduce forever, so the supervisor must stop and report instead of
    burning the attempt budget. Any progress — a new checkpoint, a further
    step, a different exit code — resets the streak (transient faults move
    the run forward between failures). Hangs killed by the watchdog are
    always transient (``hung=True``): a timeout depends on machine load,
    not on the program text.
    """

    def __init__(self, threshold: int = 2):
        self.threshold = max(0, threshold)
        self._streak = 0
        self._last: tuple | None = None
        self.history: list[dict] = []

    def record(
        self,
        *,
        rc: int,
        last_step: int | None,
        ckpt_step: int | None,
        hung: bool = False,
        transient: bool = False,
    ) -> bool:
        """Register one failed attempt; True = stop retrying. ``transient``
        marks a failure class that never feeds the deterministic-crash
        streak (like ``hung``) — e.g. ANOMALY_ESCALATION_RC, where the
        relaunch resumes past the data region that caused it."""
        signature = (rc, last_step, ckpt_step)
        if hung or transient or self.threshold == 0:
            self._streak, self._last = 0, None
        elif signature == self._last:
            self._streak += 1
        else:
            self._streak, self._last = 1, signature
        self.history.append({
            "rc": rc,
            "last_step": last_step,
            "ckpt_step": ckpt_step,
            "hung": hung,
            "transient": transient,
            "streak": self._streak,
        })
        return self.threshold > 0 and self._streak >= self.threshold

    def report(self) -> dict:
        """Structured post-mortem for the operator / telemetry stream."""
        last = self.history[-1] if self.history else {}
        return {
            "verdict": "deterministic_crash_loop",
            "streak": self._streak,
            "threshold": self.threshold,
            "rc": last.get("rc"),
            "last_step": last.get("last_step"),
            "ckpt_step": last.get("ckpt_step"),
            "attempts_recorded": len(self.history),
            "hint": (
                "the same failure reproduced at the same step with no "
                "checkpoint progress — relaunching cannot fix it; inspect "
                "the child's last log/telemetry (and any DTF_FAULTS spec) "
                "before retrying"
            ),
        }
