"""Self-healing supervision primitives: preemption, backoff, crash-loop.

The policy half of the resilience subsystem (docs/RESILIENCE.md). The
mechanisms live where the state lives — heartbeats in train/hooks.py,
integrity manifests in ckpt/manifest.py, fault injection in core/faults.py
— while this module holds the pure decision logic the supervisor
(scripts/train_resilient.py) and the trainer share:

  * the graceful-preemption contract: a SIGTERM'd trainer finishes its
    in-flight step, saves a checkpoint, and exits ``GRACEFUL_PREEMPT_RC``
    so the supervisor relaunches immediately without consuming an attempt
    (preemption is scheduling, not failure);
  * exponential backoff with jitter between relaunches (TF-Replicator-style
    supervised workers: a crashing fleet must not relaunch in lockstep);
  * the crash-loop breaker: a deterministic crash (same exit, same step,
    no checkpoint progress, attempt after attempt) is a bug, and retrying
    a bug converts one failure into ``max_attempts`` identical failures —
    stop instead, with a structured report;
  * heartbeat staleness reading, pid-scoped so a relaunched child is never
    condemned by its predecessor's stale file.

Stdlib-only so the supervisor's decision loop is unit-testable without a
device runtime.
"""

from __future__ import annotations

import json
import logging
import os
import random
import signal
import time

log = logging.getLogger(__name__)

# Exit code the trainer uses for "SIGTERM honored: step finished, checkpoint
# saved, relaunch me whenever". Distinct from 143 (SIGTERM death = operator
# cancellation, never relaunched) and from any shell 128+N signal code.
GRACEFUL_PREEMPT_RC = 83

# Exit code for "the in-process recovery ladder gave up": the anomaly
# detector fired max_rollbacks consecutive times and every in-memory
# rollback landed back on a bad step (train/anomaly.py). Distinct from a
# plain crash so the supervisor can classify it — a persistent anomaly
# (e.g. a poisoned data region) usually clears on relaunch because the
# restored checkpoint + skipped batches take a different path through the
# data, so it must not feed the crash-loop breaker's deterministic-bug
# streak.
ANOMALY_ESCALATION_RC = 85

_preempt_requested = False
_handler_installed = False


def preemption_requested() -> bool:
    return _preempt_requested


def reset_preemption() -> None:
    """Clear the flag (tests; also a relaunch-in-process harness)."""
    global _preempt_requested
    _preempt_requested = False


def install_sigterm_handler() -> bool:
    """Arm graceful preemption: the first SIGTERM sets a flag the train
    loop polls at step boundaries; a second SIGTERM restores the default
    disposition so a stuck shutdown can still be killed with plain TERM.
    Returns False (and arms nothing) outside the main thread or where
    SIGTERM does not exist — callers proceed without graceful handling.
    """
    global _handler_installed
    if _handler_installed:
        return True

    def _on_sigterm(signum, frame):
        global _preempt_requested
        if _preempt_requested:  # second TERM: operator means it
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        _preempt_requested = True
        log.warning(
            "SIGTERM received — graceful preemption armed: finishing the "
            "in-flight step, saving a checkpoint, exiting rc=%d",
            GRACEFUL_PREEMPT_RC,
        )

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, AttributeError, OSError):
        return False
    _handler_installed = True
    return True


def backoff_seconds(
    failure_index: int,
    *,
    base: float = 5.0,
    cap: float = 120.0,
    jitter: float = 0.5,
    rng: random.Random | None = None,
) -> float:
    """Sleep before relaunch ``failure_index`` (1-based): capped exponential
    ``base * 2^(i-1)`` with ±``jitter`` fractional randomization."""
    if base <= 0:
        return 0.0
    delay = min(cap, base * (2.0 ** max(0, failure_index - 1)))
    if jitter > 0:
        r = rng or random
        delay *= 1.0 + r.uniform(-jitter, jitter)
    return max(0.0, delay)


def read_heartbeat(path: str) -> dict | None:
    """The heartbeat record, or None when absent/torn. Writers commit via
    atomic rename (train/hooks.HeartbeatHook), so a partial read here means
    a non-conforming writer — treated as no heartbeat."""
    try:
        with open(path) as fh:
            record = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    return record if isinstance(record, dict) else None


def heartbeat_age_s(
    path: str, *, pid: int | None = None, now: float | None = None
) -> float | None:
    """Seconds since the child's last heartbeat, or None when no heartbeat
    from that child exists yet. ``pid`` scopes the check to the CURRENT
    child: a predecessor's leftover file reads as "no heartbeat yet", not
    as instant staleness."""
    record = read_heartbeat(path)
    if record is None:
        return None
    if pid is not None and record.get("pid") not in (None, pid):
        return None
    t = record.get("t")
    if not isinstance(t, (int, float)):
        try:
            t = os.path.getmtime(path)
        except OSError:
            return None
    return max(0.0, (time.time() if now is None else now) - float(t))


class CrashLoopBreaker:
    """Distinguish deterministic crashes from transient infrastructure.

    Each failed attempt is recorded with its exit code, the child's last
    completed step (heartbeat) and the newest committed checkpoint step.
    ``threshold`` consecutive attempts with the SAME signature and NO
    progress on either step counter trip the breaker: the crash will
    reproduce forever, so the supervisor must stop and report instead of
    burning the attempt budget. Any progress — a new checkpoint, a further
    step, a different exit code — resets the streak (transient faults move
    the run forward between failures). Hangs killed by the watchdog are
    always transient (``hung=True``): a timeout depends on machine load,
    not on the program text.
    """

    def __init__(self, threshold: int = 2):
        self.threshold = max(0, threshold)
        self._streak = 0
        self._last: tuple | None = None
        self.history: list[dict] = []

    def record(
        self,
        *,
        rc: int,
        last_step: int | None,
        ckpt_step: int | None,
        hung: bool = False,
        transient: bool = False,
    ) -> bool:
        """Register one failed attempt; True = stop retrying. ``transient``
        marks a failure class that never feeds the deterministic-crash
        streak (like ``hung``) — e.g. ANOMALY_ESCALATION_RC, where the
        relaunch resumes past the data region that caused it."""
        signature = (rc, last_step, ckpt_step)
        if hung or transient or self.threshold == 0:
            self._streak, self._last = 0, None
        elif signature == self._last:
            self._streak += 1
        else:
            self._streak, self._last = 1, signature
        self.history.append({
            "rc": rc,
            "last_step": last_step,
            "ckpt_step": ckpt_step,
            "hung": hung,
            "transient": transient,
            "streak": self._streak,
        })
        return self.threshold > 0 and self._streak >= self.threshold

    def report(self) -> dict:
        """Structured post-mortem for the operator / telemetry stream."""
        last = self.history[-1] if self.history else {}
        return {
            "verdict": "deterministic_crash_loop",
            "streak": self._streak,
            "threshold": self.threshold,
            "rc": last.get("rc"),
            "last_step": last.get("last_step"),
            "ckpt_step": last.get("ckpt_step"),
            "attempts_recorded": len(self.history),
            "hint": (
                "the same failure reproduced at the same step with no "
                "checkpoint progress — relaunching cannot fix it; inspect "
                "the child's last log/telemetry (and any DTF_FAULTS spec) "
                "before retrying"
            ),
        }
