"""Structured per-step telemetry: the ONE event schema every emitter uses.

Three consecutive rounds shipped BENCH artifacts whose real numbers lived
in side logs (VERDICT r5 items 2-4): the framework could *measure* but not
*record* in a machine-readable, cross-referenceable way. This module fixes
the recording half: a versioned JSONL event record that merges

  * ``StepTimer`` phase timings        (core/profiling.py, ``time_*_ms``)
  * ``ThroughputMeter`` rates          (core/metrics.py)
  * XLA cost-model roofline fields     (bench.py MFU/intensity/bound)
  * per-collective byte counters       (parallel/collectives.tally)

into one record shape shared by the Trainer (train/loop.py), ``cli/train``
and ``bench.py``. Artifacts from all three carry the same ``run_id`` so a
BENCH json line, a training log and a trace summary for the same run are
joinable by ``(run_id, step)`` — see docs/OBSERVABILITY.md.

Schema stability contract: ``SCHEMA`` names the record layout and bumps on
any breaking change; readers MUST check it (``read_events`` does). Unknown
*extra* keys are allowed (forward compatible); the reserved top-level keys
below are versioned.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
import uuid
from typing import Any, Iterator, Mapping

log = logging.getLogger("dtf_tpu.telemetry")

SCHEMA_VERSION = 1
SCHEMA = f"dtf-telemetry/{SCHEMA_VERSION}"

# Reserved top-level fields of every event record. Everything else rides
# in ``extra`` (emit(**extra)) so schema checks stay meaningful.
RESERVED_FIELDS = (
    "schema", "run_id", "kind", "t", "step", "metrics", "phases",
    "throughput", "roofline", "collectives", "health", "extra",
)

# Event kinds emitted by the framework. Free-form kinds are allowed (the
# schema versions the record SHAPE, not the kind vocabulary), but these
# are the ones tooling may rely on.
KIND_TRAIN_STEP = "train_step"
KIND_EVAL = "eval"
KIND_BENCH = "bench_result"
KIND_BENCH_PROBE = "backend_probe"
KIND_TRACE_SUMMARY = "trace_summary"
KIND_HEALTH = "health"
KIND_FAILURE = "failure"
KIND_RUN_META = "run_meta"
# Resilience events (docs/RESILIENCE.md): checkpoint recovery activity and
# the supervisor's relaunch loop, joinable with the run's step telemetry.
KIND_CKPT_QUARANTINED = "ckpt_quarantined"
KIND_RESTORE_FALLBACK = "restore_fallback"
KIND_SUPERVISOR_ATTEMPT = "supervisor_attempt"
KIND_CRASH_LOOP = "crash_loop"
# Per-save cost accounting (docs/PERFORMANCE.md): ``ckpt_save_blocked_ms``
# is wall time the TRAINING thread spent inside save() (wait-for-previous-
# commit + device→host snapshot); ``ckpt_save_total_ms`` is submit →
# durable commit (orbax write + manifest hash + fsync). Async saves show
# blocked ≪ total; the sync fallback shows blocked == total.
KIND_CKPT_SAVE = "ckpt_save"
# One per process: wall time from trainer construction to the first
# completed step (restore + input build + compile). The supervisor-relaunch
# cost the persistent XLA compilation cache (core/platform.py) exists to
# shrink.
KIND_STARTUP = "startup"
# In-process recovery ladder (train/anomaly.py, docs/RESILIENCE.md): a
# detected bad step (non-finite metric, loss spike, grad-norm explosion),
# the in-memory rollback that answered it, the data range skipped by
# resuming forward, and infeed-watchdog stalls retried before escalating.
KIND_ANOMALY = "anomaly_detected"
KIND_ROLLBACK = "rollback"
KIND_BATCH_SKIPPED = "batch_skipped"
KIND_INFEED_STALL = "infeed_stall"
# One per pipelined run (docs/DISTRIBUTED.md): the resolved pipeline
# schedule — name, stages/microbatches/virtual stages, analytic bubble
# fraction and peak activation residency — so a trace or step-time rollup
# can be read against the schedule that produced it. The per-step
# ``pipe_bubble_frac`` metric rides in ordinary train_step events.
KIND_PIPELINE = "pipeline_schedule"
# One per ZeRO-sharded run (optimizer.zero_sharding="shard_map",
# parallel/zero.py): the static shard/bucket plan — bucket count, shard
# (replica) count, per-shard elements, reduce-scatter vs all-gather wire
# bytes per step, the structural overlap-fraction bound (B-1)/B and the
# nominal-bandwidth estimate of collective milliseconds hidden behind
# backward compute. Analytic from the plan; measured bytes ride the
# ordinary CollectiveTally rows (zero_reduce_scatter / zero_all_gather).
KIND_ZERO_UPDATE = "zero_update"
# Elastic resharding (docs/RESILIENCE.md "losing a slice"):
# ``mesh_resized`` is the supervisor refitting the mesh to a shrunken/
# grown device set before a relaunch (scripts/train_resilient.py, rc 84);
# ``ckpt_resharded`` is the checkpoint layer restoring state saved under
# one mesh onto another (ckpt/reshard.py, checkpoint.allow_reshard).
KIND_MESH_RESIZED = "mesh_resized"
KIND_CKPT_RESHARDED = "ckpt_resharded"
# Serving SLO events (serve/engine.py, docs/SERVING.md): one per admitted
# request (queue wait + end-to-end latency), one per executed batch (real
# vs padded rows — the fill ratio — plus compute time and the queue depth
# left behind), periodic queue-depth gauges, p50/p90/p99 latency rollups
# from the bounded reservoir (core/metrics.PercentileReservoir), and the
# first execution of each (seq, rows) padding bucket — the XLA recompile
# budget is exactly the bucket set, so an unexpected recompile event IS
# the bug.
KIND_SERVE_REQUEST = "serve_request"
KIND_SERVE_BATCH = "serve_batch"
KIND_SERVE_QUEUE = "serve_queue_depth"
KIND_SERVE_LATENCY = "serve_latency"
KIND_SERVE_RECOMPILE = "serve_bucket_recompile"
# Fleet router events (serve/fleet.py, docs/SERVING.md): one per proxied
# /predict (which replica answered, attempt/retry counts, shed verdict —
# the routing-skew ledger), one per circuit-breaker transition (eject /
# readmit / restart, with the reason), and one per replica step of a
# rolling weight reload (old→new artifact digest, duration, verdict) —
# together they let analyze_trace.py reconstruct WHY p99 degraded while
# zero client requests failed.
KIND_SERVE_ROUTE = "serve_route"
KIND_SERVE_EJECT = "serve_eject"
KIND_SERVE_RELOAD = "serve_reload"
# Serving control plane (serve/autoscale.py, docs/SERVING.md): one
# KIND_SCALE event per autoscaler action (up/down, the pressure reading
# that triggered it, the replica spawned or drained), and one
# KIND_ADMISSION event per request the router REJECTED before a replica
# slot was claimed — quota breach (429) or priority-ordered shed (503) —
# carrying the tenant, priority class, verdict, and Retry-After. Routed
# requests carry their tenant on KIND_SERVE_ROUTE instead; together the
# three kinds are the per-tenant ledger in the run summary.
KIND_SCALE = "fleet_scale"
KIND_ADMISSION = "serve_admission"
# Goodput ledger (core/goodput.py, docs/OBSERVABILITY.md): periodic +
# end-of-run classification of every wall-clock second into productive
# step compute vs overhead buckets (infeed wait, recompiles, metric
# fetches, checkpoint-blocked time, rollbacks, startup). ``metrics``
# carries wall_s/goodput_frac; the per-bucket seconds ride in
# ``extra.buckets`` and the event-count tallies in ``extra.counters``.
# Cross-attempt restart gaps are NOT in the buckets — they are stitched
# at read time from per-attempt ledgers (goodput.stitch_attempts).
KIND_GOODPUT = "goodput"
# HBM memory telemetry (core/memstats.py): periodic device.memory_stats()
# samples (bytes_in_use / peak_bytes_in_use, per-chip max in ``metrics``)
# with a host-RSS fallback on backends that expose no allocator stats
# (``extra.source_kind`` says which), plus one-shot
# compiled.memory_analysis() captures of a program's argument/output/
# temp/generated-code bytes in ``extra.analysis``.
KIND_MEMORY = "memory"
# Distributed-tracing span (core/tracing.py, docs/OBSERVABILITY.md
# "Tracing and flight recorder"): one record per FINISHED span, carrying
# ``extra.trace``/``extra.span``/``extra.parent`` ids, the span ``name``,
# root-frame start time + duration, the emitting ``service``, and the
# process's estimated clock offset so scripts/analyze_trace.py --spans can
# stitch per-process streams into one causally ordered trace tree.
KIND_SPAN = "span"
# Autoregressive decode (serve/decode.py, docs/SERVING.md "Autoregressive
# decode"): one KIND_DECODE_STEP per jitted decode step (real vs padded
# rows — batch occupancy — plus step and per-token ms), and periodic +
# eviction-triggered KIND_KV_CACHE gauges of the paged pool (pages in
# use/free, active/waiting streams, cumulative preemptions). Together
# they answer the two continuous-batching questions: how full was the
# in-flight batch, and was the KV pool the thing capping it.
KIND_DECODE_STEP = "decode_step"
KIND_KV_CACHE = "kv_cache"
# Exactly-once data plane (data/shard.py, docs/RESILIENCE.md "Exactly-once
# data"): one KIND_DATA_SHARD per attempt describing this host's slice of
# every global batch (``extra.shard`` = the shard_plan dict: process
# index/count, host/global batch, shard_mode); periodic KIND_DATA_PACKING
# with the sequence-packing census (``metrics``: real/padded tokens and
# packing_efficiency — goodput per padded token, the number packing exists
# to raise); and one KIND_DATA_STATE per checkpoint restore carrying the
# restore-gate verdict (``extra.plan``: action resume|repartition|forced,
# from/to process counts, prefetch watermark at save).
KIND_DATA_SHARD = "data_shard"
KIND_DATA_PACKING = "data_packing"
KIND_DATA_STATE = "data_state"
# Goodput-driven autotuner (scripts/autotune.py, tools/autotune,
# docs/PERFORMANCE.md "Autotuning"): one event per trial decision.
# ``extra.status`` is started|done|skipped|failed|window_abort, keyed by
# ``extra.trial`` (the candidate's config digest in space mode,
# §section:label in plan mode), carrying the roofline prediction for
# pruned candidates and the goodput-weighted score for completed ones —
# the telemetry mirror of the dtf-autotune-journal/1 trial journal.
KIND_AUTOTUNE_TRIAL = "autotune_trial"


def make_run_id() -> str:
    """Short, sortable, collision-safe run id: utc-time + random tail."""
    return time.strftime("%Y%m%dT%H%M%S", time.gmtime()) + "-" + uuid.uuid4().hex[:8]


def _to_scalar(v: Any) -> Any:
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:
            return str(v)
    return v


# Metric-key routing: the Trainer's fetched metrics dict historically mixed
# model metrics, StepTimer phases and ThroughputMeter rates. The writer
# splits them into their schema fields so readers never re-parse key names.
_PHASE_PREFIX, _PHASE_SUFFIX = "time_", "_ms"
_THROUGHPUT_KEYS = (
    "examples_per_sec", "examples_per_sec_per_chip",
    "images_per_sec", "images_per_sec_per_chip",
    "tokens_per_sec", "tokens_per_sec_per_chip",
    "real_tokens_per_sec", "docs_per_sec",
)


def split_metrics(values: Mapping[str, Any]) -> tuple[dict, dict, dict]:
    """Partition a flat metrics dict into (metrics, phases, throughput)."""
    metrics: dict[str, Any] = {}
    phases: dict[str, Any] = {}
    throughput: dict[str, Any] = {}
    for k, v in values.items():
        v = _to_scalar(v)
        if k.startswith(_PHASE_PREFIX) and k.endswith(_PHASE_SUFFIX):
            phases[k[len(_PHASE_PREFIX):-len(_PHASE_SUFFIX)]] = v
        elif k in _THROUGHPUT_KEYS:
            throughput[k] = v
        else:
            metrics[k] = v
    return metrics, phases, throughput


def make_event(
    kind: str,
    *,
    run_id: str,
    step: int | None = None,
    metrics: Mapping[str, Any] | None = None,
    phases: Mapping[str, Any] | None = None,
    throughput: Mapping[str, Any] | None = None,
    roofline: Mapping[str, Any] | None = None,
    collectives: Mapping[str, Any] | None = None,
    health: Mapping[str, Any] | None = None,
    t: float | None = None,
    **extra: Any,
) -> dict:
    """Build a schema-versioned event record (pure function; no I/O)."""
    ev: dict[str, Any] = {
        "schema": SCHEMA,
        "run_id": run_id,
        "kind": kind,
        "t": time.time() if t is None else t,
    }
    if step is not None:
        ev["step"] = int(step)
    for key, val in (
        ("metrics", metrics), ("phases", phases), ("throughput", throughput),
        ("roofline", roofline), ("collectives", collectives),
        ("health", health),
    ):
        if val is not None:
            ev[key] = {k: _to_scalar(v) for k, v in dict(val).items()}
    if extra:
        ev["extra"] = {k: _to_scalar(v) for k, v in extra.items()}
    return ev


def validate_event(ev: Mapping[str, Any]) -> list[str]:
    """Schema-conformance errors for one record ([] = valid)."""
    errors: list[str] = []
    if not isinstance(ev, Mapping):
        return [f"event is {type(ev).__name__}, not a mapping"]
    schema = ev.get("schema")
    if schema != SCHEMA:
        errors.append(f"schema={schema!r}, expected {SCHEMA!r}")
    for req in ("run_id", "kind", "t"):
        if req not in ev:
            errors.append(f"missing required field {req!r}")
    if "step" in ev and not isinstance(ev["step"], int):
        errors.append(f"step={ev['step']!r} is not an int")
    for key in ("metrics", "phases", "throughput", "roofline",
                "collectives", "health", "extra"):
        if key in ev and not isinstance(ev[key], Mapping):
            errors.append(f"field {key!r} is not a mapping")
    unknown = set(ev) - set(RESERVED_FIELDS)
    if unknown:
        errors.append(
            f"unknown top-level field(s) {sorted(unknown)} — new data "
            f"belongs under 'extra' (or bump SCHEMA_VERSION)"
        )
    return errors


class TelemetryWriter:
    """Append-only JSONL sink for schema events.

    Chief-only by contract (same as MetricWriter): non-chief construction
    yields a no-op writer so call sites never need the guard. Writes are
    line-buffered so a wedged/killed run still leaves every completed
    step's record on disk — the failure-forensics property VERDICT r3/r5
    asked for.

    Thread-safe: the async checkpoint pipeline (ckpt/async_saver.py) emits
    its ``ckpt_save`` record from the background saver thread while the
    training thread keeps emitting step events; a lock around the append
    keeps every JSONL line whole.
    """

    def __init__(
        self,
        path: str | None,
        *,
        run_id: str | None = None,
        is_chief: bool = True,
    ):
        self.run_id = run_id or make_run_id()
        self._fh = None
        self._lock = threading.Lock()
        self._listeners: list[Any] = []
        self.path = path
        if not (is_chief and path):
            return
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._fh = open(path, "a", buffering=1)

    @property
    def enabled(self) -> bool:
        return self._fh is not None

    def add_listener(self, fn) -> None:
        """Register ``fn(event_dict)`` to observe every emitted record.

        This is how in-process accountants join streams without a disk
        round-trip: the goodput ledger (core/goodput.py) listens for
        ``ckpt_save`` blocked-ms so checkpoint stalls move out of its
        residual bucket the moment the saver thread reports them.
        Listeners run outside the append lock but may be called from any
        emitting thread; they must be fast and must not raise.
        """
        self._listeners.append(fn)

    def emit(self, kind: str, **fields: Any) -> dict:
        """Build + append one event; returns the record (even when no-op,
        so callers can reuse it for console/JSON-line output)."""
        ev = make_event(kind, run_id=self.run_id, **fields)
        line = json.dumps(ev, default=str) + "\n"
        with self._lock:
            if self._fh is not None:
                self._fh.write(line)
        for fn in self._listeners:
            try:
                fn(ev)
            except Exception:  # a broken observer must never lose the run
                log.exception("telemetry listener failed on kind=%s", kind)
        return ev

    def emit_run_meta(self, **describe: Any) -> dict:
        """The run's opening record: argv, config name, host — whatever
        identifies it. Emitted once so every later record can stay thin."""
        return self.emit(
            KIND_RUN_META,
            argv=" ".join(describe.pop("argv", [])) or None,
            host=socket.gethostname(),
            pid=os.getpid(),
            **describe,
        )

    def flush(self) -> None:
        """Push buffered lines to the kernel AND to disk (fsync).

        Lines are already line-buffered, so this exists for the hard-exit
        window: the graceful-preemption path calls it as soon as SIGTERM
        lands so every record is durable even if the supervisor's SIGKILL
        grace expires before close() runs.
        """
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                try:
                    os.fsync(self._fh.fileno())
                except OSError:  # non-seekable sinks (pipes) can't fsync
                    pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def read_events(path: str, *, kind: str | None = None,
                strict: bool = True) -> Iterator[dict]:
    """Stream schema-checked events from a JSONL file.

    ``strict`` raises on a schema-invalid line (tests, tooling); False
    skips them with a warning (forensics over partially-corrupt files —
    e.g. a record truncated by a SIGKILL mid-write).
    """
    with open(path) as fh:
        for lineno, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
                errors = validate_event(ev)
            except json.JSONDecodeError as e:
                ev, errors = None, [f"invalid json: {e}"]
            if errors:
                msg = f"{path}:{lineno}: {'; '.join(errors)}"
                if strict:
                    raise ValueError(msg)
                log.warning("skipping bad telemetry record %s", msg)
                continue
            if kind is None or ev["kind"] == kind:
                yield ev


# Kinds counted as recovery activity by summarize_events — the run-summary
# surface scripts/analyze_trace.py prints so "how rough was this run?" is
# answerable from the event stream alone.
RECOVERY_KINDS = (
    KIND_CKPT_QUARANTINED, KIND_RESTORE_FALLBACK,
    KIND_SUPERVISOR_ATTEMPT, KIND_CRASH_LOOP, KIND_FAILURE,
    KIND_ANOMALY, KIND_ROLLBACK, KIND_BATCH_SKIPPED, KIND_INFEED_STALL,
    KIND_MESH_RESIZED, KIND_CKPT_RESHARDED, KIND_DATA_STATE,
)


def summarize_events(path: str) -> dict:
    """Aggregate one events.jsonl into a run summary dict.

    Tolerant of torn tails (strict=False): the file is exactly what a
    SIGKILLed run leaves behind, and that is the run most worth
    summarizing. Returns event counts by kind, the step span, a
    ``ckpt_saves`` section (save count, async count, and loop-blocked vs
    total save milliseconds — the async-pipeline win is blocked ≪ total),
    a ``startups`` list (restart → first-step latency per process), a
    ``collectives`` section (the last per-step wire/logical byte tally and
    the resulting wire_compression ratio), and a
    ``recovery`` section: quarantined checkpoint steps, restore fallbacks
    (from → to), supervisor attempt classifications, preemptions, and any
    crash-loop verdict.
    """
    kinds: dict[str, int] = {}
    run_ids: list[str] = []
    first_step = last_step = None
    quarantined: list[dict] = []
    fallbacks: list[dict] = []
    attempts: dict[str, int] = {}
    preemptions = 0
    crash_loop: dict | None = None
    failures: list[dict] = []
    anomalies: list[dict] = []
    rollbacks: list[dict] = []
    batches_skipped = 0
    infeed_stalls = 0
    saves = {
        "count": 0, "async_count": 0,
        "blocked_ms_total": 0.0, "total_ms_total": 0.0,
        "blocked_ms_max": 0.0, "total_ms_max": 0.0,
    }
    startups: list[dict] = []
    pipeline: dict | None = None
    zero: dict | None = None
    step_rates: list[float] = []
    meta: dict | None = None
    evals = {"count": 0, "last_step": None}
    bench = {"count": 0, "workloads": []}
    bench_probes = 0
    trace_summaries = 0
    health_events: dict[str, int] = {}
    mesh_resizes: list[dict] = []
    ckpt_reshards: list[dict] = []
    serve = {
        "requests": 0, "rows": 0, "queue_wait_ms_total": 0.0,
        "batches": 0, "batch_rows": 0, "padded_rows": 0,
        "compute_ms_total": 0.0, "queue_depth_max": 0,
        "recompiles": [], "latency": None,
    }
    decode = {
        "steps": 0, "tokens": 0, "padded_rows": 0, "step_ms_total": 0.0,
        "occupancy_sum": 0.0, "evictions": 0, "pages_used_max": 0,
        "streams_waiting_max": 0, "kv_samples": 0,
    }
    fleet = {
        "requests": 0, "routed": {}, "retries": 0, "shed": 0,
        "deadline_exceeded": 0, "skew": None,
        "ejects": [], "readmits": 0, "restarts": 0, "reloads": [],
        # KIND_SCALE: the autoscaler's action ledger (serve/autoscale.py).
        "scaling": {"ups": 0, "downs": 0, "events": []},
        # KIND_ADMISSION + tenant-tagged KIND_SERVE_ROUTE: per-tenant
        # routed/shed/quota ledger with latency percentiles.
        "tenants": {},
    }
    tenant_latencies: dict[str, list[float]] = {}

    def _tenant(name: str) -> dict:
        led = fleet["tenants"].get(name)
        if led is None:
            led = {
                "routed": 0, "shed": 0, "quota_rejected": 0,
                "latency_ms": None,
            }
            fleet["tenants"][name] = led
        return led

    # Exactly-once data plane: the attempt's shard layout (last KIND_DATA_SHARD
    # wins — a refit re-emits it), the cumulative packing census (last
    # KIND_DATA_PACKING wins, counters are cumulative), and every restore-gate
    # verdict in order (KIND_DATA_STATE — part of the recovery story).
    data_shard: dict | None = None
    data_packing: dict | None = None
    data_restores: list[dict] = []
    last_collectives: dict | None = None
    # Per-attempt goodput rollups: one ledger per run_id (process); the
    # final rollup wins over periodic snapshots, else the last seen (a
    # SIGKILLed attempt never finalizes — its last periodic event is the
    # truth that survived).
    goodput_by_run: dict[str, dict] = {}
    memory = {
        "samples": 0, "sources": {},
        "peak_bytes_in_use": 0, "bytes_in_use_last": None,
        "analysis": None,
    }
    spans = {
        "count": 0, "traces": set(), "services": {}, "names": {},
        "errors": 0, "dur_ms_total": 0.0,
    }
    # KIND_AUTOTUNE_TRIAL ledger: trial decisions by status plus the
    # best goodput-weighted score the window produced.
    autotune = {
        "events": 0, "ran": 0, "pruned": 0, "failed": 0,
        "window_aborts": 0, "best": None,
    }
    for ev in read_events(path, strict=False):
        kind = ev["kind"]
        kinds[kind] = kinds.get(kind, 0) + 1
        if ev.get("collectives"):
            # Per-step collective byte tally (parallel/collectives.py);
            # static per compiled program, so the LAST one wins.
            last_collectives = dict(ev["collectives"])
        if ev.get("run_id") and ev["run_id"] not in run_ids:
            run_ids.append(ev["run_id"])
        step = ev.get("step")
        if isinstance(step, int):
            first_step = step if first_step is None else min(first_step, step)
            last_step = step if last_step is None else max(last_step, step)
        health = ev.get("health") or {}
        extra = ev.get("extra") or {}
        if kind == KIND_CKPT_QUARANTINED:
            quarantined.append({"step": step, "reason": health.get("reason")})
        elif kind == KIND_RESTORE_FALLBACK:
            fallbacks.append({
                "from_step": health.get("from_step"),
                "to_step": health.get("to_step"),
            })
        elif kind == KIND_SUPERVISOR_ATTEMPT:
            cls = str(extra.get("classification", "unknown"))
            attempts[cls] = attempts.get(cls, 0) + 1
        elif kind == KIND_CRASH_LOOP:
            crash_loop = dict(extra) or dict(health)
        elif kind == KIND_FAILURE:
            failures.append({"step": step, **health})
        elif kind == KIND_ANOMALY:
            anomalies.append({"step": step, "anomaly": health.get("anomaly"),
                              "metric": health.get("metric")})
        elif kind == KIND_ROLLBACK:
            rollbacks.append({
                "from_step": health.get("from_step"),
                "to_step": health.get("to_step"),
            })
        elif kind == KIND_BATCH_SKIPPED:
            batches_skipped += int(health.get("batches", 1) or 1)
        elif kind == KIND_INFEED_STALL:
            infeed_stalls += 1
        elif kind == KIND_CKPT_SAVE:
            m = ev.get("metrics") or {}
            blocked = float(m.get("ckpt_save_blocked_ms", 0.0))
            total = float(m.get("ckpt_save_total_ms", 0.0))
            saves["count"] += 1
            if extra.get("async_save"):
                saves["async_count"] += 1
            saves["blocked_ms_total"] += blocked
            saves["total_ms_total"] += total
            saves["blocked_ms_max"] = max(saves["blocked_ms_max"], blocked)
            saves["total_ms_max"] = max(saves["total_ms_max"], total)
        elif kind == KIND_STARTUP:
            startups.append({
                "step": step,
                "time_to_first_step_s": extra.get("time_to_first_step_s"),
                "restored_step": extra.get("restored_step"),
            })
        elif kind == KIND_PIPELINE:
            pipeline = dict(extra)
        elif kind == KIND_ZERO_UPDATE:
            zero = dict(extra)
        elif kind == KIND_RUN_META and meta is None:
            meta = {k: extra.get(k) for k in (
                "config_name", "model", "dataset", "mesh",
                "global_batch_size", "process_count") if k in extra}
        elif kind == KIND_EVAL:
            evals["count"] += 1
            if isinstance(step, int):
                evals["last_step"] = step
        elif kind == KIND_BENCH:
            bench["count"] += 1
            wl = extra.get("workload")
            if wl and wl not in bench["workloads"]:
                bench["workloads"].append(wl)
        elif kind == KIND_BENCH_PROBE:
            bench_probes += 1
        elif kind == KIND_TRACE_SUMMARY:
            trace_summaries += 1
        elif kind == KIND_HEALTH:
            name = str(health.get("event", "unknown"))
            health_events[name] = health_events.get(name, 0) + 1
        elif kind == KIND_MESH_RESIZED:
            mesh_resizes.append({
                "from_axes": extra.get("from_axes"),
                "to_axes": extra.get("to_axes"),
                "visible_devices": extra.get("visible_devices"),
            })
        elif kind == KIND_CKPT_RESHARDED:
            ckpt_reshards.append({
                "step": step,
                "from_axes": extra.get("from_axes"),
                "to_axes": extra.get("to_axes"),
                "leaf_count": extra.get("leaf_count"),
            })
        elif kind == KIND_SERVE_REQUEST:
            m = ev.get("metrics") or {}
            serve["requests"] += 1
            serve["rows"] += int(m.get("rows", 1) or 1)
            serve["queue_wait_ms_total"] += float(m.get("queue_wait_ms", 0.0))
        elif kind == KIND_SERVE_BATCH:
            m = ev.get("metrics") or {}
            serve["batches"] += 1
            serve["batch_rows"] += int(m.get("rows", 0) or 0)
            serve["padded_rows"] += int(m.get("padded_rows", 0) or 0)
            serve["compute_ms_total"] += float(m.get("compute_ms", 0.0))
            serve["queue_depth_max"] = max(
                serve["queue_depth_max"], int(m.get("queue_depth", 0) or 0))
        elif kind == KIND_SERVE_QUEUE:
            m = ev.get("metrics") or {}
            serve["queue_depth_max"] = max(
                serve["queue_depth_max"], int(m.get("queue_depth", 0) or 0))
        elif kind == KIND_SERVE_LATENCY:
            # Periodic rollups are cumulative over the run; the LAST one
            # (emitted at drain) wins.
            m = ev.get("metrics") or {}
            tp = ev.get("throughput") or {}
            serve["latency"] = {
                "p50_ms": m.get("p50_ms"), "p90_ms": m.get("p90_ms"),
                "p99_ms": m.get("p99_ms"), "count": m.get("count"),
                "requests_per_sec": tp.get("requests_per_sec"),
                "rows_per_sec": tp.get("rows_per_sec"),
            }
        elif kind == KIND_SERVE_RECOMPILE:
            m = ev.get("metrics") or {}
            serve["recompiles"].append({
                "bucket": extra.get("bucket"),
                "compile_ms": m.get("compile_ms"),
            })
        elif kind == KIND_DECODE_STEP:
            m = ev.get("metrics") or {}
            decode["steps"] += 1
            decode["tokens"] += int(m.get("rows", 0) or 0)
            decode["padded_rows"] += int(m.get("padded_rows", 0) or 0)
            decode["step_ms_total"] += float(m.get("step_ms", 0.0))
            decode["occupancy_sum"] += float(m.get("occupancy", 0.0))
        elif kind == KIND_KV_CACHE:
            m = ev.get("metrics") or {}
            decode["kv_samples"] += 1
            # evictions is a cumulative counter on the emitting engine —
            # the max across samples is the run total.
            decode["evictions"] = max(
                decode["evictions"], int(m.get("evictions", 0) or 0))
            decode["pages_used_max"] = max(
                decode["pages_used_max"], int(m.get("pages_used", 0) or 0))
            decode["streams_waiting_max"] = max(
                decode["streams_waiting_max"],
                int(m.get("streams_waiting", 0) or 0))
        elif kind == KIND_SERVE_ROUTE:
            m = ev.get("metrics") or {}
            fleet["requests"] += 1
            fleet["retries"] += int(m.get("retries", 0) or 0)
            if extra.get("shed"):
                fleet["shed"] += 1
            if extra.get("deadline_exceeded"):
                fleet["deadline_exceeded"] += 1
            rep = extra.get("replica")
            if rep is not None:
                rep = str(rep)
                fleet["routed"][rep] = fleet["routed"].get(rep, 0) + 1
            tenant = extra.get("tenant")
            if tenant is not None:
                led = _tenant(str(tenant))
                if extra.get("shed"):
                    led["shed"] += 1
                else:
                    led["routed"] += 1
                    lat = m.get("latency_ms")
                    if lat is not None:
                        tenant_latencies.setdefault(
                            str(tenant), []).append(float(lat))
        elif kind == KIND_ADMISSION:
            led = _tenant(str(extra.get("tenant", "default")))
            if str(extra.get("verdict")) == "quota":
                led["quota_rejected"] += 1
            else:
                led["shed"] += 1
        elif kind == KIND_SCALE:
            m = ev.get("metrics") or {}
            action = str(extra.get("action", ""))
            scaling = fleet["scaling"]
            if action == "up":
                scaling["ups"] += 1
            elif action == "down":
                scaling["downs"] += 1
            # Event order IS the scaling timeline — keep it.
            scaling["events"].append({
                "action": action,
                "reason": extra.get("reason"),
                "replica": extra.get("replica"),
                "from_replicas": extra.get("from_replicas"),
                "to_replicas": extra.get("to_replicas"),
                "pressure": m.get("pressure"),
            })
        elif kind == KIND_SERVE_EJECT:
            action = str(extra.get("action", "eject"))
            if action == "readmit":
                fleet["readmits"] += 1
            elif action == "restart":
                fleet["restarts"] += 1
            else:
                fleet["ejects"].append({
                    "replica": extra.get("replica"),
                    "reason": extra.get("reason"),
                })
        elif kind == KIND_SERVE_RELOAD:
            m = ev.get("metrics") or {}
            # Event order IS the rolling-reload timeline (one replica at
            # a time by design) — keep it, don't re-sort.
            fleet["reloads"].append({
                "replica": extra.get("replica"),
                "ok": bool(extra.get("ok")),
                "from_digest": extra.get("from_digest"),
                "to_digest": extra.get("to_digest"),
                "reload_ms": m.get("reload_ms"),
            })
        elif kind == KIND_DATA_SHARD:
            data_shard = dict(extra.get("shard") or {})
        elif kind == KIND_DATA_PACKING:
            m = ev.get("metrics") or {}
            data_packing = {
                "real_tokens": m.get("real_tokens"),
                "padded_tokens": m.get("padded_tokens"),
                "packing_efficiency": m.get("packing_efficiency"),
            }
        elif kind == KIND_DATA_STATE:
            plan = extra.get("plan") or {}
            data_restores.append({
                "step": step,
                "action": plan.get("action"),
                "from_processes": plan.get("from_processes"),
                "to_processes": plan.get("to_processes"),
                "watermark": plan.get("watermark"),
            })
        elif kind == KIND_AUTOTUNE_TRIAL:
            autotune["events"] += 1
            status = str(extra.get("status", ""))
            if status == "done":
                autotune["ran"] += 1
                score = extra.get("score")
                if isinstance(score, (int, float)) and (
                        autotune["best"] is None
                        or score > autotune["best"]["score"]):
                    autotune["best"] = {
                        "trial": extra.get("trial"), "score": score,
                        "unit": extra.get("unit"),
                    }
            elif status == "skipped":
                autotune["pruned"] += 1
            elif status == "failed":
                autotune["failed"] += 1
            elif status == "window_abort":
                autotune["window_aborts"] += 1
        elif kind == KIND_GOODPUT:
            m = ev.get("metrics") or {}
            snap = {
                "t0": extra.get("t0"),
                "wall_s": m.get("wall_s"),
                "goodput_frac": m.get("goodput_frac"),
                "buckets": dict(extra.get("buckets") or {}),
                "counters": dict(extra.get("counters") or {}),
                "final": bool(extra.get("final")),
            }
            prev = goodput_by_run.get(ev.get("run_id"))
            if prev is None or not prev["final"] or snap["final"]:
                goodput_by_run[ev.get("run_id")] = snap
        elif kind == KIND_MEMORY:
            m = ev.get("metrics") or {}
            memory["samples"] += 1
            src = str(extra.get("source", "unknown"))
            memory["sources"][src] = memory["sources"].get(src, 0) + 1
            if m.get("peak_bytes_in_use"):
                memory["peak_bytes_in_use"] = max(
                    int(memory["peak_bytes_in_use"]),
                    int(m["peak_bytes_in_use"]))
            if m.get("bytes_in_use") is not None:
                memory["bytes_in_use_last"] = int(m["bytes_in_use"])
            if extra.get("analysis"):
                memory["analysis"] = dict(extra["analysis"])
        elif kind == KIND_SPAN:
            m = ev.get("metrics") or {}
            spans["count"] += 1
            if extra.get("trace"):
                spans["traces"].add(str(extra["trace"]))
            svc = str(extra.get("service", "unknown"))
            spans["services"][svc] = spans["services"].get(svc, 0) + 1
            name = str(extra.get("name", "unknown"))
            spans["names"][name] = spans["names"].get(name, 0) + 1
            if str(extra.get("status", "ok")) != "ok":
                spans["errors"] += 1
            spans["dur_ms_total"] += float(m.get("dur_ms", 0.0) or 0.0)
        elif kind == KIND_TRAIN_STEP:
            m = ev.get("metrics") or {}
            if pipeline is not None and "pipe_bubble_frac" in m:
                pipeline["bubble_frac_logged"] = float(m["pipe_bubble_frac"])
            rate = (ev.get("throughput") or {}).get("examples_per_sec")
            if isinstance(rate, (int, float)):
                step_rates.append(float(rate))
        if health.get("event") == "graceful_preemption":
            preemptions += 1
    if pipeline is not None and step_rates:
        # Steady-state throughput: median over the back half of the
        # logged steps, past the compile/warmup ramp — the measured
        # number the analytic bubble_frac should explain.
        tail = sorted(step_rates[len(step_rates) // 2:])
        pipeline["steady_examples_per_sec"] = tail[len(tail) // 2]
    collectives = None
    if last_collectives:
        # Wire vs logical per-step bytes (CollectiveTally summary):
        # wire_compression > 1 means a narrow/quantized wire dtype
        # (parallel.collective_dtype) is actually shrinking the traffic.
        total = last_collectives.get("total_bytes")
        logical = last_collectives.get("total_logical_bytes", total)
        collectives = {
            "total_bytes": total,
            "total_logical_bytes": logical,
            "wire_compression": (
                round(float(logical) / float(total), 3)
                if total and logical is not None else None),
        }
    if fleet["routed"]:
        # Routing skew: hottest replica vs the uniform share. 1.0 is a
        # perfectly balanced fleet; ejections and stalls push it up.
        counts = list(fleet["routed"].values())
        mean = sum(counts) / len(counts)
        fleet["skew"] = round(max(counts) / mean, 3) if mean else None
    for tenant, lats in tenant_latencies.items():
        # Per-tenant latency percentiles over every routed request (the
        # event file is the reservoir; nearest-rank on the sorted list).
        lats.sort()
        n = len(lats)
        fleet["tenants"][tenant]["latency_ms"] = {
            p: round(lats[min(n - 1, int(q * n))], 3)
            for p, q in (("p50", 0.50), ("p90", 0.90), ("p99", 0.99))
        }
    goodput = None
    if goodput_by_run:
        # In-process accounting only: restart gaps BETWEEN attempts need
        # the per-attempt t0 intervals and supervisor classifications —
        # goodput.stitch_attempts() builds that cross-attempt table.
        buckets: dict[str, float] = {}
        counters: dict[str, int] = {}
        wall = productive = 0.0
        for snap in goodput_by_run.values():
            w = float(snap.get("wall_s") or 0.0)
            wall += w
            if snap.get("goodput_frac") is not None:
                productive += w * float(snap["goodput_frac"])
            for b, s in snap["buckets"].items():
                buckets[b] = buckets.get(b, 0.0) + float(s)
            for c, n in snap["counters"].items():
                counters[c] = counters.get(c, 0) + int(n)
        goodput = {
            "attempts": len(goodput_by_run),
            "wall_s": wall,
            "goodput_frac": (productive / wall) if wall else None,
            "buckets": buckets,
            "counters": counters,
        }
    return {
        "path": path,
        "run_ids": run_ids,
        "event_count": sum(kinds.values()),
        "kinds": kinds,
        "first_step": first_step,
        "last_step": last_step,
        "meta": meta,
        "evals": evals,
        "bench": bench,
        "bench_probes": bench_probes,
        "trace_summaries": trace_summaries,
        "health_events": health_events,
        "collectives": collectives,
        "ckpt_saves": saves,
        "startups": startups,
        "pipeline": pipeline,
        "zero": zero,
        "serve": (serve if (serve["requests"] or serve["batches"]
                            or serve["recompiles"]) else None),
        "decode": (decode if (decode["steps"] or decode["kv_samples"])
                   else None),
        "fleet": (fleet if (fleet["requests"] or fleet["ejects"]
                            or fleet["readmits"] or fleet["restarts"]
                            or fleet["reloads"] or fleet["tenants"]
                            or fleet["scaling"]["events"]) else None),
        "goodput": goodput,
        "autotune": (autotune if autotune["events"] else None),
        "data": ({"shard": data_shard, "packing": data_packing}
                 if (data_shard or data_packing) else None),
        "memory": (memory if memory["samples"] else None),
        "spans": ({
            "count": spans["count"],
            "traces": len(spans["traces"]),
            "services": spans["services"],
            "names": spans["names"],
            "errors": spans["errors"],
            "dur_ms_total": spans["dur_ms_total"],
        } if spans["count"] else None),
        "recovery": {
            "quarantined": quarantined,
            "restore_fallbacks": fallbacks,
            "supervisor_attempts": attempts,
            "graceful_preemptions": preemptions,
            "failures": failures,
            "crash_loop": crash_loop,
            "anomalies": anomalies,
            "rollbacks": rollbacks,
            "batches_skipped": batches_skipped,
            "infeed_stalls": infeed_stalls,
            "mesh_resizes": mesh_resizes,
            "ckpt_reshards": ckpt_reshards,
            "data_restores": data_restores,
        },
    }


def fmt_bytes(n: Any) -> str:
    """``3221225472`` -> ``3.00 GiB`` (human-scale HBM numbers)."""
    if not isinstance(n, (int, float)):
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.2f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.2f} TiB"


def _fmt_axes(axes: dict | None) -> str:
    """``{'data': 8}`` -> ``{data:8}`` (size-1 axes elided)."""
    if not axes:
        return "{?}"
    parts = [f"{a}:{int(v)}" for a, v in axes.items() if int(v) != 1]
    return "{" + ", ".join(parts) + "}" if parts else "{1 device}"


def format_run_summary(summary: dict) -> str:
    """Human-readable rendering of ``summarize_events`` output."""
    lines = [f"run summary: {summary['path']}"]
    if summary["run_ids"]:
        lines.append(f"  run ids: {', '.join(summary['run_ids'])}")
    span = ""
    if summary["last_step"] is not None:  # KIND_TRAIN_STEP rollup
        span = f", steps {summary['first_step']}..{summary['last_step']}"
    lines.append(f"  {summary['event_count']} events{span}")
    lines.append(
        "  by kind: " + ", ".join(
            f"{k}={v}" for k, v in sorted(summary["kinds"].items())
        )
    )
    meta = summary.get("meta")
    if meta:  # first KIND_RUN_META event of the run
        lines.append(
            "  run: " + ", ".join(f"{k}={v}" for k, v in meta.items())
        )
    evals = summary.get("evals") or {}
    if evals.get("count"):  # KIND_EVAL rollup
        lines.append(
            f"  evals: {evals['count']} (last at step {evals['last_step']})"
        )
    bench = summary.get("bench") or {}
    if bench.get("count"):  # KIND_BENCH rollup
        wl = ", ".join(bench.get("workloads") or []) or "?"
        lines.append(f"  bench results: {bench['count']} ({wl})")
    if summary.get("bench_probes"):  # KIND_BENCH_PROBE rollup
        lines.append(f"  backend probes: {summary['bench_probes']}")
    if summary.get("trace_summaries"):  # KIND_TRACE_SUMMARY rollup
        lines.append(f"  trace summaries: {summary['trace_summaries']}")
    colls = summary.get("collectives")
    if colls and colls.get("total_bytes") is not None:
        comp = colls.get("wire_compression")
        lines.append(
            f"  collectives: {colls['total_bytes']:,} wire bytes/step"
            f" ({colls['total_logical_bytes']:,} logical"
            + (f", {comp:g}x compression" if comp else "") + ")"
        )
    if summary.get("health_events"):  # KIND_HEALTH rollup
        lines.append(
            "  health events: " + ", ".join(
                f"{k}={v}"
                for k, v in sorted(summary["health_events"].items())
            )
        )
    saves = summary.get("ckpt_saves") or {}
    if saves.get("count"):  # KIND_CKPT_SAVE rollup
        lines.append(
            "  checkpoint saves: {count} ({async_count} async), loop "
            "blocked {blocked:.0f} ms of {total:.0f} ms total "
            "(max {bmax:.0f}/{tmax:.0f} ms)".format(
                count=saves["count"], async_count=saves["async_count"],
                blocked=saves["blocked_ms_total"],
                total=saves["total_ms_total"],
                bmax=saves["blocked_ms_max"], tmax=saves["total_ms_max"],
            )
        )
    pipe = summary.get("pipeline")
    if pipe:  # KIND_PIPELINE rollup
        bits = [
            f"{pipe.get('schedule', '?')} "
            f"S={pipe.get('stages', '?')} M={pipe.get('microbatches', '?')}"
        ]
        if (pipe.get("virtual_stages") or 1) > 1:
            bits.append(f"v={pipe['virtual_stages']}")
        if pipe.get("bubble_frac") is not None:
            bits.append(f"bubble {float(pipe['bubble_frac']):.4f}")
        if pipe.get("peak_inflight") is not None:
            bits.append(f"residency {pipe['peak_inflight']:g} acts")
        if pipe.get("steady_examples_per_sec") is not None:
            bits.append(
                f"steady {float(pipe['steady_examples_per_sec']):.1f} ex/s")
        lines.append("  pipeline: " + ", ".join(bits))
    zero = summary.get("zero")
    if zero:  # KIND_ZERO_UPDATE rollup
        bits = [
            f"{zero.get('shards', '?')} shards, "
            f"{zero.get('buckets', '?')} buckets "
            f"({zero.get('bucket_mb', '?')} MiB, wire {zero.get('wire', '?')})"
        ]
        if zero.get("rs_wire_bytes") is not None:
            bits.append(
                f"RS {int(zero['rs_wire_bytes']):,} B + "
                f"AG {int(zero.get('ag_wire_bytes') or 0):,} B/step")
        if zero.get("overlap_frac_est") is not None:
            bits.append(
                f"overlap est {float(zero['overlap_frac_est']):.2f}"
                + (f" (~{float(zero['hidden_ms_est']):.2f} ms hidden)"
                   if zero.get("hidden_ms_est") is not None else ""))
        lines.append("  zero update sharding: " + ", ".join(bits))
    serve = summary.get("serve")
    if serve:  # KIND_SERVE_REQUEST / KIND_SERVE_BATCH rollup
        fill = (serve["batch_rows"] / serve["padded_rows"]
                if serve.get("padded_rows") else None)
        lines.append(
            f"  serving: {serve['requests']} requests ({serve['rows']} rows)"
            f" in {serve['batches']} batches"
            + (f", fill {fill:.2f}" if fill is not None else "")
            + f", queue depth max {serve['queue_depth_max']}"
        )
        lat = serve.get("latency")
        if lat and lat.get("p50_ms") is not None:  # KIND_SERVE_LATENCY
            rps = lat.get("requests_per_sec")
            lines.append(
                f"    latency: p50 {float(lat['p50_ms']):.1f} ms, "
                f"p90 {float(lat.get('p90_ms') or 0):.1f} ms, "
                f"p99 {float(lat['p99_ms']):.1f} ms over {lat.get('count')} "
                f"requests"
                + (f", {float(rps):.1f} req/s" if rps is not None else "")
            )
        if serve["queue_wait_ms_total"] or serve["compute_ms_total"]:
            lines.append(
                f"    queue wait {serve['queue_wait_ms_total']:.0f} ms vs "
                f"compute {serve['compute_ms_total']:.0f} ms (totals)"
            )
        if serve["recompiles"]:  # KIND_SERVE_RECOMPILE / KIND_SERVE_QUEUE
            buckets = ", ".join(
                str(r.get("bucket")) for r in serve["recompiles"])
            lines.append(
                f"    bucket recompiles: {len(serve['recompiles'])}"
                f" ({buckets})"
            )
    decode = summary.get("decode")
    if decode:  # KIND_DECODE_STEP rollup
        fill = (decode["tokens"] / decode["padded_rows"]
                if decode.get("padded_rows") else None)
        occ = (decode["occupancy_sum"] / decode["steps"]
               if decode["steps"] else None)
        per_tok = (decode["step_ms_total"] / decode["tokens"]
                   if decode["tokens"] else None)
        lines.append(
            f"  decode: {decode['tokens']} tokens in {decode['steps']} steps"
            + (f", fill {fill:.2f}" if fill is not None else "")
            + (f", occupancy {occ:.2f}" if occ is not None else "")
            + (f", {per_tok:.1f} ms/token" if per_tok is not None else "")
        )
        if decode["kv_samples"]:  # KIND_KV_CACHE rollup
            lines.append(
                f"    kv cache: peak {decode['pages_used_max']} pages in "
                f"use, evictions {decode['evictions']}, waiting max "
                f"{decode['streams_waiting_max']} "
                f"({decode['kv_samples']} samples)"
            )
    fleet = summary.get("fleet")
    if fleet:  # KIND_SERVE_ROUTE / KIND_SERVE_EJECT / KIND_SERVE_RELOAD
        routed = ", ".join(
            f"{r}={n}" for r, n in sorted(fleet["routed"].items()))
        lines.append(
            f"  fleet: {fleet['requests']} proxied"
            + (f" ({routed})" if routed else "")
            + f", retries {fleet['retries']}, shed {fleet['shed']}"
            + (f", deadline misses {fleet['deadline_exceeded']}"
               if fleet["deadline_exceeded"] else "")
            + (f", skew {float(fleet['skew']):.2f}"
               if fleet.get("skew") is not None else "")
        )
        if fleet["ejects"] or fleet["readmits"] or fleet["restarts"]:
            ej = ", ".join(
                f"{e.get('replica')}:{e.get('reason')}"
                for e in fleet["ejects"])
            lines.append(
                f"    ejections: {len(fleet['ejects'])}"
                + (f" ({ej})" if ej else "")
                + f", readmits {fleet['readmits']}"
                f", restarts {fleet['restarts']}"
            )
        for r in fleet["reloads"]:  # timeline, one line per replica step
            ms = r.get("reload_ms")
            lines.append(
                f"    reload {r.get('replica')}: "
                f"{str(r.get('from_digest'))[:8]}"
                f" -> {str(r.get('to_digest'))[:8]} "
                + ("ok" if r.get("ok") else "REJECTED")
                + (f" in {float(ms):.0f} ms" if ms is not None else "")
            )
        scaling = fleet.get("scaling") or {}
        if scaling.get("events"):  # KIND_SCALE rollup (serve/autoscale.py)
            timeline = ", ".join(
                f"{e.get('action')}->{e.get('to_replicas')}"
                + (f"@{float(e['pressure']):.2f}"
                   if e.get("pressure") is not None else "")
                for e in scaling["events"])
            lines.append(
                f"    scaling: {scaling.get('ups', 0)} up / "
                f"{scaling.get('downs', 0)} down ({timeline})"
            )
        # KIND_ADMISSION rollup: one ledger line per tenant, best class
        # first so the shed ordering is legible at a glance.
        for tenant, led in sorted((fleet.get("tenants") or {}).items()):
            lat = led.get("latency_ms") or {}
            lines.append(
                f"    tenant {tenant}: routed {led['routed']}"
                f", shed {led['shed']}"
                f", quota_rejected {led['quota_rejected']}"
                + (f", p50/p90/p99 {lat['p50']}/{lat['p90']}/{lat['p99']} ms"
                   if lat else "")
            )
    data = summary.get("data")
    if data:  # KIND_DATA_SHARD rollup (data/shard.py shard_plan)
        sh = data.get("shard")
        if sh:
            lines.append(
                f"  data shard: host {sh.get('process_index')}/"
                f"{sh.get('process_count')} reads "
                f"{sh.get('host_batch')} of {sh.get('global_batch')} "
                f"rows/batch ({sh.get('shard_mode', '?')} mode)"
            )
        pk = data.get("packing")
        if pk and pk.get("real_tokens") is not None:  # KIND_DATA_PACKING rollup
            eff = pk.get("packing_efficiency")
            lines.append(
                f"  packing: {int(pk['real_tokens']):,} real / "
                f"{int(pk.get('padded_tokens') or 0):,} padded tokens"
                + (f", efficiency {float(eff):.3f}" if eff is not None else "")
            )
    gp = summary.get("goodput")
    if gp:  # KIND_GOODPUT rollup (per-attempt ledgers summed)
        frac = gp.get("goodput_frac")
        lines.append(
            f"  goodput: "
            + (f"{100.0 * float(frac):.1f}%" if frac is not None else "?")
            + f" of {float(gp.get('wall_s') or 0):.1f} s wall over "
            f"{gp.get('attempts')} attempt(s)"
        )
        buckets = sorted((gp.get("buckets") or {}).items(),
                         key=lambda kv: -kv[1])
        if buckets:
            lines.append("    buckets: " + ", ".join(
                f"{b} {s:.1f}s" for b, s in buckets))
    spans = summary.get("spans")
    if spans:  # KIND_SPAN rollup (core/tracing.py trace spans)
        svcs = ", ".join(
            f"{k}={v}" for k, v in sorted(spans.get("services", {}).items()))
        lines.append(
            f"  spans: {spans['count']} across {spans['traces']} trace(s)"
            + (f" [{svcs}]" if svcs else "")
            + (f", {spans['errors']} error(s)" if spans.get("errors") else "")
        )
    at = summary.get("autotune")
    if at:  # KIND_AUTOTUNE_TRIAL rollup (the autotuner's trial ledger)
        lines.append(
            f"  autotune: {at['ran']} ran / {at['pruned']} pruned / "
            f"{at['failed']} failed"
            + (f", {at['window_aborts']} window abort(s)"
               if at.get("window_aborts") else "")
        )
        best = at.get("best")
        if best:
            lines.append(
                f"    best: {best.get('trial')} score {best.get('score')}"
                + (f" {best['unit']}" if best.get("unit") else "")
            )
    mem = summary.get("memory")
    if mem:  # KIND_MEMORY rollup
        srcs = ", ".join(
            f"{k}={v}" for k, v in sorted(mem.get("sources", {}).items()))
        peak = mem.get("peak_bytes_in_use")
        lines.append(
            f"  memory: {mem['samples']} sample(s)"
            + (f", peak {fmt_bytes(peak)}/chip in use" if peak else "")
            + (f" [{srcs}]" if srcs else "")
        )
        ana = mem.get("analysis")
        if ana:
            lines.append(
                "    compiled step: args {a} + temps {t} + output {o}"
                " (+ code {c})".format(
                    a=fmt_bytes(ana.get("argument_bytes")),
                    t=fmt_bytes(ana.get("temp_bytes")),
                    o=fmt_bytes(ana.get("output_bytes")),
                    c=fmt_bytes(ana.get("generated_code_bytes")),
                )
            )
    for s in summary.get("startups") or []:  # KIND_STARTUP rollup
        t = s.get("time_to_first_step_s")
        t_str = f"{t:.1f}s" if isinstance(t, (int, float)) else "?"
        lines.append(
            f"  startup: {t_str} to first step"
            + (f" (restored step {s['restored_step']})"
               if s.get("restored_step") is not None else " (fresh)")
        )
    rec = summary["recovery"]
    activity = (
        rec["quarantined"] or rec["restore_fallbacks"]
        or rec["supervisor_attempts"] or rec["graceful_preemptions"]
        or rec["failures"] or rec["crash_loop"]
        or rec.get("anomalies") or rec.get("rollbacks")
        or rec.get("batches_skipped") or rec.get("infeed_stalls")
        or rec.get("mesh_resizes") or rec.get("ckpt_reshards")
        or rec.get("data_restores")
    )
    if not activity:
        lines.append("  recovery activity: none")
        return "\n".join(lines)
    lines.append("  recovery activity:")
    for a in rec.get("anomalies") or []:  # KIND_ANOMALY rollup
        lines.append(
            f"    anomaly at step {a.get('step')}: "
            f"{a.get('anomaly', 'unknown')} ({a.get('metric')})"
        )
    for r in rec.get("rollbacks") or []:  # KIND_ROLLBACK rollup
        lines.append(
            f"    rollback: step {r['from_step']} -> {r['to_step']}"
        )
    if rec.get("batches_skipped"):  # KIND_BATCH_SKIPPED rollup
        lines.append(f"    batches skipped: {rec['batches_skipped']}")
    if rec.get("infeed_stalls"):  # KIND_INFEED_STALL rollup
        lines.append(f"    infeed stalls retried: {rec['infeed_stalls']}")
    for m in rec.get("mesh_resizes") or []:  # KIND_MESH_RESIZED
        lines.append(
            f"    mesh resized: {_fmt_axes(m.get('from_axes'))} -> "
            f"{_fmt_axes(m.get('to_axes'))} "
            f"({m.get('visible_devices', '?')} devices visible)"
        )
    for r in rec.get("ckpt_reshards") or []:  # KIND_CKPT_RESHARDED
        lines.append(
            f"    checkpoint resharded at step {r.get('step')}: "
            f"{_fmt_axes(r.get('from_axes'))} -> {_fmt_axes(r.get('to_axes'))}"
            f" ({r.get('leaf_count', '?')} leaves)"
        )
    for d in rec.get("data_restores") or []:  # KIND_DATA_STATE rollup
        action = d.get("action") or "resume"
        refit = (f" across {d['from_processes']} -> {d['to_processes']} hosts"
                 if d.get("from_processes") != d.get("to_processes") else "")
        lines.append(
            f"    data state restored at step {d.get('step')}: "
            f"{action}{refit}"
            + (f" (watermark {d['watermark']})"
               if d.get("watermark") else "")
        )
    for q in rec["quarantined"]:  # KIND_CKPT_QUARANTINED rollup
        lines.append(
            f"    quarantined checkpoint step {q['step']} ({q['reason']})"
        )
    for f in rec["restore_fallbacks"]:  # KIND_RESTORE_FALLBACK rollup
        lines.append(
            f"    restore fell back: step {f['from_step']} -> {f['to_step']}"
        )
    if rec["supervisor_attempts"]:  # KIND_SUPERVISOR_ATTEMPT rollup
        lines.append(
            "    supervisor attempts: " + ", ".join(
                f"{k}={v}"
                for k, v in sorted(rec["supervisor_attempts"].items())
            )
        )
    if rec["graceful_preemptions"]:
        lines.append(
            f"    graceful preemptions: {rec['graceful_preemptions']}"
        )
    for f in rec["failures"]:  # KIND_FAILURE rollup
        lines.append(f"    failure at step {f.get('step')}: "
                     f"{f.get('failure', 'unknown')}")
    if rec["crash_loop"]:  # KIND_CRASH_LOOP rollup
        lines.append(f"    CRASH LOOP: {json.dumps(rec['crash_loop'])}")
    return "\n".join(lines)
