"""XPlane trace analysis: where did the traced step time actually go?

ProfileHook / BENCH_TRACE capture ``*.xplane.pb`` (an ``XSpace`` proto of
planes → lines → events). This module turns one into a time-by-category
breakdown — GEMM/conv, collectives, infeed/host transfers, optimizer
update, other compute, launch gaps — without any profiler-proto Python
package: the image ships no ``xplane_pb2``, so a ~80-line protobuf
wire-format reader below decodes the handful of fields we need. Field
numbers follow tensorflow/tsl ``xplane.proto`` (stable since 2019).

Attribution has two layers:

  * Event names. XLA trace events are named by HLO *instruction*
    (``dot.11``, ``all-reduce.3``, ``multiply_add_fusion``) — enough for
    GEMM/collective/infeed classification by opcode pattern.
  * Optimized-HLO side channel. Instruction names carry no scope, but the
    compiled executable's HLO text names instructions identically AND
    records ``metadata={op_name="...optimizer_update/mul"}`` per op. When
    the caller passes that text (ProfileHook dumps ``train_step.hlo.txt``
    next to the trace; bench dumps under BENCH_TRACE), events are mapped
    through it and scope-based categories (optimizer_update) attach.

The breakdown is exhaustive over the traced window: busy time (union of
executor-line event intervals) is split over categories proportionally to
their summed event durations (concurrent executor threads can sum past
wall time — the proportional split keeps categories + launch_gap == the
window, so fractions are honest wall-clock shares).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Iterator, Mapping

from distributed_tensorflow_framework_tpu.core import telemetry

# ------------------------------------------------------------------ wire --
# Minimal protobuf wire-format reader. Wire types: 0 varint, 1 fixed64,
# 2 length-delimited, 5 fixed32.


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long — corrupt protobuf")


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over one message's bytes."""
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 1:
            val, pos = buf[pos:pos + 8], pos + 8
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val, pos = buf[pos:pos + ln], pos + ln
        elif wire == 5:
            val, pos = buf[pos:pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported wire type {wire} (field {field})")
        yield field, wire, val


def _signed(v: int) -> int:
    """Reinterpret a varint as two's-complement int64 (proto int64)."""
    return v - (1 << 64) if v >= (1 << 63) else v


# ---------------------------------------------------------------- schema --


@dataclasses.dataclass
class TraceEvent:
    name: str
    start_ps: int  # absolute, within the trace's timebase
    duration_ps: int
    line: str
    plane: str


def _parse_map_entry(buf: bytes) -> tuple[int, bytes]:
    key, val = 0, b""
    for f, _, v in _fields(buf):
        if f == 1:
            key = v
        elif f == 2:
            val = v
    return key, val


def _parse_event_metadata(buf: bytes) -> str:
    name = display = ""
    for f, _, v in _fields(buf):
        if f == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4:
            display = v.decode("utf-8", "replace")
    return display or name


def parse_xspace(data: bytes) -> list[TraceEvent]:
    """Decode an XSpace blob into flat TraceEvents (only timed fields)."""
    events: list[TraceEvent] = []
    for f, _, plane_buf in _fields(data):
        if f != 1:  # XSpace.planes
            continue
        plane_name = ""
        metadata: dict[int, str] = {}
        line_bufs: list[bytes] = []
        for pf, _, pv in _fields(plane_buf):
            if pf == 2:  # XPlane.name
                plane_name = pv.decode("utf-8", "replace")
            elif pf == 3:  # XPlane.lines
                line_bufs.append(pv)
            elif pf == 4:  # XPlane.event_metadata (map)
                k, v = _parse_map_entry(pv)
                metadata[k] = _parse_event_metadata(v)
        for line_buf in line_bufs:
            line_name = ""
            ts_ns = 0
            event_bufs: list[bytes] = []
            for lf, _, lv in _fields(line_buf):
                if lf == 2:  # XLine.name
                    line_name = lv.decode("utf-8", "replace")
                elif lf == 3:  # XLine.timestamp_ns
                    ts_ns = _signed(lv)
                elif lf == 4:  # XLine.events
                    event_bufs.append(lv)
                elif lf == 11 and not line_name:  # display_name
                    line_name = lv.decode("utf-8", "replace")
            base_ps = ts_ns * 1000
            for ev_buf in event_bufs:
                mid = offset = dur = 0
                for ef, _, evv in _fields(ev_buf):
                    if ef == 1:
                        mid = evv
                    elif ef == 2:
                        offset = _signed(evv)
                    elif ef == 3:
                        dur = _signed(evv)
                if dur <= 0:
                    continue  # instantaneous markers carry no time
                events.append(TraceEvent(
                    name=metadata.get(mid, f"?{mid}"),
                    start_ps=base_ps + offset,
                    duration_ps=dur,
                    line=line_name,
                    plane=plane_name,
                ))
    return events


# ------------------------------------------------------- classification --

CATEGORIES = (
    "gemm_conv", "collectives", "infeed", "optimizer_update",
    "other_compute",
)
GAP = "launch_gap"

_COLLECTIVE_RE = re.compile(
    r"all-reduce|all-gather|all-to-all|reduce-scatter|collective-permute"
    r"|psum|ppermute", re.I)
_GEMM_RE = re.compile(r"\bdot\b|^dot[._]|convolution|conv[._\d]|gemm|matmul", re.I)
_INFEED_RE = re.compile(r"infeed|outfeed|copy[-._]|^copy|transfer|buffer[- ]", re.I)
# Executor lines: XLA:CPU client threads (tf_XLATfrtCpuClient/<n>) or
# TPU/GPU device streams. The "python" host line (PjitFunction spans etc.)
# wraps device time and must not be double counted.
_EXECUTOR_LINE_RE = re.compile(r"XLA|TfrtCpuClient|/device:|Stream|TensorFlow", re.I)
# Runtime bookkeeping spans that WRAP the real op events on the same lines
# (ThunkExecutor::Execute covers the whole dispatch including its waits).
# Dropped entirely: leaf ops define busy time, so wrapper-only time —
# genuinely waiting — lands in launch_gap instead of other_compute.
_WRAPPER_EVENT_RE = re.compile(
    r"ThunkExecutor|TfrtCpuExecutable|PjitFunction|ThreadpoolListener"
    r"|ExecuteGraph|BufferAllocations|RunId", re.I)

# HLO text: `  %name.1 = f32[...] opcode(...), metadata={op_name="..."}`
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.-]+)\s*=\s*[^\s]+\s+([\w-]+)\(")
_HLO_OPNAME_RE = re.compile(r'op_name="([^"]*)"')


def parse_hlo_op_map(hlo_text: str) -> dict[str, tuple[str, str]]:
    """instruction name → (opcode, op_name scope path) from HLO text."""
    out: dict[str, tuple[str, str]] = {}
    for line in hlo_text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if not m:
            continue
        name, opcode = m.groups()
        op = _HLO_OPNAME_RE.search(line)
        out[name] = (opcode, op.group(1) if op else "")
    return out


def classify(name: str, hlo_map: Mapping[str, tuple[str, str]] | None) -> str:
    opcode, scope = "", ""
    if hlo_map:
        opcode, scope = hlo_map.get(name, ("", ""))
    if "optimizer_update" in scope:
        return "optimizer_update"
    subject = f"{name} {opcode}"
    if _COLLECTIVE_RE.search(subject):
        return "collectives"
    if _GEMM_RE.search(subject):
        return "gemm_conv"
    if _INFEED_RE.search(subject):
        return "infeed"
    return "other_compute"


# ----------------------------------------------------------- aggregation --


def _union_ps(intervals: list[tuple[int, int]]) -> int:
    if not intervals:
        return 0
    intervals.sort()
    total = 0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def analyze(
    events: list[TraceEvent],
    hlo_map: Mapping[str, tuple[str, str]] | None = None,
    *,
    top_n: int = 15,
) -> dict[str, Any]:
    """Category breakdown over the executor window (see module docstring)."""
    exe = [e for e in events if _EXECUTOR_LINE_RE.search(e.line)]
    if not exe:
        # Unknown runtime naming — degrade to every timed event rather
        # than an empty report.
        exe = events
    leaf = [e for e in exe if not _WRAPPER_EVENT_RE.search(e.name)]
    if leaf:
        exe = leaf
    if not exe:
        raise ValueError("trace contains no timed events")

    window_start = min(e.start_ps for e in exe)
    window_end = max(e.start_ps + e.duration_ps for e in exe)
    window_ps = window_end - window_start
    busy_ps = _union_ps([(e.start_ps, e.start_ps + e.duration_ps) for e in exe])
    busy_ps = min(busy_ps, window_ps)
    gap_ps = window_ps - busy_ps

    raw: dict[str, int] = {c: 0 for c in CATEGORIES}
    per_op: dict[str, int] = {}
    for e in exe:
        raw[classify(e.name, hlo_map)] += e.duration_ps
        per_op[e.name] = per_op.get(e.name, 0) + e.duration_ps
    raw_total = sum(raw.values()) or 1

    # Proportional wall-clock attribution (see module docstring).
    breakdown: dict[str, dict[str, float]] = {}
    for cat in CATEGORIES:
        wall = busy_ps * raw[cat] / raw_total
        breakdown[cat] = {
            "time_ps": int(wall),
            "fraction_of_window": wall / window_ps if window_ps else 0.0,
            "summed_event_ps": raw[cat],
        }
    breakdown[GAP] = {
        "time_ps": int(gap_ps),
        "fraction_of_window": gap_ps / window_ps if window_ps else 0.0,
        "summed_event_ps": int(gap_ps),
    }
    covered = sum(v["time_ps"] for v in breakdown.values())

    top_ops = sorted(per_op.items(), key=lambda kv: -kv[1])[:top_n]
    return {
        "window_ps": int(window_ps),
        "busy_ps": int(busy_ps),
        "launch_gap_ps": int(gap_ps),
        "coverage": covered / window_ps if window_ps else 0.0,
        "num_events": len(exe),
        "hlo_map_used": bool(hlo_map),
        "breakdown": breakdown,
        "top_ops": [
            {"name": n, "summed_ps": d,
             "category": classify(n, hlo_map)}
            for n, d in top_ops
        ],
    }


# ------------------------------------------------------------ entrypoints --


def find_xplane_files(path: str) -> list[str]:
    """Accept a trace file, a trace dir, or a profiler logdir root."""
    if os.path.isfile(path):
        return [path]
    hits: list[str] = []
    for root, _, names in os.walk(path):
        hits.extend(os.path.join(root, n) for n in names
                    if n.endswith(".xplane.pb"))
    return sorted(hits)


def find_hlo_text(trace_path: str) -> str | None:
    """Locate a dumped HLO text near the trace (ProfileHook/bench layout)."""
    d = trace_path if os.path.isdir(trace_path) else os.path.dirname(trace_path)
    for _ in range(6):  # walk up through plugins/profile/<ts>/ nesting
        for name in sorted(os.listdir(d) if os.path.isdir(d) else []):
            if name.endswith(".hlo.txt"):
                return os.path.join(d, name)
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def analyze_trace_file(
    trace_path: str, hlo_text: str | None = None, *, top_n: int = 15,
) -> dict[str, Any]:
    with open(trace_path, "rb") as fh:
        events = parse_xspace(fh.read())
    hlo_map = parse_hlo_op_map(hlo_text) if hlo_text else None
    report = analyze(events, hlo_map, top_n=top_n)
    report["trace_path"] = trace_path
    return report


def format_report(report: dict[str, Any]) -> str:
    """Human-readable breakdown table."""
    def ms(ps: float) -> str:
        return f"{ps / 1e9:10.3f} ms"

    lines = [
        f"trace: {report.get('trace_path', '<memory>')}",
        f"window: {ms(report['window_ps'])}   busy: {ms(report['busy_ps'])}   "
        f"events: {report['num_events']}   "
        f"hlo attribution: {'yes' if report['hlo_map_used'] else 'no'}",
        "",
        f"{'category':<18} {'wall time':>13} {'% window':>9} {'event sum':>13}",
    ]
    for cat in (*CATEGORIES, GAP):
        b = report["breakdown"][cat]
        lines.append(
            f"{cat:<18} {ms(b['time_ps']):>13} "
            f"{100 * b['fraction_of_window']:>8.1f}% {ms(b['summed_event_ps']):>13}"
        )
    lines.append(f"{'TOTAL':<18} {'':>13} {100 * report['coverage']:>8.1f}%")
    lines.append("")
    lines.append("top ops by summed event time:")
    for op in report["top_ops"]:
        lines.append(
            f"  {ms(op['summed_ps'])}  [{op['category']:<16}] {op['name']}"
        )
    return "\n".join(lines)


def write_summary_event(report: dict[str, Any], out_path: str,
                        run_id: str | None = None) -> dict:
    """Persist the report as a schema-versioned trace_summary event."""
    writer = telemetry.TelemetryWriter(out_path, run_id=run_id)
    try:
        return writer.emit(
            telemetry.KIND_TRACE_SUMMARY,
            metrics={
                "window_ms": report["window_ps"] / 1e9,
                "busy_ms": report["busy_ps"] / 1e9,
                "launch_gap_ms": report["launch_gap_ps"] / 1e9,
                "coverage": report["coverage"],
            },
            phases={
                cat: report["breakdown"][cat]["time_ps"] / 1e9
                for cat in (*CATEGORIES, GAP)
            },
            trace_path=report.get("trace_path", ""),
            hlo_map_used=report["hlo_map_used"],
            top_ops=json.dumps(report["top_ops"][:5]),
        )
    finally:
        writer.close()
