"""Distributed tracing + flight recorder (stdlib-only control plane).

The framework spans four cooperating process families — the fleet router,
N replica servers, the gang supervisor and N training workers — each with
its own per-process JSONL telemetry island. This module gives them ONE
causal story:

* **Spans** — ``(trace_id, span_id, parent_id)`` with wall + monotonic
  timestamps, emitted as ``KIND_SPAN`` telemetry events so they ride the
  existing schema, writers and readers unchanged. A span's lifetime is
  ``Tracer.start(...)`` → ``Span.end(...)``; work that was measured before
  tracing existed (engine batch timestamps) is backfilled with
  ``Tracer.emit_span`` from raw monotonic readings.

* **Context propagation** — ``SpanContext`` serializes to the
  ``X-DTF-Trace`` HTTP header (router → replica server → engine) and the
  ``DTF_TRACE_CTX`` env var (gang supervisor → worker), so a client
  request or a supervised gang attempt hangs off one root span no matter
  how many processes it crosses.

* **Clock model** — every process derives span wall times from ONE pair
  ``(wall0, mono0)`` sampled at tracer construction: ``wall0 + (mono -
  mono0)``. That makes per-process timestamps internally consistent
  (immune to mid-run wall jumps) but says nothing about cross-host skew,
  so a context carries ``sent_at`` (the sender's best estimate of
  root-frame time at propagation) and ``Tracer.adopt`` estimates
  ``offset_s = local_now - sent_at`` — local skew plus transmission
  delay. Spans carry the estimate; ``scripts/analyze_trace.py --spans``
  subtracts it to map every stream into the root's time frame and
  additionally clamps children into their parent's window (the causal
  floor) for propagation paths where the delay term dominates (env
  propagation pays process startup). ``DTF_TRACE_SKEW_S`` injects an
  artificial wall skew for tests of exactly this model.

* **Flight recorder** — a bounded ring of recent telemetry events
  (spans included) per process, attached as a ``TelemetryWriter``
  listener. On anomaly escalation, a supervisor-observed crash, or
  SIGUSR1 it dumps ``flightrec-<pid>.json`` with the ring plus every
  still-open span, so post-mortem forensics don't depend on the full
  JSONL having survived the failure.

See docs/OBSERVABILITY.md "Tracing and flight recorder".
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import tempfile
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any

from distributed_tensorflow_framework_tpu.core import telemetry

log = logging.getLogger("dtf_tpu.tracing")

#: HTTP header carrying a serialized SpanContext (fleet → server → engine).
TRACE_HEADER = "X-DTF-Trace"
#: Env var carrying a serialized SpanContext (supervisor → worker).
TRACE_CTX_ENV = "DTF_TRACE_CTX"
#: Default directory for flight-recorder dumps + drill trace artifacts.
TRACE_DIR_ENV = "DTF_TRACE_DIR"
#: Injected wall-clock skew in seconds (clock-model tests only).
TRACE_SKEW_ENV = "DTF_TRACE_SKEW_S"

FLIGHTREC_SCHEMA = "dtf-flightrec/1"


class TraceContextError(ValueError):
    """A serialized trace context (header or env value) failed to parse.

    Raised by ``SpanContext.parse``; propagation call sites catch it (or
    use ``safe_parse``) and continue untraced — a malformed header must
    never fail the request it rode in on.
    """


def _new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def _new_span_id() -> str:
    return uuid.uuid4().hex[:8]


@dataclass(frozen=True)
class SpanContext:
    """The cross-process handle to a span: ids + a send-time clock sample.

    ``span_id`` may be ``""`` for a context that names a trace but no
    emitting span (a pure client like scripts/load_gen.py): spans adopted
    from such a context become roots of the reconstructed tree.
    ``sent_at`` is the sender's estimate of ROOT-frame wall seconds at
    propagation time — the receiving tracer's offset estimator needs it.
    """

    trace_id: str
    span_id: str = ""
    sent_at: float = 0.0

    def encode(self) -> str:
        return f"{self.trace_id}:{self.span_id}:{self.sent_at:.6f}"

    @classmethod
    def parse(cls, value: str) -> "SpanContext":
        parts = (value or "").strip().split(":")
        if len(parts) != 3 or not parts[0]:
            raise TraceContextError(
                f"trace context {value!r} is not 'trace_id:span_id:sent_at'")
        try:
            sent_at = float(parts[2])
        except ValueError as e:
            raise TraceContextError(
                f"trace context {value!r} has a non-numeric sent_at") from e
        return cls(trace_id=parts[0], span_id=parts[1], sent_at=sent_at)


def safe_parse(value: str | None) -> SpanContext | None:
    """``SpanContext.parse`` that answers None for missing/bad contexts."""
    if not value:
        return None
    try:
        return SpanContext.parse(value)
    except TraceContextError:
        log.warning("ignoring malformed trace context %r", value)
        return None


def fresh_context(now: float | None = None) -> SpanContext:
    """A brand-new trace with no emitting span — the pure-client root
    (scripts/load_gen.py stamps one per request)."""
    return SpanContext(
        trace_id=_new_trace_id(), span_id="",
        sent_at=time.time() if now is None else now)


def env_context(environ=None) -> SpanContext | None:
    """The DTF_TRACE_CTX context of this process, if a supervisor set one."""
    env = os.environ if environ is None else environ
    return safe_parse(env.get(TRACE_CTX_ENV))


class Span:
    """One in-flight span; ``end()`` emits it as a KIND_SPAN event."""

    __slots__ = ("tracer", "trace_id", "span_id", "parent_id", "name",
                 "t0_mono", "attrs", "ended")

    def __init__(self, tracer: "Tracer", trace_id: str, span_id: str,
                 parent_id: str | None, name: str, t0_mono: float,
                 attrs: dict):
        self.tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0_mono = t0_mono
        self.attrs = attrs
        self.ended = False

    def context(self) -> SpanContext:
        """Propagation handle for children of this span (header/env)."""
        return SpanContext(
            trace_id=self.trace_id, span_id=self.span_id,
            sent_at=self.tracer.root_frame_now())

    def end(self, status: str = "ok", **attrs: Any) -> dict:
        if self.ended:  # idempotent: crash paths may race the normal end
            return {}
        self.ended = True
        self.attrs.update(attrs)
        return self.tracer._emit(
            self, end_mono=time.monotonic(), status=status)

    def snapshot(self) -> dict:
        """Open-span record for flight-recorder dumps (never emitted)."""
        return {
            "trace": self.trace_id, "span": self.span_id,
            "parent": self.parent_id, "name": self.name,
            "service": self.tracer.service,
            "t_start": self.tracer.wall_of(self.t0_mono),
            "offset_s": self.tracer.offset_s,
            "attrs": dict(self.attrs), "open": True,
        }


class Tracer:
    """Per-process span factory bound to one TelemetryWriter.

    Span wall times derive from the construction-time ``(wall0, mono0)``
    pair (see module docstring); ``adopt()`` folds an incoming context
    into the per-process ``offset_s`` estimate that every emitted span
    carries for the analyzer's cross-stream stitching.
    """

    def __init__(self, writer: telemetry.TelemetryWriter | None = None,
                 *, service: str = "proc", skew_s: float | None = None):
        self.writer = writer
        self.service = service
        if skew_s is None:
            try:
                skew_s = float(os.environ.get(TRACE_SKEW_ENV, "0") or 0)
            except ValueError:
                skew_s = 0.0
        self.skew_s = skew_s
        self.mono0 = time.monotonic()
        self.wall0 = time.time() + skew_s
        self.offset_s = 0.0
        self._lock = threading.Lock()
        self._open: dict[str, Span] = {}

    # ------------------------------------------------------------- clock --
    def wall_of(self, mono: float) -> float:
        """This process's wall-clock reading for a monotonic instant."""
        return self.wall0 + (mono - self.mono0)

    def now(self) -> float:
        return self.wall_of(time.monotonic())

    def root_frame_now(self) -> float:
        """Local now mapped into the trace root's clock frame."""
        return self.now() - self.offset_s

    def adopt(self, ctx: SpanContext | None) -> None:
        """Estimate this process's clock offset from an incoming context:
        ``offset_s = local_now - ctx.sent_at`` (skew + transmission
        delay). Call it as close to receipt as possible — for HTTP the
        delay term is sub-millisecond; for env propagation it includes
        process startup and the analyzer's causal clamp absorbs it."""
        if ctx is None or not ctx.sent_at:
            return
        self.offset_s = self.now() - ctx.sent_at

    # ------------------------------------------------------------- spans --
    def start(self, name: str,
              parent: "Span | SpanContext | None" = None,
              **attrs: Any) -> Span:
        """Open a span. ``parent`` may be a local Span, a propagated
        SpanContext, or None (a fresh root trace)."""
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, SpanContext):
            trace_id = parent.trace_id
            parent_id = parent.span_id or None
        else:
            trace_id, parent_id = _new_trace_id(), None
        span = Span(self, trace_id, _new_span_id(), parent_id, name,
                    time.monotonic(), dict(attrs))
        with self._lock:
            self._open[span.span_id] = span
        return span

    def emit_span(self, name: str,
                  parent: "Span | SpanContext | None" = None, *,
                  start_mono: float, end_mono: float,
                  status: str = "ok", **attrs: Any) -> dict:
        """Backfill a span from raw monotonic readings already taken —
        the engine's enqueue/batch-form/compute timestamps predate
        tracing and are reused rather than re-measured."""
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, SpanContext):
            trace_id = parent.trace_id
            parent_id = parent.span_id or None
        else:
            trace_id, parent_id = _new_trace_id(), None
        span = Span(self, trace_id, _new_span_id(), parent_id, name,
                    start_mono, dict(attrs))
        span.ended = True
        return self._emit(span, end_mono=end_mono, status=status,
                          track=False)

    def open_spans(self) -> list[dict]:
        """Snapshots of every span started but not yet ended — the
        flight recorder includes them so a dump taken mid-request still
        shows the fault's ancestors."""
        with self._lock:
            return [s.snapshot() for s in self._open.values()]

    def _emit(self, span: Span, *, end_mono: float, status: str,
              track: bool = True) -> dict:
        if track:
            with self._lock:
                self._open.pop(span.span_id, None)
        t_start = self.wall_of(span.t0_mono)
        dur_ms = max(0.0, (end_mono - span.t0_mono) * 1e3)
        if self.writer is None:
            return {}
        return self.writer.emit(
            telemetry.KIND_SPAN,
            t=self.wall_of(end_mono),
            metrics={"dur_ms": dur_ms},
            trace=span.trace_id, span=span.span_id,
            parent=span.parent_id, name=span.name,
            service=self.service, status=status,
            t_start=t_start, offset_s=self.offset_s,
            attrs=span.attrs or None,
        )


class FlightRecorder:
    """Bounded in-memory ring of recent telemetry events (spans included).

    Attach with ``writer.add_listener(recorder.record)`` (or
    ``recorder.attach(writer)``); ``dump()`` writes the ring — plus any
    still-open spans the caller hands over — to ``flightrec-<pid>.json``
    so the fault's causal neighborhood survives even when the process is
    about to be SIGKILLed or its JSONL is torn.

    Triggers wired in this repo: the trainer's anomaly escalation
    (train/loop.py), the gang supervisor observing a crashed/hung worker
    (scripts/train_cluster.py), replica death seen by the fleet prober
    (serve/fleet.py), graceful preemption, and SIGUSR1 on demand.
    """

    def __init__(self, capacity: int = 512, *, dump_dir: str | None = None,
                 tracer: Tracer | None = None):
        if capacity < 1:
            raise ValueError(f"flight recorder capacity must be >= 1, "
                             f"got {capacity}")
        self._ring: deque = deque(maxlen=int(capacity))
        self._lock = threading.Lock()
        self.dump_dir = dump_dir or None
        self.tracer = tracer
        self.dumps = 0
        self._writer: "telemetry.TelemetryWriter | None" = None

    def record(self, event: dict) -> None:
        """TelemetryWriter listener: must be fast, must not raise."""
        with self._lock:
            self._ring.append(event)

    def attach(self, writer: telemetry.TelemetryWriter) -> "FlightRecorder":
        writer.add_listener(self.record)
        # Remember the writer: its JSONL directory is the run's log dir,
        # which default_path() prefers over littering the cwd.
        if self._writer is None:
            self._writer = writer
        return self

    def default_path(self) -> str:
        """Dump location: explicit dump_dir → DTF_TRACE_DIR → the
        attached writer's log directory → the system temp dir. The
        writer fallback is what keeps `flightrec-*.json` out of the
        repo root when tests (or ad-hoc runs) never set the env var —
        the dump lands next to the run's own telemetry instead. A
        recorder with no directory clue at all (stderr-only writer,
        e.g. a supervisor run without checkpoint.directory) dumps to
        tempfile.gettempdir(): never the process cwd, which under
        pytest is the repo root."""
        base = self.dump_dir or os.environ.get(TRACE_DIR_ENV)
        if not base and self._writer is not None:
            writer_path = getattr(self._writer, "path", None)
            if writer_path:
                base = os.path.dirname(os.path.abspath(writer_path))
        return os.path.join(base or tempfile.gettempdir(),
                            f"flightrec-{os.getpid()}.json")

    def dump(self, reason: str, *, path: str | None = None,
             open_spans: list[dict] | None = None) -> str | None:
        """Write the ring to disk; returns the path (None on failure —
        dumping is forensics, it must never take down the process)."""
        path = path or self.default_path()
        if open_spans is None and self.tracer is not None:
            open_spans = self.tracer.open_spans()
        with self._lock:
            events = list(self._ring)
        doc = {
            "schema": FLIGHTREC_SCHEMA,
            "pid": os.getpid(),
            "host": socket.gethostname(),
            "reason": reason,
            "t": time.time(),
            "event_count": len(events),
            "events": events,
            "open_spans": open_spans or [],
        }
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(doc, fh, default=str)
                fh.write("\n")
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except OSError:
            log.exception("flight recorder dump to %s failed", path)
            return None
        self.dumps += 1
        log.warning("flight recorder dumped %d event(s) to %s (%s)",
                    len(events), path, reason)
        return path

    def install_sigusr1(self) -> bool:
        """SIGUSR1 → dump (main thread only; returns False elsewhere)."""

        def _handler(signum, frame):
            self.dump("SIGUSR1")

        try:
            signal.signal(signal.SIGUSR1, _handler)
            return True
        except (ValueError, OSError):  # non-main thread / exotic platform
            return False
