"""Input pipelines.

SURVEY.md §2 row 5: the reference's L3 is a tf.data pipeline (TFRecord →
decode/augment → shuffle → batch → prefetch) feeding each worker's GPU.
Here each *host* runs a tf.data (or pure-numpy synthetic) pipeline producing
its share of the global batch; `infeed.to_global` assembles the host-local
shards into one mesh-sharded `jax.Array` (the "per-replica infeed" of
BASELINE.json's north star).

Factories are registered by name and return a `HostDataset`. The
reference is a framework TEMPLATE whose other extension point is "user
contributes a dataset factory" (SURVEY.md §1 L3); ``register_dataset``
is that hook here — a user factory slots into the same per-host
sharding, infeed, checkpointable-iterator and exact-eval machinery as
the built-ins.
"""

from __future__ import annotations

from typing import Callable

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data.pipeline import (  # noqa: F401
    HostDataset,
)

# name → factory(config, process_index, process_count, train) -> HostDataset
_CUSTOM_DATASETS: dict[str, Callable[..., "HostDataset"]] = {}


def _is_builtin_dataset_name(name: str) -> bool:
    """Name twin of get_dataset's dispatch below — keep the two in sync
    when adding a pipeline (the whole synthetic* prefix is reserved)."""
    return name.startswith("synthetic") or name in (
        "mnist", "cifar10", "imagenet", "text_mlm", "mlm")


def register_dataset(name: str):
    """Register a user dataset factory under ``data.name`` (decorator).

    The factory must return a ``HostDataset`` yielding THIS PROCESS'S
    share of each global batch (``global_batch_size // process_count``
    rows — see pipeline.host_batch_size) and honor the iterator
    state()/restore() contract for exact resume. Finite eval streams
    should set ``cardinality`` and pad the final batch with zero-weight
    rows (the exact-eval contract; pipeline.finite_array_eval is the
    reusable helper). Built-in names cannot be shadowed.

        @register_dataset("my_corpus")
        def build(config, process_index, process_count, *, train=True):
            return HostDataset(...)
    """
    key = name.lower()

    def deco(factory):
        if key in _CUSTOM_DATASETS:
            raise ValueError(f"dataset {name!r} already registered")
        if _is_builtin_dataset_name(key):
            raise ValueError(f"dataset {name!r} shadows a built-in")
        _CUSTOM_DATASETS[key] = factory
        return factory

    return deco


def get_dataset(config: DataConfig, *, process_index: int = 0,
                process_count: int = 1, train: bool = True) -> "HostDataset":
    name = config.name.lower()
    if name in _CUSTOM_DATASETS:
        return _CUSTOM_DATASETS[name](
            config, process_index, process_count, train=train)
    if name.startswith("synthetic"):
        from distributed_tensorflow_framework_tpu.data import synthetic

        if "mlm" in name or "text" in name:
            return synthetic.synthetic_mlm(config, process_index, process_count)
        return synthetic.synthetic_images(config, process_index, process_count)
    if name == "mnist":
        from distributed_tensorflow_framework_tpu.data import mnist

        return mnist.make_mnist(config, process_index, process_count, train=train)
    if name == "cifar10":
        from distributed_tensorflow_framework_tpu.data import cifar

        return cifar.make_cifar10(config, process_index, process_count, train=train)
    if name == "imagenet":
        from distributed_tensorflow_framework_tpu.data import imagenet

        return imagenet.make_imagenet(config, process_index, process_count, train=train)
    if name in ("text_mlm", "mlm"):
        from distributed_tensorflow_framework_tpu.data import text_mlm

        return text_mlm.make_mlm(config, process_index, process_count, train=train)
    raise ValueError(f"Unknown dataset {config.name!r}")
