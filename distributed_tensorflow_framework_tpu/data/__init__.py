"""Input pipelines.

SURVEY.md §2 row 5: the reference's L3 is a tf.data pipeline (TFRecord →
decode/augment → shuffle → batch → prefetch) feeding each worker's GPU.
Here each *host* runs a tf.data (or pure-numpy synthetic) pipeline producing
its share of the global batch; `infeed.to_global` assembles the host-local
shards into one mesh-sharded `jax.Array` (the "per-replica infeed" of
BASELINE.json's north star).

Factories are registered by name and return a `HostDataset`.
"""

from __future__ import annotations

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data.pipeline import (  # noqa: F401
    HostDataset,
)


def get_dataset(config: DataConfig, *, process_index: int = 0,
                process_count: int = 1, train: bool = True) -> "HostDataset":
    name = config.name.lower()
    if name.startswith("synthetic"):
        from distributed_tensorflow_framework_tpu.data import synthetic

        if "mlm" in name or "text" in name:
            return synthetic.synthetic_mlm(config, process_index, process_count)
        return synthetic.synthetic_images(config, process_index, process_count)
    if name == "mnist":
        from distributed_tensorflow_framework_tpu.data import mnist

        return mnist.make_mnist(config, process_index, process_count, train=train)
    if name == "cifar10":
        from distributed_tensorflow_framework_tpu.data import cifar

        return cifar.make_cifar10(config, process_index, process_count, train=train)
    if name == "imagenet":
        from distributed_tensorflow_framework_tpu.data import imagenet

        return imagenet.make_imagenet(config, process_index, process_count, train=train)
    if name in ("text_mlm", "mlm"):
        from distributed_tensorflow_framework_tpu.data import text_mlm

        return text_mlm.make_mlm(config, process_index, process_count, train=train)
    raise ValueError(f"Unknown dataset {config.name!r}")
