"""CIFAR-10 pipeline (BASELINE.json config 2).

Reads the python-pickle CIFAR-10 batches (``cifar-10-batches-py``) from
``data_dir``, with the reference recipe's augmentation: pad-4 + random
32×32 crop, random horizontal flip, per-image standardization
(SURVEY.md §2 row 5). Synthetic fallback when absent.
"""

from __future__ import annotations

import logging
import os
import pickle

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.core import prng
from distributed_tensorflow_framework_tpu.data.pipeline import (
    HostDataset,
    host_batch_size,
    image_np_dtype,
)
from distributed_tensorflow_framework_tpu.data import shard, synthetic

log = logging.getLogger(__name__)


def _load(data_dir: str, train: bool):
    base = os.path.join(data_dir, "cifar-10-batches-py")
    names = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
    xs, ys = [], []
    for n in names:
        with open(os.path.join(base, n), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        xs.append(d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        ys.append(np.asarray(d[b"labels"], dtype=np.int32))
    return np.concatenate(xs).astype(np.float32), np.concatenate(ys)


def make_cifar10(config: DataConfig, process_index: int, process_count: int,
                 *, train: bool = True) -> HostDataset:
    base = os.path.join(config.data_dir or "", "cifar-10-batches-py")
    if not (config.data_dir and os.path.isdir(base)):
        log.warning("CIFAR-10 not found at %r — synthetic fallback", base)
        return synthetic.synthetic_images(config, process_index, process_count)

    images, labels = _load(config.data_dir, train)
    b = host_batch_size(config.global_batch_size, process_count)
    n = len(images)
    out_dtype = image_np_dtype(config.image_dtype)

    def standardize(batch):
        mean = batch.mean(axis=(1, 2, 3), keepdims=True)
        std = batch.std(axis=(1, 2, 3), keepdims=True) + 1e-6
        return (batch - mean) / std

    if not train:
        # Exact single-pass eval: every test example once, no augmentation,
        # final batch zero-padded with per-example weights (data/pipeline.py).
        from distributed_tensorflow_framework_tpu.data.pipeline import (
            finite_array_eval,
        )

        return finite_array_eval(
            standardize(images).astype(out_dtype, copy=False), labels,
            batch=b, process_index=process_index,
            process_count=process_count, out_dtype=out_dtype,
        )

    block = config.shard_mode == "block"

    def make_iter(state):
        state.setdefault("epoch", 0)
        state.setdefault("batch_in_epoch", 0)
        while True:
            # Cross-host-shared shuffle (no process_index — see
            # core/prng.py host-side rules).
            rng = prng.host_rng(config.seed, prng.ROLE_DATA, state["epoch"])
            perm = rng.permutation(n)
            batches = shard.epoch_batches(n, b, process_count)
            for i in range(state["batch_in_epoch"], batches):
                if block:
                    # Block sharding (data/shard.py): host-count-invariant
                    # consumed prefix, so (epoch, batch_in_epoch) resumes
                    # exactly across an N→M refit. Sample IDENTITY
                    # survives the refit; the augmentation draw below is
                    # host-local by design and does not.
                    lo, hi = shard.block_bounds(
                        i, b, process_index, process_count)
                    idx = perm[lo:hi]
                else:
                    # Legacy stride sharding — not repartitionable.
                    idx = perm[process_index::process_count][i * b:(i + 1) * b]
                x = images[idx]
                if train:
                    # pad-4 + random crop + random flip (host-local
                    # augmentation: process_index IS in the derivation)
                    crop_rng = prng.host_rng(
                        config.seed, prng.ROLE_AUGMENT,
                        state["epoch"], i, process_index,
                    )
                    padded = np.pad(
                        x, ((0, 0), (4, 4), (4, 4), (0, 0)), mode="reflect"
                    )
                    out = np.empty_like(x)
                    offs = crop_rng.integers(0, 9, size=(len(x), 2))
                    flips = crop_rng.random(len(x)) < 0.5
                    for j in range(len(x)):
                        oy, ox = offs[j]
                        img = padded[j, oy:oy + 32, ox:ox + 32]
                        out[j] = img[:, ::-1] if flips[j] else img
                    x = out
                state["batch_in_epoch"] = i + 1
                yield {"image": standardize(x).astype(out_dtype, copy=False),
                       "label": labels[idx]}
            state["epoch"] += 1
            state["batch_in_epoch"] = 0

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((b, 32, 32, 3), out_dtype),
            "label": ((b,), np.int32),
        },
        initial_state={"epoch": 0, "batch_in_epoch": 0},
        cardinality=shard.epoch_batches(n, b, process_count),
        repartition=(shard.REPARTITION_INVARIANT if block
                     else shard.REPARTITION_NONE),
    )
