"""ImageNet TFRecord pipeline — the north-star input path.

SURVEY.md §2 row 5: TFRecord read → decode/augment (random crop, flip,
standardization) → shuffle → batch → prefetch. SURVEY.md §7 ranks host-side
input throughput as hard part #1: at ≥10k images/sec aggregate the decode
must be parallel and the pipeline must never sync with the device. Knobs
used: sharded file reading per host, ``interleave`` with parallel reads,
``num_parallel_calls=AUTOTUNE``, batch-then-prefetch.

Record format: the canonical ImageNet TFRecord keys (``image/encoded``
JPEG, ``image/class/label`` in [1, 1000]).
"""

from __future__ import annotations

import glob
import logging
import os

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.data.pipeline import (
    HostDataset,
    host_batch_size,
    image_np_dtype,
)
from distributed_tensorflow_framework_tpu.data import shard as data_shard
from distributed_tensorflow_framework_tpu.data import synthetic
from distributed_tensorflow_framework_tpu.data.tfdata import (
    count_records,
    eval_batches_all_hosts,
    tfdata_to_hostdataset,
)

log = logging.getLogger(__name__)

MEAN_RGB = (0.485 * 255, 0.456 * 255, 0.406 * 255)
STDDEV_RGB = (0.229 * 255, 0.224 * 255, 0.225 * 255)


_SIDECAR_EXTS = (".txt", ".json", ".yaml", ".csv")


def _record_files(config: DataConfig, train: bool) -> list[str]:
    # Canonical shard names: <split>-00000-of-00128, but accept any
    # <split>-* record file; only known sidecar extensions (stray label
    # maps, metadata json/csv a user drops next to the shards) are
    # filtered out, so a dataset with non-canonical shard names keeps
    # working.
    if not config.data_dir:
        return []
    sub = "train" if train else "validation"
    files = glob.glob(os.path.join(config.data_dir, f"{sub}-*"))
    return sorted(f for f in files if not f.lower().endswith(_SIDECAR_EXTS))




def make_imagenet(config: DataConfig, process_index: int, process_count: int,
                  *, train: bool = True) -> HostDataset:
    files = _record_files(config, train)
    if not files:
        log.warning(
            "ImageNet TFRecords not found under %r — synthetic fallback",
            config.data_dir,
        )
        cfg = config
        return synthetic.synthetic_images(cfg, process_index, process_count)

    if len(files) < process_count:
        # Same guard as data/text_mlm.py: an empty per-host file shard
        # would deadlock every host at the first collective.
        raise ValueError(
            f"ImageNet reader: {len(files)} TFRecord file(s) for "
            f"{process_count} processes — sharding by file needs at least "
            f"one file per process."
        )

    if config.use_native_reader:
        if train:
            return _make_imagenet_native(config, files, process_index,
                                         process_count)
        return _make_imagenet_native_eval(config, files, process_index,
                                          process_count)

    import tensorflow as tf

    b = host_batch_size(config.global_batch_size, process_count)
    size = config.image_size

    def parse(record, seed):
        feats = tf.io.parse_single_example(
            record,
            {
                "image/encoded": tf.io.FixedLenFeature([], tf.string),
                "image/class/label": tf.io.FixedLenFeature([], tf.int64),
            },
        )
        raw_label = tf.cast(feats["image/class/label"], tf.int32)
        # Out-of-range labels would NaN the loss metric downstream via
        # the CE gather's fill semantics — name the record problem here
        # (same guard as the native reader paths).
        with tf.control_dependencies([
            tf.debugging.assert_greater_equal(
                raw_label, 1,
                message="record label < 1 — records and the 1-based "
                        "label contract disagree"),
            tf.debugging.assert_less_equal(
                raw_label, config.num_classes,
                message="record label > data.num_classes"),
        ]):
            label = raw_label - 1                       # [1,1000]→[0,999]
        image_bytes = feats["image/encoded"]
        if train:
            # Sampled distorted bounding box crop (the Inception-style crop
            # of the reference recipe class), decode-and-crop fused so only
            # the crop window is JPEG-decoded.
            shape = tf.io.extract_jpeg_shape(image_bytes)
            bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
            begin, crop_size, _ = tf.image.stateless_sample_distorted_bounding_box(
                shape,
                bounding_boxes=bbox,
                seed=seed,
                min_object_covered=0.1,
                aspect_ratio_range=(3.0 / 4, 4.0 / 3),
                area_range=(0.08, 1.0),
                max_attempts=10,
            )
            offset_y, offset_x, _ = tf.unstack(begin)
            target_h, target_w, _ = tf.unstack(crop_size)
            image = tf.image.decode_and_crop_jpeg(
                image_bytes,
                tf.stack([offset_y, offset_x, target_h, target_w]),
                channels=3,
            )
            image = tf.image.resize(image, [size, size], method="bicubic")
            image = tf.image.stateless_random_flip_left_right(image, seed)
        else:
            image = tf.image.decode_jpeg(image_bytes, channels=3)
            # Central crop to 87.5% then resize (standard eval transform).
            image = tf.image.central_crop(image, 0.875)
            image = tf.image.resize(image, [size, size], method="bicubic")
        image = (tf.cast(image, tf.float32) - MEAN_RGB) / STDDEV_RGB
        if config.image_dtype == "bfloat16":
            image = tf.cast(image, tf.bfloat16)
        return {"image": image, "label": label}

    def make_ds(seed: int):
        ds = tf.data.Dataset.from_tensor_slices(files)
        # Disjoint file shard per host (the reference gave each worker its
        # own input stream; same contract, derived not configured).
        ds = ds.shard(process_count, process_index)
        # deterministic=True everywhere: the skip-count resume contract
        # (tfdata.py) requires the rebuilt pipeline to replay the identical
        # record order. Parallel reads still overlap; only output order is
        # pinned.
        ds = ds.interleave(
            lambda f: tf.data.TFRecordDataset(f, buffer_size=16 * 1024 * 1024),
            cycle_length=16,
            num_parallel_calls=tf.data.AUTOTUNE,
            deterministic=True,
        )
        if train:
            ds = ds.shuffle(config.shuffle_buffer, seed=seed,
                            reshuffle_each_iteration=True)
            ds = ds.repeat()
        counter = tf.data.Dataset.counter()
        ds = tf.data.Dataset.zip((ds, counter)).map(
            lambda rec, i: parse(rec, tf.stack([tf.cast(i, tf.int32), seed])),
            num_parallel_calls=tf.data.AUTOTUNE,
        )
        if train:
            ds = ds.batch(b, drop_remainder=True)
        else:
            # Exact single-pass eval (SURVEY.md §3.4): keep the remainder,
            # zero-pad it to the static batch size, and emit per-example
            # weights so padding contributes nothing to the metric sums.
            ds = ds.batch(b, drop_remainder=False)

            def pad(batch):
                k = tf.shape(batch["image"])[0]
                pad_n = b - k
                image = tf.pad(batch["image"], [[0, pad_n], [0, 0], [0, 0], [0, 0]])
                label = tf.pad(batch["label"], [[0, pad_n]])
                weight = tf.concat(
                    [tf.ones([k], tf.float32), tf.zeros([pad_n], tf.float32)], 0
                )
                image = tf.ensure_shape(image, [b, size, size, 3])
                label = tf.ensure_shape(label, [b])
                weight = tf.ensure_shape(weight, [b])
                return {"image": image, "label": label, "weight": weight}

            ds = ds.map(pad, num_parallel_calls=tf.data.AUTOTUNE)
        return ds.prefetch(tf.data.AUTOTUNE)

    img_dtype = image_np_dtype(config.image_dtype)
    if train:
        return tfdata_to_hostdataset(
            make_ds,
            element_spec={
                "image": ((b, size, size, 3), img_dtype),
                "label": ((b,), np.int32),
            },
        )

    # Count THIS host's file shard (make_ds shards files with the same
    # stride), not the full set — otherwise every host's eval pass is
    # inflated ~process_count× with zero-weight padding batches.
    host_files = files[process_index::process_count]
    num_batches = eval_batches_all_hosts(count_records(host_files), b)
    return tfdata_to_hostdataset(
        make_ds,
        element_spec={
            "image": ((b, size, size, 3), img_dtype),
            "label": ((b,), np.int32),
            "weight": ((b,), np.float32),
        },
        cardinality=num_batches,
        pad_tail_to=num_batches,
    )


def _make_imagenet_native(config: DataConfig, files: list[str],
                          process_index: int, process_count: int
                          ) -> HostDataset:
    """ImageNet pipeline on the C++ reader (native/record_reader.cc).

    TFRecord framing, Example parsing, JPEG partial decode (libjpeg-turbo
    crop/skip scanlines — IDCT cost tracks the CROP area, the native twin
    of tf.data's fused decode_and_crop), Inception-style distorted crop,
    flip and bilinear resize all run in native threads (SURVEY.md §7 hard
    part 1: host decode is the usual input-throughput wall); Python only
    standardizes. Crop/flip randomness is seeded per (epoch, batch,
    process) through core/prng.py and sampled by a fixed C++ splitmix64,
    so record order AND augmentation replay deterministically; resume
    fast-skips the consumed records natively (no JPEG decode or C-ABI
    copy of skipped batches). Shuffling matches the tf.data twin: a
    per-epoch FILE-order permutation PLUS a windowed RECORD-level shuffle
    (``config.shuffle_buffer``, C++-side, seeded per epoch) — so
    within-file record order reshuffles every epoch and which records
    fall off the final partial batch varies per epoch. Remaining delta vs
    the tf.data path: same crop family (area 8-100%, aspect 3/4-4/3),
    bilinear rather than bicubic resize.
    """
    from distributed_tensorflow_framework_tpu.core import prng
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    b = host_batch_size(config.global_batch_size, process_count)
    size = config.image_size
    shard = files[process_index::process_count]  # non-empty: caller guards
    out_dtype = image_np_dtype(config.image_dtype)
    mean = np.asarray(MEAN_RGB, np.float32)
    std = np.asarray(STDDEV_RGB, np.float32)

    def make_iter(state):
        state.setdefault("epoch", 0)
        state.setdefault("batch_in_epoch", 0)
        while True:
            epoch = state["epoch"]
            skip = state["batch_in_epoch"]
            # Per-epoch file-order shuffle (host-local stream → process
            # index in the derivation; see core/prng.py rules), plus a
            # record-shuffle seed drawn from the SAME per-epoch stream so
            # both reshuffle together and replay deterministically.
            epoch_rng = prng.host_rng(config.seed, prng.ROLE_DATA,
                                      epoch, process_index)
            order = epoch_rng.permutation(len(shard))
            epoch_files = [shard[j] for j in order]
            shuffle_seed = int(epoch_rng.integers(0, 2**63, dtype=np.uint64))

            def seed_stream(epoch=epoch, start=skip):
                i = start
                while True:
                    rng = prng.host_rng(config.seed, prng.ROLE_AUGMENT,
                                        epoch, i, process_index)
                    yield rng.integers(0, 2**63, size=b, dtype=np.uint64)
                    i += 1

            reader = NativeRecordReader(
                epoch_files,
                shuffle_window=config.shuffle_buffer,
                shuffle_seed=shuffle_seed,
            )
            if skip:
                # Fast-skip: advance the shuffled record stream past the
                # already-consumed records natively, WITHOUT JPEG-decoding
                # them — resume cost is IO-bound, not decode-bound. Goes
                # through the same shuffle window, so the stream resumes
                # exactly where the checkpoint left it.
                got = reader.skip_records(skip * b)
                if got < skip * b:
                    raise RuntimeError(
                        f"resume snapshot skips {skip * b} records but "
                        f"this host's shard holds only {got} — the shard "
                        f"set, process_count or batch size changed "
                        f"since the checkpoint was taken"
                    )
            it = reader.batches_images(b, size, size,
                                       crop_seeds=seed_stream(),
                                       mean=mean, std=std)
            for i, (images, labels) in enumerate(it, start=skip):
                state["batch_in_epoch"] = i + 1
                if (labels.min() < 1
                        or labels.max() > config.num_classes):
                    # An out-of-range label would NaN the loss metric via
                    # the CE gather's fill semantics — name the record
                    # problem here instead (cheap: b ints per batch).
                    raise ValueError(
                        f"record label {int(labels.min())}..."
                        f"{int(labels.max())} outside [1, "
                        f"{config.num_classes}] — records and "
                        f"data.num_classes disagree"
                    )
                yield {
                    "image": images.astype(out_dtype, copy=False),
                    "label": labels - 1,  # [1,1000] → [0,999]
                }
            reader.close()
            if state["batch_in_epoch"] == 0 and skip == 0:
                raise RuntimeError(
                    f"native ImageNet shard {shard!r} yielded no full "
                    f"batch of {b} records"
                )
            state["epoch"] += 1
            state["batch_in_epoch"] = 0

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((b, size, size, 3), out_dtype),
            "label": ((b,), np.int32),
        },
        initial_state={"epoch": 0, "batch_in_epoch": 0},
        # batch_in_epoch counts over THIS host's file shard — the state is
        # meaningless at another process count (data/shard.py), so the
        # restore gate blocks N→M refit unless data.resume_strict is off.
        repartition=data_shard.REPARTITION_NONE,
    )


def _make_imagenet_native_eval(config: DataConfig, files: list[str],
                               process_index: int, process_count: int
                               ) -> HostDataset:
    """Exact single-pass eval on the C++ reader (SURVEY.md §3.4 / §2 row 5).

    Same contract as the tf.data eval twin: every record of this host's
    file shard exactly once, in file order (no shuffle), deterministic
    central crop (87.5%, tf.image.central_crop arithmetic in C++) +
    resize + standardize; the final partial batch is zero-padded with
    per-example weights, and hosts that exhaust early pad with zero-weight
    batches up to the equalized batch count so multi-host collectives
    never diverge. Pixel-level delta vs tf.data: bilinear vs bicubic
    resize (the same documented delta as the train path).
    """
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
        count_records_native,
    )

    b = host_batch_size(config.global_batch_size, process_count)
    size = config.image_size
    host_files = files[process_index::process_count]
    out_dtype = image_np_dtype(config.image_dtype)
    mean = np.asarray(MEAN_RGB, np.float32)
    std = np.asarray(STDDEV_RGB, np.float32)
    # Count through the C++ framing cursor (no TF dependency, no decode)
    # so the native path stays native end to end.
    total_records = count_records_native(host_files)
    num_batches = eval_batches_all_hosts(total_records, b)

    def zero_batch():
        return {
            "image": np.zeros((b, size, size, 3), out_dtype),
            "label": np.zeros((b,), np.int32),
            "weight": np.zeros((b,), np.float32),
        }

    def make_iter(state):
        state.setdefault("batches", 0)
        # The record count rides in the snapshot so a resume can detect a
        # shard set that changed SINCE the checkpoint — a re-derived
        # count can't (skip_records is short on EOF by definition, so
        # comparing against the current files is a tautology). Mirrors
        # the train path's loud failure (ADVICE r3).
        state.setdefault("records", total_records)
        if state["records"] != total_records:
            raise RuntimeError(
                f"eval resume snapshot was taken over {state['records']} "
                f"records but this host's shard now holds "
                f"{total_records} — the shard set changed since the "
                f"checkpoint was taken"
            )
        skip = state["batches"]
        reader = NativeRecordReader(host_files)
        # Mid-pass resume: re-skip the consumed records (a short skip is
        # fine only because the count-match above already proved the
        # shard set is unchanged — it means the restore point sits in
        # the padded tail past this shard's real records).
        if skip:
            reader.skip_records(skip * b)
        it = reader.batches_images_eval(b, size, size, mean=mean, std=std)
        for images, labels, k in it:
            weight = np.zeros((b,), np.float32)
            weight[:k] = 1.0
            if k and (labels[:k].min() < 1
                      or labels[:k].max() > config.num_classes):
                # Same guard as the train reader: an out-of-range label
                # silently NaNs the eval metric via the CE gather.
                raise ValueError(
                    f"eval record label {int(labels[:k].min())}..."
                    f"{int(labels[:k].max())} outside [1, "
                    f"{config.num_classes}] — records and "
                    f"data.num_classes disagree"
                )
            labels = labels - 1  # [1,1000] → [0,999]
            labels[k:] = 0  # padding: valid class id, weighted out
            state["batches"] += 1
            yield {
                "image": images.astype(out_dtype, copy=False),
                "label": labels,
                "weight": weight,
            }
        reader.close()
        while state["batches"] < num_batches:
            state["batches"] += 1
            yield zero_batch()

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((b, size, size, 3), out_dtype),
            "label": ((b,), np.int32),
            "weight": ((b,), np.float32),
        },
        initial_state={"batches": 0},
        cardinality=num_batches,
        # Eval skip-count is per-host-shard too; eval streams are rebuilt
        # from scratch on refit anyway, but tag honestly.
        repartition=data_shard.REPARTITION_NONE,
    )
