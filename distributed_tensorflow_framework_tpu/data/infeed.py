"""Host→device infeed: assemble per-host batch shards into global arrays.

The reference feeds each worker's GPU from its local tf.data iterator; the
SPMD equivalent is `jax.make_array_from_process_local_data`: every host
contributes its shard and the result is ONE logical array sharded over the
mesh's data axes (BASELINE.json: "tf.data input pipeline hoisted to the TPU
host with per-replica infeed").
"""

from __future__ import annotations

from typing import Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.mesh import batch_spec


def to_global(batch: Mapping[str, np.ndarray], mesh: Mesh,
              spec: P | None = None) -> dict[str, jax.Array]:
    """Lift a host-local numpy batch to a mesh-sharded global jax.Array tree."""
    sharding = NamedSharding(mesh, spec if spec is not None else batch_spec(mesh))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }


def prefetch_to_device(dataset, mesh: Mesh, *, size: int = 2,
                       spec: P | None = None, background: bool = False):
    """Software-pipelined infeed: keep `size` global batches in flight.

    The analogue of tf.data's ``prefetch_to_device`` — device transfer of
    batch N+1 overlaps step N's compute (SURVEY.md §7 hard part 1: input
    throughput, not the model, is the usual wall). With
    ``background=True`` the host pipeline pull AND the device transfer run
    on a producer thread, so host-side decode/augment work (e.g. the
    native JPEG path) genuinely overlaps device steps instead of running
    in the gaps between dispatches.

    Yields ``(global_batch, iterator_state_snapshot)``. The snapshot is the
    dataset's state immediately after the yielded batch was pulled from it —
    i.e. the state to checkpoint so a restore resumes with the NEXT batch.
    Because the prefetcher runs ahead of training, ``dataset.state()`` itself
    is not safe to checkpoint (it reflects the prefetched-ahead position);
    the snapshot is (resume-exactness, SURVEY.md §7 hard part 3). The
    dataset is only ever touched from one thread (the producer), so the
    snapshot/batch pairing is identical in both modes.
    """
    snap = getattr(dataset, "state", lambda: {})

    if background:
        import queue as queue_mod
        import threading

        q: queue_mod.Queue = queue_mod.Queue(maxsize=max(size, 1))
        stop = threading.Event()
        _EOF = object()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def produce():
            try:
                for host_batch in dataset:
                    if stop.is_set():
                        return
                    if not put((to_global(host_batch, mesh, spec), snap())):
                        return
            except BaseException as e:  # surface in the consumer
                put(e)
                return
            put(_EOF)

        t = threading.Thread(target=produce, daemon=True,
                             name="infeed-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is _EOF:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            # Consumer done (total_steps reached, early break, error):
            # release the producer — it must NOT keep pulling from the
            # dataset, which the caller may restore/reuse next.
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue_mod.Empty:
                    break
            t.join(timeout=10)
            if t.is_alive():
                # Producer stuck inside a blocking dataset pull (e.g. a
                # stalled filesystem read): it may complete ONE more pull
                # after we return — restoring/reusing the dataset now
                # races it. Surface the hazard instead of failing silent.
                import logging

                logging.getLogger(__name__).warning(
                    "infeed producer thread did not stop within 10s — "
                    "the dataset may see one more pull; avoid reusing it "
                    "until the process-level pipeline unblocks"
                )

    import collections

    buf: collections.deque = collections.deque()

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                host_batch = next(dataset)
            except StopIteration:
                return
            buf.append((to_global(host_batch, mesh, spec), snap()))

    enqueue(size)
    while buf:
        yield buf.popleft()
        enqueue(1)
