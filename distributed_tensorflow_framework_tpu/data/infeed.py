"""Host→device infeed: assemble per-host batch shards into global arrays.

The reference feeds each worker's GPU from its local tf.data iterator; the
SPMD equivalent is `jax.make_array_from_process_local_data`: every host
contributes its shard and the result is ONE logical array sharded over the
mesh's data axes (BASELINE.json: "tf.data input pipeline hoisted to the TPU
host with per-replica infeed").
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.mesh import batch_spec


def to_global(batch: Mapping[str, np.ndarray], mesh: Mesh,
              spec: P | None = None) -> dict[str, jax.Array]:
    """Lift a host-local numpy batch to a mesh-sharded global jax.Array tree."""
    sharding = NamedSharding(mesh, spec if spec is not None else batch_spec(mesh))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }


def prefetch_to_device(dataset, mesh: Mesh, *, size: int = 2, spec: P | None = None):
    """Software-pipelined infeed: keep `size` global batches in flight.

    The analogue of tf.data's ``prefetch_to_device`` — device transfer of
    batch N+1 overlaps step N's compute (SURVEY.md §7 hard part 1: input
    throughput, not the model, is the usual wall).

    Yields ``(global_batch, iterator_state_snapshot)``. The snapshot is the
    dataset's state immediately after the yielded batch was pulled from it —
    i.e. the state to checkpoint so a restore resumes with the NEXT batch.
    Because the prefetcher runs ahead of training, ``dataset.state()`` itself
    is not safe to checkpoint (it reflects the prefetched-ahead position);
    the snapshot is (resume-exactness, SURVEY.md §7 hard part 3).
    """
    import collections

    queue: collections.deque = collections.deque()
    snap = getattr(dataset, "state", lambda: {})

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                host_batch = next(dataset)
            except StopIteration:
                return
            queue.append((to_global(host_batch, mesh, spec), snap()))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)
