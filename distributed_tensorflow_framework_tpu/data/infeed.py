"""Host→device infeed: assemble per-host batch shards into global arrays.

The reference feeds each worker's GPU from its local tf.data iterator; the
SPMD equivalent is `jax.make_array_from_process_local_data`: every host
contributes its shard and the result is ONE logical array sharded over the
mesh's data axes (BASELINE.json: "tf.data input pipeline hoisted to the TPU
host with per-replica infeed").

Watchdog (docs/RESILIENCE.md recovery ladder): with ``deadline_s`` set,
a batch that does not arrive within the deadline raises a typed
``InfeedStallError`` instead of wedging the step loop until the
supervisor's heartbeat watchdog SIGKILLs the process. The stalled pull
keeps running underneath — the error is a *report*, not a cancellation —
so the caller can retry (the Trainer does, with backoff) and collect the
batch once the pipeline unwedges. The ``stall_infeed`` fault
(core/faults.py) drills exactly this path.
"""

from __future__ import annotations

import collections
import concurrent.futures
import logging
import queue as queue_mod
import threading
from typing import Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

log = logging.getLogger(__name__)

_EOF = object()


class InfeedStallError(RuntimeError):
    """``next(dataset)`` exceeded the infeed watchdog deadline.

    The underlying pull is still in flight: calling ``next()`` on the
    prefetcher again waits for the SAME batch (no data is skipped or
    double-pulled). Raised only when ``deadline_s > 0`` was configured
    (resilience.infeed_deadline_s)."""

    def __init__(self, deadline_s: float):
        self.deadline_s = deadline_s
        super().__init__(
            f"infeed pull exceeded the {deadline_s:g}s watchdog deadline "
            f"(the pull is still running; retry next() to keep waiting)"
        )


def to_global(batch: Mapping[str, np.ndarray], mesh: Mesh,
              spec: P | None = None) -> dict[str, jax.Array]:
    """Lift a host-local numpy batch to a mesh-sharded global jax.Array tree."""
    sharding = NamedSharding(mesh, spec if spec is not None else batch_spec(mesh))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }


class _BackgroundInfeed:
    """Producer-thread prefetcher: host pipeline pull AND device transfer
    run off the training thread. The consumer sees ``(global_batch,
    iterator_state_snapshot)`` items in pull order; with a deadline, a
    slow producer surfaces as InfeedStallError on the consumer side while
    the producer keeps working."""

    def __init__(self, dataset, mesh: Mesh, spec: P | None, size: int,
                 deadline_s: float = 0.0):
        self._dataset = dataset
        self._deadline_s = deadline_s
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=max(size, 1))
        self._stop = threading.Event()
        self._done = False
        snap = getattr(dataset, "state", lambda: {})

        def put(item) -> bool:
            while not self._stop.is_set():
                try:
                    self._q.put(item, timeout=0.1)
                    return True
                except queue_mod.Full:
                    continue
            return False

        def produce():
            try:
                for host_batch in dataset:
                    if self._stop.is_set():
                        return
                    if not put((to_global(host_batch, mesh, spec), snap())):
                        return
            except BaseException as e:  # surface in the consumer
                put(e)
                return
            put(_EOF)

        self._thread = threading.Thread(target=produce, daemon=True,
                                        name="dtf-infeed-prefetch")
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self._deadline_s > 0:
            try:
                item = self._q.get(timeout=self._deadline_s)
            except queue_mod.Empty:
                raise InfeedStallError(self._deadline_s) from None
        else:
            item = self._q.get()
        if item is _EOF:
            self._done = True
            raise StopIteration
        if isinstance(item, BaseException):
            self._done = True
            raise item
        return item

    def watermark(self) -> int:
        """Batches pulled ahead of the consumer right now (approximate —
        the producer may be mid-pull). Recorded into the checkpoint's
        data-state commit record (data/shard.py) as the prefetch-queue
        watermark at save time; telemetry only, never folded into the
        restore position."""
        return self._q.qsize()

    def close(self) -> None:
        # Consumer done (total_steps reached, early break, error): release
        # the producer — it must NOT keep pulling from the dataset, which
        # the caller may restore/reuse next.
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                break
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # Producer stuck inside a blocking dataset pull (e.g. a
            # stalled filesystem read): it may complete ONE more pull
            # after we return — restoring/reusing the dataset now races
            # it. Surface the hazard instead of failing silent.
            log.warning(
                "infeed producer thread did not stop within 10s — "
                "the dataset may see one more pull; avoid reusing it "
                "until the process-level pipeline unblocks"
            )


class _SyncInfeed:
    """Same-thread prefetcher with a bounded lookahead buffer. With a
    deadline, each raw pull runs on a single persistent worker thread so
    it can be *timed*; a timed-out pull is kept pending and the next
    ``next()`` resumes waiting on it (never skipped, never re-issued)."""

    def __init__(self, dataset, mesh: Mesh, spec: P | None, size: int,
                 deadline_s: float = 0.0):
        self._dataset = dataset
        self._mesh = mesh
        self._spec = spec
        self._size = max(size, 1)
        self._deadline_s = deadline_s
        self._snap = getattr(dataset, "state", lambda: {})
        self._buf: collections.deque = collections.deque()
        self._primed = False
        self._eof = False
        self._pool = None
        self._pending = None
        if deadline_s > 0:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="dtf-infeed-pull")

    def _pull_raw(self):
        """One ``next(dataset)`` → host batch or _EOF; stall-guarded when
        a deadline is configured."""
        if self._pool is None:
            return next(self._dataset, _EOF)
        if self._pending is None:
            self._pending = self._pool.submit(next, self._dataset, _EOF)
        try:
            item = self._pending.result(timeout=self._deadline_s)
        except concurrent.futures.TimeoutError:
            raise InfeedStallError(self._deadline_s) from None
        self._pending = None
        return item

    def _fill(self, n: int) -> None:
        for _ in range(n):
            item = self._pull_raw()
            if item is _EOF:
                self._eof = True
                return
            self._buf.append(
                (to_global(item, self._mesh, self._spec), self._snap()))

    def __iter__(self):
        return self

    def __next__(self):
        want = 1 if self._primed else self._size
        self._primed = True
        if not self._eof:
            try:
                self._fill(want)
            except InfeedStallError:
                if not self._buf:
                    raise
                # Buffered batches still cover the consumer; the stalled
                # pull stays pending and is retried on the next call.
        if not self._buf:
            raise StopIteration
        return self._buf.popleft()

    def watermark(self) -> int:
        """Batches pulled ahead of the consumer (buffered + the pending
        stall-guarded pull, if any) — the _BackgroundInfeed.watermark
        contract for the same-thread prefetcher."""
        return len(self._buf) + (1 if self._pending is not None else 0)

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)


def prefetch_to_device(dataset, mesh: Mesh, *, size: int = 2,
                       spec: P | None = None, background: bool = False,
                       deadline_s: float = 0.0):
    """Software-pipelined infeed: keep `size` global batches in flight.

    The analogue of tf.data's ``prefetch_to_device`` — device transfer of
    batch N+1 overlaps step N's compute (SURVEY.md §7 hard part 1: input
    throughput, not the model, is the usual wall). With
    ``background=True`` the host pipeline pull AND the device transfer run
    on a producer thread, so host-side decode/augment work (e.g. the
    native JPEG path) genuinely overlaps device steps instead of running
    in the gaps between dispatches.

    Returns a closable iterator of ``(global_batch,
    iterator_state_snapshot)``. The snapshot is the dataset's state
    immediately after the yielded batch was pulled from it — i.e. the
    state to checkpoint so a restore resumes with the NEXT batch. Because
    the prefetcher runs ahead of training, ``dataset.state()`` itself is
    not safe to checkpoint (it reflects the prefetched-ahead position);
    the snapshot is (resume-exactness, SURVEY.md §7 hard part 3). The
    dataset is only ever touched from one thread (the producer/worker),
    so the snapshot/batch pairing is identical in both modes.

    ``deadline_s > 0`` arms the infeed watchdog: a pull that exceeds the
    deadline raises ``InfeedStallError`` from ``next()`` while the pull
    keeps running underneath — retrying ``next()`` resumes waiting for
    the same batch (the Trainer's retry-with-backoff rung).
    """
    cls = _BackgroundInfeed if background else _SyncInfeed
    return cls(dataset, mesh, spec, size, deadline_s)
