"""MNIST pipeline (BASELINE.json config 1).

Reads the standard ``mnist.npz`` (keras layout) from ``data_dir``; in a
zero-egress environment with no file present it falls back to synthetic
MNIST-shaped data so the workload still runs end-to-end.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.core import prng
from distributed_tensorflow_framework_tpu.data.pipeline import (
    HostDataset,
    host_batch_size,
    image_np_dtype,
)
from distributed_tensorflow_framework_tpu.data import shard, synthetic

log = logging.getLogger(__name__)


def make_mnist(config: DataConfig, process_index: int, process_count: int,
               *, train: bool = True) -> HostDataset:
    path = os.path.join(config.data_dir or "", "mnist.npz")
    if not (config.data_dir and os.path.exists(path)):
        log.warning("MNIST not found at %r — using synthetic fallback", path)
        return synthetic.synthetic_images(config, process_index, process_count)

    with np.load(path) as d:
        if train:
            images, labels = d["x_train"], d["y_train"]
        else:
            images, labels = d["x_test"], d["y_test"]
    images = images.astype(np.float32)[..., None] / 255.0
    # Per-image standardization (the reference recipe's normalization).
    mean = images.mean(axis=(1, 2, 3), keepdims=True)
    std = images.std(axis=(1, 2, 3), keepdims=True) + 1e-6
    images = (images - mean) / std
    labels = labels.astype(np.int32)

    b = host_batch_size(config.global_batch_size, process_count)
    n = len(images)
    out_dtype = image_np_dtype(config.image_dtype)

    if not train:
        # Exact single-pass eval: every test example once, final batch
        # zero-padded with per-example weights (data/pipeline.py).
        from distributed_tensorflow_framework_tpu.data.pipeline import (
            finite_array_eval,
        )

        return finite_array_eval(
            images.astype(out_dtype, copy=False), labels, batch=b,
            process_index=process_index, process_count=process_count,
            out_dtype=out_dtype,
        )

    block = config.shard_mode == "block"

    def make_iter(state):
        state.setdefault("epoch", 0)
        state.setdefault("batch_in_epoch", 0)
        while True:
            # Cross-host-shared shuffle: every host strides the SAME
            # permutation, so no process_index (core/prng.py rules).
            rng = prng.host_rng(config.seed, prng.ROLE_DATA, state["epoch"])
            perm = rng.permutation(n)
            batches = shard.epoch_batches(n, b, process_count)
            start = state["batch_in_epoch"]
            for i in range(start, batches):
                if block:
                    # Block sharding (data/shard.py): host h takes the
                    # h-th contiguous b rows of global batch i, so the
                    # consumed prefix after k batches is perm[:k*B] at
                    # ANY host count — the state (epoch, batch_in_epoch)
                    # survives an N→M elastic refit bit-exactly.
                    lo, hi = shard.block_bounds(
                        i, b, process_index, process_count)
                    idx = perm[lo:hi]
                else:
                    # Legacy stride sharding (data.shard_mode="stride"):
                    # each host reads a strided shard of the epoch. NOT
                    # repartitionable across a host-count change.
                    idx = perm[process_index::process_count][i * b:(i + 1) * b]
                state["batch_in_epoch"] = i + 1
                yield {"image": images[idx].astype(out_dtype, copy=False),
                       "label": labels[idx]}
            state["epoch"] += 1
            state["batch_in_epoch"] = 0

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((b, 28, 28, 1), out_dtype),
            "label": ((b,), np.int32),
        },
        initial_state={"epoch": 0, "batch_in_epoch": 0},
        cardinality=shard.epoch_batches(n, b, process_count),
        repartition=(shard.REPARTITION_INVARIANT if block
                     else shard.REPARTITION_NONE),
    )
