"""ctypes bindings for the native TFRecord reader (native/record_reader.cc).

Builds the shared library on first use (g++ is in the image; there is no
pybind11 — plain C ABI + ctypes per the environment's binding guidance) and
exposes two iterators:

  * ``iter_records(paths)``      — raw record payloads (bytes)
  * ``iter_batches_i32(...)``    — (batch, width) int32 arrays of a named
                                   Int64List feature, parsed in C++

Used by the MLM pipeline when ``DataConfig.use_native_reader`` is set; the
pure-tf.data path stays the default and the behavior contract (record
order, values) is identical — tested in tests/test_native_reader.py.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterator, Sequence

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "record_reader.cc")
_LIB_CACHE = os.path.join(os.path.dirname(__file__), "..", "native", "librecord_reader.so")
_lock = threading.Lock()
_lib = None


def _build() -> str:
    lib = os.path.abspath(_LIB_CACHE)
    src = os.path.abspath(_SRC)
    if os.path.exists(lib) and os.path.getmtime(lib) >= os.path.getmtime(src):
        return lib
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           src, "-o", lib]
    log.info("building native record reader: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    return lib


def load_library():
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            lib.rr_open.restype = ctypes.c_void_p
            lib.rr_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.c_int, ctypes.c_int]
            lib.rr_next_record.restype = ctypes.c_int
            lib.rr_next_record.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_long)]
            lib.rr_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
            lib.rr_next_batch_i32.restype = ctypes.c_int
            lib.rr_next_batch_i32.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int]
            lib.rr_error.restype = ctypes.c_char_p
            lib.rr_error.argtypes = [ctypes.c_void_p]
            lib.rr_close.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib


class NativeRecordReader:
    def __init__(self, paths: Sequence[str], prefetch: int = 256):
        self._lib = load_library()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = self._lib.rr_open(arr, len(paths), prefetch)
        if not self._h:
            raise RuntimeError("rr_open failed")

    def _check_error(self):
        err = self._lib.rr_error(self._h)
        if err:
            raise RuntimeError(f"native reader: {err.decode()}")

    def records(self) -> Iterator[bytes]:
        buf = ctypes.POINTER(ctypes.c_char)()
        n = ctypes.c_long()
        while True:
            rc = self._lib.rr_next_record(self._h, ctypes.byref(buf),
                                          ctypes.byref(n))
            if rc < 0:
                self._check_error()
                raise RuntimeError("native reader failed")
            if rc == 0:
                return
            try:
                yield ctypes.string_at(buf, n.value)
            finally:
                self._lib.rr_free(buf)

    def batches_i32(self, key: str, batch: int, width: int) -> Iterator[np.ndarray]:
        out = np.empty((batch, width), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            rc = self._lib.rr_next_batch_i32(self._h, key.encode(), ptr,
                                             batch, width)
            if rc < 0:
                self._check_error()
                raise RuntimeError(f"native reader parse error (rc={rc})")
            if rc == 0:
                return
            yield out.copy()

    def close(self):
        if self._h:
            self._lib.rr_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
