"""ctypes bindings for the native TFRecord reader (native/record_reader.cc).

Builds the shared library on first use (g++ is in the image; there is no
pybind11 — plain C ABI + ctypes per the environment's binding guidance) and
exposes two iterators:

  * ``iter_records(paths)``      — raw record payloads (bytes)
  * ``iter_batches_i32(...)``    — (batch, width) int32 arrays of a named
                                   Int64List feature, parsed in C++

Used by the MLM pipeline when ``DataConfig.use_native_reader`` is set; the
pure-tf.data path stays the default and the behavior contract (record
order, values) is identical — tested in tests/test_native_reader.py.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Iterator, Sequence

import numpy as np

log = logging.getLogger(__name__)

_SRC = os.path.join(os.path.dirname(__file__), "..", "native", "record_reader.cc")
_LIB_CACHE = os.path.join(os.path.dirname(__file__), "..", "native", "librecord_reader.so")
_lock = threading.Lock()
_lib = None


def _build() -> str:
    lib = os.path.abspath(_LIB_CACHE)
    src = os.path.abspath(_SRC)
    # Cache validity = source CONTENT hash (sidecar file), not mtimes: a
    # fresh clone gives lib and source the same checkout mtime, so an
    # mtime gate would silently load a stale committed .so after a source
    # change (ADVICE r3).
    import hashlib

    with open(src, "rb") as f:
        src_hash = hashlib.sha256(f.read()).hexdigest()
    sidecar = lib + ".sha256"
    if os.path.exists(lib) and os.path.exists(sidecar):
        with open(sidecar) as f:
            if f.read().strip() == src_hash:
                # Hash match isn't enough: a committed .so built against a
                # newer glibc/libjpeg fails dlopen on this host (observed:
                # GLIBC_2.34 symbols on a 2.31 image). Probe before trusting.
                try:
                    ctypes.CDLL(lib)
                    return lib
                except OSError as e:
                    log.warning(
                        "cached native reader unloadable (%s); rebuilding", e
                    )
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-pthread",
           src, "-ljpeg", "-o", lib]
    log.info("building native record reader: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    with open(sidecar, "w") as f:
        f.write(src_hash + "\n")
    return lib


def load_library():
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            lib.rr_open.restype = ctypes.c_void_p
            lib.rr_open.argtypes = [ctypes.POINTER(ctypes.c_char_p),
                                    ctypes.c_int, ctypes.c_int,
                                    ctypes.c_long, ctypes.c_uint64]
            lib.rr_skip.restype = ctypes.c_long
            lib.rr_skip.argtypes = [ctypes.c_void_p, ctypes.c_long]
            lib.rr_next_record.restype = ctypes.c_int
            lib.rr_next_record.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                ctypes.POINTER(ctypes.c_long)]
            lib.rr_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
            lib.rr_next_batch_i32.restype = ctypes.c_int
            lib.rr_next_batch_i32.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int, ctypes.c_int]
            lib.rr_next_batch_images.restype = ctypes.c_int
            lib.rr_next_batch_images.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float)]
            lib.rr_next_batch_images_eval.restype = ctypes.c_int
            lib.rr_next_batch_images_eval.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float)]
            lib.rr_error.restype = ctypes.c_char_p
            lib.rr_error.argtypes = [ctypes.c_void_p]
            lib.rr_close.argtypes = [ctypes.c_void_p]
            _lib = lib
    return _lib


# files-tuple → record count (same one-shot cache contract as
# tfdata.count_records, but through the C++ framing cursor — no TF
# dependency and no decode; restores rebuild pipelines so the count per
# shard set must not be repeated).
_COUNT_CACHE: dict[tuple[str, ...], int] = {}


def _norm_pointers(mean, std, null_f):
    """Per-channel (mean, std) → C float pointers, or nulls when neither
    is given. Exactly one of the pair is a caller bug — silently skipping
    normalization would feed unnormalized pixels downstream (ADVICE r3)."""
    if (mean is None) != (std is None):
        raise ValueError(
            "normalization needs BOTH mean and std (got only "
            + ("mean" if std is None else "std") + ")"
        )
    if mean is None:
        return None, None, null_f, null_f
    mean_arr = np.ascontiguousarray(mean, np.float32)
    std_arr = np.ascontiguousarray(std, np.float32)
    assert mean_arr.shape == (3,) and std_arr.shape == (3,)
    return (mean_arr, std_arr,
            mean_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))


def count_records_native(paths: Sequence[str]) -> int:
    key = tuple(paths)
    if key not in _COUNT_CACHE:
        reader = NativeRecordReader(key)
        try:
            _COUNT_CACHE[key] = reader.skip_records(2**62)
        finally:
            reader.close()
    return _COUNT_CACHE[key]


class NativeRecordReader:
    def __init__(self, paths: Sequence[str], prefetch: int = 256,
                 *, shuffle_window: int = 0, shuffle_seed: int = 0):
        """``shuffle_window > 1`` enables a windowed record-level shuffle
        (tf.data shuffle-buffer semantics) applied to every iterator of
        this handle, deterministic given ``shuffle_seed``. Memory cost is
        ``window`` raw records held in C++ (same class as tf.data's
        pre-decode shuffle buffer)."""
        self._lib = load_library()
        arr = (ctypes.c_char_p * len(paths))(
            *[p.encode() for p in paths]
        )
        self._h = self._lib.rr_open(arr, len(paths), prefetch,
                                    shuffle_window, shuffle_seed)
        if not self._h:
            raise RuntimeError("rr_open failed")

    def _check_error(self):
        err = self._lib.rr_error(self._h)
        if err:
            raise RuntimeError(f"native reader: {err.decode()}")

    def skip_records(self, n: int) -> int:
        """Advance the (possibly shuffled) stream ``n`` records without
        decode or C-ABI copies — the resume fast-skip. Returns how many
        were actually skipped (short on EOF)."""
        got = self._lib.rr_skip(self._h, n)
        if got < 0:
            self._check_error()
            raise RuntimeError("native reader skip failed")
        return int(got)

    def records(self) -> Iterator[bytes]:
        buf = ctypes.POINTER(ctypes.c_char)()
        n = ctypes.c_long()
        while True:
            rc = self._lib.rr_next_record(self._h, ctypes.byref(buf),
                                          ctypes.byref(n))
            if rc < 0:
                self._check_error()
                raise RuntimeError("native reader failed")
            if rc == 0:
                return
            try:
                yield ctypes.string_at(buf, n.value)
            finally:
                self._lib.rr_free(buf)

    def batches_i32(self, key: str, batch: int, width: int) -> Iterator[np.ndarray]:
        out = np.empty((batch, width), np.int32)
        ptr = out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        while True:
            rc = self._lib.rr_next_batch_i32(self._h, key.encode(), ptr,
                                             batch, width)
            if rc < 0:
                self._check_error()
                raise RuntimeError(f"native reader parse error (rc={rc})")
            if rc == 0:
                return
            yield out.copy()

    def batches_images(self, batch: int, height: int, width: int,
                       *, image_key: str = "image/encoded",
                       label_key: str = "image/class/label",
                       threads: int = 0,
                       crop_seeds: Iterator[np.ndarray] | None = None,
                       mean: np.ndarray | None = None,
                       std: np.ndarray | None = None,
                       ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        """(images f32 (b,h,w,3) in [0,255], labels i32 (b,)) per batch.

        JPEG decode + bilinear resize run in C++ worker threads (the
        ImageNet host-side hot path, SURVEY.md §7 hard part 1); Python
        receives finished pixel batches. With ``crop_seeds`` (an iterator
        of (batch,) uint64 arrays, one per batch), each image gets an
        Inception-style distorted crop + random flip decoded via PARTIAL
        IDCT (libjpeg-turbo crop/skip-scanlines) — the decode cost tracks
        the crop area, the native twin of tf.data's decode_and_crop.
        ``mean``/``std`` (per-channel, length 3) fuse standardization into
        the native resize write, skipping a full numpy pass per batch.
        """
        images = np.empty((batch, height, width, 3), np.float32)
        labels = np.empty((batch,), np.int32)
        iptr = images.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        lptr = labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        null_seeds = ctypes.POINTER(ctypes.c_uint64)()
        null_f = ctypes.POINTER(ctypes.c_float)()
        # keep mean/std arrays referenced while their pointers are in use
        _mean_arr, _std_arr, mptr, sptr_std = _norm_pointers(mean, std, null_f)
        while True:
            if crop_seeds is not None:
                seeds = np.ascontiguousarray(next(crop_seeds), np.uint64)
                assert seeds.shape == (batch,)
                sptr = seeds.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
            else:
                sptr = null_seeds
            rc = self._lib.rr_next_batch_images(
                self._h, image_key.encode(), label_key.encode(),
                iptr, lptr, batch, height, width, threads, sptr,
                mptr, sptr_std)
            if rc < 0:
                self._check_error()
                raise RuntimeError(f"native image decode error (rc={rc})")
            if rc == 0:
                return
            yield images.copy(), labels.copy()

    def batches_images_eval(self, batch: int, height: int, width: int,
                            *, image_key: str = "image/encoded",
                            label_key: str = "image/class/label",
                            threads: int = 0,
                            central_frac: float = 0.875,
                            mean: np.ndarray | None = None,
                            std: np.ndarray | None = None,
                            ) -> Iterator[tuple[np.ndarray, np.ndarray, int]]:
        """(images, labels, k) per batch for a SINGLE eval pass.

        Deterministic central-crop (``central_frac``, tf.image.central_crop
        arithmetic) + bilinear resize in C++ — the eval twin of
        ``batches_images``. ``k <= batch`` is the number of real records in
        the batch; the final batch is zero-padded past ``k`` (labels 0) so
        callers can weight the padding out (exact-eval contract)."""
        images = np.empty((batch, height, width, 3), np.float32)
        labels = np.empty((batch,), np.int32)
        iptr = images.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        lptr = labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        null_f = ctypes.POINTER(ctypes.c_float)()
        # keep mean/std arrays referenced while their pointers are in use
        _mean_arr, _std_arr, mptr, sptr_std = _norm_pointers(mean, std, null_f)
        while True:
            rc = self._lib.rr_next_batch_images_eval(
                self._h, image_key.encode(), label_key.encode(),
                iptr, lptr, batch, height, width, threads,
                central_frac, mptr, sptr_std)
            if rc < 0:
                self._check_error()
                raise RuntimeError(f"native eval decode error (rc={rc})")
            if rc == 0:
                return
            img = images.copy()
            lab = labels.copy()
            if rc < batch:  # zero the padded tail (weighted out by caller)
                img[rc:] = 0.0
                lab[rc:] = 0
            yield img, lab, rc

    def close(self):
        if self._h:
            self._lib.rr_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
