"""Sequence packing — more real tokens through the same GEMMs.

BERT-style batches are mostly padding: short documents in fixed
``seq_len`` rows waste the MXU on zero positions. ``pack_documents``
lays documents end-to-end with per-row segment ids (block-diagonal
attention masks keep them independent), and ``packing_stats`` turns the
real/padded token counters the pipeline accumulates into the
goodput-per-padded-token telemetry (KIND_DATA_PACKING) that makes the
win measurable on CPU today.

Moved here from data/text_mlm.py (which re-exports it) so packing is a
workload-independent primitive: any tokenized reader can pack.
"""

from __future__ import annotations

import numpy as np

# Iterator-state counter keys (data/text_mlm.py accumulates them; the
# Trainer reads them off its data snapshot to emit KIND_DATA_PACKING).
REAL_TOKENS_KEY = "real_tokens"
PADDED_TOKENS_KEY = "padded_tokens"


def pack_documents(tokens: np.ndarray, out_rows: int, seq_len: int
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Greedy in-order first-fit packing of zero-padded token rows.

    ``tokens`` (n, s): one document per row, trailing-zero padded (token 0
    is [PAD], never interior). Documents are laid end-to-end into
    ``out_rows`` rows of ``seq_len``; per-row ``segment_ids`` number the
    documents 1..k (0 = padding) for block-diagonal attention. In-order
    packing keeps the stream deterministic (resume replays identically);
    documents that do not fit the row budget are RETURNED as the leftover
    suffix — the caller carries them into the next packed batch so
    pack_factor overflow defers data instead of discarding it (ADVICE r3).

    Returns (packed (out_rows, seq_len), segment_ids,
    leftover (m, s) — the non-empty rows that did not fit, in order).
    """
    packed = np.zeros((out_rows, seq_len), np.int32)
    segs = np.zeros((out_rows, seq_len), np.int32)
    row, col, seg = 0, 0, 0
    leftover = tokens[:0]
    for i, doc in enumerate(tokens):
        length = int(np.count_nonzero(doc))
        if length == 0:
            continue
        if col + length > seq_len:
            row += 1
            col = 0
            seg = 0
            if row >= out_rows:
                rest = tokens[i:]
                leftover = rest[np.count_nonzero(rest, axis=1) > 0]
                break
        packed[row, col:col + length] = doc[:length]
        seg += 1
        segs[row, col:col + length] = seg
        col += length
    return packed, segs, leftover


def count_tokens(tokens: np.ndarray) -> tuple[int, int]:
    """``(real, pad)`` position counts for one emitted (b, s) batch —
    token 0 is reserved padding, so nonzero == real."""
    real = int(np.count_nonzero(tokens))
    return real, int(tokens.size) - real


def accumulate_counters(state: dict, tokens: np.ndarray) -> None:
    """Fold one emitted batch's token census into the iterator state.

    The counters ride the (JSON-serializable) state so they survive
    save/restore with the stream position and every snapshot pairs a
    batch with the cumulative census up to it.
    """
    real, pad = count_tokens(tokens)
    state[REAL_TOKENS_KEY] = int(state.get(REAL_TOKENS_KEY, 0)) + real
    state[PADDED_TOKENS_KEY] = int(state.get(PADDED_TOKENS_KEY, 0)) + pad


def packing_stats(real_tokens: int, padded_tokens: int) -> dict:
    """Goodput-per-padded-token rollup for KIND_DATA_PACKING.

    ``packing_efficiency`` is the fraction of fed positions that carry a
    real token — the number sequence packing exists to raise (unpacked
    short-document batches sit far below 1.0).
    """
    total = int(real_tokens) + int(padded_tokens)
    return {
        "real_tokens": int(real_tokens),
        "padded_tokens": int(padded_tokens),
        "total_tokens": total,
        "packing_efficiency": (real_tokens / total) if total else None,
    }
