"""Host dataset abstraction + save/restore of iterator position.

A `HostDataset` yields dict batches of numpy arrays sized
``global_batch_size // process_count`` (this process's share). Iterator
state is a small dict (epoch, position, rng state) so checkpoints can resume
the input stream exactly — the contract the reference gets from
MonitoredTrainingSession+Saver only approximately (SURVEY.md §7 hard
part 3 demands we do better).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Iterator, Mapping

import numpy as np

from distributed_tensorflow_framework_tpu.core import faults

log = logging.getLogger(__name__)

Batch = Mapping[str, np.ndarray]


def image_np_dtype(image_dtype: str) -> np.dtype:
    """Numpy dtype for DataConfig.image_dtype ('float32' | 'bfloat16').

    bfloat16 infeed halves image HBM traffic — the ResNet-50 step is
    HBM-bandwidth-bound (bench.py) — while augmentation math stays f32.
    """
    if image_dtype == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if image_dtype in ("float32", "f32"):
        return np.dtype(np.float32)
    raise ValueError(f"Unsupported image_dtype {image_dtype!r}")


def host_batch_size(global_batch_size: int, process_count: int) -> int:
    """This host's share of the global batch; rejects non-divisible splits
    (a silent floor-divide would shrink the actual global batch and skew
    the LR/throughput accounting)."""
    if global_batch_size % process_count:
        raise ValueError(
            f"global_batch_size {global_batch_size} not divisible by "
            f"process_count {process_count}"
        )
    return global_batch_size // process_count


def finite_array_eval(
    images: np.ndarray,
    labels: np.ndarray,
    *,
    batch: int,
    process_index: int,
    process_count: int,
    out_dtype: Any,
) -> "HostDataset":
    """Single-pass padded eval stream over in-memory arrays.

    The exact-evaluation contract (reference eval loop, SURVEY.md §3.4):
    every example is visited exactly once; the final partial batch is
    zero-padded to the static batch size and a per-example ``weight``
    (1.0 real / 0.0 pad) lets the eval step weight its metric sums so the
    padding contributes nothing. Every host yields the same number of
    batches (padding differs), so multi-host collectives never diverge.
    """
    n = len(images)
    shard = np.arange(n)[process_index::process_count]
    # ceil over the LARGEST host shard so all hosts agree on batch count.
    max_shard = -(-n // process_count)
    num_batches = -(-max_shard // batch)

    def make_iter(state):
        state.setdefault("batch", 0)
        for i in range(state["batch"], num_batches):
            idx = shard[i * batch:(i + 1) * batch]
            k = len(idx)
            img = np.zeros((batch,) + images.shape[1:], dtype=out_dtype)
            lab = np.zeros((batch,), np.int32)
            w = np.zeros((batch,), np.float32)
            if k:
                img[:k] = images[idx]
                lab[:k] = labels[idx]
                w[:k] = 1.0
            state["batch"] = i + 1
            yield {"image": img, "label": lab, "weight": w}

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((batch,) + tuple(images.shape[1:]), out_dtype),
            "label": ((batch,), np.int32),
            "weight": ((batch,), np.float32),
        },
        initial_state={"batch": 0},
        cardinality=num_batches,
    )


class HostDataset:
    """A restartable, checkpointable per-host batch stream."""

    def __init__(
        self,
        make_iter: Callable[[dict[str, Any]], Iterator[Batch]],
        *,
        element_spec: Mapping[str, tuple[tuple[int, ...], Any]],
        initial_state: dict[str, Any] | None = None,
        cardinality: int | None = None,
        repartition: str = "none",
    ):
        """
        Args:
          make_iter: state-dict → iterator of batches; the iterator must
            mutate the SAME state dict in place as it advances so that
            ``state()`` is always current. Nested state values must be
            REBOUND, never mutated in place: ``state()`` hands out
            shallow copies, so an in-place list/dict mutation would
            retroactively edit every snapshot already queued for a save.
          element_spec: name → (per-host batch shape, dtype).
          initial_state: starting iterator state.
          cardinality: batches per epoch per host, if known (None = infinite).
          repartition: data/shard.py capability tag — "invariant" when the
            state is host-count-invariant (an N→M gang refit may restore
            it directly), "none" when the per-host stream depends on the
            host count (skip-count/file-shard resume) and a refit must
            raise DataShardError instead of silently replaying/dropping.
        """
        self._make_iter = make_iter
        self.element_spec = dict(element_spec)
        self._state: dict[str, Any] = dict(initial_state or {})
        self._iter: Iterator[Batch] | None = None
        self.cardinality = cardinality
        self.repartition = repartition
        # Process-lifetime pull ordinal (1-based, NOT reset by restore):
        # lets stall_infeed:S:N target a specific pull — e.g. one past the
        # Trainer's build-time sample peek, inside the step loop.
        self._pulls = 0
        # Lazy shard identity for per-worker data_chaos faults — resolved
        # from the gang discovery env on first use so reader factories
        # need no extra plumbing.
        self._chaos_worker: int | None = None

    def __iter__(self):
        return self

    def _chaos_worker_index(self) -> int:
        if self._chaos_worker is None:
            from distributed_tensorflow_framework_tpu.data import shard

            self._chaos_worker = shard.ShardAssignment.from_env().process_index
        return self._chaos_worker

    def _apply_chaos(self, fault, batch: Batch) -> None:
        """Execute one matched data_chaos fault against a pulled batch.

        ``corrupt_shard`` poisons every floating field to NaN (the
        anomaly ladder's detectable signature — integer token fields are
        left alone, so image workloads are the drill surface);
        ``skew_shard`` sleeps, making this one host's reader slower than
        the gang (the straggler the infeed watchdog must surface).
        """
        if fault.kind == "corrupt_shard":
            poisoned = []
            for k, v in batch.items():
                if np.issubdtype(np.asarray(v).dtype, np.floating):
                    np.asarray(v)[...] = np.nan
                    poisoned.append(k)
            log.warning(
                "data_chaos: corrupt_shard poisoned fields %s of pull %d",
                poisoned or "<none — no floating fields>", self._pulls)
        elif fault.kind == "skew_shard":
            log.warning(
                "data_chaos: skew_shard sleeping %.1fs at pull %d",
                fault.seconds, self._pulls)
            time.sleep(fault.seconds)

    def _pull(self) -> Batch:
        # stall_infeed fault point (core/faults.py): a hung input pipeline
        # — the failure the heartbeat watchdog must catch — is one sleep
        # here; a no-op set lookup when no plan is installed.
        self._pulls += 1
        faults.fire("infeed", step=self._pulls)
        if self._iter is None:
            self._iter = self._make_iter(self._state)
        batch = next(self._iter)
        # Consumed-batch ordinal (1-based, part of the checkpointable
        # state): the coordinate the skip-batch record and the manifest's
        # data-state commit record are expressed in.
        self._state["consumed"] = int(self._state.get("consumed", 0)) + 1
        # data_chaos fault point: per-worker reader corruption/skew
        # (docs/RESILIENCE.md fault table). Matched faults are filtered to
        # THIS host's shard index so `corrupt_shard:K` hits exactly one
        # member of the gang.
        for fault in faults.fire("data_chaos", step=self._pulls,
                                 worker=self._chaos_worker_index()):
            self._apply_chaos(fault, batch)
        return batch

    def __next__(self) -> Batch:
        batch = self._pull()
        skipped = self._state.get("batches_skipped")
        if skipped:
            # Skip-batch replay (docs/RESILIENCE.md): ordinals recorded by
            # a rollback are batches the recovered run decided NOT to
            # train on. When a restore rebuilds the iterator from a state
            # positioned before the skip region, discard them again so
            # the effective stream is reconstructed instead of
            # double-counted.
            skip = {int(o) for o in skipped}
            while int(self._state["consumed"]) in skip:
                log.info("discarding batch ordinal %d (recorded as "
                         "skipped by a rollback)", self._state["consumed"])
                batch = self._pull()
        return batch

    # -- checkpointable iterator state ------------------------------------
    def state(self) -> dict[str, Any]:
        snap = dict(self._state)
        skipped = snap.get("batches_skipped")
        if skipped:
            # Prune skip ordinals the stream is already past: a restore of
            # this snapshot resumes AFTER them (its position keys pair
            # with ``consumed``), so they are dead weight in checkpoints.
            consumed = int(snap.get("consumed", 0))
            live = [int(o) for o in skipped if int(o) > consumed]
            if live:
                snap["batches_skipped"] = live
            else:
                snap.pop("batches_skipped", None)
        return snap

    def restore(self, state: dict[str, Any]) -> None:
        self._state = dict(state)
        self._iter = None  # rebuild lazily from restored state

    def record_skipped(self, ordinals) -> None:
        """Record consumed-batch ordinals a rollback skipped.

        REBINDS ``batches_skipped`` (never appends in place — ``state()``
        snapshots share nested lists by reference), so snapshots taken
        before this call are unaffected and every later one carries the
        union. Called from the consumer thread while the prefetch
        producer reads the dict: the single rebind is atomic under the
        GIL and the producer's ``make_iter`` never touches this key.
        """
        merged = sorted(
            {int(o) for o in self._state.get("batches_skipped", ())}
            | {int(o) for o in ordinals})
        if merged:
            self._state["batches_skipped"] = merged
