"""Host dataset abstraction + save/restore of iterator position.

A `HostDataset` yields dict batches of numpy arrays sized
``global_batch_size // process_count`` (this process's share). Iterator
state is a small dict (epoch, position, rng state) so checkpoints can resume
the input stream exactly — the contract the reference gets from
MonitoredTrainingSession+Saver only approximately (SURVEY.md §7 hard
part 3 demands we do better).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Mapping

import numpy as np

from distributed_tensorflow_framework_tpu.core import faults

Batch = Mapping[str, np.ndarray]


def image_np_dtype(image_dtype: str) -> np.dtype:
    """Numpy dtype for DataConfig.image_dtype ('float32' | 'bfloat16').

    bfloat16 infeed halves image HBM traffic — the ResNet-50 step is
    HBM-bandwidth-bound (bench.py) — while augmentation math stays f32.
    """
    if image_dtype == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if image_dtype in ("float32", "f32"):
        return np.dtype(np.float32)
    raise ValueError(f"Unsupported image_dtype {image_dtype!r}")


def host_batch_size(global_batch_size: int, process_count: int) -> int:
    """This host's share of the global batch; rejects non-divisible splits
    (a silent floor-divide would shrink the actual global batch and skew
    the LR/throughput accounting)."""
    if global_batch_size % process_count:
        raise ValueError(
            f"global_batch_size {global_batch_size} not divisible by "
            f"process_count {process_count}"
        )
    return global_batch_size // process_count


def finite_array_eval(
    images: np.ndarray,
    labels: np.ndarray,
    *,
    batch: int,
    process_index: int,
    process_count: int,
    out_dtype: Any,
) -> "HostDataset":
    """Single-pass padded eval stream over in-memory arrays.

    The exact-evaluation contract (reference eval loop, SURVEY.md §3.4):
    every example is visited exactly once; the final partial batch is
    zero-padded to the static batch size and a per-example ``weight``
    (1.0 real / 0.0 pad) lets the eval step weight its metric sums so the
    padding contributes nothing. Every host yields the same number of
    batches (padding differs), so multi-host collectives never diverge.
    """
    n = len(images)
    shard = np.arange(n)[process_index::process_count]
    # ceil over the LARGEST host shard so all hosts agree on batch count.
    max_shard = -(-n // process_count)
    num_batches = -(-max_shard // batch)

    def make_iter(state):
        state.setdefault("batch", 0)
        for i in range(state["batch"], num_batches):
            idx = shard[i * batch:(i + 1) * batch]
            k = len(idx)
            img = np.zeros((batch,) + images.shape[1:], dtype=out_dtype)
            lab = np.zeros((batch,), np.int32)
            w = np.zeros((batch,), np.float32)
            if k:
                img[:k] = images[idx]
                lab[:k] = labels[idx]
                w[:k] = 1.0
            state["batch"] = i + 1
            yield {"image": img, "label": lab, "weight": w}

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((batch,) + tuple(images.shape[1:]), out_dtype),
            "label": ((batch,), np.int32),
            "weight": ((batch,), np.float32),
        },
        initial_state={"batch": 0},
        cardinality=num_batches,
    )


class HostDataset:
    """A restartable, checkpointable per-host batch stream."""

    def __init__(
        self,
        make_iter: Callable[[dict[str, Any]], Iterator[Batch]],
        *,
        element_spec: Mapping[str, tuple[tuple[int, ...], Any]],
        initial_state: dict[str, Any] | None = None,
        cardinality: int | None = None,
    ):
        """
        Args:
          make_iter: state-dict → iterator of batches; the iterator must
            mutate the SAME state dict in place as it advances so that
            ``state()`` is always current.
          element_spec: name → (per-host batch shape, dtype).
          initial_state: starting iterator state.
          cardinality: batches per epoch per host, if known (None = infinite).
        """
        self._make_iter = make_iter
        self.element_spec = dict(element_spec)
        self._state: dict[str, Any] = dict(initial_state or {})
        self._iter: Iterator[Batch] | None = None
        self.cardinality = cardinality
        # Process-lifetime pull ordinal (1-based, NOT reset by restore):
        # lets stall_infeed:S:N target a specific pull — e.g. one past the
        # Trainer's build-time sample peek, inside the step loop.
        self._pulls = 0

    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        # stall_infeed fault point (core/faults.py): a hung input pipeline
        # — the failure the heartbeat watchdog must catch — is one sleep
        # here; a no-op set lookup when no plan is installed.
        self._pulls += 1
        faults.fire("infeed", step=self._pulls)
        if self._iter is None:
            self._iter = self._make_iter(self._state)
        return next(self._iter)

    # -- checkpointable iterator state ------------------------------------
    def state(self) -> dict[str, Any]:
        return dict(self._state)

    def restore(self, state: dict[str, Any]) -> None:
        self._state = dict(state)
        self._iter = None  # rebuild lazily from restored state
