"""Host dataset abstraction + save/restore of iterator position.

A `HostDataset` yields dict batches of numpy arrays sized
``global_batch_size // process_count`` (this process's share). Iterator
state is a small dict (epoch, position, rng state) so checkpoints can resume
the input stream exactly — the contract the reference gets from
MonitoredTrainingSession+Saver only approximately (SURVEY.md §7 hard
part 3 demands we do better).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Mapping

import numpy as np

Batch = Mapping[str, np.ndarray]


def image_np_dtype(image_dtype: str) -> np.dtype:
    """Numpy dtype for DataConfig.image_dtype ('float32' | 'bfloat16').

    bfloat16 infeed halves image HBM traffic — the ResNet-50 step is
    HBM-bandwidth-bound (bench.py) — while augmentation math stays f32.
    """
    if image_dtype == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    if image_dtype in ("float32", "f32"):
        return np.dtype(np.float32)
    raise ValueError(f"Unsupported image_dtype {image_dtype!r}")


def host_batch_size(global_batch_size: int, process_count: int) -> int:
    """This host's share of the global batch; rejects non-divisible splits
    (a silent floor-divide would shrink the actual global batch and skew
    the LR/throughput accounting)."""
    if global_batch_size % process_count:
        raise ValueError(
            f"global_batch_size {global_batch_size} not divisible by "
            f"process_count {process_count}"
        )
    return global_batch_size // process_count


class HostDataset:
    """A restartable, checkpointable per-host batch stream."""

    def __init__(
        self,
        make_iter: Callable[[dict[str, Any]], Iterator[Batch]],
        *,
        element_spec: Mapping[str, tuple[tuple[int, ...], Any]],
        initial_state: dict[str, Any] | None = None,
        cardinality: int | None = None,
    ):
        """
        Args:
          make_iter: state-dict → iterator of batches; the iterator must
            mutate the SAME state dict in place as it advances so that
            ``state()`` is always current.
          element_spec: name → (per-host batch shape, dtype).
          initial_state: starting iterator state.
          cardinality: batches per epoch per host, if known (None = infinite).
        """
        self._make_iter = make_iter
        self.element_spec = dict(element_spec)
        self._state: dict[str, Any] = dict(initial_state or {})
        self._iter: Iterator[Batch] | None = None
        self.cardinality = cardinality

    def __iter__(self):
        return self

    def __next__(self) -> Batch:
        if self._iter is None:
            self._iter = self._make_iter(self._state)
        return next(self._iter)

    # -- checkpointable iterator state ------------------------------------
    def state(self) -> dict[str, Any]:
        return dict(self._state)

    def restore(self, state: dict[str, Any]) -> None:
        self._state = dict(state)
        self._iter = None  # rebuild lazily from restored state
