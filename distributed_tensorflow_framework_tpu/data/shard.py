"""Deterministic per-host shard assignment + resumable data-state records.

Two halves of the exactly-once data story (docs/RESILIENCE.md "Exactly-once
data"):

  * **Shard assignment** — which slice of every global batch THIS process
    reads. Identity comes from the same ``jax.distributed`` discovery env
    the gang supervisor writes (core/cluster.py ``worker_env``), so data
    sharding can never disagree with gang membership, and the assignment
    is validated against the mesh's data-parallel extent before the first
    batch moves (``shard_plan`` — the Trainer emits it as KIND_DATA_SHARD).

    Block sharding (the default, ``data.shard_mode="block"``) gives host
    ``h`` the ``h``-th contiguous ``host_batch`` rows of global batch
    ``i`` inside the epoch permutation: after ``k`` global batches the
    consumed prefix is exactly ``perm[:k * global_batch]`` REGARDLESS of
    how many hosts read it. That host-count invariance is what makes an
    N→M elastic refit resume from the same global offset with no sample
    replayed and none dropped. (With one process, block and stride
    sharding are bit-identical.)

  * **Data-state commit records** — a sha256'd summary of the iterator
    state written into the checkpoint manifest next to the mesh-topology
    record (ckpt/reshard.py), so "where was the data stream?" is part of
    the same integrity contract as "which bytes are the weights?".
    ``check_restore_data`` is the restore-time gate: digest-checks the
    restored state against the commit record and decides whether an N→M
    host refit may repartition it (position-keyed, host-count-invariant
    states) or must refuse with a typed error (skip-count / file-sharded
    states, where the per-host stream itself depends on the host count).

Stdlib + numpy-free on purpose: the supervisor and tests reason about
shard assignment without touching JAX.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from dataclasses import dataclass
from typing import Any, Mapping

from distributed_tensorflow_framework_tpu.core import cluster

log = logging.getLogger(__name__)

# Manifest commit-record field for the chief's iterator state (rides
# ``write_manifest(extra=...)`` next to ckpt/reshard.py's MESH_RECORD_KEY).
DATA_RECORD_KEY = "data_state"
DATA_STATE_SCHEMA = "dtf-data-state/1"

# HostDataset.repartition capability values (data/pipeline.py):
#   invariant — the state is host-count-invariant (block-sharded or
#               positionless streams): restoring the chief's state at ANY
#               process count resumes the same global offset.
#   none      — the per-host stream depends on the host count (stride/file
#               sharding, skip-count resume): an N→M refit cannot
#               repartition it and must raise DataShardError.
REPARTITION_INVARIANT = "invariant"
REPARTITION_NONE = "none"


class DataShardError(ValueError):
    """A shard-assignment or data-state contract violation.

    Raised when a host's shard assignment is inconsistent with the gang
    (bad index, indivisible batch), when a restored iterator state fails
    its manifest digest, or when an N→M host refit asks a non-
    repartitionable state to move. Carries an optional ``hint`` with the
    unblocking knob, mirroring ckpt/reshard.MeshTopologyError.
    """

    def __init__(self, message: str, *, hint: str | None = None):
        if hint:
            message = f"{message}\n  hint: {hint}"
        super().__init__(message)
        self.hint = hint


@dataclass(frozen=True)
class ShardAssignment:
    """This process's slot in the data-reading gang."""

    process_index: int
    process_count: int

    def __post_init__(self):
        if self.process_count < 1:
            raise DataShardError(
                f"process_count {self.process_count} < 1")
        if not 0 <= self.process_index < self.process_count:
            raise DataShardError(
                f"process_index {self.process_index} outside gang of "
                f"{self.process_count}")

    @classmethod
    def from_env(cls, environ: Mapping[str, str] | None = None
                 ) -> "ShardAssignment":
        """Assignment from the gang's discovery env (cluster.worker_env).

        Single-process runs (no discovery vars) read shard 0 of 1 — the
        same default ``get_dataset`` uses — so shard identity is ALWAYS
        derived from the env the supervisor controls, never guessed.
        """
        env = os.environ if environ is None else environ
        try:
            count = int(env.get(cluster.ENV_NUM_PROCESSES) or 1)
            index = int(env.get(cluster.ENV_PROCESS_ID) or 0)
        except ValueError as e:
            raise DataShardError(
                f"malformed gang discovery env: {e} "
                f"({cluster.ENV_NUM_PROCESSES}="
                f"{env.get(cluster.ENV_NUM_PROCESSES)!r}, "
                f"{cluster.ENV_PROCESS_ID}="
                f"{env.get(cluster.ENV_PROCESS_ID)!r})") from e
        return cls(process_index=index, process_count=count)


def shard_plan(assignment: ShardAssignment, *, global_batch: int,
               data_parallel: int | None = None,
               shard_mode: str = "block") -> dict:
    """Validate and describe this host's slice of every global batch.

    The Trainer runs this once at build time and emits the result as a
    KIND_DATA_SHARD event, so the shard layout of every attempt is in
    the telemetry record. ``data_parallel`` is the mesh's data-parallel
    extent (data*fsdp axis sizes): each host's rows must map to a whole
    number of data-parallel rows or ``to_global`` would split a host's
    shard across process boundaries.
    """
    p, n = assignment.process_index, assignment.process_count
    if global_batch % n:
        raise DataShardError(
            f"global_batch_size {global_batch} not divisible by "
            f"process_count {n}",
            hint="pick a global batch that is a multiple of the gang size")
    if data_parallel is not None and data_parallel > 0:
        if data_parallel % n:
            raise DataShardError(
                f"mesh data-parallel extent {data_parallel} not divisible "
                f"by process_count {n} — hosts would feed unequal numbers "
                f"of data-parallel rows",
                hint="size the mesh's data/fsdp axes as a multiple of the "
                     "gang size")
    return {
        "process_index": p,
        "process_count": n,
        "host_batch": global_batch // n,
        "global_batch": int(global_batch),
        "shard_mode": shard_mode,
        "data_parallel": data_parallel,
    }


def block_bounds(batch_index: int, host_batch: int, process_index: int,
                 process_count: int) -> tuple[int, int]:
    """``[lo, hi)`` into the epoch permutation for this host's block of
    global batch ``batch_index``: global batch ``i`` is
    ``perm[i*B : (i+1)*B]`` and host ``h`` takes rows
    ``[h*b, (h+1)*b)`` of it — so the consumed prefix after ``k``
    batches is ``perm[:k*B]`` at any host count."""
    lo = (batch_index * process_count + process_index) * host_batch
    return lo, lo + host_batch


def epoch_batches(n_examples: int, host_batch: int,
                  process_count: int) -> int:
    """Full global batches per epoch (identical on every host — the
    ragged tail past ``n // global_batch`` batches is dropped)."""
    return n_examples // (host_batch * process_count)


# --------------------------------------------------------------- records

def state_digest(state: Mapping[str, Any]) -> str:
    """sha256 over the canonical-JSON form of an iterator state — the
    same JSON round-trip Orbax's JsonSave applies, so the digest computed
    at save time matches a digest of the restored object bit-for-bit."""
    canonical = json.dumps(state, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()


def _position(state: Mapping[str, Any]) -> dict:
    """Human-readable position extract for the commit record."""
    return {k: state[k] for k in
            ("epoch", "batch_in_epoch", "step", "batches", "consumed",
             "emitted")
            if k in state and isinstance(state[k], int)}


def data_state_record(state: Mapping[str, Any], *, process_count: int,
                      repartition: str = REPARTITION_NONE,
                      watermark: int = 0) -> dict:
    """The manifest commit record for one saved iterator state.

    ``watermark`` is the prefetch depth at save time (batches pulled
    ahead of the consumer, infeed ``watermark()``) — recorded for the
    post-mortem story ("how far ahead was the producer when we died?"),
    NOT folded into the restore position: the saved state is the
    snapshot paired with the last CONSUMED batch, so prefetched-ahead
    batches are re-produced after restore, never lost.
    """
    return {
        "schema": DATA_STATE_SCHEMA,
        "sha256": state_digest(state),
        "process_count": int(process_count),
        "repartition": repartition,
        "watermark": int(watermark),
        "position": _position(state),
    }


def check_restore_data(record: Mapping[str, Any] | None,
                       state: Mapping[str, Any], *,
                       process_count: int,
                       resume_strict: bool = True) -> dict | None:
    """Restore-time gate for a saved iterator state.

    ``record`` is the manifest's DATA_RECORD_KEY entry (None for legacy
    checkpoints — restored with a warning, no integrity claim).
    ``state`` is the restored ``data_iter`` object. Returns a plan dict
    (``action`` resume|repartition|forced) the caller emits as
    KIND_DATA_STATE, or None for legacy records; raises
    :class:`DataShardError` when the digest fails or an N→M host change
    meets a non-repartitionable state (``data.resume_strict=false``
    downgrades both to warnings, action "forced").
    """
    if record is None:
        log.warning(
            "checkpoint has no data-state commit record (pre-exactly-once "
            "save) — restoring the iterator state without an integrity "
            "check")
        return None
    if record.get("schema") != DATA_STATE_SCHEMA:
        raise DataShardError(
            f"unknown data-state record schema {record.get('schema')!r} "
            f"(this build reads {DATA_STATE_SCHEMA!r})")
    digest = state_digest(state)
    saved_digest = record.get("sha256")
    if digest != saved_digest:
        msg = (f"restored iterator state does not match its manifest "
               f"commit record: sha256 {digest[:12]}… vs recorded "
               f"{str(saved_digest)[:12]}…")
        if resume_strict:
            raise DataShardError(
                msg, hint="the data_iter payload was mutated after commit; "
                          "restore an older step, or set "
                          "data.resume_strict=false to proceed anyway")
        log.warning("%s — proceeding (data.resume_strict=false)", msg)
        return {"action": "forced", "reason": "digest_mismatch",
                "from_processes": record.get("process_count"),
                "to_processes": process_count}
    saved_count = int(record.get("process_count") or process_count)
    if saved_count == process_count:
        return {"action": "resume", "from_processes": saved_count,
                "to_processes": process_count,
                "watermark": record.get("watermark", 0)}
    if record.get("repartition") == REPARTITION_INVARIANT:
        # Host-count-invariant state: the same state restored on every
        # host of the new gang resumes at the same global offset — the
        # unconsumed remainder of the epoch repartitions over M hosts by
        # construction (block sharding), nothing to transform.
        log.info(
            "repartitioning data state across host-count change "
            "%d -> %d (host-count-invariant position %s)",
            saved_count, process_count, record.get("position"))
        return {"action": "repartition", "from_processes": saved_count,
                "to_processes": process_count,
                "watermark": record.get("watermark", 0)}
    msg = (f"data state saved by {saved_count} process(es) cannot be "
           f"repartitioned onto {process_count}: this reader resumes by "
           f"per-host skip-count or file shard, which does not survive a "
           f"host-count change (position {record.get('position')})")
    if resume_strict:
        raise DataShardError(
            msg, hint="use a block-shardable reader (data.shard_mode="
                      "\"block\" readers repartition freely), or set "
                      "data.resume_strict=false to resume the stream "
                      "from this state anyway (samples may replay or "
                      "drop across the refit)")
    log.warning("%s — proceeding (data.resume_strict=false)", msg)
    return {"action": "forced", "reason": "host_count_change",
            "from_processes": saved_count, "to_processes": process_count}
