"""Synthetic data sources — benchmarking and hardware-free tests.

Mirrors the role of the reference's "fake cluster on localhost" smoke path
(SURVEY.md §4): exercise the full runtime with no dataset on disk. Labels
are a deterministic function of the image/token content so models can
actually overfit them in integration tests (loss must go down).
"""

from __future__ import annotations

from typing import Any

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.core import prng
from distributed_tensorflow_framework_tpu.data.pipeline import (
    HostDataset,
    image_np_dtype,
)
from distributed_tensorflow_framework_tpu.data import shard


def _host_batch(config: DataConfig, process_count: int) -> int:
    g = config.global_batch_size
    if g % process_count:
        raise ValueError(
            f"global_batch_size {g} not divisible by process_count {process_count}"
        )
    return g // process_count


def synthetic_images(
    config: DataConfig, process_index: int, process_count: int
) -> HostDataset:
    b = _host_batch(config, process_count)
    h = w = config.image_size
    c = config.channels
    num_classes = config.num_classes
    out_dtype = image_np_dtype(config.image_dtype)

    def make_iter(state: dict[str, Any]):
        state.setdefault("step", 0)
        while True:
            # Host-local stream: process_index in the derivation
            # (core/prng.py host-side rules).
            rng = prng.host_rng(config.seed, prng.ROLE_DATA,
                                process_index, state["step"])
            images = rng.standard_normal((b, h, w, c), dtype=np.float32)
            # Label = argmax over the first num_classes pixels: uniform over
            # classes, perfectly learnable, and stable at any image size
            # (a per-image-mean hash degenerates by CLT as size grows).
            labels = np.argmax(
                images.reshape(b, -1)[:, :num_classes], axis=1
            ).astype(np.int32)
            state["step"] += 1
            yield {"image": images.astype(out_dtype, copy=False), "label": labels}

    return HostDataset(
        make_iter,
        element_spec={
            "image": ((b, h, w, c), out_dtype),
            "label": ((b,), np.int32),
        },
        initial_state={"step": 0},
        # Generated data has no sample identity to replay or drop: the
        # {"step": N} state restores at any host count (each host simply
        # draws its own stream), so an N→M refit is trivially exact.
        repartition=shard.REPARTITION_INVARIANT,
    )


def synthetic_mlm(
    config: DataConfig, process_index: int, process_count: int
) -> HostDataset:
    b = _host_batch(config, process_count)
    s = config.seq_len
    vocab = config.vocab_size
    lo = min(1000, vocab // 2)  # keep low ids free for specials

    def make_iter(state: dict[str, Any]):
        state.setdefault("step", 0)
        # BERT's [MASK]=103 when it sits below the token range [lo, vocab)
        # (vocab > 103 is NOT enough: e.g. vocab=128 → tokens span [64,128)
        # and 103 would collide with a real token). Fallback is id 0, which
        # is always below lo>=1 and in embedding range.
        mask_id = 103 if lo > 103 else 0
        while True:
            rng = prng.host_rng(config.seed, prng.ROLE_DATA,
                                process_index, state["step"])
            tokens = rng.integers(lo, vocab, size=(b, s), dtype=np.int64).astype(np.int32)
            mask = rng.random((b, s)) < config.mask_prob
            mask[:, 0] = False
            input_ids = np.where(mask, mask_id, tokens)
            targets = np.where(mask, tokens, -1).astype(np.int32)
            state["step"] += 1
            yield {
                "input_ids": input_ids,
                "targets": targets,
                "attention_mask": np.ones((b, s), dtype=np.int32),
            }

    return HostDataset(
        make_iter,
        element_spec={
            "input_ids": ((b, s), np.int32),
            "targets": ((b, s), np.int32),
            "attention_mask": ((b, s), np.int32),
        },
        initial_state={"step": 0},
        # Same refit-safety as synthetic_images: no sample identity.
        repartition=shard.REPARTITION_INVARIANT,
    )
