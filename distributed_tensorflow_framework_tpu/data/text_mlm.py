"""Text MLM pipeline (BASELINE.json config 5).

Consumes pre-tokenized sequences from TFRecords (``input_ids`` int64 list)
and applies BERT-style dynamic masking on the host: 15% of positions, of
which 80% → [MASK], 10% → random token, 10% kept. Synthetic fallback when
no data is present.
"""

from __future__ import annotations

import glob
import logging
import os

import numpy as np

from distributed_tensorflow_framework_tpu.core.config import DataConfig
from distributed_tensorflow_framework_tpu.core import prng
from distributed_tensorflow_framework_tpu.data import packing
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset, host_batch_size
from distributed_tensorflow_framework_tpu.data import synthetic
from distributed_tensorflow_framework_tpu.data.tfdata import tfdata_to_hostdataset

# Back-compat: pack_documents lived here before data/packing.py (ISSUE 19).
pack_documents = packing.pack_documents

log = logging.getLogger(__name__)

MASK_ID = 103
CLS_ID = 101
SEP_ID = 102
VOCAB = 30522


def apply_mlm_mask(tokens: np.ndarray, rng: np.random.Generator,
                   mask_prob: float, vocab_size: int = VOCAB
                   ) -> tuple[np.ndarray, np.ndarray]:
    """BERT dynamic masking. tokens: (b, s) int32. Returns (inputs, targets);
    targets are -1 at unmasked positions. Random-replacement tokens are
    drawn from [lo, vocab_size) so they stay inside the embedding table."""
    special = (tokens == CLS_ID) | (tokens == SEP_ID) | (tokens == 0)
    candidates = ~special
    sel = (rng.random(tokens.shape) < mask_prob) & candidates
    action = rng.random(tokens.shape)
    inputs = tokens.copy()
    inputs[sel & (action < 0.8)] = MASK_ID
    rand_sel = sel & (action >= 0.8) & (action < 0.9)
    lo = min(1000, vocab_size // 2)
    inputs[rand_sel] = rng.integers(lo, vocab_size, size=int(rand_sel.sum()))
    targets = np.where(sel, tokens, -1).astype(np.int32)
    return inputs, targets




def make_mlm(config: DataConfig, process_index: int, process_count: int,
             *, train: bool = True) -> HostDataset:
    files = (
        sorted(glob.glob(os.path.join(config.data_dir, "*.tfrecord*")))
        if config.data_dir else []
    )
    if not files:
        log.warning("MLM TFRecords not found under %r — synthetic fallback",
                    config.data_dir)
        if train and config.pack_factor > 1:
            log.warning(
                "data.pack_factor=%d is IGNORED on the synthetic fallback: "
                "synthetic rows are full-density with no segment ids, so "
                "training runs unpacked. Packing engages only on the "
                "tf.data TFRecord path (set data.data_dir).",
                config.pack_factor)
        return synthetic.synthetic_mlm(config, process_index, process_count)

    if len(files) < process_count:
        # Guard BOTH reader paths here, where files are resolved: the
        # native path would re-read the same shard on several hosts
        # (duplicate data); the tf.data path's ds.shard() would hand some
        # hosts an EMPTY file shard — their infeed never yields and every
        # host deadlocks at the first collective.
        raise ValueError(
            f"MLM reader: {len(files)} TFRecord file(s) for "
            f"{process_count} processes — sharding by file needs at least "
            f"one file per process. Provide more shards or fewer hosts."
        )

    if config.use_native_reader:
        if not train:
            # The native reader streams full batches in an infinite epoch
            # loop — it has no single-pass padded mode, so exact eval
            # (every record once, tail included) can't be honored. Refuse
            # rather than silently recycling/dropping validation records.
            raise ValueError(
                "use_native_reader has no exact-eval path — use the "
                "tf.data reader (use_native_reader=false) for evaluation"
            )
        if config.pack_factor > 1:
            raise ValueError(
                "data.pack_factor>1 (sequence packing) is wired for the "
                "tf.data MLM path only — set use_native_reader=false"
            )
        return _make_mlm_native(config, files, process_index, process_count)

    import tensorflow as tf

    b = host_batch_size(config.global_batch_size, process_count)
    s = config.seq_len

    def make_tok_ds(seed: int):
        ds = tf.data.Dataset.from_tensor_slices(files)
        ds = ds.shard(process_count, process_index)
        ds = ds.interleave(
            tf.data.TFRecordDataset,
            cycle_length=8,
            num_parallel_calls=tf.data.AUTOTUNE,
            # Deterministic ALWAYS: resume replays by skip-count
            # (data/tfdata.py contract), which requires the interleave to
            # produce an identical record order on every run — train
            # included (same fix as data/imagenet.py).
            deterministic=True,
        )
        def parse(rec):
            feats = tf.io.parse_single_example(
                rec, {"input_ids": tf.io.FixedLenFeature([s], tf.int64)}
            )
            return {"tokens": tf.cast(feats["input_ids"], tf.int32)}
        ds = ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
        if train:
            ds = ds.shuffle(config.shuffle_buffer, seed=seed).repeat()
            ds = ds.batch(b, drop_remainder=True)
        else:
            # Exact single-pass eval: keep the remainder, zero-pad to the
            # static batch. Pad rows are all-zero tokens, which the masker
            # treats as special (never selected) — they contribute no
            # masked positions, hence nothing to the MLM metric sums.
            ds = ds.batch(b, drop_remainder=False)

            def pad(batch):
                k = tf.shape(batch["tokens"])[0]
                tokens = tf.pad(batch["tokens"], [[0, b - k], [0, 0]])
                return {"tokens": tf.ensure_shape(tokens, [b, s])}

            ds = ds.map(pad, num_parallel_calls=tf.data.AUTOTUNE)
        return ds.prefetch(tf.data.AUTOTUNE)

    if train:
        base = tfdata_to_hostdataset(
            make_tok_ds,
            element_spec={"tokens": ((b, s), np.int32)},
        )
        num_batches = None
    else:
        from distributed_tensorflow_framework_tpu.data.tfdata import (
            count_records,
            eval_batches_all_hosts,
        )

        host_files = files[process_index::process_count]
        num_batches = eval_batches_all_hosts(count_records(host_files), b)
        base = tfdata_to_hostdataset(
            make_tok_ds,
            element_spec={"tokens": ((b, s), np.int32)},
            cardinality=num_batches,
            pad_tail_to=num_batches,
        )

    # Sequence packing (train only): each packed batch consumes
    # ``pack_factor`` raw record batches, lays the (zero-padded) documents
    # end-to-end into b rows and emits per-row segment ids for
    # block-diagonal attention — fewer pad positions per step means more
    # useful tokens through the same GEMMs (PERF_NOTES.md BERT findings).
    # Eval streams stay unpacked — a deliberate non-feature, not an
    # omission: (a) the exact-eval contract counts real masked tokens
    # either way, and unpacked rows keep per-document metrics comparable
    # across configs; (b) packing would make the eval batch count
    # DATA-DEPENDENT per host, but the multi-host exact-eval machinery
    # requires a fixed cardinality every host agrees on up front
    # (eval_batches_all_hosts) — hosts running different step counts
    # desync collectives. A packed eval would need a pre-pass packing
    # plan plus a cross-host max; the ~3x eval-throughput win does not
    # justify that risk to the exactness story.
    pack = config.pack_factor if train else 1

    # Wrap with host-side dynamic masking (rng keyed off batch counter so
    # restores re-create identical masks).
    def make_iter(state):
        base.restore(state.get("inner", base.state()))
        it = iter(base)
        while True:
            if pack > 1:
                # Leftover documents from the previous pack group ride in
                # the (JSON-serializable) state so overflow DEFERS data to
                # the next batch instead of discarding it, and restores
                # replay identically (ADVICE r3).
                raws = []
                carry = state.get("carry")
                if carry:
                    # Stored trimmed to each doc's nonzero prefix (token 0
                    # is reserved padding) so snapshots stay small.
                    arr = np.zeros((len(carry), s), np.int32)
                    for j, doc in enumerate(carry):
                        arr[j, :len(doc)] = doc
                    raws.append(arr)
                # Throttle fresh intake by the backlog (in raw-batch
                # units) so a too-high pack_factor DRAINS the carry
                # instead of growing it without bound: the packer only
                # absorbs ~b rows per step, so keep (carry + fresh)
                # around pack batches total.
                n_fresh = max(0, pack - (len(carry) if carry else 0) // b)
                exhausted = False
                for _ in range(n_fresh):
                    try:
                        raws.append(next(it)["tokens"])
                    except StopIteration:
                        exhausted = True
                        break
                if not raws or sum(len(r) for r in raws) == 0:
                    return
                tokens, seg_ids, leftover = pack_documents(
                    np.concatenate(raws, axis=0), b, s)
                state["carry"] = [
                    doc[:int(np.count_nonzero(doc))].tolist()
                    for doc in leftover
                ]
                if n_fresh == 0 and not exhausted:
                    log.warning(
                        "sequence packing backlog: %d carried docs — "
                        "pack_factor=%d overflows the row budget; this "
                        "batch packs from the carry alone (consider "
                        "lowering data.pack_factor)", len(leftover), pack)
            else:
                try:
                    tokens = next(it)["tokens"]
                except StopIteration:
                    return
                seg_ids = None
            state["inner"] = base.state()
            if train:
                # Real/padded-token census (data/packing.py counters):
                # rides the state so every snapshot pairs a batch with
                # the cumulative census — the Trainer reads it off its
                # data snapshot to emit KIND_DATA_PACKING (goodput per
                # padded token, the number packing exists to raise).
                packing.accumulate_counters(state, tokens)
            # Mask key from the EMITTED-batch counter, not the consumed
            # raw-batch count: a packed batch that drains the carry alone
            # consumes zero raw batches, and keying off the inner counter
            # would replay the previous batch's mask positions verbatim.
            emitted = state.get(
                "emitted", state["inner"].get("batches", 0))
            state["emitted"] = emitted + 1
            rng = prng.host_rng(
                config.seed, prng.ROLE_MASK, emitted, process_index,
            )
            inputs, targets = apply_mlm_mask(tokens, rng,
                                             config.mask_prob,
                                             config.vocab_size)
            out = {
                "input_ids": inputs,
                "targets": targets,
                "attention_mask": (tokens != 0).astype(np.int32),
            }
            if seg_ids is not None:
                out["segment_ids"] = seg_ids
            yield out

    element_spec = {
        "input_ids": ((b, s), np.int32),
        "targets": ((b, s), np.int32),
        "attention_mask": ((b, s), np.int32),
    }
    if pack > 1:
        element_spec["segment_ids"] = ((b, s), np.int32)
    return HostDataset(
        make_iter,
        element_spec=element_spec,
        initial_state={"inner": base.state()},
        cardinality=num_batches,
    )


def _make_mlm_native(config: DataConfig, files: list[str],
                     process_index: int, process_count: int) -> HostDataset:
    """MLM pipeline on the C++ record reader (data/native_reader.py).

    The reader decodes TFRecord framing and parses the fixed-schema
    Example in native threads; Python only applies the dynamic mask. Record
    order is file order (deterministic), so resume = skip N batches within
    the epoch.
    """
    from distributed_tensorflow_framework_tpu.data.native_reader import (
        NativeRecordReader,
    )

    b = host_batch_size(config.global_batch_size, process_count)
    s = config.seq_len
    shard = files[process_index::process_count]  # non-empty: make_mlm guards

    def make_iter(state):
        state.setdefault("epoch", 0)
        state.setdefault("batch_in_epoch", 0)
        state.setdefault("total_batches", 0)
        while True:
            reader = NativeRecordReader(shard)
            it = reader.batches_i32("input_ids", b, s)
            skip = state["batch_in_epoch"]
            for i, tokens in enumerate(it):
                if i < skip:
                    continue
                rng = prng.host_rng(
                    config.seed, prng.ROLE_MASK,
                    state["epoch"], i, process_index,
                )
                inputs, targets = apply_mlm_mask(tokens, rng, config.mask_prob,
                                                 config.vocab_size)
                state["batch_in_epoch"] = i + 1
                state["total_batches"] += 1
                yield {
                    "input_ids": inputs,
                    "targets": targets,
                    "attention_mask": (tokens != 0).astype(np.int32),
                }
            reader.close()
            if state["batch_in_epoch"] == 0 and skip == 0:
                raise RuntimeError(
                    f"native MLM shard {shard!r} yielded no full batch of "
                    f"{b} records — shard too small for this process count"
                )
            state["epoch"] += 1
            state["batch_in_epoch"] = 0

    return HostDataset(
        make_iter,
        element_spec={
            "input_ids": ((b, s), np.int32),
            "targets": ((b, s), np.int32),
            "attention_mask": ((b, s), np.int32),
        },
        initial_state={"epoch": 0, "batch_in_epoch": 0, "total_batches": 0},
    )
