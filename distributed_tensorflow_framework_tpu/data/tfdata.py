"""tf.data → HostDataset adapter.

The reference's input pipeline is tf.data end-to-end (SURVEY.md §2 row 5).
TF (CPU-only) is in the image precisely for this: TFRecord readers, JPEG
decode and augmentation run on the host CPU; JAX only ever sees the final
numpy batches.

Iterator checkpointing: tf.data iterators aren't portably serializable, so
the adapter records ``batches`` consumed and, on restore, rebuilds the
(seed-deterministic) pipeline and skips that many batches. Skip cost is
IO-bound only and amortized over a restart. This is strictly stronger than
the reference's contract (MonitoredTrainingSession restarts re-read the
stream from wherever the input threads happen to be).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset


def tfdata_to_hostdataset(
    make_batched_ds: Callable[[int], Any],
    *,
    element_spec: dict,
    cardinality: int | None = None,
) -> HostDataset:
    """Wrap a factory of batched+repeated tf.data datasets.

    Args:
      make_batched_ds: seed → batched, repeated, deterministic tf.data
        Dataset yielding dict elements matching element_spec.
      element_spec: name → (per-host batch shape, numpy dtype).
    """

    def make_iter(state: dict[str, Any]):
        state.setdefault("batches", 0)
        state.setdefault("seed", 0)
        ds = make_batched_ds(int(state["seed"]))
        skip = int(state["batches"])
        if skip:
            ds = ds.skip(skip)
        for elem in ds.as_numpy_iterator():
            state["batches"] += 1
            yield {k: np.asarray(v) for k, v in elem.items()}

    return HostDataset(
        make_iter,
        element_spec=element_spec,
        initial_state={"batches": 0, "seed": 0},
        cardinality=cardinality,
    )
