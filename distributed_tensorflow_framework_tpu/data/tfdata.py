"""tf.data → HostDataset adapter.

The reference's input pipeline is tf.data end-to-end (SURVEY.md §2 row 5).
TF (CPU-only) is in the image precisely for this: TFRecord readers, JPEG
decode and augmentation run on the host CPU; JAX only ever sees the final
numpy batches.

Iterator checkpointing: tf.data iterators aren't portably serializable, so
the adapter records ``batches`` consumed and, on restore, rebuilds the
(seed-deterministic) pipeline and skips that many batches. Skip cost is
IO-bound only and amortized over a restart. This is strictly stronger than
the reference's contract (MonitoredTrainingSession restarts re-read the
stream from wherever the input threads happen to be).

The skip-count is measured over THIS host's file shard, so it is only
meaningful at the process count it was taken at: resuming on a different
host count would re-deal the files and the count would index a different
stream. The adapter therefore tags its datasets
``repartition="none"`` (data/shard.py) — the restore gate in
ckpt/checkpoint.py refuses an N→M refit unless ``data.resume_strict``
is off.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from distributed_tensorflow_framework_tpu.data import shard
from distributed_tensorflow_framework_tpu.data.pipeline import HostDataset

# files-tuple → record count. Restores rebuild the pipeline (skip-count
# contract), so the one-time count per shard set must not be repeated.
_RECORD_COUNT_CACHE: dict[tuple[str, ...], int] = {}


def count_records(files: list[str]) -> int:
    """Number of TFRecords across ``files`` (raw framing read, no decode).

    For exact eval, call with THIS HOST'S file shard, not the full file
    list — the per-host batch count must reflect the records this host
    will actually stream.
    """
    key = tuple(files)
    if key not in _RECORD_COUNT_CACHE:
        import tensorflow as tf

        ds = tf.data.TFRecordDataset(files, num_parallel_reads=tf.data.AUTOTUNE)
        n = int(ds.reduce(np.int64(0), lambda x, _: x + 1).numpy())
        _RECORD_COUNT_CACHE[key] = n
    return _RECORD_COUNT_CACHE[key]


def eval_batches_all_hosts(host_records: int, batch: int) -> int:
    """Per-host eval batch count, equalized across hosts.

    Exact evaluation needs every host to run the same number of eval steps
    (each step is a collective), while file-sharded hosts hold different
    record counts. Take the max of ceil(records/batch) across processes;
    hosts that exhaust early pad with zero-weight batches (``pad_tail_to``).
    """
    import jax

    mine = -(-host_records // batch)
    if jax.process_count() == 1:
        return mine
    from jax.experimental import multihost_utils

    counts = multihost_utils.process_allgather(np.int64(mine))
    return int(np.max(counts))


def tfdata_to_hostdataset(
    make_batched_ds: Callable[[int], Any],
    *,
    element_spec: dict,
    cardinality: int | None = None,
    pad_tail_to: int | None = None,
) -> HostDataset:
    """Wrap a factory of batched+repeated tf.data datasets.

    Args:
      make_batched_ds: seed → batched, repeated, deterministic tf.data
        Dataset yielding dict elements matching element_spec.
      element_spec: name → (per-host batch shape, numpy dtype).
      cardinality: batches per epoch per host (None = infinite stream).
      pad_tail_to: for finite eval streams on multi-host jobs — if this
        host's stream exhausts before yielding this many batches, emit
        all-zero batches (weight 0) up to the target so every host runs
        the same number of collective eval steps.
    """

    def _zero_batch():
        return {
            k: np.zeros(shape, dtype) for k, (shape, dtype) in element_spec.items()
        }

    def make_iter(state: dict[str, Any]):
        state.setdefault("batches", 0)
        state.setdefault("seed", 0)
        ds = make_batched_ds(int(state["seed"]))
        skip = int(state["batches"])
        if skip:
            ds = ds.skip(skip)
        for elem in ds.as_numpy_iterator():
            state["batches"] += 1
            yield {k: np.asarray(v) for k, v in elem.items()}
        while pad_tail_to is not None and state["batches"] < pad_tail_to:
            state["batches"] += 1
            yield _zero_batch()

    return HostDataset(
        make_iter,
        element_spec=element_spec,
        initial_state={"batches": 0, "seed": 0},
        cardinality=cardinality,
        # Skip-count over a per-host file shard: only valid at the process
        # count it was taken at (module docstring).
        repartition=shard.REPARTITION_NONE,
    )
