"""Model zoo: the reference's model families, rebuilt as Flax modules.

SURVEY.md §2 rows 6–8 + BASELINE.json configs: LeNet-5 (MNIST smoke test),
ResNet-50 (CIFAR-10 and ImageNet variants, fused/cross-replica BN),
Inception-v3 (the reference's async-PS workload, here sync replicas), and
BERT-base MLM (the new-build transformer workload).

``get_model(config)`` is the registry — the analogue of the reference's
model-name flag dispatch.
"""

from __future__ import annotations

from typing import Any

from distributed_tensorflow_framework_tpu.core.config import ModelConfig


def get_model(config: ModelConfig, *, bn_axis_name=None, mesh=None) -> Any:
    """Build a Flax module from a ModelConfig (name-based dispatch).

    ``bn_axis_name`` is only set when the caller will run the model inside
    shard_map and wants cross-replica BN statistics (see
    models/layers.py docstring); under jit it must stay None. ``mesh`` is
    required only for BERT with ``attention_impl="ring"`` (sequence-parallel
    attention needs the physical mesh for its nested shard_map).
    """
    import jax.numpy as jnp

    dtype = jnp.dtype(config.dtype)
    name = config.name.lower()
    is_bert = name in ("bert", "bert_base", "bert-base")
    if config.remat and not (is_bert or name.startswith("resnet")
                             or name.startswith("inception")):
        # Honest failure beats a silently-ignored knob: activation remat is
        # wired for the transformer encoder stack (models/bert.py), the
        # ResNet residual blocks (models/resnet.py) and the Inception
        # mixed/reduction blocks (models/inception.py).
        raise ValueError(
            f"model.remat is only supported for the transformer (bert), "
            f"resnet and inception models, not {config.name!r}"
        )
    if config.remat and config.pipeline_stages > 1:
        raise ValueError(
            "model.remat inside the pipelined stack is unsupported — the "
            "GPipe stage body manages its own activation lifetime"
        )
    if config.space_to_depth_stem and not name.startswith("resnet"):
        raise ValueError(
            f"model.space_to_depth_stem is a ResNet ImageNet-stem "
            f"optimization, not supported for {config.name!r}"
        )
    if name in ("lenet", "lenet5", "lenet-5"):
        from distributed_tensorflow_framework_tpu.models.lenet import LeNet5

        return LeNet5(num_classes=config.num_classes, dtype=dtype)
    import re

    m = re.fullmatch(r"resnet-?(\d+)(_cifar|-cifar)?", name)
    if m:
        from distributed_tensorflow_framework_tpu.models.resnet import make_resnet

        return make_resnet(
            int(m.group(1)),
            num_classes=config.num_classes,
            dtype=dtype,
            bn_axis_name=bn_axis_name,
            cifar_stem=m.group(2) is not None,
            space_to_depth_stem=config.space_to_depth_stem,
            remat=config.remat,
        )
    if name in ("inception_v3", "inception-v3", "inceptionv3"):
        from distributed_tensorflow_framework_tpu.models.inception import InceptionV3

        return InceptionV3(
            num_classes=config.num_classes,
            dtype=dtype,
            bn_axis_name=bn_axis_name,
            remat=config.remat,
        )
    if is_bert:
        if config.pipeline_stages > 1:
            if config.num_experts > 0:
                raise ValueError(
                    "MoE inside the pipelined stack is unsupported "
                    "(num_experts>0 with pipeline_stages>1) — the stage "
                    "shard_map would need manual expert collectives"
                )
            from distributed_tensorflow_framework_tpu.parallel.pipeline import (
                PipelinedBert,
            )

            return PipelinedBert(
                vocab_size=config.vocab_size,
                hidden_size=config.hidden_size,
                num_layers=config.num_layers,
                num_heads=config.num_heads,
                mlp_dim=config.mlp_dim,
                max_seq_len=config.max_seq_len,
                dropout_rate=config.dropout_rate,
                dtype=dtype,
                mesh=mesh,
                num_stages=config.pipeline_stages,
                num_microbatches=config.pipeline_microbatches,
                attention_impl=config.attention_impl,
            )
        from distributed_tensorflow_framework_tpu.models.bert import BertForMLM

        return BertForMLM(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_layers,
            num_heads=config.num_heads,
            mlp_dim=config.mlp_dim,
            max_seq_len=config.max_seq_len,
            dropout_rate=config.dropout_rate,
            dtype=dtype,
            attention_impl=config.attention_impl,
            mesh=mesh,
            num_experts=config.num_experts,
            moe_every=config.moe_every,
            expert_topk=config.expert_topk,
            capacity_factor=config.capacity_factor,
            remat=config.remat,
        )
    raise ValueError(f"Unknown model {config.name!r}")
