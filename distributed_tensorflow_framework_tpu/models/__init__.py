"""Model zoo: the reference's model families, rebuilt as Flax modules.

SURVEY.md §2 rows 6–8 + BASELINE.json configs: LeNet-5 (MNIST smoke test),
ResNet-50 (CIFAR-10 and ImageNet variants, fused/cross-replica BN),
Inception-v3 (the reference's async-PS workload, here sync replicas), and
BERT-base MLM (the new-build transformer workload).

``get_model(config)`` is the registry — the analogue of the reference's
model-name flag dispatch. The reference is a framework TEMPLATE whose
extension point is "user plugs in a model build function" (SURVEY.md §1
L4); ``register_model`` is that extension point here: a user package
registers a builder under a name and every runtime feature (Trainer,
sharding rules, checkpointing, eval) works unchanged.
"""

from __future__ import annotations

from typing import Any, Callable

from distributed_tensorflow_framework_tpu.core.config import ModelConfig

# name → (builder(config, bn_axis_name=..., mesh=...) -> module, task).
_CUSTOM_MODELS: dict[str, tuple[Callable[..., Any], str]] = {}


def _is_builtin_model_name(name: str) -> bool:
    """Name-pattern twin of get_model's built-in dispatch below — keep the
    two in sync when adding a model family. The whole resnet-N pattern is
    reserved (including depths that don't exist yet)."""
    import re

    return (
        name in ("lenet", "lenet5", "lenet-5",
                 "bert", "bert_base", "bert-base",
                 "inception_v3", "inception-v3", "inceptionv3")
        or re.fullmatch(r"resnet-?(\d+)(_cifar|-cifar)?", name) is not None
    )


def register_model(name: str, *, task: str = "classification"):
    """Register a user model builder under ``model.name`` (decorator).

    The builder receives the full ModelConfig plus the same keyword
    context the built-ins get (``bn_axis_name``, ``mesh``) and returns a
    Flax module. The module's ``__call__`` MUST accept a ``train``
    keyword (the Trainer calls ``init(..., train=False)`` and
    ``apply(..., train=True, rngs={"dropout": ...})``) and its positional
    inputs must match ``task``: "classification" (images → logits) or
    "mlm" ((ids, mask[, segment_ids]) → logits) — the task picks the
    loss and batch wiring (train/step.py). The builder owns the
    interpretation of every other ModelConfig knob (e.g. ``remat``).
    Built-in names cannot be shadowed, and duplicate registrations fail
    loudly.

        @register_model("my_net")
        def build(config, *, bn_axis_name=None, mesh=None):
            return MyNet(num_classes=config.num_classes)

        class MyNet(nn.Module):
            num_classes: int
            @nn.compact
            def __call__(self, x, *, train: bool = True):
                ...
    """
    key = name.lower()
    if task not in ("classification", "mlm"):
        raise ValueError(f"unknown task {task!r} for model {name!r}")

    def deco(builder):
        if key in _CUSTOM_MODELS:
            raise ValueError(f"model {name!r} already registered")
        if _is_builtin_model_name(key):
            raise ValueError(f"model {name!r} shadows a built-in")
        _CUSTOM_MODELS[key] = (builder, task)
        return builder

    return deco


def decode_support_reason(model_config) -> str | None:
    """Why ``model_config`` cannot take the autoregressive decode path
    (None = supported) — re-exported from models/bert.py so the serving
    layer (serve/decode.py) need not import a model file directly."""
    from distributed_tensorflow_framework_tpu.models import bert

    if model_config.name.lower() in _CUSTOM_MODELS:
        return (f"custom model {model_config.name!r} has no causal decode "
                f"head (decode supports the dense bert family)")
    return bert.decode_support_reason(model_config)


def custom_model_task(name: str) -> str | None:
    """Task family of a registered custom model, or None if not custom."""
    entry = _CUSTOM_MODELS.get(name.lower())
    return entry[1] if entry else None


def get_model(config: ModelConfig, *, bn_axis_name=None, mesh=None,
              precision=None) -> Any:
    """Build a Flax module from a ModelConfig (name-based dispatch).

    ``bn_axis_name`` is only set when the caller will run the model inside
    shard_map and wants cross-replica BN statistics (see
    models/layers.py docstring); under jit it must stay None. ``mesh`` is
    required only for BERT with ``attention_impl="ring"`` (sequence-parallel
    attention needs the physical mesh for its nested shard_map).

    ``precision`` is the optional PrecisionConfig (core/config.py): its
    ``activation_dtype`` overrides ``model.dtype`` for the compute casts
    (params stay f32 masters either way), ``matmul_dtype`` selects the
    int8 block-codec matmul path, and ``remat_policy`` maps onto
    jax.checkpoint_policies in the remat-capable builders. None (the
    serving path) leaves every model exactly as before.
    """
    import jax.numpy as jnp

    dtype = jnp.dtype(config.dtype)
    matmul_dtype = ""
    ckpt_policy = None
    if precision is not None:
        if precision.activation_dtype:
            dtype = jnp.dtype(
                {"f32": jnp.float32, "bf16": jnp.bfloat16}[
                    precision.activation_dtype]
            )
        matmul_dtype = precision.matmul_dtype
        if precision.remat_policy != "none":
            from jax.ad_checkpoint import checkpoint_policies

            ckpt_policy = {
                # Save every matmul output, replay the cheap elementwise
                # tail: recompute ≈ free, roughly half the activation bytes.
                "dots_saveable": checkpoint_policies.dots_saveable,
                # Save only block/layer inputs, replay everything: the max
                # memory savings / max recompute point (long-context fit).
                "save_nothing": checkpoint_policies.nothing_saveable,
            }[precision.remat_policy]
    name = config.name.lower()
    if name in _CUSTOM_MODELS:
        if matmul_dtype or ckpt_policy is not None or (
                precision is not None and precision.activation_dtype):
            raise ValueError(
                f"precision.activation_dtype/matmul_dtype/remat_policy are "
                f"not threaded through custom model {config.name!r} — the "
                f"registered builder owns its ModelConfig interpretation"
            )
        return _CUSTOM_MODELS[name][0](
            config, bn_axis_name=bn_axis_name, mesh=mesh)
    is_bert = name in ("bert", "bert_base", "bert-base")
    if ckpt_policy is not None:
        if config.remat_policy != "full":
            raise ValueError(
                "precision.remat_policy conflicts with "
                f"model.remat_policy={config.remat_policy!r} — pick one "
                "spelling (the precision block is the cross-model one)"
            )
        if not config.remat and config.pipeline_stages <= 1:
            raise ValueError(
                "precision.remat_policy requires model.remat=true (the "
                "policy selects WHAT the per-block checkpoint saves; "
                "pipeline stages checkpoint their own layer applies and "
                "are exempt)"
            )
    if matmul_dtype and not (
            name in ("lenet", "lenet5", "lenet-5") or name.startswith("resnet")):
        raise ValueError(
            f"precision.matmul_dtype='int8' is wired for the dense/conv "
            f"image models (lenet, resnet), not {config.name!r}"
        )
    if config.remat and not (is_bert or name.startswith("resnet")
                             or name.startswith("inception")):
        # Honest failure beats a silently-ignored knob: activation remat is
        # wired for the transformer encoder stack (models/bert.py), the
        # ResNet residual blocks (models/resnet.py) and the Inception
        # mixed/reduction blocks (models/inception.py).
        raise ValueError(
            f"model.remat is only supported for the transformer (bert), "
            f"resnet and inception models, not {config.name!r}"
        )
    if config.remat_policy != "full" and not (
            config.remat and name.startswith("resnet")):
        raise ValueError(
            f"model.remat_policy={config.remat_policy!r} requires "
            f"model.remat=true on a resnet model (the conv_saved policy "
            f"keys on the ConvBN conv_out tag; models/resnet.py)"
        )
    if config.remat and config.pipeline_stages > 1:
        raise ValueError(
            "model.remat inside the pipelined stack is unsupported — the "
            "GPipe stage body manages its own activation lifetime"
        )
    if config.space_to_depth_stem and not name.startswith("resnet"):
        raise ValueError(
            f"model.space_to_depth_stem is a ResNet ImageNet-stem "
            f"optimization, not supported for {config.name!r}"
        )
    if name in ("lenet", "lenet5", "lenet-5"):
        from distributed_tensorflow_framework_tpu.models.lenet import LeNet5

        return LeNet5(num_classes=config.num_classes, dtype=dtype,
                      matmul_dtype=matmul_dtype)
    import re

    m = re.fullmatch(r"resnet-?(\d+)(_cifar|-cifar)?", name)
    if m:
        from distributed_tensorflow_framework_tpu.models.resnet import make_resnet

        return make_resnet(
            int(m.group(1)),
            num_classes=config.num_classes,
            dtype=dtype,
            bn_axis_name=bn_axis_name,
            cifar_stem=m.group(2) is not None,
            space_to_depth_stem=config.space_to_depth_stem,
            remat=config.remat,
            remat_policy=config.remat_policy,
            ckpt_policy=ckpt_policy,
            matmul_dtype=matmul_dtype,
        )
    if name in ("inception_v3", "inception-v3", "inceptionv3"):
        from distributed_tensorflow_framework_tpu.models.inception import InceptionV3

        return InceptionV3(
            num_classes=config.num_classes,
            dtype=dtype,
            bn_axis_name=bn_axis_name,
            remat=config.remat,
            ckpt_policy=ckpt_policy,
        )
    if is_bert:
        if config.pipeline_stages > 1:
            if config.num_experts > 0:
                raise ValueError(
                    "MoE inside the pipelined stack is unsupported "
                    "(num_experts>0 with pipeline_stages>1) — the stage "
                    "shard_map would need manual expert collectives"
                )
            from distributed_tensorflow_framework_tpu.parallel.pipeline import (
                PipelinedBert,
            )

            return PipelinedBert(
                vocab_size=config.vocab_size,
                hidden_size=config.hidden_size,
                num_layers=config.num_layers,
                num_heads=config.num_heads,
                mlp_dim=config.mlp_dim,
                max_seq_len=config.max_seq_len,
                dropout_rate=config.dropout_rate,
                dtype=dtype,
                mesh=mesh,
                num_stages=config.pipeline_stages,
                num_microbatches=config.pipeline_microbatches,
                attention_impl=config.attention_impl,
                fused_qkv=config.fused_qkv,
                schedule=config.pipeline_schedule,
                virtual_stages=config.pipeline_virtual_stages,
                ckpt_policy=ckpt_policy,
            )
        from distributed_tensorflow_framework_tpu.models.bert import BertForMLM

        return BertForMLM(
            vocab_size=config.vocab_size,
            hidden_size=config.hidden_size,
            num_layers=config.num_layers,
            num_heads=config.num_heads,
            mlp_dim=config.mlp_dim,
            max_seq_len=config.max_seq_len,
            dropout_rate=config.dropout_rate,
            dtype=dtype,
            attention_impl=config.attention_impl,
            mesh=mesh,
            fused_qkv=config.fused_qkv,
            num_experts=config.num_experts,
            moe_every=config.moe_every,
            expert_topk=config.expert_topk,
            capacity_factor=config.capacity_factor,
            moe_dispatch=config.moe_dispatch,
            moe_zloss_weight=config.moe_zloss_weight,
            remat=config.remat,
            ckpt_policy=ckpt_policy,
        )
    raise ValueError(f"Unknown model {config.name!r}")
