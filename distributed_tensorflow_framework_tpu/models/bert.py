"""BERT-base for masked-LM pretraining (BASELINE.json config 5).

Transformer encoder exercising the MXU (attention + MLP matmuls) and the
Adam all-reduce path. Post-LayerNorm BERT topology: token+position
embeddings → N×(MHA → add&norm → MLP → add&norm) → tied-embedding MLM head.

Parallelism hooks:
  * Parameter names are chosen to match ``parallel/sharding.py``'s TP
    rules: ``query/key/value`` (column-parallel), ``attn_out``
    (row-parallel), ``mlp_in``/``mlp_out``, ``embed/embedding`` — setting
    mesh axis ``model>1`` shards the transformer megatron-style with no
    model changes.
  * ``attention_impl``: "xla" (jnp einsum attention, XLA-fused),
    "pallas" (ops/flash_attention.py fused online-softmax kernel),
    "ring" (parallel/ring.py sequence-parallel ring attention over the
    ``seq`` mesh axis, for long-context).

Param count pinned by test: 109.5M (BERT-base, tied MLM head).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_framework_tpu.models.layers import dense_kernel_init


def dot_product_attention(q, k, v, *, mask=None, segment_ids=None,
                          dtype=jnp.float32):
    """Reference XLA attention. q,k,v: (B, S, H, D); mask: (B, 1, 1, S) or
    any shape broadcastable to (B, H, Sq, Sk); segment_ids: (B, S) packed-
    sequence ids (attend only within equal ids) or None."""
    d = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(d).astype(q.dtype)
    # Mask in f32: f32-min rounds to -inf in bf16, and a fully-masked row
    # (a padding query under packing) would then softmax to NaN
    # (max=-inf → -inf-(-inf)); in f32 the min is finite so the row
    # degrades to a harmless uniform distribution instead.
    scores = scores.astype(jnp.float32)
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    if segment_ids is not None:
        seg_mask = (segment_ids[:, None, :, None]
                    == segment_ids[:, None, None, :])
        scores = jnp.where(seg_mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


class MultiHeadAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_impl: str = "xla"
    mesh: Any = None  # required for attention_impl="ring"
    # One (H, 3·H) projection GEMM instead of three (H, H) — fewer,
    # fatter MXU calls on a step whose measured limit is GEMM
    # fragmentation, not a roofline (PERF_NOTES.md BERT analysis).
    # Column-block-exact: the fused output's q/k/v slices equal the
    # separate projections (parity-tested by weight transplant in
    # tests/test_models.py). The kernel is laid out (H, 3, H) so the TP
    # rule shards the LAST axis: every model-axis shard holds its own
    # q/k/v column slice and the split below stays shard-local — a flat
    # (H, 3H) layout would put whole projections on single shards and
    # force per-layer resharding under TP.
    fused_qkv: bool = False

    @nn.compact
    def __call__(self, x, mask=None, segment_ids=None):
        b, s, h = x.shape
        head_dim = h // self.num_heads
        dense = lambda name: nn.Dense(  # noqa: E731
            h, dtype=self.dtype, param_dtype=jnp.float32,
            kernel_init=dense_kernel_init, name=name,
        )
        if self.fused_qkv:
            # DenseGeneral flattens the kernel to (H, 3*H) before calling
            # kernel_init, so fan_in is H — identical init statistics to
            # the three separate projections.
            qkv = nn.DenseGeneral(
                features=(3, h), dtype=self.dtype, param_dtype=jnp.float32,
                kernel_init=dense_kernel_init, name="qkv",
            )(x)                                   # (B, S, 3, H)
            q, k, v = (qkv[..., i, :].reshape(b, s, self.num_heads, head_dim)
                       for i in range(3))
        else:
            q = dense("query")(x).reshape(b, s, self.num_heads, head_dim)
            k = dense("key")(x).reshape(b, s, self.num_heads, head_dim)
            v = dense("value")(x).reshape(b, s, self.num_heads, head_dim)

        if self.attention_impl == "pallas":
            from distributed_tensorflow_framework_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v, mask=mask,
                                  segment_ids=segment_ids)
        elif self.attention_impl == "ring":
            from distributed_tensorflow_framework_tpu.parallel.ring import (
                ring_attention_sharded,
            )

            out = ring_attention_sharded(q, k, v, mesh=self.mesh, mask=mask,
                                         segment_ids=segment_ids)
        else:
            out = dot_product_attention(q, k, v, mask=mask,
                                        segment_ids=segment_ids,
                                        dtype=self.dtype)
        out = out.reshape(b, s, h)
        return nn.Dense(h, dtype=self.dtype, param_dtype=jnp.float32,
                        kernel_init=dense_kernel_init, name="attn_out")(out)


class EncoderLayer(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    attention_impl: str = "xla"
    mesh: Any = None
    fused_qkv: bool = False
    # MoE FFN (models/moe.py): 0 = dense MLP; >0 = expert-parallel MoE.
    num_experts: int = 0
    expert_topk: int = 2
    capacity_factor: float = 1.25
    moe_dispatch: str = "sorted"
    moe_zloss_weight: float = 0.0

    @nn.compact
    def __call__(self, x, mask=None, train: bool = True, segment_ids=None):
        # NOTE: ``train`` is positional-able (not keyword-only) so nn.remat
        # can mark it static by argnum (BertForMLM.remat).
        attn = MultiHeadAttention(
            self.num_heads, dtype=self.dtype,
            attention_impl=self.attention_impl, mesh=self.mesh,
            fused_qkv=self.fused_qkv, name="attn",
        )(x, mask, segment_ids)
        attn = nn.Dropout(self.dropout_rate, deterministic=not train)(attn)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x + attn)
        # Aux outputs are a type-stable dict either way (zeros for the
        # dense FFN) so callers — including nn.remat'd instances, whose
        # return values are the ONLY thing that survives the checkpoint
        # boundary — never branch on the layer flavor.
        aux = {k: jnp.zeros((), jnp.float32)
               for k in ("aux_loss", "zloss", "drop_frac")}
        if self.num_experts > 0:
            from distributed_tensorflow_framework_tpu.models.moe import MoEMlp

            y, aux = MoEMlp(
                num_experts=self.num_experts, mlp_dim=self.mlp_dim,
                topk=self.expert_topk, capacity_factor=self.capacity_factor,
                dispatch_impl=self.moe_dispatch,
                zloss_weight=self.moe_zloss_weight,
                dtype=self.dtype, name="moe",
            )(x)
        else:
            y = nn.Dense(self.mlp_dim, dtype=self.dtype, param_dtype=jnp.float32,
                         kernel_init=dense_kernel_init, name="mlp_in")(x)
            y = nn.gelu(y, approximate=True)
            y = nn.Dense(x.shape[-1], dtype=self.dtype, param_dtype=jnp.float32,
                         kernel_init=dense_kernel_init, name="mlp_out")(y)
        y = nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        return nn.LayerNorm(dtype=jnp.float32, name="ln2")(x + y), aux


class BertEmbed(nn.Module):
    """Token + position embedding front. Returns the activations AND the
    raw embedding table so the caller can tie the MLM projection to it."""

    vocab_size: int
    hidden_size: int
    max_seq_len: int
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, input_ids, position_ids=None, *, train: bool = True):
        s = input_ids.shape[1]
        embed = nn.Embed(self.vocab_size, self.hidden_size,
                         param_dtype=jnp.float32, dtype=self.dtype,
                         embedding_init=nn.initializers.normal(0.02),
                         name="embed")
        x = embed(input_ids)
        pos = self.param(
            "pos_embedding", nn.initializers.normal(0.02),
            (self.max_seq_len, self.hidden_size), jnp.float32,
        )
        if position_ids is None:
            x = x + pos[None, :s, :].astype(self.dtype)
        else:
            # Packed rows: per-document positions (reset at each segment
            # boundary) so packed training sees the same position
            # distribution as unpacked training/eval.
            x = x + jnp.take(pos, position_ids, axis=0).astype(self.dtype)
        x = nn.LayerNorm(dtype=jnp.float32, name="embed_ln")(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x.astype(self.dtype), embed.embedding


class MLMHead(nn.Module):
    """MLM head: transform → gelu → LN → tied-embedding projection + bias.
    The embedding table is passed in (tying is the caller's wiring)."""

    vocab_size: int
    hidden_size: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, embedding):
        x = nn.Dense(self.hidden_size, dtype=self.dtype,
                     param_dtype=jnp.float32, kernel_init=dense_kernel_init,
                     name="mlm_transform")(x)
        x = nn.gelu(x, approximate=True)
        x = nn.LayerNorm(dtype=jnp.float32, name="mlm_ln")(x)
        # Vocab projection in the compute dtype (bf16 on TPU): this is the
        # model's largest matmul (H×V) — running it f32 would double its
        # MXU cost. The f32 promotion happens at the bias add; the loss
        # does its softmax in f32 regardless.
        logits = x.astype(self.dtype) @ embedding.astype(self.dtype).T
        bias = self.param("mlm_bias", nn.initializers.zeros,
                          (self.vocab_size,), jnp.float32)
        return logits.astype(jnp.float32) + bias


# ---------------------------------------------------------------------------
# Autoregressive decode path (serve/decode.py, docs/SERVING.md
# "Autoregressive decode").
#
# The decode engine needs two forwards the training module cannot express:
# a CAUSAL prefill over the prompt that also exports every layer's K/V, and
# a per-token step whose keys/values come from a paged cache instead of the
# layer input. Both are pure jnp functions over the trained BertForMLM
# parameter tree (same names: embed_block/layer{i}/head), with the KV
# residency abstracted behind an ``attend`` callback so the engine owns
# paging while the model owns the math. Everything runs in f32: decode
# parity is pinned BITWISE between batched and unbatched streams, and a
# replicated f32 walk is the cheapest way to make that hold by
# construction.
# ---------------------------------------------------------------------------


def decode_support_reason(model_config) -> str | None:
    """Why this model config cannot take the autoregressive decode path
    (None = supported). The pure-jnp decode forward walks the dense BERT
    parameter tree by name; trees it does not know must be refused by
    name rather than failing as a KeyError mid-stream."""
    if model_config.name.lower() not in ("bert", "bert_base", "bert-base"):
        return (f"model {model_config.name!r} has no causal decode head "
                f"(decode supports the dense bert family)")
    if getattr(model_config, "num_experts", 0):
        return "MoE encoder layers are not supported by the decode path"
    if getattr(model_config, "pipeline_stages", 1) > 1:
        return "pipelined checkpoints are not servable (see serve/export.py)"
    return None


def _decode_ln(p, x):
    """f32 LayerNorm matching nn.LayerNorm(epsilon=1e-6) semantics."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + 1e-6) * p["scale"] + p["bias"]


def _decode_dense(p, x):
    return x @ p["kernel"].astype(jnp.float32) + p["bias"]


def _decode_qkv(attn_params, x):
    """q/k/v projections for one layer, handling both parameter layouts
    (separate query/key/value vs the fused (H, 3, H) qkv kernel)."""
    if "qkv" in attn_params:
        w = attn_params["qkv"]["kernel"].astype(jnp.float32)  # (H, 3, H)
        b = attn_params["qkv"]["bias"].astype(jnp.float32)    # (3, H)
        qkv = jnp.einsum("...h,hco->...co", x, w) + b
        return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    return (_decode_dense(attn_params["query"], x),
            _decode_dense(attn_params["key"], x),
            _decode_dense(attn_params["value"], x))


def bert_decode_layers(params, ids, positions, attend):
    """The shared causal walk: embed -> N x (attn -> add&norm -> MLP ->
    add&norm), f32. ``ids``/``positions``: (B, T) int32. ``attend(layer,
    q, k, v) -> context`` with q/k/v/context all (B, T, H) f32 — prefill
    passes an in-register causal attention, the per-token decode step a
    paged-pool write+gather. Returns the final hidden states (B, T, H)."""
    emb = params["embed_block"]
    table = emb["embed"]["embedding"].astype(jnp.float32)
    x = jnp.take(table, ids, axis=0)
    x = x + jnp.take(emb["pos_embedding"].astype(jnp.float32),
                     positions, axis=0)
    x = _decode_ln(emb["embed_ln"], x)
    n_layers = sum(1 for k in params if str(k).startswith("layer"))
    for i in range(n_layers):
        lp = params[f"layer{i}"]
        q, k, v = _decode_qkv(lp["attn"], x)
        ctx = attend(i, q, k, v)
        x = _decode_ln(lp["ln1"], x + _decode_dense(lp["attn"]["attn_out"],
                                                    ctx))
        y = nn.gelu(_decode_dense(lp["mlp_in"], x), approximate=True)
        x = _decode_ln(lp["ln2"], x + _decode_dense(lp["mlp_out"], y))
    return x


def bert_decode_head_params(params):
    """Derive serving-layout head params: adds ``mlm_projection``, the
    tied embedding table pre-transposed to (H, V). Transposing inside
    the jitted step makes XLA CPU materialize the 4-byte-per-vocab-entry
    transpose on EVERY call — at serving batch sizes that one op dwarfs
    the whole forward pass (B=1 prefill especially). Paying it once per
    weight (re)load keeps the per-call matmul in the same (B,H)@(H,V)
    kernel for every row bucket, which is also what keeps logits
    bitwise-identical across batch sizes."""
    table = params["embed_block"]["embed"]["embedding"]
    head = dict(params["head"])
    head["mlm_projection"] = jnp.asarray(
        np.ascontiguousarray(np.asarray(table).T))
    out = dict(params)
    out["head"] = head
    return out


def bert_decode_logits(params, hidden):
    """MLM head over decode hidden states: transform -> gelu -> LN ->
    tied-embedding projection + bias, all f32. hidden: (..., H).
    Prefers the pre-transposed ``mlm_projection`` planted by
    :func:`bert_decode_head_params`; falls back to transposing the tied
    table in-graph (slow on CPU, see above) so direct callers without
    the derived leaf still work."""
    head = params["head"]
    t = nn.gelu(_decode_dense(head["mlm_transform"], hidden),
                approximate=True)
    t = _decode_ln(head["mlm_ln"], t)
    proj = head.get("mlm_projection")
    if proj is None:
        proj = params["embed_block"]["embed"]["embedding"].T
    logits = t @ proj.astype(jnp.float32)
    return logits + head["mlm_bias"].astype(jnp.float32)


def causal_prefill_attention(q, k, v, length, num_heads):
    """In-register causal attention for the prefill pass. q/k/v:
    (B, S, H) f32; ``length`` (B,) masks keys past each row's prompt.
    Query row i attends keys j <= i (and j < length)."""
    b, s, h = q.shape
    d = h // num_heads
    qh = q.reshape(b, s, num_heads, d)
    kh = k.reshape(b, s, num_heads, d)
    vh = v.reshape(b, s, num_heads, d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / jnp.sqrt(
        jnp.float32(d))
    idx = jnp.arange(s, dtype=jnp.int32)
    causal = idx[None, :] <= idx[:, None]                      # (Sq, Sk)
    valid = idx[None, None, None, :] < length[:, None, None, None]
    mask = causal[None, None, :, :] & valid
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vh)
    return out.reshape(b, s, h)


def paged_decode_attention(q, k_keys, v_keys, positions, num_heads):
    """One-token attention over gathered paged KV. q: (B, H) for the
    current token; k_keys/v_keys: (B, S_kv, H) gathered from the page
    pool (padding included); keys at j <= positions[b] are live, the
    rest — page-table padding and not-yet-written slots — are masked."""
    b, h = q.shape
    s_kv = k_keys.shape[1]
    d = h // num_heads
    qh = q.reshape(b, num_heads, d)
    kh = k_keys.reshape(b, s_kv, num_heads, d)
    vh = v_keys.reshape(b, s_kv, num_heads, d)
    scores = jnp.einsum("bhd,bkhd->bhk", qh, kh) / jnp.sqrt(jnp.float32(d))
    live = (jnp.arange(s_kv, dtype=jnp.int32)[None, :]
            <= positions[:, None])
    scores = jnp.where(live[:, None, :], scores,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhk,bkhd->bhd", probs, vh)
    return out.reshape(b, h)


class BertForMLM(nn.Module):
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    mlp_dim: int = 3072
    max_seq_len: int = 512
    dropout_rate: float = 0.1
    dtype: Any = jnp.bfloat16
    attention_impl: str = "xla"
    mesh: Any = None
    fused_qkv: bool = False
    # MoE: with num_experts>0, every `moe_every`-th layer (from the top of
    # each group) uses an expert-parallel FFN; returns a dict with the
    # load-balancing aux loss alongside the logits.
    num_experts: int = 0
    moe_every: int = 2
    expert_topk: int = 2
    capacity_factor: float = 1.25
    moe_dispatch: str = "sorted"
    moe_zloss_weight: float = 0.0
    # Rematerialize each encoder layer in the backward pass
    # (jax.checkpoint): activations are recomputed per layer instead of
    # stored, cutting activation memory from O(layers) to O(1) layers at
    # ~30% extra forward FLOPs — the fit lever for long-context/big-model
    # configs (ModelConfig.remat). Numerically exact (same ops replayed;
    # parity-tested in tests/test_remat.py).
    remat: bool = False
    # Selective-remat override (precision.remat_policy): a
    # jax.checkpoint_policies callable applied to the per-layer checkpoint
    # when set — e.g. dots_saveable keeps the GEMM outputs and replays
    # only the cheap elementwise tail. None = save-nothing-but-inputs
    # (jax.checkpoint's default), the max-savings/max-recompute point.
    ckpt_policy: Any = None

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, segment_ids=None,
                 *, train: bool = True):
        position_ids = None
        if segment_ids is not None:
            # Positions restart at every segment boundary: each packed
            # document sees pos_embedding[0..len) exactly as it would
            # unpacked (index i minus the running start-of-segment index).
            idx = jnp.arange(segment_ids.shape[1], dtype=jnp.int32)
            change = jnp.concatenate([
                jnp.ones_like(segment_ids[:, :1], bool),
                segment_ids[:, 1:] != segment_ids[:, :-1],
            ], axis=1)
            starts = jax.lax.cummax(
                jnp.where(change, idx[None, :], 0), axis=1)
            position_ids = idx[None, :] - starts
        x, emb_table = BertEmbed(
            self.vocab_size, self.hidden_size, self.max_seq_len,
            self.dropout_rate, self.dtype, name="embed_block",
        )(input_ids, position_ids, train=train)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        aux_total = jnp.zeros((), jnp.float32)
        zloss_total = jnp.zeros((), jnp.float32)
        drop_total = jnp.zeros((), jnp.float32)
        n_moe = 0
        # argnums of EncoderLayer.__call__: 0=self, 1=x, 2=mask, 3=train —
        # train branches Python-side (Dropout determinism) so it must stay
        # static under the checkpoint transform.
        if self.remat:
            remat_kwargs: dict[str, Any] = {"static_argnums": (3,)}
            if self.ckpt_policy is not None:
                remat_kwargs["policy"] = self.ckpt_policy
            layer_cls = nn.remat(EncoderLayer, **remat_kwargs)
        else:
            layer_cls = EncoderLayer
        for i in range(self.num_layers):
            use_moe = (
                self.num_experts > 0
                and i % max(self.moe_every, 1) == max(self.moe_every, 1) - 1
            )
            x, aux = layer_cls(
                self.num_heads, self.mlp_dim, self.dropout_rate,
                dtype=self.dtype, attention_impl=self.attention_impl,
                mesh=self.mesh, fused_qkv=self.fused_qkv,
                num_experts=self.num_experts if use_moe else 0,
                expert_topk=self.expert_topk,
                capacity_factor=self.capacity_factor,
                moe_dispatch=self.moe_dispatch,
                moe_zloss_weight=self.moe_zloss_weight,
                name=f"layer{i}",
            )(x, mask, train, segment_ids)
            if use_moe:
                aux_total = aux_total + aux["aux_loss"]
                zloss_total = zloss_total + aux["zloss"]
                drop_total = drop_total + aux["drop_frac"]
                n_moe += 1

        logits = MLMHead(self.vocab_size, self.hidden_size, self.dtype,
                         name="head")(x, emb_table)
        if self.num_experts > 0:
            out = {
                "logits": logits,
                "moe_aux_loss": aux_total / max(n_moe, 1),
                "moe_drop_frac": drop_total / max(n_moe, 1),
            }
            if self.moe_zloss_weight:
                # Only when armed — matches the metric's conditional
                # presence in the step output (train/step.py).
                out["moe_zloss"] = zloss_total / max(n_moe, 1)
            return out
        return logits
