"""Inception-v3 — the reference's async-PS workload (SURVEY.md §2 row 8).

Canonical Inception-v3 topology (299×299 input): stem of 3×3 convs →
3× InceptionA (35×35) → ReductionA → 4× InceptionB (17×17) → ReductionB →
2× InceptionC (8×8) → global pool → dense(classes), with an optional
auxiliary classifier off the last 17×17 block. All branches are ConvBN
units, so the same cross-replica-BN switch as ResNet applies.

In the reference this model runs ASYNC parameter-server training; per
BASELINE.json's north star the capability maps to synchronous TPU replicas
(SURVEY.md §2 row 4 + §7 hard part 4) — nothing in the model itself changes,
only the optimizer semantics (see configs/inception_v3.yaml).

Param count pinned by test: 23.83M (1000 classes, with aux head).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.models.layers import ConvBN, dense_kernel_init


class _C(nn.Module):
    """ConvBN shorthand with Inception's 'same/valid' conventions."""

    features: int
    kernel: tuple[int, int]
    strides: tuple[int, int] = (1, 1)
    padding: str = "SAME"
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        return ConvBN(
            self.features, self.kernel, strides=self.strides,
            padding=self.padding, train=self.train, dtype=self.dtype,
            bn_axis_name=self.bn_axis_name, name="convbn",
        )(x)


def _avg_pool_same(x):
    return nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        kw = dict(train=self.train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = _C(64, (1, 1), **kw, name="b1x1")(x)
        b2 = _C(48, (1, 1), **kw, name="b5x5_1")(x)
        b2 = _C(64, (5, 5), **kw, name="b5x5_2")(b2)
        b3 = _C(64, (1, 1), **kw, name="b3x3dbl_1")(x)
        b3 = _C(96, (3, 3), **kw, name="b3x3dbl_2")(b3)
        b3 = _C(96, (3, 3), **kw, name="b3x3dbl_3")(b3)
        b4 = _C(self.pool_features, (1, 1), **kw, name="bpool")(_avg_pool_same(x))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        kw = dict(train=self.train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = _C(384, (3, 3), strides=(2, 2), padding="VALID", **kw, name="b3x3")(x)
        b2 = _C(64, (1, 1), **kw, name="b3x3dbl_1")(x)
        b2 = _C(96, (3, 3), **kw, name="b3x3dbl_2")(b2)
        b2 = _C(96, (3, 3), strides=(2, 2), padding="VALID", **kw, name="b3x3dbl_3")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    channels_7x7: int
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        kw = dict(train=self.train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        c = self.channels_7x7
        b1 = _C(192, (1, 1), **kw, name="b1x1")(x)
        b2 = _C(c, (1, 1), **kw, name="b7x7_1")(x)
        b2 = _C(c, (1, 7), **kw, name="b7x7_2")(b2)
        b2 = _C(192, (7, 1), **kw, name="b7x7_3")(b2)
        b3 = _C(c, (1, 1), **kw, name="b7x7dbl_1")(x)
        b3 = _C(c, (7, 1), **kw, name="b7x7dbl_2")(b3)
        b3 = _C(c, (1, 7), **kw, name="b7x7dbl_3")(b3)
        b3 = _C(c, (7, 1), **kw, name="b7x7dbl_4")(b3)
        b3 = _C(192, (1, 7), **kw, name="b7x7dbl_5")(b3)
        b4 = _C(192, (1, 1), **kw, name="bpool")(_avg_pool_same(x))
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        kw = dict(train=self.train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = _C(192, (1, 1), **kw, name="b3x3_1")(x)
        b1 = _C(320, (3, 3), strides=(2, 2), padding="VALID", **kw, name="b3x3_2")(b1)
        b2 = _C(192, (1, 1), **kw, name="b7x7x3_1")(x)
        b2 = _C(192, (1, 7), **kw, name="b7x7x3_2")(b2)
        b2 = _C(192, (7, 1), **kw, name="b7x7x3_3")(b2)
        b2 = _C(192, (3, 3), strides=(2, 2), padding="VALID", **kw, name="b7x7x3_4")(b2)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x):
        kw = dict(train=self.train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        b1 = _C(320, (1, 1), **kw, name="b1x1")(x)
        b2 = _C(384, (1, 1), **kw, name="b3x3_1")(x)
        b2a = _C(384, (1, 3), **kw, name="b3x3_2a")(b2)
        b2b = _C(384, (3, 1), **kw, name="b3x3_2b")(b2)
        b3 = _C(448, (1, 1), **kw, name="b3x3dbl_1")(x)
        b3 = _C(384, (3, 3), **kw, name="b3x3dbl_2")(b3)
        b3a = _C(384, (1, 3), **kw, name="b3x3dbl_3a")(b3)
        b3b = _C(384, (3, 1), **kw, name="b3x3dbl_3b")(b3)
        b4 = _C(192, (1, 1), **kw, name="bpool")(_avg_pool_same(x))
        return jnp.concatenate([b1, b2a, b2b, b3a, b3b, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    aux_head: bool = True
    dropout_rate: float = 0.2
    # Per-block activation remat (jax.checkpoint via nn.remat on the
    # mixed/reduction blocks): trades recompute FLOPs for activation
    # bytes. Replays the same ops but is NOT guaranteed bitwise (XLA may
    # fuse the wrapped forward differently, ~1e-6/block), and the deep
    # train-mode BN cascade amplifies that — equivalent training, not
    # bit-identical trajectories (tests/test_remat.py).
    remat: bool = False
    # Selective-remat override (precision.remat_policy): a
    # jax.checkpoint_policies callable applied to each block checkpoint
    # when set (None = jax.checkpoint's save-nothing default).
    ckpt_policy: Any = None
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        kw = dict(train=train, dtype=self.dtype, bn_axis_name=self.bn_axis_name)
        if self.remat and self.ckpt_policy is not None:
            ck = lambda cls: nn.remat(cls, policy=self.ckpt_policy)  # noqa: E731
        elif self.remat:
            ck = nn.remat
        else:
            ck = lambda cls: cls  # noqa: E731
        x = x.astype(self.dtype)
        x = _C(32, (3, 3), strides=(2, 2), padding="VALID", **kw, name="stem1")(x)
        x = _C(32, (3, 3), padding="VALID", **kw, name="stem2")(x)
        x = _C(64, (3, 3), **kw, name="stem3")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = _C(80, (1, 1), padding="VALID", **kw, name="stem4")(x)
        x = _C(192, (3, 3), padding="VALID", **kw, name="stem5")(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        x = ck(InceptionA)(32, **kw, name="mixed1")(x)
        x = ck(InceptionA)(64, **kw, name="mixed2")(x)
        x = ck(InceptionA)(64, **kw, name="mixed3")(x)
        x = ck(ReductionA)(**kw, name="reduce1")(x)
        x = ck(InceptionB)(128, **kw, name="mixed4")(x)
        x = ck(InceptionB)(160, **kw, name="mixed5")(x)
        x = ck(InceptionB)(160, **kw, name="mixed6")(x)
        x = ck(InceptionB)(192, **kw, name="mixed7")(x)

        # Built whenever aux_head is on (params must exist at init regardless
        # of mode); returned only in train mode — XLA dead-code-eliminates
        # the branch in eval.
        aux = None
        if self.aux_head:
            # Canonical 299px input: 17×17 map → pool → 5×5 → conv VALID →
            # 1×1. Smaller debug inputs would produce empty (0×0) maps, so
            # fall back to SAME at each stage.
            pool_pad = "VALID" if x.shape[1] >= 5 and x.shape[2] >= 5 else "SAME"
            a = nn.avg_pool(x, (5, 5), strides=(3, 3), padding=pool_pad)
            a = _C(128, (1, 1), **kw, name="aux_proj")(a)
            conv_pad = "VALID" if a.shape[1] >= 5 and a.shape[2] >= 5 else "SAME"
            a = _C(768, (5, 5), padding=conv_pad, **kw, name="aux_conv")(a)
            a = jnp.mean(a, axis=(1, 2))
            aux = nn.Dense(self.num_classes, dtype=jnp.float32,
                           param_dtype=jnp.float32,
                           kernel_init=dense_kernel_init,
                           name="aux_classifier")(a.astype(jnp.float32))

        x = ck(ReductionB)(**kw, name="reduce2")(x)
        x = ck(InceptionC)(**kw, name="mixed8")(x)
        x = ck(InceptionC)(**kw, name="mixed9")(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32,
                          param_dtype=jnp.float32,
                          kernel_init=dense_kernel_init,
                          name="classifier")(x.astype(jnp.float32))
        if aux is not None and train:
            # Caller folds aux into the loss with the canonical 0.4 weight
            # (see train/step.py); eval mode never returns aux.
            return {"logits": logits, "aux_logits": aux}
        return logits
