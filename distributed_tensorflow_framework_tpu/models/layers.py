"""Shared layer building blocks.

The key design point is BatchNorm statistic scope (SURVEY.md §7 hard part 2).
The reference's fused BN is per-GPU: each replica normalizes with its own
minibatch statistics. Under this framework:

  * In the jit/pjit path the batch is one logical array sharded over the
    ``data`` axis, so plain `nn.BatchNorm` statistics are **global** — XLA
    inserts the cross-replica reduction automatically. This is cross-replica
    ("sync") BN by construction.
  * In the shard_map path the code is per-replica, so `nn.BatchNorm` without
    an ``axis_name`` reproduces the reference's per-replica semantics, and
    passing ``axis_name=('data','fsdp')`` (threaded via the module's
    ``bn_axis_name``) upgrades it to cross-replica.

Models expose ``cross_replica_bn`` and receive the runtime's axis names via
`flax`'s module attribute; the train step decides what to pass based on
``TrainConfig.spmd_mode``.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from distributed_tensorflow_framework_tpu.parallel.quantization import (
    DEFAULT_BLOCK_SIZE,
)

Dtype = Any

# Initializers matching the reference recipe class: He/variance-scaling for
# conv (the TF slim/layers default for ResNet), zeros for BN beta, ones gamma.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_kernel_init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal")


class BatchNorm(nn.Module):
    """BatchNorm with switchable cross-replica statistics.

    ``axis_name`` is only set when running under shard_map (see module
    docstring); ``scale_init`` supports the zero-init-gamma trick for the
    last BN of each residual block.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | Sequence[str] | None = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        return nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
            scale_init=self.scale_init,
            name="bn",
        )(x)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (N,H,W,C) → (N,H/b,W/b,b²·C).

    Channel order of the output is (di, dj, c) flattened — pixel (2i+di,
    2j+dj, c) lands at channel (di·b + dj)·C + c. The ResNet s2d stem's
    kernel mapping (tests/test_s2d_stem.py) depends on this order.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"space_to_depth: {h}x{w} not divisible by {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


def quantized_matmul(
    x: jnp.ndarray, w: jnp.ndarray, block_size: int = DEFAULT_BLOCK_SIZE
) -> jnp.ndarray:
    """Block-scaled int8 matmul with s32 accumulation (precision.matmul_dtype).

    Both operands are quantized along the contraction axis with one f32
    scale per ``block_size`` run — the same symmetric-max contract as the
    `parallel/quantization.py` wire codecs (maxabs/127 scale, rint, clamp
    to ±127, all-zero block → scale 1.0), so the per-element error bound
    is the familiar maxabs/254 per operand. The int8·int8 products
    accumulate in int32 (``preferred_element_type``, the MXU-native mode)
    and each block's partial sum is rescaled in f32 before the cross-block
    reduction. On CPU this is bit-exact emulation of the TPU int8 MXU
    path; only the dot itself is quantized — callers keep params in f32.

    ``x``: (..., K) activations; ``w``: (K, N) weights; returns (..., N)
    in f32.
    """
    *lead, k = x.shape
    if w.shape[0] != k:
        raise ValueError(f"quantized_matmul: {x.shape} @ {w.shape}")
    n = w.shape[1]
    xf = x.astype(jnp.float32).reshape(-1, k)
    wf = w.astype(jnp.float32)
    pad = (-k) % block_size
    if pad:
        xf = jnp.pad(xf, ((0, 0), (0, pad)))
        wf = jnp.pad(wf, ((0, pad), (0, 0)))
    nb = (k + pad) // block_size
    xb = xf.reshape(-1, nb, block_size)
    wb = wf.reshape(nb, block_size, n)
    x_amax = jnp.max(jnp.abs(xb), axis=2)  # (M, nb)
    w_amax = jnp.max(jnp.abs(wb), axis=1)  # (nb, N)
    x_scale = jnp.where(x_amax > 0.0, x_amax / 127.0, 1.0)
    w_scale = jnp.where(w_amax > 0.0, w_amax / 127.0, 1.0)
    xq = jnp.clip(jnp.rint(xb / x_scale[:, :, None]), -127, 127)
    wq = jnp.clip(jnp.rint(wb / w_scale[:, None, :]), -127, 127)
    acc = jnp.einsum(
        "mbk,bkn->mbn",
        xq.astype(jnp.int8),
        wq.astype(jnp.int8),
        preferred_element_type=jnp.int32,
    )
    out = jnp.sum(
        acc.astype(jnp.float32) * x_scale[:, :, None] * w_scale[None, :, :],
        axis=1,
    )
    return out.reshape(*lead, n)


class QuantDense(nn.Module):
    """Dense layer whose forward matmul runs on the int8 block codec.

    Parameters stay f32 (masters are policy-independent — MIGRATING.md);
    only the activation·weight product is quantized, via
    :func:`quantized_matmul`. The bias add and output stay f32 and are
    cast to ``dtype`` at the end, mirroring `nn.Dense`'s promotion rules.
    Gradients flow through the quantized forward as-is (straight-through
    on the rounded values), which is the standard QAT-free inference
    emulation — training probes that need exact grads keep matmul_dtype
    unset.
    """

    features: int
    use_bias: bool = True
    dtype: Dtype = jnp.float32
    block_size: int = DEFAULT_BLOCK_SIZE
    kernel_init: Callable = dense_kernel_init

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features), jnp.float32
        )
        y = quantized_matmul(x, kernel, self.block_size)
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y + bias
        return y.astype(self.dtype)


class QuantConv(nn.Module):
    """Conv whose contraction runs on the int8 block codec (im2col form).

    The convolution is lowered to patches × kernel-matrix so the same
    :func:`quantized_matmul` path (and its error contract) covers conv —
    on real TPU hardware this is exactly how the int8 MXU consumes convs.
    The parameter is named/shaped identically to `nn.Conv`'s ("kernel",
    (kh, kw, cin, cout), f32), keeping checkpoints interchangeable with
    the unquantized path.
    """

    features: int
    kernel_size: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    dtype: Dtype = jnp.float32
    block_size: int = DEFAULT_BLOCK_SIZE

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", conv_kernel_init, (kh, kw, cin, self.features), jnp.float32
        )
        patches = jax.lax.conv_general_dilated_patches(
            x.astype(jnp.float32),
            filter_shape=(kh, kw),
            window_strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        # conv_general_dilated_patches orders the patch axis (cin, kh, kw);
        # permute the kernel to match before flattening the contraction.
        kmat = kernel.transpose(2, 0, 1, 3).reshape(cin * kh * kw, self.features)
        y = quantized_matmul(patches, kmat, self.block_size)
        return y.astype(self.dtype)


class ConvBN(nn.Module):
    """Conv → BN → (optional) ReLU — the reference's fused conv/BN unit.

    On TPU the fusion the reference gets from cuDNN fused-BN comes from XLA:
    the BN scale/shift and ReLU fuse into the convolution's epilogue
    (SURVEY.md §2 native rows "cuDNN conv" / "fused batch-norm").
    """

    features: int
    kernel_size: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    use_relu: bool = True
    train: bool = True
    dtype: Dtype = jnp.float32
    bn_axis_name: str | Sequence[str] | None = None
    zero_init_gamma: bool = False
    matmul_dtype: str = ""

    @nn.compact
    def __call__(self, x):
        if self.matmul_dtype == "int8":
            # QuantConv declares the identical "conv"/kernel param, so
            # checkpoints round-trip across matmul_dtype settings.
            x = QuantConv(
                self.features,
                self.kernel_size,
                strides=self.strides,
                padding=self.padding,
                dtype=self.dtype,
                name="conv",
            )(x)
        else:
            x = nn.Conv(
                self.features,
                self.kernel_size,
                strides=self.strides,
                padding=self.padding,
                use_bias=False,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=conv_kernel_init,
                name="conv",
            )(x)
        # Identity marker for the "conv_saved" remat policy (resnet.py):
        # jax.checkpoint(policy=save_only_these_names("conv_out")) keeps
        # this tensor and replays only the BN/ReLU tail. A no-op outside
        # such a checkpoint.
        x = checkpoint_name(x, "conv_out")
        x = BatchNorm(
            use_running_average=not self.train,
            dtype=self.dtype,
            axis_name=self.bn_axis_name,
            scale_init=(
                nn.initializers.zeros if self.zero_init_gamma
                else nn.initializers.ones
            ),
        )(x)
        if self.use_relu:
            x = nn.relu(x)
        return x
