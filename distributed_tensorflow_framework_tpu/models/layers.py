"""Shared layer building blocks.

The key design point is BatchNorm statistic scope (SURVEY.md §7 hard part 2).
The reference's fused BN is per-GPU: each replica normalizes with its own
minibatch statistics. Under this framework:

  * In the jit/pjit path the batch is one logical array sharded over the
    ``data`` axis, so plain `nn.BatchNorm` statistics are **global** — XLA
    inserts the cross-replica reduction automatically. This is cross-replica
    ("sync") BN by construction.
  * In the shard_map path the code is per-replica, so `nn.BatchNorm` without
    an ``axis_name`` reproduces the reference's per-replica semantics, and
    passing ``axis_name=('data','fsdp')`` (threaded via the module's
    ``bn_axis_name``) upgrades it to cross-replica.

Models expose ``cross_replica_bn`` and receive the runtime's axis names via
`flax`'s module attribute; the train step decides what to pass based on
``TrainConfig.spmd_mode``.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

Dtype = Any

# Initializers matching the reference recipe class: He/variance-scaling for
# conv (the TF slim/layers default for ResNet), zeros for BN beta, ones gamma.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")
dense_kernel_init = nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal")


class BatchNorm(nn.Module):
    """BatchNorm with switchable cross-replica statistics.

    ``axis_name`` is only set when running under shard_map (see module
    docstring); ``scale_init`` supports the zero-init-gamma trick for the
    last BN of each residual block.
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: str | Sequence[str] | None = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, x):
        return nn.BatchNorm(
            use_running_average=self.use_running_average,
            momentum=self.momentum,
            epsilon=self.epsilon,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            axis_name=self.axis_name,
            scale_init=self.scale_init,
            name="bn",
        )(x)


def space_to_depth(x: jnp.ndarray, block: int = 2) -> jnp.ndarray:
    """NHWC space-to-depth: (N,H,W,C) → (N,H/b,W/b,b²·C).

    Channel order of the output is (di, dj, c) flattened — pixel (2i+di,
    2j+dj, c) lands at channel (di·b + dj)·C + c. The ResNet s2d stem's
    kernel mapping (tests/test_s2d_stem.py) depends on this order.
    """
    n, h, w, c = x.shape
    if h % block or w % block:
        raise ValueError(f"space_to_depth: {h}x{w} not divisible by {block}")
    x = x.reshape(n, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // block, w // block, block * block * c)


class ConvBN(nn.Module):
    """Conv → BN → (optional) ReLU — the reference's fused conv/BN unit.

    On TPU the fusion the reference gets from cuDNN fused-BN comes from XLA:
    the BN scale/shift and ReLU fuse into the convolution's epilogue
    (SURVEY.md §2 native rows "cuDNN conv" / "fused batch-norm").
    """

    features: int
    kernel_size: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence[tuple[int, int]] = "SAME"
    use_relu: bool = True
    train: bool = True
    dtype: Dtype = jnp.float32
    bn_axis_name: str | Sequence[str] | None = None
    zero_init_gamma: bool = False

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(
            self.features,
            self.kernel_size,
            strides=self.strides,
            padding=self.padding,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=conv_kernel_init,
            name="conv",
        )(x)
        # Identity marker for the "conv_saved" remat policy (resnet.py):
        # jax.checkpoint(policy=save_only_these_names("conv_out")) keeps
        # this tensor and replays only the BN/ReLU tail. A no-op outside
        # such a checkpoint.
        x = checkpoint_name(x, "conv_out")
        x = BatchNorm(
            use_running_average=not self.train,
            dtype=self.dtype,
            axis_name=self.bn_axis_name,
            scale_init=(
                nn.initializers.zeros if self.zero_init_gamma
                else nn.initializers.ones
            ),
        )(x)
        if self.use_relu:
            x = nn.relu(x)
        return x
