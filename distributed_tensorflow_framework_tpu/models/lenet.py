"""LeNet-5 — the reference's MNIST smoke-test model.

SURVEY.md §2 row 6 / BASELINE.json config 1: "LeNet-5 on MNIST, single
worker (CPU-runnable smoke test)". Classic conv(6)→pool→conv(16)→pool→
dense(120)→dense(84)→dense(classes) topology; runs in seconds on CPU and
exercises the full runtime (mesh, collectives, loop, checkpointing).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.models.layers import (
    QuantDense,
    dense_kernel_init,
)


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32
    # "" = full-precision matmuls; "int8" = block-scaled int8 forward
    # matmuls in the fc body (precision.matmul_dtype; layers.QuantDense).
    # The logits head stays full-precision — same justified-head contract
    # the jaxpr-f32-upcast pass audits for the dtype policy.
    matmul_dtype: str = ""

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        del train  # no BN/dropout in the classic topology
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="SAME", dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv1")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype,
                    param_dtype=jnp.float32, name="conv2")(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        if self.matmul_dtype == "int8":
            # QuantDense declares the same kernel/bias params as nn.Dense,
            # so checkpoints round-trip across matmul_dtype settings.
            x = QuantDense(120, dtype=self.dtype, name="fc1")(x)
            x = nn.relu(x)
            x = QuantDense(84, dtype=self.dtype, name="fc2")(x)
        else:
            x = nn.Dense(120, dtype=self.dtype, param_dtype=jnp.float32,
                         kernel_init=dense_kernel_init, name="fc1")(x)
            x = nn.relu(x)
            x = nn.Dense(84, dtype=self.dtype, param_dtype=jnp.float32,
                         kernel_init=dense_kernel_init, name="fc2")(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, kernel_init=dense_kernel_init,
                     name="logits")(x)
        return x
