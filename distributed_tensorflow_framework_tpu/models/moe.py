"""Mixture-of-Experts FFN with expert parallelism (GShard/Switch-style).

The reference framework has no MoE (SURVEY.md §2 parallelism inventory —
expert parallel: NO); this extends the capability surface the TPU-native
way: experts live on a dedicated ``expert`` mesh axis, tokens are routed by
a learned top-k gate, and the dispatch/combine einsums against
expert-sharded weights make XLA emit ``all_to_all`` collectives over ICI —
the idiomatic pjit MoE (no hand-written routing RPCs).

Design points:
  * **Two dispatchers, one semantics** (parity pinned in tests/test_moe.py):
    the default **sorted** dispatch ranks assignments inside their expert
    with one argsort and gathers/scatters through O(B·E·C) index tables —
    linear in tokens, scales to hundreds of experts; the **dense** dispatch
    (one-hot (B,S,E,C) dispatch/combine einsums) is kept as the reference.
    Both use a static per-group capacity — shapes are static so everything
    jits; tokens over capacity are dropped (standard GShard semantics) and
    their combine weight is zero, which keeps the layer differentiable.
  * **Grouping**: the batch dim is the dispatch group — capacity is
    ``ceil(topk * seq / num_experts * capacity_factor)`` per example.
  * **Load-balancing aux loss** (Switch Transformer): E * Σ_e me·ce where
    me = mean gate prob, ce = fraction of tokens whose first choice is e.
    Perfectly balanced routing gives 1.0.
  * Gating math runs in float32 regardless of compute dtype.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.models.layers import dense_kernel_init

expert_kernel_init = nn.initializers.variance_scaling(
    1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1
)


def topk_dispatch(
    gate_logits: jax.Array,  # (B, S, E) float32
    topk: int,
    capacity: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-group (= per-batch-row) capacity.

    Returns ``(dispatch, combine, aux_loss)`` where dispatch/combine are
    (B, S, E, C) one-hot/weighted one-hot tensors and aux_loss is the
    scalar load-balancing loss.

    Scale limits (dense dispatch): the one-hot dispatch/combine tensors
    are O(B·S·E·C) with C ≈ topk·S/E·cf. Fine for small mixtures
    (E ≤ 64, topk ≤ 2); at hundreds of experts use
    ``topk_dispatch_sorted`` (the MoEMlp default), which produces the
    same routing through O(B·E·C) index tables.
    """
    b, s, e = gate_logits.shape
    if not 1 <= topk <= e:
        raise ValueError(
            f"topk={topk} must be in [1, num_experts={e}] — above e, argmax "
            f"over the exhausted gate would silently re-dispatch to expert 0"
        )
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    choices, first_mask = _topk_choices(probs, topk)  # the SHARED decision

    dispatch = jnp.zeros((b, s, e, capacity), jnp.float32)
    gate_weights = jnp.zeros((b, s, e), jnp.float32)
    # Tokens already claimed per (group, expert) by earlier choices.
    claimed = jnp.zeros((b, e), jnp.float32)
    for k in range(topk):
        mask = jax.nn.one_hot(choices[:, k], e, dtype=jnp.float32)  # (B,S,E)
        # Position of each token within its chosen expert's buffer.
        pos = jnp.cumsum(mask, axis=1) - 1.0 + claimed[:, None, :]
        mask = mask * (pos < capacity)
        claimed = claimed + mask.sum(axis=1)
        gate_weights = gate_weights + probs * mask
        pos_in = (pos * mask).sum(axis=-1)  # (B, S)
        cap_oh = jax.nn.one_hot(pos_in.astype(jnp.int32), capacity,
                                dtype=jnp.float32)
        cap_oh = cap_oh * mask.sum(axis=-1, keepdims=True)
        dispatch = dispatch + mask[..., None] * cap_oh[..., None, :]

    if topk == 1:
        # Switch-style: scale by the RAW top-1 prob. Normalizing would make
        # the weight identically 1, killing the router's task-loss gradient
        # (it would then learn only from the aux loss).
        combine = dispatch * gate_weights[..., None]
    else:
        # GShard top-k: normalize selected gate probs to sum to 1 per token.
        denom = gate_weights.sum(axis=-1, keepdims=True)
        gate_weights = gate_weights / jnp.maximum(denom, 1e-9)
        combine = dispatch * gate_weights[..., None]

    me = probs.mean(axis=(0, 1))          # (E,) mean gate prob
    ce = first_mask.mean(axis=(0, 1))     # (E,) first-choice fraction
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


def _topk_choices(probs: jax.Array, topk: int
                  ) -> tuple[jax.Array, jax.Array]:
    """The shared routing decision: iterated argmax-with-masking (NOT
    jnp.top_k — tie-breaking must match between the dense and sorted
    dispatchers for their parity contract). Returns (choices (B,K,S),
    first_mask (B,S,E))."""
    e = probs.shape[-1]
    choices = []
    remaining = probs
    first_mask = None
    for _ in range(topk):
        choice = jnp.argmax(remaining, axis=-1)          # (B, S)
        if first_mask is None:
            first_mask = jax.nn.one_hot(choice, e, dtype=jnp.float32)
        choices.append(choice)
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, e,
                                                      dtype=jnp.float32))
    return jnp.stack(choices, axis=1), first_mask


def topk_dispatch_sorted(
    gate_logits: jax.Array,  # (B, S, E) float32
    topk: int,
    capacity: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Sort-based top-k routing — same semantics as ``topk_dispatch``
    (same choices, same first-come-first-served positions, same drops,
    same combine weights; pinned in tests/test_moe.py) WITHOUT the
    O(B·S·E·C) one-hot tensors that cap the dense path's scale
    (VERDICT r3 missing #5).

    Mechanics: the B·K·S assignments are ranked within their expert by a
    single integer sort key ``expert·A + (k-major index)`` — reproducing
    the dense path's round-then-position claim order — and scattered into
    an O(B·E·C) token table (a C+1-wide dump column absorbs over-capacity
    assignments). Everything is O(B·S·E) gating math, one O(A log A)
    argsort, and O(B·E·C) tables: linear in tokens, never quadratic in
    capacity.

    Returns ``(token_table (B,E,C) i32, table_valid (B,E,C) f32,
    expert_a (B,K,S) i32, pos_a (B,K,S) i32 — clamped to [0, C),
    combine_w (B,K,S) f32 — 0 for dropped, aux_loss scalar)``.
    """
    b, s, e = gate_logits.shape
    if not 1 <= topk <= e:
        raise ValueError(
            f"topk={topk} must be in [1, num_experts={e}] — above e, argmax "
            f"over the exhausted gate would silently re-dispatch to expert 0"
        )
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert_a, first_mask = _topk_choices(probs, topk)    # (B,K,S)

    w_a = jnp.take_along_axis(
        jnp.broadcast_to(probs[:, None], (b, topk, s, e)),
        expert_a[..., None], axis=-1,
    )[..., 0]                                            # (B,K,S)

    a = topk * s  # assignments per batch row, k-major s-minor
    expert_f = expert_a.reshape(b, a)
    token_f = jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, topk, s)).reshape(b, a)
    # Rank assignments within their expert in (round, position) order —
    # the dense path's claim order — via one sort on a composite key.
    key = expert_f * a + jnp.arange(a, dtype=expert_f.dtype)[None, :]
    order = jnp.argsort(key, axis=-1)
    se_ = jnp.take_along_axis(expert_f, order, axis=-1)
    st_ = jnp.take_along_axis(token_f, order, axis=-1)
    counts = jax.nn.one_hot(expert_f, e, dtype=jnp.int32).sum(axis=1)
    starts = jnp.cumsum(counts, axis=-1) - counts        # (B, E) exclusive
    pos_sorted = (jnp.arange(a, dtype=jnp.int32)[None, :]
                  - jnp.take_along_axis(starts, se_, axis=-1))
    valid_sorted = pos_sorted < capacity
    dest = jnp.where(valid_sorted, pos_sorted, capacity)  # dump column C

    bidx = jnp.arange(b)[:, None]
    token_table = jnp.zeros((b, e, capacity + 1), jnp.int32)
    token_table = token_table.at[bidx, se_, dest].set(st_)[:, :, :capacity]
    table_valid = jnp.zeros((b, e, capacity + 1), jnp.float32)
    table_valid = table_valid.at[bidx, se_, dest].set(
        valid_sorted.astype(jnp.float32))[:, :, :capacity]

    # Unsort position/validity back to assignment (k-major) order for the
    # combine-side gather.
    inv = jnp.argsort(order, axis=-1)
    pos_a = jnp.take_along_axis(pos_sorted, inv, axis=-1).reshape(b, topk, s)
    valid_a = jnp.take_along_axis(
        valid_sorted, inv, axis=-1).reshape(b, topk, s).astype(jnp.float32)
    pos_a = jnp.clip(pos_a, 0, capacity - 1)

    w_placed = w_a * valid_a
    if topk == 1:
        combine_w = w_placed  # Switch-style raw prob (see topk_dispatch)
    else:
        denom = w_placed.sum(axis=1, keepdims=True)
        combine_w = w_placed / jnp.maximum(denom, 1e-9)

    me = probs.mean(axis=(0, 1))
    ce = first_mask.mean(axis=(0, 1))
    aux_loss = e * jnp.sum(me * ce)
    return token_table, table_valid, expert_a, pos_a, combine_w, aux_loss


class MoEMlp(nn.Module):
    """Expert-parallel MLP block replacing the dense transformer FFN.

    Expert weights ``wi`` (E, H, F) / ``wo`` (E, F, H) are sharded
    ``P("expert", ...)`` by parallel/sharding.py's MoE rules (plus megatron
    column/row splits over ``model`` when TP is on); the dispatch einsum
    below then lowers to an XLA all_to_all between the data and expert
    shards.
    """

    num_experts: int
    mlp_dim: int
    topk: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    # "sorted" (default): index/gather dispatch, O(B·E·C) tables — scales
    # in experts and capacity. "dense": the original O(B·S·E·C) one-hot
    # einsum dispatch — kept as the parity reference (tests/test_moe.py)
    # and for shapes where XLA fuses the one-hots well. Sharding note:
    # the combine gather's expert dim is data-dependently indexed, which
    # the SPMD partitioner can't partition (b/433785288) — the explicit
    # pre-gather constraint below turns that into a clean all-gather over
    # ``expert`` instead of an involuntary full remat; both dispatchers
    # now partition dp+ep+tp warning-free (verified in the dryrun gate).
    dispatch_impl: str = "sorted"
    # Router z-loss weight RELATIVE to the balance aux (see
    # core/config.py ModelConfig.moe_zloss_weight for the weighting
    # contract). 0 = off, bit-identical to the pre-knob module.
    zloss_weight: float = 0.0

    @nn.compact
    def __call__(self, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        if self.dispatch_impl not in ("sorted", "dense"):
            # A typo here would silently run the O(B·S·E·C) dense path —
            # the exact cost the sorted default exists to avoid.
            raise ValueError(
                f"moe dispatch_impl must be 'sorted' or 'dense', got "
                f"{self.dispatch_impl!r}"
            )
        b, s, h = x.shape
        e = self.num_experts
        capacity = max(
            self.topk,
            int(math.ceil(self.topk * s / e * self.capacity_factor)),
        )
        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=dense_kernel_init, name="gate",
        )(x.astype(jnp.float32))
        zloss = jnp.zeros((), jnp.float32)
        if self.zloss_weight:
            # ST-MoE router z-loss: mean over tokens of logsumexp(logits)².
            # Bounds router-logit magnitude so early reduction-order noise
            # cannot push the softmax into a winner-take-all collapse
            # (PERF_NOTES round-5 forensics); gradient is well-defined and
            # small near uniform logits.
            z = jax.scipy.special.logsumexp(gate_logits, axis=-1)  # (B,S)
            zloss = jnp.mean(jnp.square(z))

        wi = self.param("wi", expert_kernel_init, (e, h, self.mlp_dim),
                        jnp.float32)
        wo = self.param("wo", expert_kernel_init, (e, self.mlp_dim, h),
                        jnp.float32)

        # Expert-tensor sharding hint: keep every (B, E, C, *) tensor —
        # and, via propagation, its AD cotangent — sharded batch-over-data
        # and experts-over-expert. Without it the SPMD partitioner batch-
        # shards some backward intermediates over the WHOLE mesh and then
        # "involuntarily fully rematerializes" (replicates) them to reach
        # the expert-sharded weights. No-op without a mesh context (plain
        # tests, init, the shard_map twin) — see sharding.constrain_activation.
        from distributed_tensorflow_framework_tpu.parallel.sharding import (
            constrain_activation,
        )

        # B stays on the data-like axes (the batch enters sharded over
        # ("data","fsdp","expert") — core/mesh.batch_spec); E moves to the
        # ``expert`` axis. The batch-dim expert→data reshard is exactly
        # the dispatch/return all_to_all. The hidden dim (xe/oe) is
        # replicated; he's mlp dim keeps the megatron "model" split that
        # column-parallel wi produces and row-parallel wo consumes.
        expert_hint = lambda t: constrain_activation(  # noqa: E731
            t, ("data", "fsdp"), "expert", None, None)
        expert_hint_mlp = lambda t: constrain_activation(  # noqa: E731
            t, ("data", "fsdp"), "expert", None, "model")

        if self.dispatch_impl == "sorted":
            (token_table, table_valid, expert_a, pos_a, combine_w,
             aux_loss) = topk_dispatch_sorted(gate_logits, self.topk,
                                              capacity)
            drop_frac = 1.0 - table_valid.sum() / (b * s * self.topk)
            # Dispatch: gather each expert's claimed tokens from x —
            # (B,E,C,H), the all_to_all site under dp+ep sharding (tokens
            # move from data shards to expert shards), with no
            # (B,S,E,C) intermediary.
            xg = jnp.take_along_axis(
                x[:, None].astype(self.dtype),
                token_table[..., None], axis=2)           # (B,E,C,H)
            xe = xg * table_valid[..., None].astype(self.dtype)
        else:
            dispatch, combine, aux_loss = topk_dispatch(
                gate_logits, self.topk, capacity
            )
            # Router overflow diagnostic: fraction of the B·S·topk
            # assignments dropped by the static capacity — persistently
            # high drop means the gate is imbalanced or cf is too tight.
            drop_frac = 1.0 - dispatch.sum() / (b * s * self.topk)
            # (B,S,E,C) × (B,S,H) → (B,E,C,H): the all_to_all site.
            xe = jnp.einsum("bsec,bsh->bech", dispatch.astype(self.dtype),
                            x.astype(self.dtype))

        xe = expert_hint(xe)
        he = nn.gelu(
            jnp.einsum("bech,ehf->becf", xe, wi.astype(self.dtype)),
            approximate=True,
        )
        he = expert_hint_mlp(he)
        oe = expert_hint(
            jnp.einsum("becf,efh->bech", he, wo.astype(self.dtype)))

        if self.dispatch_impl == "sorted":
            # Combine: gather each token's expert outputs back and weight
            # them — the return all_to_all, again with no (B,S,E,C).
            # The gather's expert dim is indexed by DATA-DEPENDENT
            # expert_a, which the SPMD partitioner cannot partition over
            # the ``expert`` axis — left alone it falls back to
            # "involuntary full rematerialization" of the (B,E,C,H)
            # cotangent over the whole mesh (b/433785288, VERDICT r4).
            # Constraining oe to batch-sharded/expert-REPLICATED right
            # before the gather makes the movement an explicit all-gather
            # over ``expert`` (the return hop of the a2a pair), the
            # gather itself shard-local in B, and the backward a clean
            # slice back to expert shards at the expert_hint site.
            oe = constrain_activation(oe, ("data", "fsdp"), None, None, None)
            og = oe[jnp.arange(b)[:, None, None], expert_a, pos_a]
            out = (og * combine_w[..., None].astype(self.dtype)).sum(axis=1)
        else:
            out = jnp.einsum("bsec,bech->bsh", combine.astype(self.dtype),
                             oe)
        # Metrics ride the return value as EXPLICIT aux outputs (not sown
        # intermediates): return values thread through jax.checkpoint —
        # ``model.remat=true`` keeps moe_drop_frac/moe_zloss observable,
        # where sown intermediates are silently dropped in replayed
        # segments. ``aux_loss`` is the loss-side term (balance aux PLUS
        # the weighted z term — the contract core/config.py documents);
        # zloss/drop_frac are diagnostics.
        return out, {
            "aux_loss": aux_loss + self.zloss_weight * zloss,
            "zloss": zloss,
            "drop_frac": drop_frac,
        }
