"""Mixture-of-Experts FFN with expert parallelism (GShard/Switch-style).

The reference framework has no MoE (SURVEY.md §2 parallelism inventory —
expert parallel: NO); this extends the capability surface the TPU-native
way: experts live on a dedicated ``expert`` mesh axis, tokens are routed by
a learned top-k gate, and the dispatch/combine einsums against
expert-sharded weights make XLA emit ``all_to_all`` collectives over ICI —
the idiomatic pjit MoE (no hand-written routing RPCs).

Design points:
  * **Dense dispatch** (one-hot dispatch/combine tensors) with a static
    per-group capacity — shapes are static so everything jits; tokens over
    capacity are dropped (standard GShard semantics) and their combine
    weight is zero, which keeps the layer differentiable.
  * **Grouping**: the batch dim is the dispatch group — capacity is
    ``ceil(topk * seq / num_experts * capacity_factor)`` per example.
  * **Load-balancing aux loss** (Switch Transformer): E * Σ_e me·ce where
    me = mean gate prob, ce = fraction of tokens whose first choice is e.
    Perfectly balanced routing gives 1.0.
  * Gating math runs in float32 regardless of compute dtype.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.models.layers import dense_kernel_init

expert_kernel_init = nn.initializers.variance_scaling(
    1.0, "fan_in", "truncated_normal", in_axis=-2, out_axis=-1
)


def topk_dispatch(
    gate_logits: jax.Array,  # (B, S, E) float32
    topk: int,
    capacity: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k routing with per-group (= per-batch-row) capacity.

    Returns ``(dispatch, combine, aux_loss)`` where dispatch/combine are
    (B, S, E, C) one-hot/weighted one-hot tensors and aux_loss is the
    scalar load-balancing loss.

    Scale limits (v1, dense dispatch): the one-hot dispatch/combine
    tensors are O(B·S·E·C) with C ≈ topk·S/E·cf, i.e. memory grows
    ~linearly with topk·S·B and the top-k loop is Python-unrolled (topk
    compiled matmul passes). Fine for the mixture sizes this framework
    ships (E ≤ 64, topk ≤ 2); at hundreds of experts or topk ≫ 2 a
    sort-based (argsort-over-expert-affinity) dispatch that never
    materializes (B,S,E,C) is the known replacement — not implemented.
    """
    b, s, e = gate_logits.shape
    if not 1 <= topk <= e:
        raise ValueError(
            f"topk={topk} must be in [1, num_experts={e}] — above e, argmax "
            f"over the exhausted gate would silently re-dispatch to expert 0"
        )
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((b, s, e, capacity), jnp.float32)
    gate_weights = jnp.zeros((b, s, e), jnp.float32)
    # Tokens already claimed per (group, expert) by earlier choices.
    claimed = jnp.zeros((b, e), jnp.float32)
    remaining = probs
    first_mask = None
    for _ in range(topk):
        choice = jnp.argmax(remaining, axis=-1)  # (B, S)
        mask = jax.nn.one_hot(choice, e, dtype=jnp.float32)  # (B, S, E)
        if first_mask is None:
            first_mask = mask
        # Position of each token within its chosen expert's buffer.
        pos = jnp.cumsum(mask, axis=1) - 1.0 + claimed[:, None, :]
        mask = mask * (pos < capacity)
        claimed = claimed + mask.sum(axis=1)
        gate_weights = gate_weights + probs * mask
        pos_in = (pos * mask).sum(axis=-1)  # (B, S)
        cap_oh = jax.nn.one_hot(pos_in.astype(jnp.int32), capacity,
                                dtype=jnp.float32)
        cap_oh = cap_oh * mask.sum(axis=-1, keepdims=True)
        dispatch = dispatch + mask[..., None] * cap_oh[..., None, :]
        remaining = remaining * (1.0 - jax.nn.one_hot(choice, e,
                                                      dtype=jnp.float32))

    if topk == 1:
        # Switch-style: scale by the RAW top-1 prob. Normalizing would make
        # the weight identically 1, killing the router's task-loss gradient
        # (it would then learn only from the aux loss).
        combine = dispatch * gate_weights[..., None]
    else:
        # GShard top-k: normalize selected gate probs to sum to 1 per token.
        denom = gate_weights.sum(axis=-1, keepdims=True)
        gate_weights = gate_weights / jnp.maximum(denom, 1e-9)
        combine = dispatch * gate_weights[..., None]

    me = probs.mean(axis=(0, 1))          # (E,) mean gate prob
    ce = first_mask.mean(axis=(0, 1))     # (E,) first-choice fraction
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


class MoEMlp(nn.Module):
    """Expert-parallel MLP block replacing the dense transformer FFN.

    Expert weights ``wi`` (E, H, F) / ``wo`` (E, F, H) are sharded
    ``P("expert", ...)`` by parallel/sharding.py's MoE rules (plus megatron
    column/row splits over ``model`` when TP is on); the dispatch einsum
    below then lowers to an XLA all_to_all between the data and expert
    shards.
    """

    num_experts: int
    mlp_dim: int
    topk: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        b, s, h = x.shape
        e = self.num_experts
        capacity = max(
            self.topk,
            int(math.ceil(self.topk * s / e * self.capacity_factor)),
        )
        gate_logits = nn.Dense(
            e, use_bias=False, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=dense_kernel_init, name="gate",
        )(x.astype(jnp.float32))
        dispatch, combine, aux_loss = topk_dispatch(
            gate_logits, self.topk, capacity
        )
        # Router overflow diagnostic: fraction of the B·S·topk assignments
        # dropped by the static capacity. Sown (not returned) so the layer
        # signature stays stable; retrieve with
        # ``apply(..., mutable=["intermediates"])`` when debugging a
        # capacity_factor choice — persistently high drop means the gate
        # is imbalanced or cf is too tight.
        self.sow("intermediates", "moe_drop_frac",
                 1.0 - dispatch.sum() / (b * s * self.topk))

        wi = self.param("wi", expert_kernel_init, (e, h, self.mlp_dim),
                        jnp.float32)
        wo = self.param("wo", expert_kernel_init, (e, self.mlp_dim, h),
                        jnp.float32)
        # (B,S,E,C) × (B,S,H) → (B,E,C,H): the all_to_all site (tokens move
        # from data shards to expert shards).
        xe = jnp.einsum("bsec,bsh->bech", dispatch.astype(self.dtype),
                        x.astype(self.dtype))
        he = nn.gelu(
            jnp.einsum("bech,ehf->becf", xe, wi.astype(self.dtype)),
            approximate=True,
        )
        oe = jnp.einsum("becf,efh->bech", he, wo.astype(self.dtype))
        # Combine: expert shards → data shards (the return all_to_all).
        out = jnp.einsum("bsec,bech->bsh", combine.astype(self.dtype), oe)
        return out, aux_loss
