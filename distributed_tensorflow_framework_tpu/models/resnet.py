"""ResNet-50 — the headline model (SURVEY.md §2 row 7).

Bottleneck-v1.5 topology (stride-2 in the 3×3 conv): conv7×7/s2 → BN →
relu → maxpool/2 → stages [3,4,6,3] of 1×1/3×3/1×1 bottlenecks with
residual adds → global average pool → dense(classes). The reference builds
this from TF layers over cuDNN conv + fused BN; here every conv lowers to
an MXU convolution and BN+relu fuse into the conv epilogue via XLA.

TPU-specific choices:
  * compute in bfloat16, params + BN stats in float32 (MXU-native mixed
    precision; the reference is fp32-only on V100);
  * zero-init of the last BN gamma in each block (standard large-batch
    recipe — identity residual branches at init);
  * ``bn_axis_name`` threads shard_map axis names for cross-replica BN
    (SURVEY.md §7 hard part 2); under jit, BN stats are global already.

``ResNet50Cifar`` swaps the 7×7/s2+maxpool stem for a 3×3/s1 stem — the
standard CIFAR variant (config 2 of BASELINE.json).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from distributed_tensorflow_framework_tpu.models.layers import (
    ConvBN,
    dense_kernel_init,
    space_to_depth,
)


REMAT_POLICIES = ("full", "conv_saved")


def _remat_policy_error(got: str) -> str:
    return f"remat_policy must be one of {REMAT_POLICIES}, got {got!r}"


class Bottleneck(nn.Module):
    features: int          # bottleneck width; output is 4x this
    strides: tuple[int, int] = (1, 1)
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None
    matmul_dtype: str = ""

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, (1, 1), train=self.train, dtype=self.dtype,
                   bn_axis_name=self.bn_axis_name,
                   matmul_dtype=self.matmul_dtype, name="conv1")(x)
        y = ConvBN(self.features, (3, 3), strides=self.strides,
                   train=self.train, dtype=self.dtype,
                   bn_axis_name=self.bn_axis_name,
                   matmul_dtype=self.matmul_dtype, name="conv2")(y)
        y = ConvBN(4 * self.features, (1, 1), use_relu=False,
                   train=self.train, dtype=self.dtype,
                   bn_axis_name=self.bn_axis_name, zero_init_gamma=True,
                   matmul_dtype=self.matmul_dtype, name="conv3")(y)
        if residual.shape != y.shape:
            residual = ConvBN(4 * self.features, (1, 1), strides=self.strides,
                              use_relu=False, train=self.train,
                              dtype=self.dtype, bn_axis_name=self.bn_axis_name,
                              matmul_dtype=self.matmul_dtype,
                              name="proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """Two-3×3 residual block — the ResNet-18/34 unit."""

    features: int
    strides: tuple[int, int] = (1, 1)
    train: bool = True
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None
    matmul_dtype: str = ""

    @nn.compact
    def __call__(self, x):
        residual = x
        y = ConvBN(self.features, (3, 3), strides=self.strides,
                   train=self.train, dtype=self.dtype,
                   bn_axis_name=self.bn_axis_name,
                   matmul_dtype=self.matmul_dtype, name="conv1")(x)
        y = ConvBN(self.features, (3, 3), use_relu=False, train=self.train,
                   dtype=self.dtype, bn_axis_name=self.bn_axis_name,
                   zero_init_gamma=True, matmul_dtype=self.matmul_dtype,
                   name="conv2")(y)
        if residual.shape != y.shape:
            residual = ConvBN(self.features, (1, 1), strides=self.strides,
                              use_relu=False, train=self.train,
                              dtype=self.dtype, bn_axis_name=self.bn_axis_name,
                              matmul_dtype=self.matmul_dtype,
                              name="proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    cifar_stem: bool = False
    basic_block: bool = False  # True → ResNet-18/34 topology
    # Space-to-depth stem: reshape (H,W,3) → (H/2,W/2,12) and replace the
    # 7×7/s2 conv with an equivalent 4×4/s1 conv. The 3-channel 7×7 conv
    # wastes the MXU (3 input channels padded up to the tile) and streams
    # the full 224² activation through HBM; s2d quadruples input channels
    # and quarters the spatial extent at identical math — the classic TPU
    # ResNet input optimization. The 4×4×12 kernel is a superset of the
    # 7×7×3 kernel (zero-pad to 8×8, regroup; tests/test_s2d_stem.py
    # verifies output equivalence to 1e-5). The 45 zero-padded kernel
    # positions are trainable, so the trained function class is a strict
    # superset of the 7×7 stem's. Param count differs from torchvision
    # (12288 vs 9408 stem weights).
    space_to_depth_stem: bool = False
    # Per-block activation rematerialization (jax.checkpoint via nn.remat):
    # the backward re-runs each residual block's forward from its input
    # instead of reading the stored intermediate conv activations back
    # from HBM. On the HBM-bandwidth-bound ImageNet step this trades MXU
    # FLOPs (idle headroom: MFU ~31%, PERF_NOTES.md) for bytes; it is
    # also the memory lever for deep variants (101/152) at large batch.
    # Numerically exact (same ops replayed; tests/test_remat.py).
    remat: bool = False
    # "full": replay the whole block (max memory savings; measured -13%
    # img/s on the HBM-bound v5e step — the conv recompute outweighs the
    # byte savings, PERF_NOTES.md). "conv_saved": keep each ConvBN's conv
    # output (checkpoint_name tag in layers.py) and replay only the
    # BN/ReLU/residual tail — near-zero extra flops for roughly half the
    # activation bytes.
    remat_policy: str = "full"
    # Selective-remat override (precision.remat_policy): a
    # jax.checkpoint_policies callable that wins over the remat_policy
    # string when set. Resolved by models.get_model from the config name.
    ckpt_policy: Any = None
    # "" = full-precision convs; "int8" = block-scaled int8 conv
    # contractions (precision.matmul_dtype; layers.QuantConv). The f32
    # classifier head is never quantized.
    matmul_dtype: str = ""
    dtype: Any = jnp.bfloat16
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, *, train: bool = True):
        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = ConvBN(self.width, (3, 3), train=train, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name,
                       matmul_dtype=self.matmul_dtype, name="stem")(x)
        elif self.space_to_depth_stem:
            # Padding ((1,2),(1,2)) on the half-res grid reproduces the
            # 7×7/s2 SAME padding (2 before / 3 after at full res).
            x = space_to_depth(x, 2)
            x = ConvBN(self.width, (4, 4), padding=((1, 2), (1, 2)),
                       train=train, dtype=self.dtype,
                       bn_axis_name=self.bn_axis_name,
                       matmul_dtype=self.matmul_dtype, name="stem_s2d")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        else:
            x = ConvBN(self.width, (7, 7), strides=(2, 2), train=train,
                       dtype=self.dtype, bn_axis_name=self.bn_axis_name,
                       matmul_dtype=self.matmul_dtype, name="stem")(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = BasicBlock if self.basic_block else Bottleneck
        if self.remat:
            # All block config is module attributes (train included), so no
            # static_argnums are needed; BN stat mutations replay exactly.
            if self.ckpt_policy is not None:
                block_cls = nn.remat(block_cls, policy=self.ckpt_policy)
            elif self.remat_policy == "conv_saved":
                from jax.ad_checkpoint import checkpoint_policies

                block_cls = nn.remat(
                    block_cls,
                    policy=checkpoint_policies.save_only_these_names(
                        "conv_out"),
                )
            elif self.remat_policy == "full":
                block_cls = nn.remat(block_cls)
            else:  # direct-construction guard; make_resnet pre-validates
                raise ValueError(_remat_policy_error(self.remat_policy))
        for stage, size in enumerate(self.stage_sizes):
            for block in range(size):
                strides = (2, 2) if stage > 0 and block == 0 else (1, 1)
                x = block_cls(
                    self.width * 2 ** stage,
                    strides=strides,
                    train=train,
                    dtype=self.dtype,
                    bn_axis_name=self.bn_axis_name,
                    matmul_dtype=self.matmul_dtype,
                    name=f"stage{stage + 1}_block{block + 1}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32, kernel_init=dense_kernel_init,
                     name="classifier")(x.astype(jnp.float32))
        return x


# Canonical depth → (stage sizes, block type). Param counts match
# torchvision's resnetN exactly (pinned in tests/test_models_big.py).
RESNET_DEPTHS: dict[int, tuple[tuple[int, ...], bool]] = {
    18: ((2, 2, 2, 2), True),
    34: ((3, 4, 6, 3), True),
    50: ((3, 4, 6, 3), False),
    101: ((3, 4, 23, 3), False),
    152: ((3, 8, 36, 3), False),
}


def make_resnet(depth: int, num_classes: int = 1000,
                dtype: Any = jnp.bfloat16, bn_axis_name: Any = None,
                cifar_stem: bool = False,
                space_to_depth_stem: bool = False,
                remat: bool = False,
                remat_policy: str = "full",
                ckpt_policy: Any = None,
                matmul_dtype: str = "") -> ResNet:
    if depth not in RESNET_DEPTHS:
        raise ValueError(
            f"resnet depth {depth} not in {sorted(RESNET_DEPTHS)}"
        )
    if cifar_stem and space_to_depth_stem:
        raise ValueError("space_to_depth_stem only applies to the ImageNet "
                         "stem (cifar_stem=False)")
    if remat_policy not in REMAT_POLICIES:
        raise ValueError(_remat_policy_error(remat_policy))
    stages, basic = RESNET_DEPTHS[depth]
    return ResNet(stage_sizes=stages, num_classes=num_classes,
                  basic_block=basic, cifar_stem=cifar_stem,
                  space_to_depth_stem=space_to_depth_stem, remat=remat,
                  remat_policy=remat_policy, ckpt_policy=ckpt_policy,
                  matmul_dtype=matmul_dtype,
                  dtype=dtype, bn_axis_name=bn_axis_name)


def ResNet50(num_classes: int = 1000, dtype: Any = jnp.bfloat16,
             bn_axis_name: Any = None) -> ResNet:
    return make_resnet(50, num_classes, dtype, bn_axis_name)


def ResNet50Cifar(num_classes: int = 10, dtype: Any = jnp.bfloat16,
                  bn_axis_name: Any = None) -> ResNet:
    return make_resnet(50, num_classes, dtype, bn_axis_name, cifar_stem=True)
