// Native TFRecord reader with background prefetch.
//
// The reference leans on the TF C++ runtime for its input path (SURVEY.md
// §2 row 5 / L0: the repo's Python tf.data graph executes in native
// threads). This is the equivalent native substrate for this framework:
// a C++ reader thread pool that decodes the TFRecord framing (length +
// masked crc32c + payload), optionally parses the fixed-schema
// tf.train.Example used by the MLM pipeline, and hands whole batches to
// Python through a lock-free-enough ring buffer — so the Python side does
// a single memcpy per batch instead of per-record framing work under the
// GIL.
//
// Exposed C ABI (consumed by ctypes in data/native_reader.py):
//   rr_open(paths, n_paths, prefetch, shuffle_window, shuffle_seed)
//                                                -> handle
//     shuffle_window > 1 turns on a windowed record-level shuffle
//     (tf.data shuffle-buffer semantics) applied to EVERY consumer of
//     the handle, deterministically from shuffle_seed.
//   rr_skip(h, n)                                -> records skipped, -1 err
//   rr_next_record(h, &buf, &len)                -> 1 ok, 0 EOF, <0 error
//   rr_free(buf)
//   rr_next_batch_i32(h, key, out, batch, width) -> 1 ok, 0 EOF, <0 error
//   rr_next_batch_images(h, ikey, lkey, imgs, labels, batch, th, tw,
//                        threads, crop_seeds, mean, std)
//                                                -> 1 ok, 0 EOF, <0 error
//   rr_next_batch_images_eval(h, ikey, lkey, imgs, labels, batch, th, tw,
//                             threads, central_frac, mean, std)
//                                                -> k filled, 0 EOF, <0 err
//     The native ImageNet input path (SURVEY.md §7 hard part 1):
//     per-image Inception-style distorted crop + flip sampled from
//     crop_seeds (host-derived; splitmix64 here), decoded via PARTIAL
//     IDCT (libjpeg-turbo DCT scaling + crop/skip scanlines — cost tracks
//     the crop area, the native twin of tf.data's decode_and_crop),
//     bilinear-resized with per-channel standardization fused into the
//     output write, multi-threaded across the batch. crop_seeds=null →
//     full-image resize; mean/std=null → raw [0,255] pixels.
//   rr_close(h)
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread record_reader.cc
//        -ljpeg -o librecord_reader.so

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

// ---------------------------------------------------------------- crc32c --
// Castagnoli CRC (the TFRecord checksum), software table version.
uint32_t kCrcTable[256];
std::once_flag kCrcOnce;

void InitCrcTable() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
    kCrcTable[i] = c;
  }
}

uint32_t Crc32c(const char* data, size_t n) {
  std::call_once(kCrcOnce, InitCrcTable);
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < n; ++i)
    c = kCrcTable[(c ^ static_cast<uint8_t>(data[i])) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

uint32_t MaskedCrc(const char* data, size_t n) {
  uint32_t crc = Crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// --------------------------------------------------------------- tiny rng --
// splitmix64 — deterministic PRNG shared by the crop sampler and the
// record shuffle; seeds are derived host-side through the documented
// core/prng.py discipline, the sampling algorithms are fixed here.
struct Rng {
  uint64_t s;
  uint64_t Next() {
    uint64_t z = (s += 0x9e3779b97f4a7c15ull);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  }
  float Uniform() { return (Next() >> 40) * (1.0f / (1 << 24)); }
};

// ------------------------------------------------------------ ring buffer --
struct Record {
  std::vector<char> bytes;
};

struct Reader {
  std::vector<std::string> paths;
  size_t prefetch;
  std::deque<Record> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::thread worker;
  std::atomic<bool> done{false}, stop{false};
  std::string error;
  // Windowed record-level shuffle (tf.data shuffle-buffer semantics):
  // consumers pop a uniform-random slot out of a W-record window that is
  // refilled from the in-order stream. Deterministic given (file order,
  // seed, W) — the resume fast-skip replays the identical sequence.
  size_t shuffle_window = 0;
  Rng shuffle_rng{0};
  std::vector<Record> shuffle_buf;
  bool shuffle_primed = false;

  ~Reader() {
    {
      // Set stop under the lock: the worker checks the predicate while
      // holding mu inside cv.wait, so an unlocked store+notify can land
      // between its predicate check and its sleep (lost wakeup → join
      // hangs forever).
      std::lock_guard<std::mutex> lock(mu);
      stop = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    if (worker.joinable()) worker.join();
  }
};

void ReadLoop(Reader* r) {
  for (const auto& path : r->paths) {
    if (r->stop) break;
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      std::lock_guard<std::mutex> lock(r->mu);
      r->error = "open failed: " + path;
      break;
    }
    while (!r->stop) {
      char header[12];
      size_t got = std::fread(header, 1, 12, f);
      if (got == 0) break;  // clean EOF
      if (got != 12) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated header: " + path;
        break;
      }
      uint64_t len;
      std::memcpy(&len, header, 8);
      uint32_t len_crc;
      std::memcpy(&len_crc, header + 8, 4);
      if (MaskedCrc(header, 8) != len_crc) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "length crc mismatch: " + path;
        break;
      }
      Record rec;
      rec.bytes.resize(len);
      if (std::fread(rec.bytes.data(), 1, len, f) != len) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated payload: " + path;
        break;
      }
      char footer[4];
      if (std::fread(footer, 1, 4, f) != 4) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated footer: " + path;
        break;
      }
      uint32_t data_crc;
      std::memcpy(&data_crc, footer, 4);
      if (MaskedCrc(rec.bytes.data(), len) != data_crc) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "payload crc mismatch: " + path;
        break;
      }
      std::unique_lock<std::mutex> lock(r->mu);
      r->cv_push.wait(lock, [r] {
        return r->queue.size() < r->prefetch || r->stop;
      });
      if (r->stop) break;
      r->queue.push_back(std::move(rec));
      r->cv_pop.notify_one();
    }
    std::fclose(f);
    {
      std::lock_guard<std::mutex> lock(r->mu);
      if (!r->error.empty()) break;
    }
  }
  r->done = true;
  r->cv_pop.notify_all();
}

// ------------------------------------------------- minimal Example parser --
// Parses tf.train.Example just enough to pull one named Int64List feature.
// Wire layout (all protobuf):
//   Example        { features = 1 (msg) }
//   Features       { feature  = 1 (map<string, Feature>) }
//   map entry      { key = 1 (string), value = 2 (Feature msg) }
//   Feature        { int64_list = 3 (msg) }  [bytes_list=1, float_list=2]
//   Int64List      { value = 1 (repeated varint, possibly packed) }

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  // End pointer for a nested length-delimited view, clamped to this
  // view's end — a malformed length prefix (CRC only proves the writer
  // wrote it, not that it is sane) must not create an out-of-bounds
  // cursor.
  const uint8_t* Sub(uint64_t len) const {
    return len > static_cast<uint64_t>(end - p) ? end : p + len;
  }

  uint64_t Varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  void Skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: Varint(); break;
      case 1: p += 8; break;
      case 2: {
        uint64_t n = Varint();
        if (n > static_cast<uint64_t>(end - p)) { ok = false; p = end; }
        else { p += n; }
        break;
      }
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }
};

// Extract int64s for `key` into out (up to width); returns count or -1.
int ParseExampleInt64(const char* data, size_t size, const char* key,
                      int32_t* out, int width) {
  Cursor ex{reinterpret_cast<const uint8_t*>(data),
            reinterpret_cast<const uint8_t*>(data) + size};
  size_t key_len = std::strlen(key);
  while (ex.ok && ex.p < ex.end) {
    uint64_t tag = ex.Varint();
    if (!ex.ok) return -1;
    if ((tag >> 3) != 1 || (tag & 7) != 2) { ex.Skip(tag & 7); continue; }
    uint64_t features_len = ex.Varint();
    Cursor feats{ex.p, ex.Sub(features_len)};
    ex.p += features_len;
    while (feats.ok && feats.p < feats.end) {
      uint64_t ftag = feats.Varint();
      if (!feats.ok) return -1;
      if ((ftag >> 3) != 1 || (ftag & 7) != 2) { feats.Skip(ftag & 7); continue; }
      uint64_t entry_len = feats.Varint();
      Cursor entry{feats.p, feats.Sub(entry_len)};
      feats.p += entry_len;
      bool key_match = false;
      Cursor value{nullptr, nullptr};
      while (entry.ok && entry.p < entry.end) {
        uint64_t etag = entry.Varint();
        if (!entry.ok) return -1;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          // Bound BEFORE memcmp: a truncated entry may claim key bytes
          // that are not there (same malformed-length class Sub guards).
          if (n > static_cast<uint64_t>(entry.end - entry.p)) {
            entry.p = entry.end;
            break;
          }
          key_match = (n == key_len &&
                       std::memcmp(entry.p, key, key_len) == 0);
          entry.p += n;
        } else if ((etag >> 3) == 2 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          value = Cursor{entry.p, entry.Sub(n)};
          entry.p += n;
        } else {
          entry.Skip(etag & 7);
        }
      }
      if (!key_match || value.p == nullptr) continue;
      // value: Feature { int64_list = 3 }
      while (value.ok && value.p < value.end) {
        uint64_t vtag = value.Varint();
        if (!value.ok) return -1;
        if ((vtag >> 3) != 3 || (vtag & 7) != 2) { value.Skip(vtag & 7); continue; }
        uint64_t list_len = value.Varint();
        Cursor list{value.p, value.Sub(list_len)};
        value.p += list_len;
        int count = 0;
        while (list.ok && list.p < list.end && count < width) {
          uint64_t ltag = list.Varint();
          if (!list.ok) return -1;
          if ((ltag >> 3) != 1) { list.Skip(ltag & 7); continue; }
          if ((ltag & 7) == 2) {  // packed
            uint64_t n = list.Varint();
            const uint8_t* stop_at = list.Sub(n);
            while (list.ok && list.p < stop_at && count < width)
              out[count++] = static_cast<int32_t>(list.Varint());
          } else {  // single varint
            out[count++] = static_cast<int32_t>(list.Varint());
          }
        }
        return list.ok ? count : -1;
      }
    }
  }
  return 0;  // key not found
}

// Extract the FIRST BytesList value for `key`; returns a view into `data`
// (no copy) — 1 found, 0 missing, -1 malformed.
int ParseExampleBytes(const char* data, size_t size, const char* key,
                      const char** out, uint64_t* out_len) {
  Cursor ex{reinterpret_cast<const uint8_t*>(data),
            reinterpret_cast<const uint8_t*>(data) + size};
  size_t key_len = std::strlen(key);
  while (ex.ok && ex.p < ex.end) {
    uint64_t tag = ex.Varint();
    if (!ex.ok) return -1;
    if ((tag >> 3) != 1 || (tag & 7) != 2) { ex.Skip(tag & 7); continue; }
    uint64_t features_len = ex.Varint();
    Cursor feats{ex.p, ex.Sub(features_len)};
    ex.p += features_len;
    while (feats.ok && feats.p < feats.end) {
      uint64_t ftag = feats.Varint();
      if (!feats.ok) return -1;
      if ((ftag >> 3) != 1 || (ftag & 7) != 2) { feats.Skip(ftag & 7); continue; }
      uint64_t entry_len = feats.Varint();
      Cursor entry{feats.p, feats.Sub(entry_len)};
      feats.p += entry_len;
      bool key_match = false;
      Cursor value{nullptr, nullptr};
      while (entry.ok && entry.p < entry.end) {
        uint64_t etag = entry.Varint();
        if (!entry.ok) return -1;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          // Bound BEFORE memcmp: a truncated entry may claim key bytes
          // that are not there (same malformed-length class Sub guards).
          if (n > static_cast<uint64_t>(entry.end - entry.p)) {
            entry.p = entry.end;
            break;
          }
          key_match = (n == key_len &&
                       std::memcmp(entry.p, key, key_len) == 0);
          entry.p += n;
        } else if ((etag >> 3) == 2 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          value = Cursor{entry.p, entry.Sub(n)};
          entry.p += n;
        } else {
          entry.Skip(etag & 7);
        }
      }
      if (!key_match || value.p == nullptr) continue;
      // value: Feature { bytes_list = 1 } ; BytesList { value = 1 (bytes) }
      while (value.ok && value.p < value.end) {
        uint64_t vtag = value.Varint();
        if (!value.ok) return -1;
        if ((vtag >> 3) != 1 || (vtag & 7) != 2) { value.Skip(vtag & 7); continue; }
        uint64_t list_len = value.Varint();
        Cursor list{value.p, value.Sub(list_len)};
        value.p += list_len;
        while (list.ok && list.p < list.end) {
          uint64_t ltag = list.Varint();
          if (!list.ok) return -1;
          if ((ltag >> 3) != 1 || (ltag & 7) != 2) { list.Skip(ltag & 7); continue; }
          uint64_t n = list.Varint();
          // Subtraction form: `list.p + n` could wrap on a near-2^64
          // varint and sail past the check.
          if (n > static_cast<uint64_t>(list.end - list.p)) return -1;
          *out = reinterpret_cast<const char*>(list.p);
          *out_len = n;
          return 1;
        }
      }
    }
  }
  return 0;
}

// ------------------------------------------------------------ JPEG decode --
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf env;
};

void JpegErrorExit(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<JpegErr*>(cinfo->err);
  std::longjmp(err->env, 1);
}

// Decode JPEG bytes to 8-bit RGB. When the caller only needs
// (min_width × min_height) output, DCT-scaled decode (1/2, 1/4, 1/8) does
// the IDCT at reduced resolution — the dominant decode cost drops nearly
// quadratically while staying ≥ the resize target (the libjpeg analogue
// of tf.data's decode_and_crop trick). Pass 0/0 for full resolution.
bool DecodeJpeg(const char* data, size_t n, std::vector<uint8_t>* rgb,
                int* width, int* height, int min_width = 0,
                int min_height = 0) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JpegErrorExit;
  if (setjmp(jerr.env)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, reinterpret_cast<const unsigned char*>(data), n);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // grayscale/YCbCr → RGB conversion
  if (min_width > 0 && min_height > 0) {
    cinfo.scale_num = 1;
    cinfo.scale_denom = 1;
    for (int denom = 8; denom >= 2; denom /= 2) {
      // Output dims at scale 1/denom are ceil(dim/denom).
      int ow = (static_cast<int>(cinfo.image_width) + denom - 1) / denom;
      int oh = (static_cast<int>(cinfo.image_height) + denom - 1) / denom;
      if (ow >= min_width && oh >= min_height) {
        cinfo.scale_denom = denom;
        break;
      }
    }
  }
  jpeg_start_decompress(&cinfo);
  *width = cinfo.output_width;
  *height = cinfo.output_height;
  rgb->resize(static_cast<size_t>(*width) * *height * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = rgb->data() + static_cast<size_t>(cinfo.output_scanline) *
                                     *width * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// Bilinear resize of an (sw,sh) RGB region within a row-stride buffer →
// float32 (tw,th) RGB, values in [0,255]. Half-pixel-center sampling (the
// TF2 tf.image.resize convention), so the native pipeline's geometry
// matches the tf.data pipeline's.
void ResizeBilinear(const uint8_t* src, int sw, int sh, int src_stride,
                    float* dst, int tw, int th,
                    const float* mean = nullptr,
                    const float* inv_std = nullptr) {
  const float x_scale = float(sw) / tw;
  const float y_scale = float(sh) / th;
  for (int y = 0; y < th; ++y) {
    float fy = (y + 0.5f) * y_scale - 0.5f;
    if (fy < 0) fy = 0;
    if (fy > sh - 1) fy = float(sh - 1);
    int y0 = static_cast<int>(fy);
    int y1 = y0 + 1 < sh ? y0 + 1 : y0;
    float wy = fy - y0;
    for (int x = 0; x < tw; ++x) {
      float fx = (x + 0.5f) * x_scale - 0.5f;
      if (fx < 0) fx = 0;
      if (fx > sw - 1) fx = float(sw - 1);
      int x0 = static_cast<int>(fx);
      int x1 = x0 + 1 < sw ? x0 + 1 : x0;
      float wx = fx - x0;
      for (int c = 0; c < 3; ++c) {
        float a = src[(y0 * src_stride + x0) * 3 + c];
        float b = src[(y0 * src_stride + x1) * 3 + c];
        float d = src[(y1 * src_stride + x0) * 3 + c];
        float e = src[(y1 * src_stride + x1) * 3 + c];
        float top = a + (b - a) * wx;
        float bot = d + (e - d) * wx;
        float v = top + (bot - top) * wy;
        if (mean != nullptr) v = (v - mean[c]) * inv_std[c];
        dst[(y * tw + x) * 3 + c] = v;
      }
    }
  }
}

// ------------------------------------------------------------ crop sampler --
// Inception-style distorted crop in full-res pixel coords: area fraction
// U[0.08,1], aspect U[3/4,4/3], 10 attempts, central-full fallback.
void SampleCrop(Rng* rng, int W, int H, int* cx, int* cy, int* cw, int* ch) {
  for (int attempt = 0; attempt < 10; ++attempt) {
    float area = (0.08f + 0.92f * rng->Uniform()) * W * H;
    float ar = 0.75f + (4.0f / 3 - 0.75f) * rng->Uniform();
    int w = static_cast<int>(std::sqrt(area * ar) + 0.5f);
    int h = static_cast<int>(std::sqrt(area / ar) + 0.5f);
    if (w < 1) w = 1;
    if (h < 1) h = 1;
    if (w <= W && h <= H) {
      *cx = static_cast<int>(rng->Uniform() * (W - w + 1));
      *cy = static_cast<int>(rng->Uniform() * (H - h + 1));
      if (*cx > W - w) *cx = W - w;
      if (*cy > H - h) *cy = H - h;
      *cw = w;
      *ch = h;
      return;
    }
  }
  *cx = 0; *cy = 0; *cw = W; *ch = H;
}

// Decode ONLY a chosen crop window: DCT-scaled decode sized to the
// crop, jpeg_crop_scanline for the column range (iMCU-aligned),
// jpeg_skip_scanlines for the rows above/below — the libjpeg-turbo
// equivalent of tf.data's fused decode_and_crop, so the IDCT cost tracks
// the CROP area, not the full frame. `choose(W, H, &cx, &cy, &cw, &ch,
// &flip)` picks the full-resolution window once the header is parsed —
// shared by the train distorted-crop and eval central-crop paths.
template <typename ChooseCrop>
bool DecodeJpegWindow(const char* data, size_t n, int tw, int th,
                      float* out /* th*tw*3 */, const float* mean,
                      const float* inv_std, ChooseCrop choose) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JpegErrorExit;
  std::vector<uint8_t> buf;
  if (setjmp(jerr.env)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, reinterpret_cast<const unsigned char*>(data), n);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  const int W = cinfo.image_width, H = cinfo.image_height;

  int cx, cy, cw, ch;
  bool flip = false;
  choose(W, H, &cx, &cy, &cw, &ch, &flip);

  // DCT-scale so the SCALED crop still covers the resize target.
  cinfo.scale_num = 1;
  cinfo.scale_denom = 1;
  for (int denom = 8; denom >= 2; denom /= 2) {
    if ((cw + denom - 1) / denom >= tw && (ch + denom - 1) / denom >= th) {
      cinfo.scale_denom = denom;
      break;
    }
  }
  jpeg_start_decompress(&cinfo);
  const int ow = cinfo.output_width, oh = cinfo.output_height;
  // Crop coords in scaled space (clamped).
  auto scl = [&](int v, int full, int scaled) {
    long r = static_cast<long>(v) * scaled / full;
    return static_cast<int>(r);
  };
  int sx = scl(cx, W, ow), sy = scl(cy, H, oh);
  int sw = scl(cw, W, ow), sh = scl(ch, H, oh);
  if (sw < 1) sw = 1;
  if (sh < 1) sh = 1;
  if (sx + sw > ow) sx = ow - sw;
  if (sy + sh > oh) sy = oh - sh;
  if (sx < 0) sx = 0;
  if (sy < 0) sy = 0;

  JDIMENSION xoff = sx, xw = sw;
  jpeg_crop_scanline(&cinfo, &xoff, &xw);  // aligns to the iMCU grid
  const int xpad = sx - static_cast<int>(xoff);  // crop offset inside buffer
  if (sy > 0) jpeg_skip_scanlines(&cinfo, sy);
  buf.resize(static_cast<size_t>(sh) * xw * 3);
  while (static_cast<int>(cinfo.output_scanline) < sy + sh) {
    JSAMPROW row = buf.data() +
        static_cast<size_t>(cinfo.output_scanline - sy) * xw * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_abort_decompress(&cinfo);  // discard the remaining rows unread
  jpeg_destroy_decompress(&cinfo);

  ResizeBilinear(buf.data() + static_cast<size_t>(xpad) * 3, sw, sh,
                 static_cast<int>(xw), out, tw, th, mean, inv_std);
  if (flip) {
    for (int y = 0; y < th; ++y)
      for (int x = 0; x < tw / 2; ++x)
        for (int c = 0; c < 3; ++c)
          std::swap(out[(y * tw + x) * 3 + c],
                    out[(y * tw + (tw - 1 - x)) * 3 + c]);
  }
  return true;
}

// Train path: seeded Inception-style distorted crop + coin-flip mirror.
bool DecodeJpegCropped(const char* data, size_t n, uint64_t seed, int tw,
                       int th, float* out, const float* mean = nullptr,
                       const float* inv_std = nullptr) {
  return DecodeJpegWindow(
      data, n, tw, th, out, mean, inv_std,
      [seed](int W, int H, int* cx, int* cy, int* cw, int* ch, bool* flip) {
        Rng rng{seed};
        SampleCrop(&rng, W, H, cx, cy, cw, ch);
        *flip = rng.Uniform() < 0.5f;  // horizontal flip, same stream
      });
}

// Eval path: deterministic central crop. The window arithmetic mirrors
// tf.image.central_crop — offset = int((D - D*frac) / 2) computed in
// float, target = D - 2*offset — so the native eval sees the same pixels
// as the tf.data eval twin (resize filter remains bilinear-vs-bicubic,
// the documented delta).
bool DecodeJpegCentral(const char* data, size_t n, float central_frac,
                       int tw, int th, float* out,
                       const float* mean = nullptr,
                       const float* inv_std = nullptr) {
  return DecodeJpegWindow(
      data, n, tw, th, out, mean, inv_std,
      [central_frac](int W, int H, int* cx, int* cy, int* cw, int* ch,
                     bool* flip) {
        *cx = static_cast<int>((W - W * central_frac) / 2);
        *cy = static_cast<int>((H - H * central_frac) / 2);
        *cw = W - 2 * *cx;
        *ch = H - 2 * *cy;
        *flip = false;
      });
}

// Pop one record out of the queue by MOVE — 1 ok, 0 EOF, -1 error.
int PopRecord(Reader* r, Record* out) {
  std::unique_lock<std::mutex> lock(r->mu);
  r->cv_pop.wait(lock, [r] {
    return !r->queue.empty() || r->done || r->stop;
  });
  if (!r->error.empty()) return -1;
  if (r->queue.empty()) return 0;  // EOF
  *out = std::move(r->queue.front());
  r->queue.pop_front();
  r->cv_push.notify_one();
  return 1;
}

// Pop through the shuffle window when one is configured. All consumers
// (raw records, i32 batches, image batches, skip) share this path, so a
// resume that skips k records replays exactly what reading-and-discarding
// k records would have produced. Single-consumer (the Python side is one
// thread per handle), so no extra locking.
int PopNext(Reader* r, Record* out) {
  if (r->shuffle_window <= 1) return PopRecord(r, out);
  if (!r->shuffle_primed) {
    r->shuffle_buf.reserve(r->shuffle_window);
    while (r->shuffle_buf.size() < r->shuffle_window) {
      Record rec;
      int rc = PopRecord(r, &rec);
      if (rc < 0) return rc;
      if (rc == 0) break;
      r->shuffle_buf.push_back(std::move(rec));
    }
    r->shuffle_primed = true;
  }
  if (r->shuffle_buf.empty()) return PopRecord(r, out);  // EOF (or error)
  size_t j = static_cast<size_t>(r->shuffle_rng.Next() % r->shuffle_buf.size());
  *out = std::move(r->shuffle_buf[j]);
  Record rec;
  int rc = PopRecord(r, &rec);
  if (rc < 0) return rc;
  if (rc == 1) {
    r->shuffle_buf[j] = std::move(rec);
  } else {  // stream drained: shrink the window
    r->shuffle_buf[j] = std::move(r->shuffle_buf.back());
    r->shuffle_buf.pop_back();
  }
  return 1;
}

}  // namespace

extern "C" {

// shuffle_window > 1 enables the windowed record shuffle (tf.data
// shuffle-buffer semantics, deterministic given shuffle_seed).
void* rr_open(const char** paths, int n_paths, int prefetch,
              long shuffle_window, uint64_t shuffle_seed) {
  auto* r = new Reader();
  for (int i = 0; i < n_paths; ++i) r->paths.emplace_back(paths[i]);
  r->prefetch = prefetch > 0 ? prefetch : 256;
  r->shuffle_window = shuffle_window > 1 ? static_cast<size_t>(shuffle_window)
                                         : 0;
  r->shuffle_rng.s = shuffle_seed;
  r->worker = std::thread(ReadLoop, r);
  return r;
}

// Skip `n` records of the (possibly shuffled) stream without the C-ABI
// handoff copy or JPEG decode — the resume fast-skip. Returns the number
// actually skipped (short on EOF), or -1 on a reader error.
long rr_skip(void* h, long n) {
  auto* r = static_cast<Reader*>(h);
  Record rec;
  long i = 0;
  for (; i < n; ++i) {
    int rc = PopNext(r, &rec);
    if (rc < 0) return -1;
    if (rc == 0) break;
  }
  return i;
}

// Pops one record; caller owns *buf (free with rr_free). (The malloc+
// copy is the C-ABI handoff cost; the batch paths below move instead.)
int rr_next_record(void* h, char** buf, long* len) {
  Record rec;
  int rc = PopNext(static_cast<Reader*>(h), &rec);
  if (rc <= 0) return rc;
  *len = static_cast<long>(rec.bytes.size());
  *buf = static_cast<char*>(std::malloc(rec.bytes.size()));
  std::memcpy(*buf, rec.bytes.data(), rec.bytes.size());
  return 1;
}

void rr_free(char* buf) { std::free(buf); }

// Fills out[batch][width] with the named Int64List feature of the next
// `batch` records. Returns 1 ok, 0 EOF (not enough records), <0 error.
int rr_next_batch_i32(void* h, const char* key, int32_t* out, int batch,
                      int width) {
  auto* r = static_cast<Reader*>(h);
  Record rec;
  for (int i = 0; i < batch; ++i) {
    int rc = PopNext(r, &rec);
    if (rc <= 0) return rc;
    int got = ParseExampleInt64(rec.bytes.data(), rec.bytes.size(), key,
                                out + i * width, width);
    if (got < 0) return -2;
    if (got < width)  // pad short sequences with zeros
      std::memset(out + i * width + got, 0, sizeof(int32_t) * (width - got));
  }
  return 1;
}

// Pulls `batch` records, decodes their `image_key` JPEGs and bilinearly
// resizes to (th, tw) into out_images[batch][th][tw][3] (float32, 0..255),
// and writes the `label_key` int64 into out_labels[batch]. JPEG decode +
// resize run in `threads` parallel workers across the batch — the hot
// host-side cost at ImageNet rates. Returns 1 ok, 0 EOF, <0 error.
int rr_next_batch_images(void* h, const char* image_key,
                         const char* label_key, float* out_images,
                         int32_t* out_labels, int batch, int th, int tw,
                         int threads, const uint64_t* crop_seeds,
                         const float* mean, const float* stddev) {
  // Standardization fused into the resize output write: one multiply-add
  // per pixel instead of a second full pass over the batch in numpy.
  float inv_std_buf[3];
  const float* inv_std = nullptr;
  if (mean != nullptr && stddev != nullptr) {
    for (int c = 0; c < 3; ++c) inv_std_buf[c] = 1.0f / stddev[c];
    inv_std = inv_std_buf;
  } else {
    mean = nullptr;
  }
  // Records must be pulled serially (queue order = deterministic resume
  // contract); decode is the parallel part.
  std::vector<Record> records(batch);
  for (int i = 0; i < batch; ++i) {
    int rc = PopNext(static_cast<Reader*>(h), &records[i]);
    if (rc <= 0) return rc;  // records pulled by MOVE, no copies
  }
  std::atomic<int> next{0};
  std::atomic<int> failed{-1};
  int n_threads = threads > 0 ? threads : 8;
  if (n_threads > batch) n_threads = batch;
  auto work = [&] {
    std::vector<uint8_t> rgb;
    for (int i = next.fetch_add(1); i < batch; i = next.fetch_add(1)) {
      const auto& rec = records[i].bytes;
      const char* jpg = nullptr;
      uint64_t jpg_len = 0;
      if (ParseExampleBytes(rec.data(), rec.size(), image_key, &jpg,
                            &jpg_len) != 1) {
        failed = i;
        return;
      }
      float* dst = out_images + static_cast<size_t>(i) * th * tw * 3;
      if (crop_seeds != nullptr) {
        // Train path: distorted crop + flip decoded via partial IDCT.
        if (!DecodeJpegCropped(jpg, jpg_len, crop_seeds[i], tw, th, dst,
                               mean, inv_std)) {
          failed = i;
          return;
        }
      } else {
        int sw = 0, sh = 0;
        if (!DecodeJpeg(jpg, jpg_len, &rgb, &sw, &sh, tw, th) ||
            sw <= 0 || sh <= 0) {
          failed = i;
          return;
        }
        ResizeBilinear(rgb.data(), sw, sh, sw, dst, tw, th, mean, inv_std);
      }
      int32_t label = 0;
      // < 1 covers BOTH malformed (-1) and key-missing (0): a silently
      // defaulted label would train the model on garbage targets.
      if (ParseExampleInt64(rec.data(), rec.size(), label_key, &label, 1) < 1) {
        failed = i;
        return;
      }
      out_labels[i] = label;
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(work);
  for (auto& t : pool) t.join();
  return failed.load() >= 0 ? -3 : 1;
}

// Eval twin of rr_next_batch_images: deterministic central-crop
// (central_frac) decode + bilinear resize, single pass — no crop seeds,
// no flip. Pops UP TO `batch` records and returns the number filled
// (0 = clean EOF, <0 error); rows past the returned count are untouched
// (the caller zero-pads and weights them) — this is what lets the exact-
// eval contract (every record once, padded final batch) run through the
// native path.
int rr_next_batch_images_eval(void* h, const char* image_key,
                              const char* label_key, float* out_images,
                              int32_t* out_labels, int batch, int th, int tw,
                              int threads, float central_frac,
                              const float* mean, const float* stddev) {
  float inv_std_buf[3];
  const float* inv_std = nullptr;
  if (mean != nullptr && stddev != nullptr) {
    for (int c = 0; c < 3; ++c) inv_std_buf[c] = 1.0f / stddev[c];
    inv_std = inv_std_buf;
  } else {
    mean = nullptr;
  }
  std::vector<Record> records;
  records.reserve(batch);
  for (int i = 0; i < batch; ++i) {
    Record rec;
    int rc = PopNext(static_cast<Reader*>(h), &rec);
    if (rc < 0) return rc;
    if (rc == 0) break;  // partial final batch
    records.push_back(std::move(rec));
  }
  const int k = static_cast<int>(records.size());
  if (k == 0) return 0;
  std::atomic<int> next{0};
  std::atomic<int> failed{-1};
  int n_threads = threads > 0 ? threads : 8;
  if (n_threads > k) n_threads = k;
  auto work = [&] {
    for (int i = next.fetch_add(1); i < k; i = next.fetch_add(1)) {
      const auto& rec = records[i].bytes;
      const char* jpg = nullptr;
      uint64_t jpg_len = 0;
      if (ParseExampleBytes(rec.data(), rec.size(), image_key, &jpg,
                            &jpg_len) != 1) {
        failed = i;
        return;
      }
      float* dst = out_images + static_cast<size_t>(i) * th * tw * 3;
      if (!DecodeJpegCentral(jpg, jpg_len, central_frac, tw, th, dst, mean,
                             inv_std)) {
        failed = i;
        return;
      }
      int32_t label = 0;
      if (ParseExampleInt64(rec.data(), rec.size(), label_key, &label, 1) < 1) {
        failed = i;
        return;
      }
      out_labels[i] = label;
    }
  };
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) pool.emplace_back(work);
  for (auto& t : pool) t.join();
  return failed.load() >= 0 ? -3 : k;
}

const char* rr_error(void* h) {
  auto* r = static_cast<Reader*>(h);
  std::lock_guard<std::mutex> lock(r->mu);
  return r->error.empty() ? nullptr : r->error.c_str();
}

void rr_close(void* h) { delete static_cast<Reader*>(h); }

}  // extern "C"
