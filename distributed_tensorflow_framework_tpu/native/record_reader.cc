// Native TFRecord reader with background prefetch.
//
// The reference leans on the TF C++ runtime for its input path (SURVEY.md
// §2 row 5 / L0: the repo's Python tf.data graph executes in native
// threads). This is the equivalent native substrate for this framework:
// a C++ reader thread pool that decodes the TFRecord framing (length +
// masked crc32c + payload), optionally parses the fixed-schema
// tf.train.Example used by the MLM pipeline, and hands whole batches to
// Python through a lock-free-enough ring buffer — so the Python side does
// a single memcpy per batch instead of per-record framing work under the
// GIL.
//
// Exposed C ABI (consumed by ctypes in data/native_reader.py):
//   rr_open(paths, n_paths, prefetch)            -> handle
//   rr_next_record(h, &buf, &len)                -> 1 ok, 0 EOF, <0 error
//   rr_free(buf)
//   rr_next_batch_i32(h, key, out, batch, width) -> 1 ok, 0 EOF, <0 error
//   rr_close(h)
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread record_reader.cc
//        -o librecord_reader.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------- crc32c --
// Castagnoli CRC (the TFRecord checksum), software table version.
uint32_t kCrcTable[256];
std::once_flag kCrcOnce;

void InitCrcTable() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? (0x82f63b78u ^ (c >> 1)) : (c >> 1);
    kCrcTable[i] = c;
  }
}

uint32_t Crc32c(const char* data, size_t n) {
  std::call_once(kCrcOnce, InitCrcTable);
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < n; ++i)
    c = kCrcTable[(c ^ static_cast<uint8_t>(data[i])) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

uint32_t MaskedCrc(const char* data, size_t n) {
  uint32_t crc = Crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// ------------------------------------------------------------ ring buffer --
struct Record {
  std::vector<char> bytes;
};

struct Reader {
  std::vector<std::string> paths;
  size_t prefetch;
  std::deque<Record> queue;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::thread worker;
  std::atomic<bool> done{false}, stop{false};
  std::string error;

  ~Reader() {
    {
      // Set stop under the lock: the worker checks the predicate while
      // holding mu inside cv.wait, so an unlocked store+notify can land
      // between its predicate check and its sleep (lost wakeup → join
      // hangs forever).
      std::lock_guard<std::mutex> lock(mu);
      stop = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    if (worker.joinable()) worker.join();
  }
};

void ReadLoop(Reader* r) {
  for (const auto& path : r->paths) {
    if (r->stop) break;
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      std::lock_guard<std::mutex> lock(r->mu);
      r->error = "open failed: " + path;
      break;
    }
    while (!r->stop) {
      char header[12];
      size_t got = std::fread(header, 1, 12, f);
      if (got == 0) break;  // clean EOF
      if (got != 12) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated header: " + path;
        break;
      }
      uint64_t len;
      std::memcpy(&len, header, 8);
      uint32_t len_crc;
      std::memcpy(&len_crc, header + 8, 4);
      if (MaskedCrc(header, 8) != len_crc) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "length crc mismatch: " + path;
        break;
      }
      Record rec;
      rec.bytes.resize(len);
      if (std::fread(rec.bytes.data(), 1, len, f) != len) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated payload: " + path;
        break;
      }
      char footer[4];
      if (std::fread(footer, 1, 4, f) != 4) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "truncated footer: " + path;
        break;
      }
      uint32_t data_crc;
      std::memcpy(&data_crc, footer, 4);
      if (MaskedCrc(rec.bytes.data(), len) != data_crc) {
        std::lock_guard<std::mutex> lock(r->mu);
        r->error = "payload crc mismatch: " + path;
        break;
      }
      std::unique_lock<std::mutex> lock(r->mu);
      r->cv_push.wait(lock, [r] {
        return r->queue.size() < r->prefetch || r->stop;
      });
      if (r->stop) break;
      r->queue.push_back(std::move(rec));
      r->cv_pop.notify_one();
    }
    std::fclose(f);
    {
      std::lock_guard<std::mutex> lock(r->mu);
      if (!r->error.empty()) break;
    }
  }
  r->done = true;
  r->cv_pop.notify_all();
}

// ------------------------------------------------- minimal Example parser --
// Parses tf.train.Example just enough to pull one named Int64List feature.
// Wire layout (all protobuf):
//   Example        { features = 1 (msg) }
//   Features       { feature  = 1 (map<string, Feature>) }
//   map entry      { key = 1 (string), value = 2 (Feature msg) }
//   Feature        { int64_list = 3 (msg) }  [bytes_list=1, float_list=2]
//   Int64List      { value = 1 (repeated varint, possibly packed) }

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint64_t Varint() {
    uint64_t v = 0;
    int shift = 0;
    while (p < end && shift < 64) {
      uint8_t b = *p++;
      v |= static_cast<uint64_t>(b & 0x7f) << shift;
      if (!(b & 0x80)) return v;
      shift += 7;
    }
    ok = false;
    return 0;
  }

  void Skip(uint32_t wire_type) {
    switch (wire_type) {
      case 0: Varint(); break;
      case 1: p += 8; break;
      case 2: { uint64_t n = Varint(); p += n; break; }
      case 5: p += 4; break;
      default: ok = false;
    }
    if (p > end) ok = false;
  }
};

// Extract int64s for `key` into out (up to width); returns count or -1.
int ParseExampleInt64(const char* data, size_t size, const char* key,
                      int32_t* out, int width) {
  Cursor ex{reinterpret_cast<const uint8_t*>(data),
            reinterpret_cast<const uint8_t*>(data) + size};
  size_t key_len = std::strlen(key);
  while (ex.ok && ex.p < ex.end) {
    uint64_t tag = ex.Varint();
    if (!ex.ok) return -1;
    if ((tag >> 3) != 1 || (tag & 7) != 2) { ex.Skip(tag & 7); continue; }
    uint64_t features_len = ex.Varint();
    Cursor feats{ex.p, ex.p + features_len};
    ex.p += features_len;
    while (feats.ok && feats.p < feats.end) {
      uint64_t ftag = feats.Varint();
      if (!feats.ok) return -1;
      if ((ftag >> 3) != 1 || (ftag & 7) != 2) { feats.Skip(ftag & 7); continue; }
      uint64_t entry_len = feats.Varint();
      Cursor entry{feats.p, feats.p + entry_len};
      feats.p += entry_len;
      bool key_match = false;
      Cursor value{nullptr, nullptr};
      while (entry.ok && entry.p < entry.end) {
        uint64_t etag = entry.Varint();
        if (!entry.ok) return -1;
        if ((etag >> 3) == 1 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          key_match = (n == key_len &&
                       std::memcmp(entry.p, key, key_len) == 0);
          entry.p += n;
        } else if ((etag >> 3) == 2 && (etag & 7) == 2) {
          uint64_t n = entry.Varint();
          value = Cursor{entry.p, entry.p + n};
          entry.p += n;
        } else {
          entry.Skip(etag & 7);
        }
      }
      if (!key_match || value.p == nullptr) continue;
      // value: Feature { int64_list = 3 }
      while (value.ok && value.p < value.end) {
        uint64_t vtag = value.Varint();
        if (!value.ok) return -1;
        if ((vtag >> 3) != 3 || (vtag & 7) != 2) { value.Skip(vtag & 7); continue; }
        uint64_t list_len = value.Varint();
        Cursor list{value.p, value.p + list_len};
        value.p += list_len;
        int count = 0;
        while (list.ok && list.p < list.end && count < width) {
          uint64_t ltag = list.Varint();
          if (!list.ok) return -1;
          if ((ltag >> 3) != 1) { list.Skip(ltag & 7); continue; }
          if ((ltag & 7) == 2) {  // packed
            uint64_t n = list.Varint();
            const uint8_t* stop_at = list.p + n;
            while (list.ok && list.p < stop_at && count < width)
              out[count++] = static_cast<int32_t>(list.Varint());
          } else {  // single varint
            out[count++] = static_cast<int32_t>(list.Varint());
          }
        }
        return list.ok ? count : -1;
      }
    }
  }
  return 0;  // key not found
}

}  // namespace

extern "C" {

void* rr_open(const char** paths, int n_paths, int prefetch) {
  auto* r = new Reader();
  for (int i = 0; i < n_paths; ++i) r->paths.emplace_back(paths[i]);
  r->prefetch = prefetch > 0 ? prefetch : 256;
  r->worker = std::thread(ReadLoop, r);
  return r;
}

// Pops one record; caller owns *buf (free with rr_free).
int rr_next_record(void* h, char** buf, long* len) {
  auto* r = static_cast<Reader*>(h);
  std::unique_lock<std::mutex> lock(r->mu);
  r->cv_pop.wait(lock, [r] {
    return !r->queue.empty() || r->done || r->stop;
  });
  if (!r->error.empty()) return -1;
  if (r->queue.empty()) return 0;  // EOF
  Record rec = std::move(r->queue.front());
  r->queue.pop_front();
  r->cv_push.notify_one();
  lock.unlock();
  *len = static_cast<long>(rec.bytes.size());
  *buf = static_cast<char*>(std::malloc(rec.bytes.size()));
  std::memcpy(*buf, rec.bytes.data(), rec.bytes.size());
  return 1;
}

void rr_free(char* buf) { std::free(buf); }

// Fills out[batch][width] with the named Int64List feature of the next
// `batch` records. Returns 1 ok, 0 EOF (not enough records), <0 error.
int rr_next_batch_i32(void* h, const char* key, int32_t* out, int batch,
                      int width) {
  auto* r = static_cast<Reader*>(h);
  for (int i = 0; i < batch; ++i) {
    char* buf = nullptr;
    long len = 0;
    int rc = rr_next_record(h, &buf, &len);
    if (rc <= 0) return rc;
    int got = ParseExampleInt64(buf, len, key, out + i * width, width);
    std::free(buf);
    if (got < 0) return -2;
    if (got < width)  // pad short sequences with zeros
      std::memset(out + i * width + got, 0, sizeof(int32_t) * (width - got));
  }
  (void)r;
  return 1;
}

const char* rr_error(void* h) {
  auto* r = static_cast<Reader*>(h);
  std::lock_guard<std::mutex> lock(r->mu);
  return r->error.empty() ? nullptr : r->error.c_str();
}

void rr_close(void* h) { delete static_cast<Reader*>(h); }

}  // extern "C"
