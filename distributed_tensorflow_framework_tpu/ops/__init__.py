"""Pallas TPU kernels for hot ops.

The reference's hot kernels are closed-source cuDNN/NCCL binaries linked
through the TF wheel (SURVEY.md §2 native rows). Convolution/BN come free
from XLA on TPU; the kernels here cover the ops where a hand-fused Pallas
implementation beats naive XLA:

  flash_attention.py   fused attention (no HBM S×S materialization)
"""
