"""Fused attention Pallas kernel.

Computes softmax(qkᵀ/√d)·v with the S×S score matrix living only in VMEM —
one HBM read of q/k/v and one write of the output per (batch, head, q-block)
program, the memory-optimal pattern for self-attention at BERT-scale
sequence lengths. XLA alone materializes (or at best tiles) the score
tensor through HBM for the unfused einsum+softmax+einsum chain; this kernel
is the TPU analogue of the reference's fused cuDNN attention path would-be
(the reference predates flash attention; SURVEY.md §5 long-context row).

Shapes: q, k, v are (B, S, H, D); grid is (B, H, S/BLOCK_Q); each program
holds its q block and the full K/V for that head in VMEM (fine to S≈4K;
beyond that use ring attention over the ``seq`` mesh axis or the xla impl).

The kernel runs in interpret mode off-TPU so the CPU test mesh exercises
the same code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = float(jnp.finfo(jnp.float32).min)
BLOCK_Q = 128
# Whole-K VMEM budget: S*D*4B*2 (K and V, f32 upcast) + scores BLOCK_Q*S*4B
# must fit in ~16MB with double buffering.
MAX_SEQ_VMEM = 4096


def _attn_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, *, scale: float):
    q = q_ref[0, 0].astype(jnp.float32)          # (BQ, D)
    k = k_ref[0, 0].astype(jnp.float32)          # (S, D)
    v = v_ref[0, 0].astype(jnp.float32)          # (S, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # (BQ, S)
    s = s + bias_ref[0][None, :]                  # additive mask bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / l                                         # (BQ, D)
    o_ref[0, 0] = o.astype(o_ref.dtype)


def _xla_reference(q, k, v, bias):
    """Plain-XLA attention on the (B,H,S,D) layout — the autodiff source of
    truth for the backward pass (forward runs the fused kernel; backward
    rematerializes through this, trading HBM for FLOPs exactly like
    jax.checkpoint would)."""
    d = q.shape[-1]
    s = jax.lax.dot_general(
        q.astype(jnp.float32), k.astype(jnp.float32),
        (((3,), (3,)), ((0, 1), (0, 1))),
    ) / (d ** 0.5)                                  # (B,H,S,S)
    s = s + bias[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1)
    return jax.lax.dot_general(
        p, v.astype(jnp.float32),
        (((3,), (2,)), ((0, 1), (0, 1))),
    ).astype(q.dtype)                               # (B,H,S,D)


@jax.custom_vjp
def _fused(q, k, v, bias):
    interpret = jax.default_backend() != "tpu"
    return _flash_attention(q, k, v, bias, interpret=interpret)


def _fused_fwd(q, k, v, bias):
    return _fused(q, k, v, bias), (q, k, v, bias)


def _fused_bwd(res, g):
    q, k, v, bias = res
    _, vjp = jax.vjp(_xla_reference, q, k, v, bias)
    return vjp(g)


_fused.defvjp(_fused_fwd, _fused_bwd)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _flash_attention(q, k, v, bias, *, interpret: bool):
    b, h, s, d = q.shape
    scale = 1.0 / (d ** 0.5)
    block_q = min(BLOCK_Q, s)
    grid = (b, h, s // block_q)
    return pl.pallas_call(
        functools.partial(_attn_kernel, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, s), lambda bi, hi, qi: (bi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        interpret=interpret,
    )(q, k, v, bias)


def flash_attention(q, k, v, *, mask=None):
    """Fused attention. q,k,v: (B, S, H, D); mask: (B,1,1,S) bool or None.

    Returns (B, S, H, D) in q's dtype.
    """
    b, s, hh, d = q.shape
    if s > MAX_SEQ_VMEM:
        raise ValueError(
            f"flash_attention holds full K/V in VMEM; seq {s} > "
            f"{MAX_SEQ_VMEM}. Use attention_impl='ring' for long context."
        )
    if s % min(BLOCK_Q, s):
        raise ValueError(f"seq len {s} must be a multiple of {BLOCK_Q}")
    # (B, S, H, D) → (B, H, S, D) for contiguous per-head blocks.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if mask is not None:
        bias = jnp.where(mask[:, 0, 0, :], 0.0, NEG_INF).astype(jnp.float32)
    else:
        bias = jnp.zeros((b, s), jnp.float32)
    out = _fused(qt, kt, vt, bias)
    return out.transpose(0, 2, 1, 3)
