"""Fused attention Pallas kernels — forward AND backward.

Computes softmax(qkᵀ/√d)·v with the S×S score matrix living only in VMEM.
Forward: one HBM read of q/k/v and one write of o (+ the per-row
logsumexp) per (batch, head, q-block) program. Backward: two Pallas
kernels (dq over q-blocks; dk/dv over k-blocks) that RECOMPUTE the
probability blocks online from the saved (q, k, v, o, lse) — so training
peak memory is O(S·D) end to end; no O(S²) tensor is ever materialized in
HBM in either direction. This is the flash-attention recompute pattern
(PAPERS.md); XLA alone tiles but still round-trips the score tensor for
the unfused einsum+softmax+einsum chain.

Shapes: q, k, v are (B, S, H, D). Two kernel regimes, dispatched on
sequence length (see MAX_SEQ_VMEM): whole-K (each program holds its
block plus the full opposing sequence in VMEM — the measured-fast path
to S=4K) and K-blocked streaming (sequential k-axis grid with running
softmax state in VMEM scratch — any length, VMEM use O(block²)). Ring
attention over the ``seq`` mesh axis composes on top for sharded
sequences.

The kernels run in interpret mode off-TPU so the CPU test mesh exercises
the same code path; tests/test_attention.py pins fwd+bwd numerics against
the plain-XLA reference.
"""

from __future__ import annotations

import functools
import logging
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

log = logging.getLogger(__name__)

NEG_INF = float(jnp.finfo(jnp.float32).min)
BLOCK_Q = 128
BLOCK_K = 128
# Streaming-kernel tile sizes (the s_k > MAX_SEQ_VMEM regime only). The
# 128×128 tiles the whole-K path uses are far too fine here: at S=8192
# they make a (B,H,64,64) grid of ~200k programs whose per-program
# overhead swamps the 128×64×128 matmuls (measured 3% MFU on v5e,
# PERF_NOTES.md round 4). Fatter tiles amortize the grid: 8 sequential
# k-steps instead of 64, and each dot is MXU-sized. Measured ladder at
# seq 8192 (PERF_NOTES round 4): 128/128 → 7.9k tok/s, 256/1024 → 30k,
# 512/1024 → 35.2k, 1024/1024 → 35.4k, 512/2048 → 31.9k (VMEM pressure).
# 512/1024 ships: within noise of the peak at half the q-tile VMEM.
# Env-tunable for A/Bs, same spirit as the BENCH_* knobs.
BLOCK_Q_KB = int(os.environ.get("FLASH_BLOCK_Q_KB", "512"))
BLOCK_K_KB = int(os.environ.get("FLASH_BLOCK_K_KB", "1024"))
# VMEM dispatch policy (VERDICT r3 weak #2 — no silent fallback above this):
#   s_k ≤ MAX_SEQ_VMEM → whole-K kernels: each program holds the full
#     opposing sequence in VMEM at INPUT dtype (S*D*2B*2 for bf16 K and
#     V — the round-4 kernels dot in input dtype, no f32 upcast — plus
#     the BLOCK_Q*S*4B f32 score block) — fits ~16MB with double
#     buffering, and is the variant whose perf was measured on real TPU
#     (PERF_NOTES.md).
#   s_k > MAX_SEQ_VMEM → K-blocked streaming kernels: the grid gains a
#     sequential k-axis; running (m, l, acc) softmax state lives in VMEM
#     scratch and K/V stream through in BLOCK_K_KB tiles, so VMEM use is
#     O(BLOCK_Q_KB·BLOCK_K_KB) regardless of sequence length. No
#     fallback to the O(S²)-materializing XLA chain exists above the
#     threshold — long chunks stay fused (tests/test_attention.py pins
#     8192), and the chain is not even COMPILABLE there: at seq 8192 the
#     XLA impl fails remote compilation outright (PERF_NOTES.md round 4).
# Env-tunable so the whole-K vs K-blocked crossover can be re-measured
# without an edit (FLASH_MAX_SEQ_VMEM=0 forces the streaming kernels
# everywhere).
MAX_SEQ_VMEM = int(os.environ.get("FLASH_MAX_SEQ_VMEM", "4096"))
# Fused one-pass streaming backward: one kernel over grid (B,H,nq,nk)
# produces dq AND dk/dv/dbias, computing each (q-block, k-block)
# probability block ONCE — the two-pass backward exps every block twice
# (dq pass + dkv pass). The round-5 PERF_NOTES bound analysis puts the
# streaming regime's cost in exactly that S² VPU transcendental work,
# at the price of full-length (S_k, D) f32 dk/dv VMEM accumulators —
# hence the MAX gate (4 MB at 8192; beyond ~2·8192 it cannot fit and
# the two-pass kernels remain the only path).
#
# Tri-state default: ``None`` (env unset) = auto — ON only on backends
# where scripts/verify_fused_bwd.py results are RECORDED (the
# 2026-08-01 v5e window: EXACT on-device agreement with the two-pass
# kernels at seq 8192, worst rel diff 0.0, and the step A/B measured
# 36,150 vs 33,526 tok/s, +7.8%, at seq 8192 bs 4 — PERF_NOTES round
# 5). On any other real TPU generation the fused dk/dv/dbias flush
# ordering is UNVERIFIED silicon behavior (ADVICE r5): auto keeps the
# two-pass backward and says so once. FLASH_FUSED_BWD=1/0 forces either
# way (env read at import time like the other FLASH_* knobs); tests and
# scripts/verify_fused_bwd.py assign the module global directly — the
# backward closures consult it at call time through fused_bwd_enabled().
_FUSED_BWD_ENV = os.environ.get("FLASH_FUSED_BWD")
FUSED_BWD: bool | None = (
    None if _FUSED_BWD_ENV is None else _FUSED_BWD_ENV not in ("", "0"))
FUSED_BWD_MAX = int(os.environ.get("FLASH_FUSED_BWD_MAX", "8192"))
# Backend substrings (matched against device_kind, lowercased) with
# recorded verify_fused_bwd.py + step-A/B results.
FUSED_BWD_VERIFIED_PLATFORMS = ("v5 lite", "v5e")
# The fused one-pass backward can also REPLACE the whole-K two-pass
# backward for mid-length sequences (FUSED_WHOLE_K_MIN ≤ s ≤
# MAX_SEQ_VMEM): the whole-K dq/dkv kernel pair pays the same three S²
# exp evaluations the streaming two-pass does, and the round-4 crossover
# showed the K-blocked kernels already TIE whole-K at 2048 — so the fused
# kernel's saved exp SHOULD be pure win from there up. But that band's
# win is EXTRAPOLATED from the 8192 measurement, not measured for f32.
# The bf16 arm of the §13 precision ladder
# (scripts/chip_window_queue.sh) re-ran the crossover under the
# production compute dtype: at bf16 the MXU matmuls halve, leaving the
# fused kernel's saved S² exp pass as a larger FRACTION of the backward
# — the takeover is armed by default at 2048 for bf16 inputs only. f32
# keeps the conservative park above MAX_SEQ_VMEM (where the streaming
# kernels are the only path anyway and the knob is inert) until the
# wk2048/wk4096 f32 A/B (scripts/chip_window_queue.sh item 7) lands.
# FLASH_FUSED_WHOLE_K_MIN=<n> forces one threshold for every dtype
# (tests and scripts assign the module global directly, same contract);
# unset leaves the dtype-aware default via fused_whole_k_min(). Forward
# stays whole-K either way (the streaming backward needs only
# q/k/v/bias/lse/do, all of which the whole-K forward saves).
_FUSED_WHOLE_K_MIN_ENV = os.environ.get("FLASH_FUSED_WHOLE_K_MIN")
FUSED_WHOLE_K_MIN: int | None = (
    None if _FUSED_WHOLE_K_MIN_ENV is None else int(_FUSED_WHOLE_K_MIN_ENV))
FUSED_WHOLE_K_MIN_BF16 = 2048


def fused_whole_k_min(dtype) -> int:
    """Minimum sequence length where the fused one-pass backward takes
    over from the whole-K two-pass pair, resolved per input dtype.
    An explicit FUSED_WHOLE_K_MIN (env or direct module-global
    assignment — tests/scripts do the latter) wins for every dtype;
    otherwise bf16 gets the armed 2048 default and everything else stays
    parked above MAX_SEQ_VMEM. Reads the module globals at call time so
    monkeypatching keeps working."""
    if FUSED_WHOLE_K_MIN is not None:
        return FUSED_WHOLE_K_MIN
    if jnp.dtype(dtype) == jnp.bfloat16:
        return FUSED_WHOLE_K_MIN_BF16
    return MAX_SEQ_VMEM + 1


def _attn_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, *rest,
                     scale: float, segmented: bool):
    # Segment-id refs only exist in the segmented variant — the common
    # unsegmented path carries no extra operands (and no VMEM traffic).
    if segmented:
        qseg_ref, kseg_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    # Dots take the INPUT dtype (bf16 in production) with f32 accumulation:
    # bf16 products are exact in the f32 MXU accumulator, so this matches
    # an upcast-then-f32-dot bitwise up to summation order while running
    # at the 2x bf16 MXU rate. Only the p/ds downcasts below round.
    q = q_ref[0, 0]                               # (BQ, D)
    k = k_ref[0, 0]                               # (S, D)
    v = v_ref[0, 0]                               # (S, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # (BQ, S) f32
    s = s + bias_ref[0]                           # additive mask bias, (1,S)
    if segmented:
        # Packed-sequence block-diagonal mask: token i may attend token j
        # only within the same segment (segment ids ride as f32 so the
        # custom_vjp stays all-float; equality on small ints is exact).
        qs = qseg_ref[0, 0]                       # (BQ,)
        ks = kseg_ref[0, 0]                       # (S,)
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) / l                                         # (BQ, D)
    o_ref[0, 0] = o.astype(o_ref.dtype)
    # Per-row logsumexp: the only softmax statistic the backward needs.
    lse_ref[0, 0] = (m + jnp.log(l)).astype(jnp.float32)


def _attn_bwd_dq_kernel(q_ref, k_ref, v_ref, bias_ref, *rest,
                        scale: float, segmented: bool):
    """dQ for one q-block: recompute p from (q, k, lse), no S×S residual."""
    if segmented:
        qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref, dq_ref = rest
    else:
        do_ref, lse_ref, delta_ref, dq_ref = rest
    q = q_ref[0, 0]                               # (BQ, D) input dtype
    k = k_ref[0, 0]                               # (S, D)
    v = v_ref[0, 0]                               # (S, D)
    do = do_ref[0, 0]                             # (BQ, D)
    lse = lse_ref[0, 0]                           # (BQ, 1)
    delta = delta_ref[0, 0]                       # (BQ, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (BQ, S)
    if segmented:
        qs = qseg_ref[0, 0]
        ks = kseg_ref[0, 0]
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    p = jnp.exp(s - lse)                          # recomputed probabilities
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BQ, S)
    ds = p * (dp - delta)                         # (BQ, S) f32
    dq = jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel(q_ref, k_ref, v_ref, bias_ref, *rest,
                         scale: float, segmented: bool):
    """dK/dV (+ per-head dbias) for one k-block: full Q/dO in VMEM."""
    if segmented:
        (qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dbias_ref) = rest
    else:
        do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dbias_ref = rest
    q = q_ref[0, 0]                               # (S, D) input dtype
    k = k_ref[0, 0]                               # (BK, D)
    v = v_ref[0, 0]                               # (BK, D)
    do = do_ref[0, 0]                             # (S, D)
    lse = lse_ref[0, 0]                           # (S, 1)
    delta = delta_ref[0, 0]                       # (S, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (S, BK)
    if segmented:
        qs = qseg_ref[0, 0]                       # (S,)
        ks = kseg_ref[0, 0]                       # (BK,)
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    p = jnp.exp(s - lse)
    dv = jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BK, D)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (S, BK)
    ds = p * (dp - delta)                         # (S, BK) f32
    dk = jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # (BK, D)
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)
    dbias_ref[0, 0] = jnp.sum(ds, axis=0, keepdims=True)  # (1, BK)


def _attn_fwd_kernel_kb(q_ref, k_ref, v_ref, bias_ref, *rest,
                        scale: float, segmented: bool):
    """K-blocked forward: grid (B, H, nq, nk) with nk innermost/sequential.

    Running-softmax state (m, l, acc) persists in VMEM scratch across the
    k-blocks of one q-block; K/V stream through in BLOCK_K tiles so no
    whole-sequence operand ever sits in VMEM. Finite NEG_INF arithmetic
    gives bit-compatible fully-masked-row semantics with the whole-K
    kernel (garbage o, lse ≈ NEG_INF — the ring merge weights it to 0).
    """
    if segmented:
        qseg_ref, kseg_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)
        m_ref[...] = jnp.full(m_ref.shape, NEG_INF, m_ref.dtype)
        l_ref[...] = jnp.zeros(l_ref.shape, l_ref.dtype)

    q = q_ref[0, 0]                               # (BQ, D) input dtype
    k = k_ref[0, 0]                               # (BK, D)
    v = v_ref[0, 0]                               # (BK, D)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (BQ, BK) f32
    if segmented:
        qs = qseg_ref[0, 0]                       # (BQ,)
        ks = kseg_ref[0, 0]                       # (BK,)
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    m_prev = m_ref[...]                           # (BQ, 1)
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_ref[...] = l_ref[...] * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[...] = m_new

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        o_ref[0, 0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_ref[...] + jnp.log(l_ref[...])


def _attn_bwd_dq_kernel_kb(q_ref, k_ref, v_ref, bias_ref, *rest,
                           scale: float, segmented: bool):
    """K-blocked dQ: accumulate ds·k over streamed K/V tiles in scratch."""
    if segmented:
        qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref, dq_ref, acc_ref = rest
    else:
        do_ref, lse_ref, delta_ref, dq_ref, acc_ref = rest
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros(acc_ref.shape, acc_ref.dtype)

    q = q_ref[0, 0]                               # (BQ, D) input dtype
    k = k_ref[0, 0]                               # (BK, D)
    v = v_ref[0, 0]                               # (BK, D)
    do = do_ref[0, 0]                             # (BQ, D)
    lse = lse_ref[0, 0]                           # (BQ, 1)
    delta = delta_ref[0, 0]                       # (BQ, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (BQ, BK) f32
    if segmented:
        qs = qseg_ref[0, 0]
        ks = kseg_ref[0, 0]
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BQ, BK)
    ds = p * (dp - delta)                         # f32
    acc_ref[...] = acc_ref[...] + jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _attn_bwd_dkv_kernel_kb(q_ref, k_ref, v_ref, bias_ref, *rest,
                            scale: float, segmented: bool):
    """K-blocked dK/dV/dbias: grid (B, H, nk, nq) with the q-axis
    innermost/sequential; Q/dO stream through in BLOCK_Q tiles while the
    (dk, dv, dbias) accumulators for one k-block live in scratch."""
    if segmented:
        (qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, db_acc) = rest
    else:
        (do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dbias_ref, dk_acc, dv_acc, db_acc) = rest
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros(dk_acc.shape, dk_acc.dtype)
        dv_acc[...] = jnp.zeros(dv_acc.shape, dv_acc.dtype)
        db_acc[...] = jnp.zeros(db_acc.shape, db_acc.dtype)

    q = q_ref[0, 0]                               # (BQ, D) input dtype
    k = k_ref[0, 0]                               # (BK, D)
    v = v_ref[0, 0]                               # (BK, D)
    do = do_ref[0, 0]                             # (BQ, D)
    lse = lse_ref[0, 0]                           # (BQ, 1)
    delta = delta_ref[0, 0]                       # (BQ, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (BQ, BK) f32
    if segmented:
        qs = qseg_ref[0, 0]
        ks = kseg_ref[0, 0]
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    p = jnp.exp(s - lse)
    dv_acc[...] = dv_acc[...] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BK, D)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BQ, BK)
    ds = p * (dp - delta)                         # f32
    dk_acc[...] = dk_acc[...] + jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # (BK, D)
    db_acc[...] = db_acc[...] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(qi == pl.num_programs(3) - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)
        dbias_ref[0, 0] = db_acc[...]


def _attn_bwd_fused_kernel_kb(q_ref, k_ref, v_ref, bias_ref, *rest,
                              scale: float, segmented: bool):
    """Fused one-pass streaming backward: grid (B, H, nq, nk), BOTH inner
    axes sequential ("arbitrary"). Each (q-block, k-block) pair is
    visited once; its probability block is exp'd ONCE and feeds all four
    cotangents. dq accumulates per q-block in block scratch (finalized
    when the k-scan ends); dk/dv/dbias accumulate in FULL-LENGTH VMEM
    scratch across the whole per-(b,h) subgrid, and each visit stores
    the current partial to the block output — grid steps execute in
    order on the core, so the final visit's flush (qi == nq-1) is what
    HBM keeps. Earlier flushes are dead writes: ~(nq-1)·S_k·D·4B extra
    HBM-write traffic per (b,h), orders below the exp savings
    (PERF_NOTES round-5 analysis)."""
    if segmented:
        (qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref, dbias_ref,
         dq_acc, dk_full, dv_full, db_full) = rest
    else:
        (do_ref, lse_ref, delta_ref,
         dq_ref, dk_ref, dv_ref, dbias_ref,
         dq_acc, dk_full, dv_full, db_full) = rest
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init_dq():
        dq_acc[...] = jnp.zeros(dq_acc.shape, dq_acc.dtype)

    @pl.when((qi == 0) & (ki == 0))
    def _init_dkv():
        dk_full[...] = jnp.zeros(dk_full.shape, dk_full.dtype)
        dv_full[...] = jnp.zeros(dv_full.shape, dv_full.dtype)
        db_full[...] = jnp.zeros(db_full.shape, db_full.dtype)

    q = q_ref[0, 0]                               # (BQ, D) input dtype
    k = k_ref[0, 0]                               # (BK, D)
    v = v_ref[0, 0]                               # (BK, D)
    do = do_ref[0, 0]                             # (BQ, D)
    lse = lse_ref[0, 0]                           # (BQ, 1)
    delta = delta_ref[0, 0]                       # (BQ, 1)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale + bias_ref[0]                       # (BQ, BK) f32
    if segmented:
        qs = qseg_ref[0, 0]
        ks = kseg_ref[0, 0]
        s = jnp.where(qs[:, None] == ks[None, :], s, NEG_INF)
    p = jnp.exp(s - lse)                          # the ONE exp per pair
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BQ, BK)
    ds = p * (dp - delta)                         # f32
    dq_acc[...] = dq_acc[...] + jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    bk = k.shape[0]
    sl = pl.ds(ki * bk, bk)
    dv_full[sl, :] = dv_full[sl, :] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                             # (BK, D)
    dk_full[sl, :] = dk_full[sl, :] + jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                     # (BK, D)
    db_full[:, sl] = db_full[:, sl] + jnp.sum(ds, axis=0, keepdims=True)

    @pl.when(ki == pl.num_programs(3) - 1)
    def _finalize_dq():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)

    # Store the running partials every visit; the last (qi) visit wins.
    dk_ref[0, 0] = dk_full[sl, :].astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_full[sl, :].astype(dv_ref.dtype)
    dbias_ref[0, 0] = db_full[:, sl]


def _xla_reference(q, k, v, bias):
    """Plain-XLA attention on the (B,H,S,D) layout — the numerics source of
    truth the kernels are tested against (tests/test_attention.py)."""
    d = q.shape[-1]
    s = jax.lax.dot_general(
        q.astype(jnp.float32), k.astype(jnp.float32),
        (((3,), (3,)), ((0, 1), (0, 1))),
    ) / (d ** 0.5)                                  # (B,H,S,S)
    s = s + bias[:, None, :, :]
    p = jax.nn.softmax(s, axis=-1)
    return jax.lax.dot_general(
        p, v.astype(jnp.float32),
        (((3,), (2,)), ((0, 1), (0, 1))),
    ).astype(q.dtype)                               # (B,H,S,D)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


_fused_bwd_auto: bool | None = None  # memoized auto-resolution


def fused_bwd_enabled() -> bool:
    """Resolve the FUSED_BWD tri-state at backward-dispatch time.

    A bool in the module global (env knob, test monkeypatch, or
    scripts/verify_fused_bwd.py's direct assignment) always wins. ``None``
    = auto: ON only when the default backend is a TPU whose device_kind
    matches a FUSED_BWD_VERIFIED_PLATFORMS entry; any OTHER real TPU gets
    the two-pass backward plus a one-line warning (once) — the fused
    flush ordering is verified per-generation, and silently-wrong
    gradients are the worst possible failure mode. Non-TPU backends run
    the kernels in interpret mode where perf is moot: auto stays off,
    quietly (CPU parity for the fused path is pinned by tests that force
    the flag)."""
    global _fused_bwd_auto
    if FUSED_BWD is not None:
        return FUSED_BWD
    if _fused_bwd_auto is None:
        if jax.default_backend() != "tpu":
            _fused_bwd_auto = False
        else:
            kind = jax.devices()[0].device_kind.lower()
            _fused_bwd_auto = any(
                p in kind for p in FUSED_BWD_VERIFIED_PLATFORMS)
            if not _fused_bwd_auto:
                log.warning(
                    "fused flash-attention backward disabled: no recorded "
                    "verify_fused_bwd.py results for TPU %r — run "
                    "scripts/verify_fused_bwd.py and set FLASH_FUSED_BWD=1 "
                    "to enable", kind,
                )
    return _fused_bwd_auto


def _make_fused(segmented: bool, return_lse: bool):
    """Build the custom-VJP fused attention for one (segmented, lse)
    variant. Unsegmented signature: (q, k, v, bias) — the common path
    carries NO segment operands or VMEM traffic. Segmented adds
    (qseg, kseg): (B,1,Sq)/(B,1,Sk) FLOAT segment ids (all-float
    custom_vjp; zero cotangents). ``return_lse`` additionally returns the
    per-row logsumexp — the chunk primitive for ring attention, whose
    online merge needs lse and therefore flows a cotangent into it.
    Residuals are all O(S·D)/O(S): no score-matrix-shaped tensor is ever
    saved.
    """
    if segmented:
        @jax.custom_vjp
        def fused(q, k, v, bias, qseg, kseg):
            o, lse = _flash_fwd(q, k, v, bias, qseg, kseg,
                                segmented=True, interpret=_interpret())
            return (o, lse) if return_lse else o

        def fwd(q, k, v, bias, qseg, kseg):
            o, lse = _flash_fwd(q, k, v, bias, qseg, kseg,
                                segmented=True, interpret=_interpret())
            out = (o, lse) if return_lse else o
            return out, (q, k, v, bias, qseg, kseg, o, lse)

        def bwd(res, g):
            q, k, v, bias, qseg, kseg, o, lse = res
            do, dlse = g if return_lse else (g, None)
            use_fused = fused_bwd_enabled() and k.shape[2] <= FUSED_BWD_MAX
            dq, dk, dv, dbias = _flash_bwd(
                q, k, v, bias, qseg, kseg, o, lse, do, dlse=dlse,
                segmented=True, interpret=_interpret(),
                fused=use_fused,
                force_stream=use_fused and min(
                    q.shape[2], k.shape[2]) >= fused_whole_k_min(q.dtype))
            return (dq, dk, dv, dbias,
                    jnp.zeros_like(qseg), jnp.zeros_like(kseg))
    else:
        @jax.custom_vjp
        def fused(q, k, v, bias):
            o, lse = _flash_fwd(q, k, v, bias,
                                segmented=False, interpret=_interpret())
            return (o, lse) if return_lse else o

        def fwd(q, k, v, bias):
            o, lse = _flash_fwd(q, k, v, bias,
                                segmented=False, interpret=_interpret())
            out = (o, lse) if return_lse else o
            return out, (q, k, v, bias, o, lse)

        def bwd(res, g):
            q, k, v, bias, o, lse = res
            do, dlse = g if return_lse else (g, None)
            use_fused = fused_bwd_enabled() and k.shape[2] <= FUSED_BWD_MAX
            dq, dk, dv, dbias = _flash_bwd(
                q, k, v, bias, o, lse, do, dlse=dlse,
                segmented=False, interpret=_interpret(),
                fused=use_fused,
                force_stream=use_fused and min(
                    q.shape[2], k.shape[2]) >= fused_whole_k_min(q.dtype))
            return dq, dk, dv, dbias

    fused.defvjp(fwd, bwd)
    return fused


_FUSED = {(seg, lse): _make_fused(seg, lse)
          for seg in (False, True) for lse in (False, True)}


def chunk_supported(s: int) -> bool:
    """Whether a ring chunk of per-shard length ``s`` fits the kernel's
    constraints (the same ones flash_attention_chunk's guards enforce) —
    the single source of truth for dispatch-vs-fallback decisions
    (parallel/ring.py). No upper bound: chunks above MAX_SEQ_VMEM take
    the K-blocked streaming kernels instead of falling back (module
    docstring; VERDICT r3 weak #2)."""
    return s > 0 and s % min(BLOCK_Q, s) == 0


def _seg_f32(seg):
    """(B,1,S) f32 view of integer segment ids for the fused kernels
    (float ids keep the custom_vjp all-float; equality on small ints is
    exact in f32)."""
    return seg.astype(jnp.float32)[:, None, :]


def flash_attention_chunk(q, k, v, bias, q_seg=None, kv_seg=None):
    """Per-chunk fused attention for the ring: (B,S,H,D) q/k/v (equal-length
    shards) + additive key bias (B, Sk) → (o (B,S,H,D), lse (B,S,H,1)).

    ``q_seg``/``kv_seg`` (B, Sq)/(B, Sk) optional packed-sequence segment
    ids: tokens attend only within equal ids (block-diagonal mask).
    ``o`` is normalized *within the chunk*; the caller merges chunks with
    the standard logsumexp reweighting (parallel/ring.py). Differentiable
    in all float inputs including through ``lse``.
    """
    s_q, s_k = q.shape[1], k.shape[1]
    if s_q != s_k or v.shape[1] != s_k:
        # _flash_fwd indexes K/V blocks by q's length; unequal shards
        # would silently read a K/V prefix.
        raise ValueError(
            f"flash_attention_chunk needs equal-length q/k/v shards, got "
            f"q={s_q} k={s_k} v={v.shape[1]}"
        )
    if s_q % min(BLOCK_Q, s_q):
        # The fwd grid is s // block_q: a non-multiple chunk (e.g.
        # seq/ring_shards = 192) would silently drop the tail rows.
        raise ValueError(
            f"chunk len {s_q} must be a multiple of {BLOCK_Q} (or smaller "
            f"than {BLOCK_Q}) — pick mesh.seq so the per-shard chunk "
            f"seq/ring_shards is a {BLOCK_Q}-multiple"
        )
    qt, kt, vt = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    bias_f = bias[:, None, :].astype(jnp.float32)
    if q_seg is None:
        o, lse = _FUSED[(False, True)](qt, kt, vt, bias_f)
    else:
        o, lse = _FUSED[(True, True)](qt, kt, vt, bias_f,
                                      _seg_f32(q_seg), _seg_f32(kv_seg))
    return o.transpose(0, 2, 1, 3), lse.transpose(0, 2, 1, 3)


@functools.partial(jax.jit, static_argnames=("segmented", "interpret"))
def _flash_fwd(q, k, v, bias, qseg=None, kseg=None, *, segmented: bool,
               interpret: bool):
    b, h, s, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_q = min(BLOCK_Q, s)
    if s_k > MAX_SEQ_VMEM:
        return _flash_fwd_kb(q, k, v, bias, qseg, kseg,
                             segmented=segmented, interpret=interpret)
    grid = (b, h, s // block_q)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        pl.BlockSpec((1, 1, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        pl.BlockSpec((1, 1, s_k), lambda bi, hi, qi: (bi, 0, 0)),
    ]
    operands = [q, k, v, bias]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi: (bi, 0, qi)),
            pl.BlockSpec((1, 1, s_k), lambda bi, hi, qi: (bi, 0, 0)),
        ]
        operands += [qseg, kseg]
    return pl.pallas_call(
        functools.partial(_attn_fwd_kernel, scale=scale, segmented=segmented),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        interpret=interpret,
    )(*operands)


def _vmem_scratch(*shapes_dtypes):
    """VMEM scratch specs for the K-blocked kernels (plain buffers under
    interpret mode on CPU)."""
    from jax.experimental.pallas import tpu as pltpu

    return [pltpu.VMEM(shape, dtype) for shape, dtype in shapes_dtypes]


def _pick_block(s: int, target: int) -> int:
    """Largest BLOCK_Q-multiple ≤ ``target`` that divides ``s`` (clamped
    to at least BLOCK_Q, so an env target below the hardware tile floor
    degrades to BLOCK_Q instead of dividing by zero). The dispatch
    guards already force s to be a BLOCK_Q-multiple (or < BLOCK_Q), so
    BLOCK_Q always divides and the loop terminates; non-power-of-two
    lengths like 4224 = 33·128 simply land on a smaller tile."""
    if s <= BLOCK_Q:
        return s
    b = max(BLOCK_Q, min(target - target % BLOCK_Q, s))
    while s % b:
        b -= BLOCK_Q
    return b


def _kb_params(interpret: bool, n_parallel: int = 3):
    """Mosaic grid semantics for the streaming kernels: the leading
    ``n_parallel`` axes are parallel, the rest sequential ("arbitrary").
    The two-pass kernels accumulate only over their innermost axis
    (n_parallel=3); the fused backward reduces over BOTH inner axes
    (n_parallel=2). Interpret mode (CPU tests) takes no TPU compiler
    params."""
    if interpret:
        return {}
    from jax.experimental.pallas import tpu as pltpu

    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("parallel",) * n_parallel
        + ("arbitrary",) * (4 - n_parallel))}


def _flash_fwd_kb(q, k, v, bias, qseg, kseg, *, segmented: bool,
                  interpret: bool):
    """Streaming forward for s_k > MAX_SEQ_VMEM: sequential k-axis grid +
    VMEM-scratch running softmax (kernel docstring)."""
    b, h, s, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_q = _pick_block(s, BLOCK_Q_KB)
    block_k = _pick_block(s_k, BLOCK_K_KB)
    grid = (b, h, s // block_q, s_k // block_k)
    in_specs = [
        pl.BlockSpec((1, 1, block_q, d),
                     lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, block_k, d),
                     lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
    ]
    operands = [q, k, v, bias]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
        ]
        operands += [qseg, kseg]
    return pl.pallas_call(
        functools.partial(_attn_fwd_kernel_kb, scale=scale,
                          segmented=segmented),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        scratch_shapes=_vmem_scratch(
            ((block_q, d), jnp.float32),
            ((block_q, 1), jnp.float32),
            ((block_q, 1), jnp.float32),
        ),
        interpret=interpret,
        **_kb_params(interpret),
    )(*operands)


@functools.partial(jax.jit,
                   static_argnames=("segmented", "interpret", "fused",
                                    "force_stream"))
def _flash_bwd(q, k, v, bias, *seg_then_rest, segmented: bool,
               interpret: bool, dlse=None, fused: bool = False,
               force_stream: bool = False):
    if segmented:
        qseg, kseg, o, lse, do = seg_then_rest
    else:
        qseg = kseg = None
        o, lse, do = seg_then_rest
    b, h, s, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    # delta_i = Σ_d dO_i·O_i — the softmax-jacobian row correction; an
    # O(S·D) elementwise+reduce, cheap in plain XLA.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)        # (B,H,S,1)
    if dlse is not None:
        # lse cotangent (ring-merge path): ∂lse_i/∂s_ij = p_ij, so the
        # contribution folds into ds = p·(dp − delta + dlse) — i.e. the
        # kernels run unchanged with delta := delta − dlse.
        delta = delta - dlse.astype(jnp.float32)

    seg_operands = [qseg, kseg] if segmented else []

    if max(s, s_k) > MAX_SEQ_VMEM or force_stream:
        # force_stream: mid-length sequences take the FUSED streaming
        # backward instead of the whole-K two-pass (FUSED_WHOLE_K_MIN
        # note above). The decision is made at the custom_vjp layer —
        # this function is jitted, so a module-attr read HERE would
        # freeze into the first trace's cache (the _flash_bwd_kb
        # docstring's rule; MAX_SEQ_VMEM predates it and is accepted).
        return _flash_bwd_kb(q, k, v, bias, qseg, kseg, lse, do, delta,
                             segmented=segmented, interpret=interpret,
                             fused=fused)

    block_q = min(BLOCK_Q, s)
    dq_seg_specs = [
        pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi: (bi, 0, qi)),
        pl.BlockSpec((1, 1, s_k), lambda bi, hi, qi: (bi, 0, 0)),
    ] if segmented else []
    dq = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel, scale=scale,
                          segmented=segmented),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s_k, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s_k), lambda bi, hi, qi: (bi, 0, 0)),
        ] + dq_seg_specs + [
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)
        ),
        interpret=interpret,
    )(q, k, v, bias, *seg_operands, do, lse, delta)

    block_k = min(BLOCK_K, s_k)
    dkv_seg_specs = [
        pl.BlockSpec((1, 1, s), lambda bi, hi, ki: (bi, 0, 0)),
        pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki: (bi, 0, ki)),
    ] if segmented else []
    dk, dv, dbias_h = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel, scale=scale,
                          segmented=segmented),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s_k, d), v.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s_k), jnp.float32),
        ],
        grid=(b, h, s_k // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki: (bi, 0, ki)),
        ] + dkv_seg_specs + [
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, 1, block_k), lambda bi, hi, ki: (bi, hi, 0, ki)),
        ],
        interpret=interpret,
    )(q, k, v, bias, *seg_operands, do, lse, delta)
    dbias = jnp.sum(dbias_h, axis=1)               # (B, 1, S): Σ over heads
    return dq, dk, dv, dbias


def _flash_bwd_kb(q, k, v, bias, qseg, kseg, lse, do, delta, *,
                  segmented: bool, interpret: bool, fused: bool = False):
    """Streaming backward for sequences > MAX_SEQ_VMEM: dQ accumulates
    over a sequential k-axis, dK/dV/dbias over a sequential q-axis; no
    whole-sequence operand in VMEM (kernel docstrings). ``fused`` is the
    COMPLETE FLASH_FUSED_BWD ∧ s_k ≤ FUSED_BWD_MAX decision, made at the
    custom_vjp layer OUTSIDE the inner jit — both module attrs are jit-
    invisible, so reading either here would freeze it into the first
    trace's cache."""
    b, h, s, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_q = _pick_block(s, BLOCK_Q_KB)
    block_k = _pick_block(s_k, BLOCK_K_KB)

    if fused:
        return _flash_bwd_fused_kb(q, k, v, bias, qseg, kseg, lse, do,
                                   delta, segmented=segmented,
                                   interpret=interpret)

    seg_operands = [qseg, kseg] if segmented else []
    dq_seg_specs = [
        pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
        pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
    ] if segmented else []
    dq = pl.pallas_call(
        functools.partial(_attn_bwd_dq_kernel_kb, scale=scale,
                          segmented=segmented),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        grid=(b, h, s // block_q, s_k // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
        ] + dq_seg_specs + [
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)
        ),
        scratch_shapes=_vmem_scratch(((block_q, d), jnp.float32)),
        interpret=interpret,
        **_kb_params(interpret),
    )(q, k, v, bias, *seg_operands, do, lse, delta)

    dkv_seg_specs = [
        pl.BlockSpec((1, 1, block_q), lambda bi, hi, ki, qi: (bi, 0, qi)),
        pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki, qi: (bi, 0, ki)),
    ] if segmented else []
    dk, dv, dbias_h = pl.pallas_call(
        functools.partial(_attn_bwd_dkv_kernel_kb, scale=scale,
                          segmented=segmented),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s_k, d), v.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s_k), jnp.float32),
        ],
        grid=(b, h, s_k // block_k, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, ki, qi: (bi, 0, ki)),
        ] + dkv_seg_specs + [
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, ki, qi: (bi, hi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, ki, qi: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda bi, hi, ki, qi: (bi, hi, 0, ki)),
        ],
        scratch_shapes=_vmem_scratch(
            ((block_k, d), jnp.float32),
            ((block_k, d), jnp.float32),
            ((1, block_k), jnp.float32),
        ),
        interpret=interpret,
        **_kb_params(interpret),
    )(q, k, v, bias, *seg_operands, do, lse, delta)
    dbias = jnp.sum(dbias_h, axis=1)               # (B, 1, S): Σ over heads
    return dq, dk, dv, dbias


def _flash_bwd_fused_kb(q, k, v, bias, qseg, kseg, lse, do, delta, *,
                        segmented: bool, interpret: bool):
    """One-pass streaming backward (FLASH_FUSED_BWD; kernel docstring):
    one grid, one exp per (q-block, k-block) pair, full-length dk/dv
    VMEM accumulators — gated to s_k ≤ FUSED_BWD_MAX by the caller."""
    b, h, s, d = q.shape
    s_k = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    block_q = _pick_block(s, BLOCK_Q_KB)
    block_k = _pick_block(s_k, BLOCK_K_KB)

    seg_operands = [qseg, kseg] if segmented else []
    seg_specs = [
        pl.BlockSpec((1, 1, block_q), lambda bi, hi, qi, ki: (bi, 0, qi)),
        pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
    ] if segmented else []
    dq, dk, dv, dbias_h = pl.pallas_call(
        functools.partial(_attn_bwd_fused_kernel_kb, scale=scale,
                          segmented=segmented),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s_k, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, s_k, d), v.dtype),
            jax.ShapeDtypeStruct((b, h, 1, s_k), jnp.float32),
        ],
        grid=(b, h, s // block_q, s_k // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k), lambda bi, hi, qi, ki: (bi, 0, ki)),
        ] + seg_specs + [
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, 1, block_k),
                         lambda bi, hi, qi, ki: (bi, hi, 0, ki)),
        ],
        scratch_shapes=_vmem_scratch(
            ((block_q, d), jnp.float32),
            ((s_k, d), jnp.float32),
            ((s_k, d), jnp.float32),
            ((1, s_k), jnp.float32),
        ),
        interpret=interpret,
        **_kb_params(interpret, n_parallel=2),
    )(q, k, v, bias, *seg_operands, do, lse, delta)
    dbias = jnp.sum(dbias_h, axis=1)               # (B, 1, S): Σ over heads
    return dq, dk, dv, dbias


def flash_attention(q, k, v, *, mask=None, segment_ids=None):
    """Fused attention. q,k,v: (B, S, H, D); mask: (B,1,1,S) bool or None;
    segment_ids: (B, S) int packed-sequence ids or None — tokens attend
    only within equal ids (block-diagonal mask computed INSIDE the kernel
    from O(S) ids, so packing never materializes an S×S mask).

    Returns (B, S, H, D) in q's dtype. Differentiable end to end with
    Pallas forward AND backward kernels (module docstring).
    """
    b, s, hh, d = q.shape
    if s % min(BLOCK_Q, s):
        raise ValueError(f"seq len {s} must be a multiple of {BLOCK_Q}")
    # (B, S, H, D) → (B, H, S, D) for contiguous per-head blocks.
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    if mask is not None:
        bias = jnp.where(mask[:, 0, :, :], 0.0, NEG_INF).astype(jnp.float32)
    else:
        bias = jnp.zeros((b, 1, s), jnp.float32)
    if segment_ids is None:
        out = _FUSED[(False, False)](qt, kt, vt, bias)
    else:
        seg = _seg_f32(segment_ids)
        out = _FUSED[(True, False)](qt, kt, vt, bias, seg, seg)
    return out.transpose(0, 2, 1, 3)
