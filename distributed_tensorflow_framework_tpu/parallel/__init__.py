"""Parallelism: sharding rules, collectives, shard_map training path.

The reference's parallelism story is synchronous data parallelism
(SyncReplicasOptimizer + NCCL all-reduce) plus an async parameter-server
mode (SURVEY.md §2 rows 3–4). Here:

  sharding.py     param/batch PartitionSpec rules: DP (replicated params),
                  FSDP (ZeRO-style), TP (megatron-style for transformer
                  blocks) — all expressed against the canonical 4-axis mesh
  collectives.py  thin named wrappers over psum/pmean/all_gather/ppermute/
                  reduce_scatter (the XLA/ICI equivalent of NCCL calls)
  ring.py         ring attention: sequence-parallel exact attention via
                  ppermute over the ``seq`` axis (long-context support)
"""

from distributed_tensorflow_framework_tpu.parallel.sharding import (  # noqa: F401
    infer_param_specs,
    shard_pytree,
)
