"""Named collective wrappers — the XLA/ICI analogue of the NCCL call sites.

The reference's gradient aggregation is NCCL all-reduce hidden inside
``SyncReplicasOptimizer`` (SURVEY.md §2 row 3 + native rows); its variable
traffic is grpc to the PS. Under SPMD both collapse into XLA collectives
emitted inside jit/shard_map and scheduled on ICI (intra-slice) or DCN
(inter-slice) by the compiler. These wrappers exist so call sites name the
intent (``allreduce_gradients``) rather than the primitive, and so the
shard_map training path reads like the reference's pipeline.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

DATA_AXES = ("data", "fsdp")


def allreduce_gradients(
    grads: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    compute_dtype: Any = None,
    accumulate_f32: bool = True,
) -> Any:
    """Mean-reduce gradients across data-parallel replicas (sync-DP core).

    ``compute_dtype`` (e.g. jnp.bfloat16) compresses the all-reduce wire
    format — the block-free core of the EQuARX idea (PAPERS.md: quantized
    all-reduce). Two accumulation modes:

    ``accumulate_f32=True`` (default): reduce-scatter the gradients at
    full precision (f32 adds), then all-gather the reduced shard in the
    narrow dtype. Collective bytes per link: (n-1)/n·G·(4+2) = 6/8 of an
    f32 ring all-reduce. Precision loss is dominated by ONE rounding of
    the final mean to the narrow dtype — effectively independent of
    replica count (the f32 adds still round at f32 eps, ~2^-15 below the
    bf16 quantum) — safe at the multislice/DCN scale (n≫8) this feature
    targets.

    ``accumulate_f32=False`` (opt-in): pure narrow-dtype pmean. Bytes:
    4/8 of f32 — the maximum compression — but both the wire AND the
    reduction are narrow: each of the ~log2(n) reduction adds contributes
    bf16-level relative error, so the mean degrades with replica count
    (the bf16-vs-f32 trajectory test bounds it at n=8). Use only when the
    extra 2 bytes/element of the f32 scatter phase actually binds and the
    optimizer tolerates the noise.
    """
    if compute_dtype is None:
        return jax.tree.map(lambda g: lax.pmean(g, axis_names), grads)
    compute_dtype = jnp.dtype(compute_dtype)

    if not accumulate_f32 or compute_dtype.itemsize >= 4:
        def reduce(g):
            return lax.pmean(g.astype(compute_dtype), axis_names).astype(g.dtype)

        return jax.tree.map(reduce, grads)

    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    n = 1
    for a in axes:
        n *= lax.axis_size(a)

    def reduce(g):
        flat = g.astype(jnp.float32).reshape(-1)
        pad = (-flat.size) % n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # Exact f32 adds on the scatter; the only lossy step is the final
        # narrow-dtype representation of the already-reduced mean.
        shard = lax.psum_scatter(flat, axes, scatter_dimension=0, tiled=True) / n
        full = lax.all_gather(shard.astype(compute_dtype), axes, axis=0, tiled=True)
        return full[: g.size].astype(g.dtype).reshape(g.shape)

    return jax.tree.map(reduce, grads)


def psum(x: Any, axis_names: Sequence[str] | str) -> Any:
    return jax.tree.map(lambda v: lax.psum(v, axis_names), x)


def pmean(x: Any, axis_names: Sequence[str] | str) -> Any:
    return jax.tree.map(lambda v: lax.pmean(v, axis_names), x)


def all_gather(x: jax.Array, axis_name: str, *, axis: int = 0, tiled: bool = True) -> jax.Array:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str, *, scatter_axis: int = 0) -> jax.Array:
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ppermute_shift(x: jax.Array, axis_name: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: send to (i + shift) mod N — the ring-attention primitive."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
