"""Named collective wrappers — the XLA/ICI analogue of the NCCL call sites.

The reference's gradient aggregation is NCCL all-reduce hidden inside
``SyncReplicasOptimizer`` (SURVEY.md §2 row 3 + native rows); its variable
traffic is grpc to the PS. Under SPMD both collapse into XLA collectives
emitted inside jit/shard_map and scheduled on ICI (intra-slice) or DCN
(inter-slice) by the compiler. These wrappers exist so call sites name the
intent (``allreduce_gradients``) rather than the primitive, and so the
shard_map training path reads like the reference's pipeline.
"""

from __future__ import annotations

import contextlib
from typing import Any, Iterator, Sequence

import jax
import jax.numpy as jnp
from jax import lax

DATA_AXES = ("data", "fsdp")


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside shard_map, across jax versions.

    ``lax.axis_size`` is newer than 0.4; ``lax.psum`` of a Python literal
    has always constant-folded to ``size * x`` at trace time, so it
    yields the same static int on old jaxlibs.
    """
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    The public ``jax.shard_map`` (with ``check_vma``) landed after 0.4;
    earlier jaxlibs only have ``jax.experimental.shard_map.shard_map``
    whose equivalent knob is ``check_rep``. All in-repo call sites go
    through this wrapper so the version split lives in one place.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm

    return legacy_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


class CollectiveTally:
    """Per-collective call and byte counters, recorded at JAX *trace* time.

    Every wrapper below reports (kind, payload bytes) for each leaf it
    lowers while a tally is active. Because jit traces once per shape,
    wrap the FIRST dispatch (or an explicit lower/compile) in ``tally()``
    and the numbers describe every subsequent step of that executable.

    Bytes are the logical per-device payload at the collective's wire
    dtype (size × itemsize of the reduced/gathered operand) — the
    topology-independent quantity. Per-link ring traffic is
    ``(n-1)/n × payload`` for reduce/gather collectives; readers that
    want wire bytes apply that factor with their own axis size.
    """

    def __init__(self) -> None:
        self.calls: dict[str, int] = {}
        self.bytes: dict[str, int] = {}

    def record(self, kind: str, nbytes: int) -> None:
        self.calls[kind] = self.calls.get(kind, 0) + 1
        self.bytes[kind] = self.bytes.get(kind, 0) + int(nbytes)

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    def summary(self) -> dict[str, int]:
        """Flat dict for the telemetry event's ``collectives`` field."""
        out: dict[str, int] = {}
        for kind in sorted(self.calls):
            out[f"{kind}_calls"] = self.calls[kind]
            out[f"{kind}_bytes"] = self.bytes[kind]
        out["total_bytes"] = self.total_bytes
        return out


_TALLY_STACK: list[CollectiveTally] = []


@contextlib.contextmanager
def tally() -> Iterator[CollectiveTally]:
    """Collect collective byte counters from wrappers traced inside."""
    t = CollectiveTally()
    _TALLY_STACK.append(t)
    try:
        yield t
    finally:
        _TALLY_STACK.remove(t)


def _record(kind: str, leaf: Any, dtype: Any = None) -> None:
    if not _TALLY_STACK:
        return
    try:
        size = leaf.size
        itemsize = jnp.dtype(dtype or leaf.dtype).itemsize
    except Exception:  # non-array leaf (python scalar etc.)
        size, itemsize = 1, 4
    for t in _TALLY_STACK:
        t.record(kind, size * itemsize)


def allreduce_gradients(
    grads: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    compute_dtype: Any = None,
    accumulate_f32: bool = True,
) -> Any:
    """Mean-reduce gradients across data-parallel replicas (sync-DP core).

    ``compute_dtype`` (e.g. jnp.bfloat16) compresses the all-reduce wire
    format — the block-free core of the EQuARX idea (PAPERS.md: quantized
    all-reduce). Two accumulation modes:

    ``accumulate_f32=True`` (default): reduce-scatter the gradients at
    full precision (f32 adds), then all-gather the reduced shard in the
    narrow dtype. Collective bytes per link: (n-1)/n·G·(4+2) = 6/8 of an
    f32 ring all-reduce. Precision loss is dominated by ONE rounding of
    the final mean to the narrow dtype — effectively independent of
    replica count (the f32 adds still round at f32 eps, ~2^-15 below the
    bf16 quantum) — safe at the multislice/DCN scale (n≫8) this feature
    targets.

    ``accumulate_f32=False`` (opt-in): pure narrow-dtype pmean. Bytes:
    4/8 of f32 — the maximum compression — but both the wire AND the
    reduction are narrow: each of the ~log2(n) reduction adds contributes
    bf16-level relative error, so the mean degrades with replica count
    (the bf16-vs-f32 trajectory test bounds it at n=8). Use only when the
    extra 2 bytes/element of the f32 scatter phase actually binds and the
    optimizer tolerates the noise.
    """
    if compute_dtype is None:
        def reduce(g):
            _record("allreduce_grads_pmean", g)
            return lax.pmean(g, axis_names)

        return jax.tree.map(reduce, grads)
    compute_dtype = jnp.dtype(compute_dtype)

    if not accumulate_f32 or compute_dtype.itemsize >= 4:
        def reduce(g):
            _record("allreduce_grads_pmean_narrow", g, compute_dtype)
            return lax.pmean(g.astype(compute_dtype), axis_names).astype(g.dtype)

        return jax.tree.map(reduce, grads)

    axes = (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)
    n = 1
    for a in axes:
        n *= axis_size(a)

    def reduce(g):
        flat = g.astype(jnp.float32).reshape(-1)
        pad = (-flat.size) % n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        # Exact f32 adds on the scatter; the only lossy step is the final
        # narrow-dtype representation of the already-reduced mean.
        _record("allreduce_grads_scatter_f32", flat)
        shard = lax.psum_scatter(flat, axes, scatter_dimension=0, tiled=True) / n
        narrow = shard.astype(compute_dtype)
        _record("allreduce_grads_gather_narrow", narrow)
        full = lax.all_gather(narrow, axes, axis=0, tiled=True)
        return full[: g.size].astype(g.dtype).reshape(g.shape)

    return jax.tree.map(reduce, grads)


def psum(x: Any, axis_names: Sequence[str] | str) -> Any:
    def op(v):
        _record("psum", v)
        return lax.psum(v, axis_names)

    return jax.tree.map(op, x)


def pmean(x: Any, axis_names: Sequence[str] | str) -> Any:
    def op(v):
        _record("pmean", v)
        return lax.pmean(v, axis_names)

    return jax.tree.map(op, x)


def all_gather(x: jax.Array, axis_name: str, *, axis: int = 0, tiled: bool = True) -> jax.Array:
    _record("all_gather", x)
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str, *, scatter_axis: int = 0) -> jax.Array:
    _record("reduce_scatter", x)
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ppermute_shift(x: jax.Array, axis_name: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: send to (i + shift) mod N — the ring-attention primitive."""
    _record("ppermute", x)
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
