"""Named collective wrappers — the XLA/ICI analogue of the NCCL call sites.

The reference's gradient aggregation is NCCL all-reduce hidden inside
``SyncReplicasOptimizer`` (SURVEY.md §2 row 3 + native rows); its variable
traffic is grpc to the PS. Under SPMD both collapse into XLA collectives
emitted inside jit/shard_map and scheduled on ICI (intra-slice) or DCN
(inter-slice) by the compiler. These wrappers exist so call sites name the
intent (``allreduce_gradients``) rather than the primitive, and so the
shard_map training path reads like the reference's pipeline.

Quantized wire formats (``parallel.collective_dtype``, docs/PERFORMANCE.md):
``all_gather`` / ``reduce_scatter`` / the gradient all-reduce accept a
``wire_dtype`` — ``bfloat16`` casts the payload, ``int8`` applies the
EQuARX block-scaled protocol (parallel/quantization.py): per-block max-abs
scales ride the wire next to the int8 payload, partials are dequantized and
accumulated in f32, and the reduced result is requantized for the gather
phase. ``allreduce_gradients_ef`` adds the error-feedback residual so the
compression error is compensated on the next step rather than accumulated.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Any, Iterator, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from distributed_tensorflow_framework_tpu.parallel.quantization import (
    DEFAULT_BLOCK_SIZE,
    SCALE_BYTES,
    dequantize_blockwise,
    quantize_blockwise,
)

log = logging.getLogger(__name__)

DATA_AXES = ("data", "fsdp")

# The tally's grand-total fields — every one must surface in the
# core/telemetry.py rollups (audited by tests/test_marker_audit.py).
TALLY_TOTAL_FIELDS = ("total_bytes", "total_logical_bytes")


def axis_size(axis_name) -> int:
    """Static mesh-axis size inside shard_map, across jax versions.

    ``lax.axis_size`` is newer than 0.4; ``lax.psum`` of a Python literal
    has always constant-folded to ``size * x`` at trace time, so it
    yields the same static int on old jaxlibs.
    """
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


def _axes_tuple(axis_names) -> tuple:
    return (axis_names,) if isinstance(axis_names, str) else tuple(axis_names)


def _axes_size(axis_names) -> int:
    n = 1
    for a in _axes_tuple(axis_names):
        n *= axis_size(a)
    return n


def linear_axis_index(axis_names) -> jax.Array:
    """Linearized device index over an axis tuple, first axis major —
    the same ordering multi-axis collectives use to stack/route shards
    (asserted against ``all_gather(tiled=False)`` row order in
    tests/test_compressed_allreduce.py)."""
    idx = jnp.zeros((), jnp.int32)
    for a in _axes_tuple(axis_names):
        idx = idx * axis_size(a) + lax.axis_index(a)
    return idx


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    The public ``jax.shard_map`` (with ``check_vma``) landed after 0.4;
    earlier jaxlibs only have ``jax.experimental.shard_map.shard_map``
    whose equivalent knob is ``check_rep``. All in-repo call sites go
    through this wrapper so the version split lives in one place.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as legacy_sm

    return legacy_sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


class CollectiveTally:
    """Per-collective call and byte counters, recorded at JAX *trace* time.

    Every wrapper below reports (kind, wire bytes, logical bytes) for each
    leaf it lowers while a tally is active. Because jit traces once per
    shape, wrap the FIRST dispatch (or an explicit lower/compile) in
    ``tally()`` and the numbers describe every subsequent step of that
    executable.

    Byte convention — per-device bytes crossing the links, with the
    topology-dependent ``(n-1)/n`` ring factor dropped:

      * all-reduce (psum/pmean): 2 × payload (reduce-scatter phase +
        all-gather phase of the ring algorithm);
      * reduce-scatter / all_to_all / ppermute: 1 × input payload;
      * all-gather: 1 × OUTPUT payload (each device receives the full
        gathered array, n × its shard).

    ``wire`` bytes are at the collective's wire dtype plus any block-scale
    overhead (parallel/quantization.py); ``logical`` bytes are the same
    traffic at the operand's logical dtype — their ratio is the wire
    compression the telemetry rollup reports.
    """

    def __init__(self) -> None:
        self.calls: dict[str, int] = {}
        self.bytes: dict[str, int] = {}          # wire bytes
        self.logical_bytes: dict[str, int] = {}

    def record(self, kind: str, nbytes: int, logical_bytes: int | None = None) -> None:
        self.calls[kind] = self.calls.get(kind, 0) + 1
        self.bytes[kind] = self.bytes.get(kind, 0) + int(nbytes)
        self.logical_bytes[kind] = self.logical_bytes.get(kind, 0) + int(
            nbytes if logical_bytes is None else logical_bytes)

    @property
    def total_bytes(self) -> int:
        return sum(self.bytes.values())

    @property
    def total_logical_bytes(self) -> int:
        return sum(self.logical_bytes.values())

    def summary(self) -> dict[str, int]:
        """Flat dict for the telemetry event's ``collectives`` field.

        ``{kind}_bytes`` is the wire traffic; ``{kind}_logical_bytes``
        appears only when a narrow wire format made it differ, so the
        uncompressed common case stays compact.
        """
        out: dict[str, int] = {}
        for kind in sorted(self.calls):
            out[f"{kind}_calls"] = self.calls[kind]
            out[f"{kind}_bytes"] = self.bytes[kind]
            if self.logical_bytes[kind] != self.bytes[kind]:
                out[f"{kind}_logical_bytes"] = self.logical_bytes[kind]
        out["total_bytes"] = self.total_bytes
        out["total_logical_bytes"] = self.total_logical_bytes
        return out


_TALLY_STACK: list[CollectiveTally] = []


@contextlib.contextmanager
def tally() -> Iterator[CollectiveTally]:
    """Collect collective byte counters from wrappers traced inside."""
    t = CollectiveTally()
    _TALLY_STACK.append(t)
    try:
        yield t
    finally:
        _TALLY_STACK.remove(t)


def _record(kind: str, leaf: Any, *, wire_dtype: Any = None,
            logical_dtype: Any = None, multiplier: int = 1,
            overhead_bytes: int = 0) -> None:
    """Tally one collective over ``leaf``.

    ``multiplier`` carries the convention factor (2 for all-reduce, the
    axis size for all-gather's output payload); ``overhead_bytes`` is the
    extra wire traffic of a block-scaled format (the f32 scales). A leaf
    with no size/dtype (python scalar etc.) is SKIPPED with a debug log —
    it lowers to a scalar fast-path, and the old silent assume-4-bytes
    fallback miscounted exactly the compressed paths this tally exists
    to A/B.
    """
    if not _TALLY_STACK:
        return
    size = getattr(leaf, "size", None)
    ldt = logical_dtype if logical_dtype is not None else getattr(leaf, "dtype", None)
    if size is None or ldt is None:
        log.debug("collective tally: skipping non-array %s operand of type %s",
                  kind, type(leaf).__name__)
        return
    logical = int(size) * jnp.dtype(ldt).itemsize * multiplier
    # Wire dtype: explicit > the leaf's own dtype (a pre-narrowed operand
    # like the bf16 gather phase) > the logical dtype.
    wdt = (wire_dtype if wire_dtype is not None
           else getattr(leaf, "dtype", ldt))
    wire = int(size) * jnp.dtype(wdt).itemsize * multiplier + overhead_bytes
    for t in _TALLY_STACK:
        t.record(kind, wire, logical)


def _canon_wire(wire_dtype: Any):
    """None/"" → None, else a jnp dtype."""
    if wire_dtype is None or wire_dtype == "":
        return None
    return jnp.dtype(wire_dtype)


def _pad_to(flat: jax.Array, multiple: int) -> jax.Array:
    pad = (-flat.size) % multiple
    return jnp.pad(flat, (0, pad)) if pad else flat


# --------------------------------------------------------- all-reduce ----
def allreduce_gradients(
    grads: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    compute_dtype: Any = None,
    accumulate_f32: bool = True,
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> Any:
    """Mean-reduce gradients across data-parallel replicas (sync-DP core).

    ``compute_dtype`` (e.g. jnp.bfloat16) compresses the all-reduce wire
    format — the block-free core of the EQuARX idea (PAPERS.md: quantized
    all-reduce). Two accumulation modes:

    ``accumulate_f32=True`` (default): reduce-scatter the gradients at
    full precision (f32 adds), then all-gather the reduced shard in the
    narrow dtype. Wire bytes: 6/8 of an f32 ring all-reduce. Precision
    loss is dominated by ONE rounding of the final mean to the narrow
    dtype — effectively independent of replica count (the f32 adds still
    round at f32 eps, ~2^-15 below the bf16 quantum) — safe at the
    multislice/DCN scale (n≫8) this feature targets.

    ``accumulate_f32=False`` (opt-in): pure narrow-dtype pmean. Wire
    bytes: 4/8 of f32 — the maximum bf16 compression — but both the wire
    AND the reduction are narrow: each of the ~log2(n) reduction adds
    contributes bf16-level relative error, so the mean degrades with
    replica count (the bf16-vs-f32 trajectory test bounds it at n=8).

    ``compute_dtype=int8`` dispatches to the block-scaled protocol
    (:func:`allreduce_gradients_ef` without a residual): ~2/8 of f32
    wire bytes, f32 accumulation of dequantized partials. For training
    use the error-feedback variant so the block rounding is compensated.
    """
    wire = _canon_wire(compute_dtype)
    if wire == jnp.int8:
        means, _ = allreduce_gradients_ef(
            grads, None, axis_names, block_size=block_size)
        return means
    if wire is None:
        def reduce(g):
            _record("allreduce_grads_pmean", g, multiplier=2)
            return lax.pmean(g, axis_names)

        return jax.tree.map(reduce, grads)

    if not accumulate_f32 or wire.itemsize >= 4:
        def reduce(g):
            _record("allreduce_grads_pmean_narrow", g, wire_dtype=wire,
                    multiplier=2)
            return lax.pmean(g.astype(wire), axis_names).astype(g.dtype)

        return jax.tree.map(reduce, grads)

    axes = _axes_tuple(axis_names)
    n = _axes_size(axes)

    def reduce(g):
        flat = g.astype(jnp.float32).reshape(-1)
        flat = _pad_to(flat, n)
        # Exact f32 adds on the scatter; the only lossy step is the final
        # narrow-dtype representation of the already-reduced mean.
        _record("allreduce_grads_scatter_f32", flat)
        shard = lax.psum_scatter(flat, axes, scatter_dimension=0, tiled=True) / n
        narrow = shard.astype(wire)
        _record("allreduce_grads_gather_narrow", narrow, logical_dtype=jnp.float32,
                multiplier=n)
        full = lax.all_gather(narrow, axes, axis=0, tiled=True)
        return full[: g.size].astype(g.dtype).reshape(g.shape)

    return jax.tree.map(reduce, grads)


def allreduce_gradients_ef(
    grads: Any,
    residuals: Any | None,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> tuple[Any, Any | None]:
    """Block-scaled int8 all-reduce-mean with error feedback.

    The EQuARX protocol per leaf, all inside one shard_map trace:

      1. compensate: ``c = g + r`` (``r`` is this device's residual);
      2. quantize ``c`` blockwise, int8 payload + f32 scales;
      3. scatter: one ``all_to_all`` routes chunk ``p`` of every device
         to device ``p`` (the reduce-scatter phase, int8 on the wire);
      4. accumulate the dequantized partials in f32, divide by n;
      5. requantize the reduced chunk, ``all_gather`` it (int8 wire);
      6. dequantize everyone's chunks — every device now holds the same
         compressed mean ``D(Q(m))``.

    The new residual carries BOTH lossy steps forward so nothing is
    silently dropped: ``r' = e1 + n·e2[own chunk]`` where ``e1`` is the
    local quantization error ``c - D(Q(c))`` and ``e2`` the chunk owner's
    requantization error ``m - D(Q(m))``. Summed over devices,
    ``mean(r') = mean(e1) + e2 = true_mean - D(Q(m))`` — exactly the
    gradient signal this step's update missed, re-injected next step.

    ``residuals=None`` disables error feedback (single-shot mean, new
    residual returned as None). Padding to a whole number of blocks per
    chunk adds zero elements whose quantization error is exactly zero.
    """
    axes = _axes_tuple(axis_names)
    n = _axes_size(axes)
    idx = linear_axis_index(axes)

    def reduce(g, r):
        flat = _pad_to(g.astype(jnp.float32).reshape(-1), n * block_size)
        if r is not None:
            flat = flat + _pad_to(r.astype(jnp.float32).reshape(-1),
                                  n * block_size)
        chunk = flat.size // n
        rows = flat.reshape(n, chunk)
        q, scales = jax.vmap(lambda v: quantize_blockwise(v, block_size))(rows)
        _record("allreduce_grads_q8_scatter", q, wire_dtype=jnp.int8,
                logical_dtype=jnp.float32,
                overhead_bytes=scales.size * SCALE_BYTES)
        # Device p receives row p of every device: all partials of chunk p.
        qx = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=False)
        sx = lax.all_to_all(scales, axes, split_axis=0, concat_axis=0,
                            tiled=False)
        partials = jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, block_size))(qx, sx)
        mean_chunk = partials.sum(axis=0) / n
        q2, s2 = quantize_blockwise(mean_chunk, block_size)
        _record("allreduce_grads_q8_gather", q2, wire_dtype=jnp.int8,
                logical_dtype=jnp.float32, multiplier=n,
                overhead_bytes=n * s2.size * SCALE_BYTES)
        qg = lax.all_gather(q2, axes, axis=0, tiled=False)   # row j = chunk j
        sg = lax.all_gather(s2, axes, axis=0, tiled=False)
        mean_full = jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, block_size))(qg, sg)
        mean = mean_full.reshape(-1)[: g.size].astype(g.dtype).reshape(g.shape)
        if r is None:
            return mean, None
        # e1 everywhere, plus n·e2 on the chunk this device reduced (the
        # n· undoes next step's mean so e2 is re-injected at full weight).
        e1 = flat - jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, block_size)
        )(q, scales).reshape(-1)
        e2 = mean_chunk - dequantize_blockwise(q2, s2, block_size)
        own = lax.dynamic_slice(e1, (idx * chunk,), (chunk,))
        new_r = lax.dynamic_update_slice(e1, own + n * e2, (idx * chunk,))
        return mean, new_r[: g.size].reshape(g.shape).astype(jnp.float32)

    if residuals is None:
        means = jax.tree.map(lambda g: reduce(g, None)[0], grads)
        return means, None
    pairs = jax.tree.map(reduce, grads, residuals)
    means = jax.tree.map(lambda p: p[0], pairs, is_leaf=lambda p: isinstance(p, tuple))
    new_res = jax.tree.map(lambda p: p[1], pairs, is_leaf=lambda p: isinstance(p, tuple))
    return means, new_res


# ------------------------------------------------------ other wrappers ----
def psum(x: Any, axis_names: Sequence[str] | str) -> Any:
    def op(v):
        _record("psum", v, multiplier=2)
        return lax.psum(v, axis_names)

    return jax.tree.map(op, x)


def pmean(x: Any, axis_names: Sequence[str] | str) -> Any:
    def op(v):
        _record("pmean", v, multiplier=2)
        return lax.pmean(v, axis_names)

    return jax.tree.map(op, x)


def all_gather(x: jax.Array, axis_name, *, axis: int = 0, tiled: bool = True,
               wire_dtype: Any = None,
               block_size: int = DEFAULT_BLOCK_SIZE,
               kind: str = "all_gather") -> jax.Array:
    """All-gather with an optional narrow wire format.

    ``bfloat16`` casts the payload (lossy for f32 operands — no error
    feedback exists for gathered values, see docs/PERFORMANCE.md);
    ``int8`` ships block-scaled int8 and dequantizes on arrival. The
    fsdp param gather (train/step.py) is the hot call site. ``kind``
    relabels the tally row for call sites that need their bytes
    attributed separately (the ZeRO update gather, parallel/zero.py).
    """
    wire = _canon_wire(wire_dtype)
    n = _axes_size(axis_name)
    if wire is None or wire == x.dtype:
        _record(kind, x, multiplier=n)
        return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)
    if wire != jnp.int8:
        _record(kind, x, wire_dtype=wire, multiplier=n)
        return lax.all_gather(x.astype(wire), axis_name, axis=axis,
                              tiled=tiled).astype(x.dtype)
    flat = _pad_to(x.astype(jnp.float32).reshape(-1), block_size)
    q, scales = quantize_blockwise(flat, block_size)
    _record(kind, x, wire_dtype=jnp.int8, multiplier=n,
            overhead_bytes=n * scales.size * SCALE_BYTES)
    qg = lax.all_gather(q, axis_name, axis=0, tiled=False)       # (n, padded)
    sg = lax.all_gather(scales, axis_name, axis=0, tiled=False)
    deq = jax.vmap(lambda qq, ss: dequantize_blockwise(qq, ss, block_size))(qg, sg)
    stacked = deq[:, : x.size].reshape((n,) + x.shape).astype(x.dtype)
    if not tiled:
        return jnp.moveaxis(stacked, 0, axis)
    moved = jnp.moveaxis(stacked, 0, axis)  # (..., n, shard_k, ...)
    shape = list(x.shape)
    shape[axis] = n * x.shape[axis]
    return moved.reshape(shape)


def reduce_scatter(x: jax.Array, axis_name, *, scatter_axis: int = 0,
                   wire_dtype: Any = None,
                   block_size: int = DEFAULT_BLOCK_SIZE,
                   kind: str = "reduce_scatter") -> jax.Array:
    """Reduce-scatter (sum) with an optional narrow wire format.

    The int8 path quantizes each destination's chunk independently (so
    scales travel with their chunk), routes chunks with one
    ``all_to_all``, and accumulates the dequantized partials in f32 —
    the scatter half of the EQuARX all-reduce, usable standalone for
    ZeRO-2-style scattered grad updates. ``kind`` relabels the tally row
    for call sites needing separate byte attribution (parallel/zero.py).
    """
    wire = _canon_wire(wire_dtype)
    if wire is None or wire == x.dtype:
        _record(kind, x)
        return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                                tiled=True)
    if wire != jnp.int8:
        # Narrow-float wire AND accumulation (document at call sites).
        _record(kind, x, wire_dtype=wire)
        return lax.psum_scatter(
            x.astype(wire), axis_name, scatter_dimension=scatter_axis,
            tiled=True).astype(x.dtype)
    axes = _axes_tuple(axis_name)
    n = _axes_size(axes)
    if x.shape[scatter_axis] % n:
        raise ValueError(
            f"reduce_scatter axis {scatter_axis} of shape {x.shape} does "
            f"not divide the axis size {n}")
    moved = jnp.moveaxis(x.astype(jnp.float32), scatter_axis, 0)
    rows = moved.reshape(n, -1)                      # row p = chunk for dev p
    rows = jax.vmap(lambda v: _pad_to(v, block_size))(rows)
    q, scales = jax.vmap(lambda v: quantize_blockwise(v, block_size))(rows)
    _record(kind, x, wire_dtype=jnp.int8,
            overhead_bytes=scales.size * SCALE_BYTES)
    qx = lax.all_to_all(q, axes, split_axis=0, concat_axis=0, tiled=False)
    sx = lax.all_to_all(scales, axes, split_axis=0, concat_axis=0, tiled=False)
    partials = jax.vmap(
        lambda qq, ss: dequantize_blockwise(qq, ss, block_size))(qx, sx)
    chunk_elems = moved.size // n
    summed = partials.sum(axis=0)[:chunk_elems]
    shard_shape = (moved.shape[0] // n,) + moved.shape[1:]
    return jnp.moveaxis(summed.reshape(shard_shape), 0,
                        scatter_axis).astype(x.dtype)


def ppermute_shift(x: jax.Array, axis_name: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: send to (i + shift) mod N — the ring-attention primitive."""
    _record("ppermute", x)
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
