"""Named collective wrappers — the XLA/ICI analogue of the NCCL call sites.

The reference's gradient aggregation is NCCL all-reduce hidden inside
``SyncReplicasOptimizer`` (SURVEY.md §2 row 3 + native rows); its variable
traffic is grpc to the PS. Under SPMD both collapse into XLA collectives
emitted inside jit/shard_map and scheduled on ICI (intra-slice) or DCN
(inter-slice) by the compiler. These wrappers exist so call sites name the
intent (``allreduce_gradients``) rather than the primitive, and so the
shard_map training path reads like the reference's pipeline.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from jax import lax

DATA_AXES = ("data", "fsdp")


def allreduce_gradients(
    grads: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    compute_dtype: Any = None,
) -> Any:
    """Mean-reduce gradients across data-parallel replicas (sync-DP core).

    ``compute_dtype`` (e.g. jnp.bfloat16) compresses the all-reduce wire
    format: grads are cast down before the pmean and restored after —
    halving collective bytes, which matters most when the reduction spans
    DCN (multislice). This is the block-free core of the EQuARX idea
    (PAPERS.md: quantized all-reduce).

    Precision: both the wire format AND the reduction accumulate in the
    narrow dtype. The cast costs one bf16 round-trip (~3 significant
    digits) and each of the log2(n) reduction adds contributes bf16-level
    relative error, so the mean degrades slowly with replica count —
    acceptable for SGD-class training at practical n (the bf16-vs-f32
    trajectory test bounds it at n=8), but keep the default f32 wire when
    gradients are ill-scaled (e.g. fp16 without loss scaling) or when
    reproducing a reference trajectory exactly.
    """
    if compute_dtype is None:
        return jax.tree.map(lambda g: lax.pmean(g, axis_names), grads)

    def reduce(g):
        return lax.pmean(g.astype(compute_dtype), axis_names).astype(g.dtype)

    return jax.tree.map(reduce, grads)


def psum(x: Any, axis_names: Sequence[str] | str) -> Any:
    return jax.tree.map(lambda v: lax.psum(v, axis_names), x)


def pmean(x: Any, axis_names: Sequence[str] | str) -> Any:
    return jax.tree.map(lambda v: lax.pmean(v, axis_names), x)


def all_gather(x: jax.Array, axis_name: str, *, axis: int = 0, tiled: bool = True) -> jax.Array:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x: jax.Array, axis_name: str, *, scatter_axis: int = 0) -> jax.Array:
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis, tiled=True)


def ppermute_shift(x: jax.Array, axis_name: str, *, shift: int = 1) -> jax.Array:
    """Ring shift: send to (i + shift) mod N — the ring-attention primitive."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))
