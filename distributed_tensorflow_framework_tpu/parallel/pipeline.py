"""Pipeline parallelism — microbatched stages over ``pipe``, 3 schedules.

The reference has no pipeline parallelism (SURVEY.md §2 parallelism
inventory — PP: NO); this extends the capability surface the TPU way: the
transformer layer stack is *stacked* (leading dim = num_layers) and that
dim is sharded over the ``pipe`` mesh axis, so each device owns a
contiguous stage of layers. A nested shard_map (the same
inside-jit pattern as parallel/ring.py) runs a static schedule
(parallel/schedule.py picks it from ``model.pipeline_schedule``):

``gpipe`` (default) — circular fill-drain:

    t:      0    1    2    ...                (M + S - 1 steps total)
    stage0  mb0  mb1  mb2
    stage1       mb0  mb1  ...
    stage2            mb0  ...

Each step every stage applies its layers to its current activation, then
``ppermute`` rotates activations one stage forward — neighbor ICI traffic
that XLA overlaps with the next step's compute. The backward comes from
autodiff: transposing the scan+ppermute yields the mirror-image drain
schedule for free. The reverse scan keeps every forward slot's residuals
live until the mirrored backward slot: activation residency O(M + S)
stage-sets per device.

NOTE on validating grads: eager ``jnp.concatenate`` over leaves sharded
``P("pipe", ...)`` on a mesh with replicated data axes mis-reshards on
this jax version and returns values scaled by the data-axis size — so
``jax.flatten_util.ravel_pytree`` on the grad tree is NOT a valid parity
probe. Compare per-leaf (``np.asarray`` each leaf) instead; the tests do.

``1f1b`` — the forward pass is the same circular schedule, but the
backward is HAND-BUILT (autodiff cannot express it: a 1F1B slot runs the
forward of one microbatch and the backward of a *different* microbatch).
``_pipeline_apply_1f1b`` wraps the stack in a jax.custom_vjp whose bwd
unrolls the combined recompute+backward slot table: per slot, one
forward (re)compute hop down the ring (``ppermute`` +1) feeding a
depth-``2S-1`` rolling store of stage-input boundary activations, and
one backward hop up the ring (``ppermute`` -1) where each stage runs a
per-microbatch VJP against its local layer params from its stored
boundary input. Per-layer residuals exist only transiently inside that
slot's VJP → activation residency O(S), independent of M — 1f1b is the
MEMORY schedule (same analytic bubble as gpipe; it buys more
microbatches at a fixed activation budget, at one extra forward of
recompute in the backward pass).

``interleaved`` — v virtual stages per device, round-robin chunk
assignment (global chunk q = c·S + s lives on device s): the circular
schedule runs over v·M chunk-slots of 1/v-sized work, cutting the
fill/drain bubble to (S-1)/(v·M + S-1) — the THROUGHPUT schedule.
Backward from autodiff like gpipe.

The batch stays sharded over the data axes (replicated across ``pipe``);
microbatching happens on the per-shard batch inside the shard_map, so PP
composes with DP/FSDP for free (pinned by tests/test_pipeline.py's
{fsdp:2, pipe:4} parity case).

v1 scope: the pipelined stack itself is sharded ONLY over ``pipe`` —
combining TP / sequence (ring) / expert parallelism *inside* the pipelined
layers needs hand-placed collectives in manual mode and is rejected at
StepBuilder level; dense (embed/head) params still get FSDP/TP from the
jit path as usual.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.parallel import schedule as sched

# Param-tree key for the stacked layer stack — parallel/sharding.py keys its
# P("pipe", None, ...) rule off this prefix.
STACK_KEY = "pipeline_layers"


def _stage_apply(layer: nn.Module, stage_params: Any, x: jax.Array,
                 mask: jax.Array | None, rng: jax.Array | None,
                 layer0: jax.Array, *, train: bool,
                 ckpt_policy: Any = None) -> jax.Array:
    """Apply this stage's local layers (leading dim = layers-per-stage)
    sequentially. ``layer0`` is the stage's first global layer index, used
    to give every (microbatch, layer) a distinct dropout stream.
    ``ckpt_policy`` (precision.remat_policy) checkpoints each layer apply
    with the given jax.checkpoint_policies callable — the selective-remat
    lever for the pipelined stack, whose stage body otherwise manages its
    own activation lifetime."""
    n_local = jax.tree.leaves(stage_params)[0].shape[0]

    def one_layer(p, h, rngs):
        out, _aux = layer.apply({"params": p}, h, mask, train=train,
                                rngs=rngs)
        return out

    if ckpt_policy is not None:
        one_layer = jax.checkpoint(one_layer, policy=ckpt_policy)

    def body(h, xs):
        p, i = xs
        rngs = None
        if train and rng is not None:
            rngs = {"dropout": jax.random.fold_in(rng, layer0 + i)}
        return one_layer(p, h, rngs), None

    x, _ = lax.scan(body, x, (stage_params, jnp.arange(n_local)))
    return x


def _check_microbatch(b_loc: int, m: int) -> None:
    if b_loc % m:
        raise ValueError(
            f"per-shard batch {b_loc} not divisible by "
            f"num_microbatches={m}"
        )


def _circular_fwd_fn(layer, s_stages: int, m: int, num_layers: int,
                     train: bool, axis_name: str, ckpt_policy: Any = None):
    """Per-shard forward of the circular fill-drain schedule — the gpipe
    forward AND the 1f1b primal forward (they are the same pass; the
    schedules differ only in how the backward is produced)."""
    layers_per_stage = num_layers // s_stages

    def fn(p_local, x_loc, mask_loc, rng_in):
        idx = lax.axis_index(axis_name)
        b_loc = x_loc.shape[0]
        _check_microbatch(b_loc, m)
        xm = x_loc.reshape((m, b_loc // m) + x_loc.shape[1:])
        maskm = None
        if mask_loc is not None:
            maskm = mask_loc.reshape((m, b_loc // m) + mask_loc.shape[1:])
        layer0 = idx * layers_per_stage

        def body(buf, t):
            # Rotate: stage p's activation moves to stage p+1 (stage 0
            # receives S-1's garbage, overwritten by the injection below).
            buf = lax.ppermute(
                buf, axis_name, [(i, (i + 1) % s_stages) for i in range(s_stages)]
            )
            inject = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            buf = jnp.where((idx == 0) & (t < m), inject, buf)
            # The microbatch currently in this stage is t - idx.
            mb_id = jnp.clip(t - idx, 0, m - 1)
            mb_mask = None
            if maskm is not None:
                mb_mask = lax.dynamic_index_in_dim(maskm, mb_id, 0,
                                                   keepdims=False)
            mb_rng = None
            if rng_in is not None:
                mb_rng = jax.random.fold_in(rng_in, mb_id * num_layers)
            buf = _stage_apply(layer, p_local, buf, mb_mask, mb_rng, layer0,
                               train=train, ckpt_policy=ckpt_policy)
            return buf, buf

        buf0 = jnp.zeros_like(xm[0])
        _, emitted = lax.scan(body, buf0, jnp.arange(m + s_stages - 1))
        # The last stage emits microbatch t-(S-1) at step t, so its slice
        # emitted[S-1:] is exactly [mb0..mbM-1]; other stages' slices are
        # pipeline garbage, dropped by the [-1] selection outside (the
        # stacked out-spec makes that a one-hop broadcast from the last
        # stage, not a ring-wide all-reduce of zeros).
        outs = emitted[s_stages - 1:].reshape(x_loc.shape)
        return outs[None]

    return fn


def _interleaved_fwd_fn(layer, s_stages: int, m: int, v: int,
                        num_layers: int, train: bool, axis_name: str,
                        ckpt_policy: Any = None):
    """Per-shard forward of the interleaved schedule: v·M + S - 1 slots;
    at stage-local clock t' = t - s, chunk c = (t' % (S·v)) // S of
    microbatch (t' // (S·v))·S + t' % S. Microbatches advance through the
    virtual chunks in groups of S (needs M % S == 0); the ring hop is the
    same +1 ppermute as gpipe — global chunk q on device q mod S hands to
    chunk q+1 on device (q+1) mod S exactly one slot later."""
    chunk_layers = num_layers // (s_stages * v)
    t_total = v * m + s_stages - 1

    def fn(p_local, x_loc, mask_loc, rng_in):
        idx = lax.axis_index(axis_name)
        b_loc = x_loc.shape[0]
        _check_microbatch(b_loc, m)
        xm = x_loc.reshape((m, b_loc // m) + x_loc.shape[1:])
        maskm = None
        if mask_loc is not None:
            maskm = mask_loc.reshape((m, b_loc // m) + mask_loc.shape[1:])
        # Local stack rows are the device's v round-robin chunks in c
        # order (pipeline_apply pre-permuted the stacked dim).
        p_chunks = jax.tree.map(
            lambda leaf: leaf.reshape((v, chunk_layers) + leaf.shape[1:]),
            p_local,
        )

        def body(buf, t):
            buf = lax.ppermute(
                buf, axis_name, [(i, (i + 1) % s_stages) for i in range(s_stages)]
            )
            tp = t - idx  # stage-local clock; negative/overflow = idle
            tpc = jnp.clip(tp, 0, v * m - 1)
            g = tpc // (s_stages * v)
            r = tpc % (s_stages * v)
            c = r // s_stages
            j = r % s_stages
            mb_id = g * s_stages + j
            inject = lax.dynamic_index_in_dim(xm, mb_id, 0, keepdims=False)
            buf = jnp.where((idx == 0) & (c == 0) & (tp < v * m), inject, buf)
            mb_mask = None
            if maskm is not None:
                mb_mask = lax.dynamic_index_in_dim(maskm, mb_id, 0,
                                                   keepdims=False)
            mb_rng = None
            if rng_in is not None:
                mb_rng = jax.random.fold_in(rng_in, mb_id * num_layers)
            # Global first layer of this chunk — keeps the per-(mb, layer)
            # dropout streams identical to gpipe and the reference.
            layer0 = (c * s_stages + idx) * chunk_layers
            p_c = jax.tree.map(
                lambda leaf: lax.dynamic_index_in_dim(leaf, c, 0,
                                                      keepdims=False),
                p_chunks,
            )
            buf = _stage_apply(layer, p_c, buf, mb_mask, mb_rng, layer0,
                               train=train, ckpt_policy=ckpt_policy)
            return buf, buf

        buf0 = jnp.zeros_like(xm[0])
        _, emitted = lax.scan(body, buf0, jnp.arange(t_total))
        # Microbatch g·S+j finishes its last chunk (v-1 on device S-1) at
        # global slot g·S·v + (v-1)·S + j + (S-1); the slots are ascending
        # in microbatch order, so one static gather reassembles the batch.
        out_slots = jnp.asarray([
            g * s_stages * v + (v - 1) * s_stages + j + s_stages - 1
            for g in range(m // s_stages) for j in range(s_stages)
        ])
        outs = emitted[out_slots].reshape(x_loc.shape)
        return outs[None]

    return fn


def _interleave_perm(num_layers: int, s_stages: int, v: int) -> np.ndarray:
    """Row permutation putting device s's round-robin chunks (global
    chunk q = c·S + s, c ascending) into its contiguous pipe-shard."""
    chunk_layers = num_layers // (s_stages * v)
    perm = [
        layer
        for s in range(s_stages)
        for c in range(v)
        for layer in range((c * s_stages + s) * chunk_layers,
                           (c * s_stages + s + 1) * chunk_layers)
    ]
    return np.asarray(perm, np.int32)


def _nondiff_cotangent(x):
    """float0 cotangent for non-differentiable primal inputs (bool
    attention masks, PRNG keys) — the custom_vjp contract for
    non-inexact dtypes."""
    if x is None:
        return None
    return np.zeros(np.shape(x), jax.dtypes.float0)


def _pipeline_apply_1f1b(layer, stacked_params, x, mask, rng, *, mesh,
                         num_stages, num_microbatches, num_layers, train,
                         axis_name, in_specs, out_spec, x_spec, stack_spec,
                         ckpt_policy=None):
    """The 1f1b executor: primal forward is the circular schedule; the
    hand-built backward unrolls parallel/schedule.py's combined
    recompute+backward slot table (see module docstring)."""
    s_stages, m = num_stages, num_microbatches
    layers_per_stage = num_layers // s_stages
    fwd_mapped = coll.shard_map(
        _circular_fwd_fn(layer, s_stages, m, num_layers, train, axis_name,
                         ckpt_policy),
        mesh=mesh, in_specs=in_specs, out_specs=out_spec, check_vma=False,
    )

    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

    data_axes = batch_spec(mesh)[0]

    def bwd_fn(p_local, x_loc, mask_loc, rng_in, dy_loc):
        idx = lax.axis_index(axis_name)
        b_loc = x_loc.shape[0]
        _check_microbatch(b_loc, m)
        xm = x_loc.reshape((m, b_loc // m) + x_loc.shape[1:])
        dym = dy_loc.reshape(xm.shape)
        maskm = None
        if mask_loc is not None:
            maskm = mask_loc.reshape((m, b_loc // m) + mask_loc.shape[1:])
        layer0 = idx * layers_per_stage

        def stage_f(p, xin, mb_id):
            mb_mask = None
            if maskm is not None:
                mb_mask = lax.dynamic_index_in_dim(maskm, mb_id, 0,
                                                   keepdims=False)
            mb_rng = None
            if rng_in is not None:
                # Same per-(microbatch, layer) streams as the forward pass
                # — the recompute replays identical dropout masks.
                mb_rng = jax.random.fold_in(rng_in, mb_id * num_layers)
            return _stage_apply(layer, p, xin, mb_mask, mb_rng, layer0,
                                train=train, ckpt_policy=ckpt_policy)

        fwd_perm = [(i, (i + 1) % s_stages) for i in range(s_stages)]
        bwd_perm = [(i, (i - 1) % s_stages) for i in range(s_stages)]
        # Rolling store of stage-INPUT boundary activations: microbatch mb
        # enters stage s's forward at slot mb+s and its backward fires at
        # slot mb+2(S-1)-s — a span of at most 2S-1 slots, so depth 2S-1
        # suffices for every stage. This store (plus the one transient VJP
        # below) IS the 1f1b memory story: O(S) live microbatch states vs
        # the gpipe scan's O(M+S) saved residual sets.
        depth = 2 * s_stages - 1
        store = jnp.zeros((depth,) + xm.shape[1:], xm.dtype)
        fbuf = jnp.zeros_like(xm[0])
        gbuf = jnp.zeros_like(xm[0])
        dp_sum = jax.tree.map(jnp.zeros_like, p_local)
        dxm = jnp.zeros_like(xm)
        for slot in sched.slot_table("1f1b", s_stages, m):
            t = slot.t
            if slot.fwd:  # forward (re)compute phase
                fbuf = lax.ppermute(fbuf, axis_name, fwd_perm)
                inject = lax.dynamic_index_in_dim(
                    xm, jnp.clip(t, 0, m - 1), 0, keepdims=False
                )
                fbuf = jnp.where((idx == 0) & (t < m), inject, fbuf)
                mb_f = jnp.clip(t - idx, 0, m - 1)
                store = lax.dynamic_update_index_in_dim(
                    store, fbuf, t % depth, 0
                )
                fbuf = stage_f(p_local, fbuf, mb_f)
            if slot.bwd:  # backward phase
                gbuf = lax.ppermute(gbuf, axis_name, bwd_perm)
                mb_b = t - 2 * (s_stages - 1) + idx
                active_b = (mb_b >= 0) & (mb_b < m)
                mb_b_c = jnp.clip(mb_b, 0, m - 1)
                ginj = lax.dynamic_index_in_dim(dym, mb_b_c, 0,
                                                keepdims=False)
                gbuf = jnp.where(
                    (idx == s_stages - 1) & (t - (s_stages - 1) < m),
                    ginj, gbuf,
                )
                # This stage forwarded mb_b at slot t - (2(S-1) - 2·idx);
                # fetch its saved boundary input and run the
                # per-microbatch VJP against the local layer params.
                t_f = t - (2 * (s_stages - 1) - 2 * idx)
                xin = lax.dynamic_index_in_dim(store, t_f % depth, 0,
                                               keepdims=False)
                _, pb = jax.vjp(
                    lambda p, xin_: stage_f(p, xin_, mb_b_c), p_local, xin
                )
                dp, dxin = pb(gbuf)
                dp_sum = jax.tree.map(
                    lambda a, b: a + jnp.where(active_b, b, 0.0),
                    dp_sum, dp,
                )
                dxin = jnp.where(active_b, dxin, jnp.zeros_like(dxin))
                dxm = dxm.at[mb_b_c].add(
                    jnp.where(idx == 0, dxin, jnp.zeros_like(dxin))
                )
                gbuf = dxin
        # The stacked params entered replicated over the data axes, so
        # their true cotangent is the sum of the per-data-shard grads;
        # dx is only real on stage 0 (others masked to zero) — the psum
        # over pipe is a one-hop broadcast of stage 0's value.
        dp_sum = lax.psum(dp_sum, data_axes)
        dx = lax.psum(dxm.reshape(x_loc.shape), axis_name)
        return dp_sum, dx

    dx_out_spec = P(data_axes, *([None] * (x.ndim - 1)))
    bwd_mapped = coll.shard_map(
        bwd_fn, mesh=mesh,
        in_specs=in_specs + (x_spec,),
        out_specs=(stack_spec, dx_out_spec),
        check_vma=False,
    )

    @jax.custom_vjp
    def run(p, x_, mask_, rng_):
        return fwd_mapped(p, x_, mask_, rng_)[-1]

    def run_fwd(p, x_, mask_, rng_):
        return run(p, x_, mask_, rng_), (p, x_, mask_, rng_)

    def run_bwd(res, dy):
        p, x_, mask_, rng_ = res
        dp, dx = bwd_mapped(p, x_, mask_, rng_, dy)
        return (dp, dx, _nondiff_cotangent(mask_), _nondiff_cotangent(rng_))

    run.defvjp(run_fwd, run_bwd)
    return run(stacked_params, x, mask, rng)


def pipeline_apply(
    layer: nn.Module,
    stacked_params: Any,
    x: jax.Array,
    mask: jax.Array | None,
    rng: jax.Array | None,
    *,
    mesh,
    num_stages: int,
    num_microbatches: int,
    train: bool,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
    axis_name: str = "pipe",
    ckpt_policy: Any = None,
) -> jax.Array:
    """Run the stacked layer params over ``x`` with the configured
    schedule (gpipe | 1f1b | interleaved — see module docstring).

    ``stacked_params`` leaves have leading dim num_layers (sharded over
    ``pipe``); ``x`` is (B, S, H) sharded over the data axes. Returns the
    activations after the full stack, same sharding as ``x``.
    """
    s_stages, m = num_stages, num_microbatches
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % s_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by pipeline stages {s_stages}"
        )
    v = sched.resolve_virtual(schedule, s_stages, m, virtual_stages,
                              num_layers)

    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

    data_axes = batch_spec(mesh)[0]  # the canonical batch-sharding axes
    x_spec = P(data_axes, *([None] * (x.ndim - 1)))
    stack_spec = jax.tree.map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    mask_spec = None
    if mask is not None:
        mask_spec = P(data_axes, *([None] * (mask.ndim - 1)))
    rng_spec = None if rng is None else P()
    out_spec = P(axis_name, data_axes, *([None] * (x.ndim - 1)))
    in_specs = (stack_spec, x_spec, mask_spec, rng_spec)

    if schedule == "1f1b":
        return _pipeline_apply_1f1b(
            layer, stacked_params, x, mask, rng, mesh=mesh,
            num_stages=s_stages, num_microbatches=m, num_layers=num_layers,
            train=train, axis_name=axis_name, in_specs=in_specs,
            out_spec=out_spec, x_spec=x_spec, stack_spec=stack_spec,
            ckpt_policy=ckpt_policy,
        )
    if schedule == "interleaved":
        # Reorder the stacked dim so each device's contiguous pipe-shard
        # holds its v round-robin chunks (autodiff scatters the grads
        # back through the gather; the reshuffle is a per-step
        # collective-permute of the small layer params).
        perm = _interleave_perm(num_layers, s_stages, v)
        stacked_params = jax.tree.map(
            lambda leaf: jnp.take(leaf, jnp.asarray(perm), axis=0),
            stacked_params,
        )
        fn = _interleaved_fwd_fn(layer, s_stages, m, v, num_layers, train,
                                 axis_name, ckpt_policy)
    else:
        fn = _circular_fwd_fn(layer, s_stages, m, num_layers, train,
                              axis_name, ckpt_policy)
    mapped = coll.shard_map(fn, mesh=mesh, in_specs=in_specs,
                            out_specs=out_spec, check_vma=False)
    # Stacked out over pipe: every stage emits its slot trace; only the
    # last stage's row is the real output (selected outside shard_map so
    # the transpose routes the cotangent to stage S-1 alone).
    return mapped(stacked_params, x, mask, rng)[-1]


class PipelinedBert:
    """BERT-for-MLM with the encoder stack pipelined over ``pipe``.

    Flax-compatible ``init``/``apply`` surface (duck-typed for
    train/step.py's StepBuilder) without being an nn.Module: the stacked
    layer params are built with a vmapped per-layer init and managed as a
    plain pytree under params["pipeline_layers"], which is what the
    sharding rules key on. ``schedule``/``virtual_stages`` select the
    stage schedule (parallel/schedule.py); the parameter tree is
    schedule-independent, so checkpoints are interchangeable across
    schedules.
    """

    def __init__(self, *, vocab_size: int, hidden_size: int, num_layers: int,
                 num_heads: int, mlp_dim: int, max_seq_len: int,
                 dropout_rate: float, dtype: Any, mesh,
                 num_stages: int, num_microbatches: int,
                 attention_impl: str = "xla", fused_qkv: bool = False,
                 schedule: str = "gpipe", virtual_stages: int = 0,
                 ckpt_policy: Any = None):
        if mesh is None:
            raise ValueError("PipelinedBert needs the physical mesh")
        if num_layers % num_stages:
            raise ValueError(
                f"num_layers={num_layers} must divide into "
                f"pipeline_stages={num_stages}"
            )
        if attention_impl == "ring":
            raise ValueError(
                "attention_impl='ring' nests a shard_map inside the pipeline "
                "shard_map — unsupported; use 'xla' or 'pallas' with PP"
            )
        from distributed_tensorflow_framework_tpu.models.bert import (
            BertEmbed,
            EncoderLayer,
            MLMHead,
        )

        self.num_layers = num_layers
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches or num_stages
        self.schedule = schedule
        # Fails loudly at model build on a bad (schedule, S, M, v, L).
        self.virtual_stages = sched.resolve_virtual(
            schedule, num_stages, self.num_microbatches, virtual_stages,
            num_layers,
        )
        self.mesh = mesh
        # Selective-remat policy for the per-layer stage applies
        # (precision.remat_policy; see _stage_apply).
        self.ckpt_policy = ckpt_policy
        self.embed = BertEmbed(vocab_size, hidden_size, max_seq_len,
                               dropout_rate, dtype)
        self.layer = EncoderLayer(num_heads, mlp_dim, dropout_rate,
                                  dtype=dtype, attention_impl=attention_impl,
                                  fused_qkv=fused_qkv)
        self.head = MLMHead(vocab_size, hidden_size, dtype)

    # ---------------------------------------------------- flax-like API --
    def init(self, rngs: dict, input_ids, attention_mask=None, *,
             train: bool = False) -> dict:
        del attention_mask, train
        params_rng = rngs["params"]
        k_embed, k_layers, k_head = jax.random.split(params_rng, 3)
        e_vars = self.embed.init({"params": k_embed}, input_ids, train=False)
        x, emb_table = self.embed.apply(e_vars, input_ids, train=False)

        keys = jax.random.split(k_layers, self.num_layers)
        stacked = jax.vmap(
            lambda k: self.layer.init({"params": k}, x, None,
                                      train=False)["params"]
        )(keys)

        h_vars = self.head.init({"params": k_head}, x, emb_table)
        return {"params": {
            "embed_block": e_vars["params"],
            STACK_KEY: stacked,
            "head": h_vars["params"],
        }}

    def apply(self, variables: dict, input_ids, attention_mask=None, *,
              train: bool = True, mutable=False, rngs: dict | None = None):
        p = variables["params"]
        embed_rngs = None
        rng = None
        if rngs is not None and train:
            rng = rngs.get("dropout")
            if rng is not None:
                embed_rngs = {"dropout": jax.random.fold_in(rng, 0x5A5A)}
        x, emb_table = self.embed.apply({"params": p["embed_block"]},
                                        input_ids, train=train,
                                        rngs=embed_rngs)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        x = pipeline_apply(
            self.layer, p[STACK_KEY], x, mask, rng,
            mesh=self.mesh, num_stages=self.num_stages,
            num_microbatches=self.num_microbatches, train=train,
            schedule=self.schedule, virtual_stages=self.virtual_stages,
            ckpt_policy=self.ckpt_policy,
        )
        logits = self.head.apply({"params": p["head"]}, x, emb_table)
        if mutable:
            return logits, {}
        return logits

    # Reference (non-pipelined) forward with the same params — used by the
    # numerics tests to pin the schedules' correctness.
    def apply_reference(self, variables: dict, input_ids,
                        attention_mask=None, *, train: bool = False):
        p = variables["params"]
        x, emb_table = self.embed.apply({"params": p["embed_block"]},
                                        input_ids, train=train)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(self.num_layers):
            layer_p = jax.tree.map(lambda leaf: leaf[i], p[STACK_KEY])
            x, _ = self.layer.apply({"params": layer_p}, x, mask, train=train)
        return self.head.apply({"params": p["head"]}, x, emb_table)
