"""Pipeline parallelism — GPipe-style microbatched stages over ``pipe``.

The reference has no pipeline parallelism (SURVEY.md §2 parallelism
inventory — PP: NO); this extends the capability surface the TPU way: the
transformer layer stack is *stacked* (leading dim = num_layers) and that
dim is sharded over the ``pipe`` mesh axis, so each device owns a
contiguous stage of layers. A nested shard_map (the same
inside-jit pattern as parallel/ring.py) runs the circular schedule:

    t:      0    1    2    ...                (M + S - 1 steps total)
    stage0  mb0  mb1  mb2
    stage1       mb0  mb1  ...
    stage2            mb0  ...

Each step every stage applies its layers to its current activation, then
``ppermute`` rotates activations one stage forward — neighbor ICI traffic
that XLA overlaps with the next step's compute. The batch stays sharded
over the data axes (replicated across ``pipe``); microbatching happens on
the per-shard batch inside the shard_map, so PP composes with DP/FSDP for
free. Autodiff through the scan+ppermute gives the reverse schedule
(backward bubbles mirror forward) with no hand-written backward pass.

v1 scope: the pipelined stack itself is sharded ONLY over ``pipe`` —
combining TP / sequence (ring) / expert parallelism *inside* the pipelined
layers needs hand-placed collectives in manual mode and is rejected at
StepBuilder level; dense (embed/head) params still get FSDP/TP from the
jit path as usual.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_framework_tpu.parallel import collectives as coll

# Param-tree key for the stacked layer stack — parallel/sharding.py keys its
# P("pipe", None, ...) rule off this prefix.
STACK_KEY = "pipeline_layers"


def _stage_apply(layer: nn.Module, stage_params: Any, x: jax.Array,
                 mask: jax.Array | None, rng: jax.Array | None,
                 layer0: jax.Array, *, train: bool) -> jax.Array:
    """Apply this stage's local layers (leading dim = layers-per-stage)
    sequentially. ``layer0`` is the stage's first global layer index, used
    to give every (microbatch, layer) a distinct dropout stream."""
    n_local = jax.tree.leaves(stage_params)[0].shape[0]

    def body(h, xs):
        p, i = xs
        rngs = None
        if train and rng is not None:
            rngs = {"dropout": jax.random.fold_in(rng, layer0 + i)}
        h, _aux = layer.apply({"params": p}, h, mask, train=train, rngs=rngs)
        return h, None

    x, _ = lax.scan(body, x, (stage_params, jnp.arange(n_local)))
    return x


def pipeline_apply(
    layer: nn.Module,
    stacked_params: Any,
    x: jax.Array,
    mask: jax.Array | None,
    rng: jax.Array | None,
    *,
    mesh,
    num_stages: int,
    num_microbatches: int,
    train: bool,
    axis_name: str = "pipe",
) -> jax.Array:
    """Run the stacked layer params over ``x`` with the circular schedule.

    ``stacked_params`` leaves have leading dim num_layers (sharded over
    ``pipe``); ``x`` is (B, S, H) sharded over the data axes. Returns the
    activations after the full stack, same sharding as ``x``.
    """
    s_stages, m = num_stages, num_microbatches
    num_layers = jax.tree.leaves(stacked_params)[0].shape[0]
    if num_layers % s_stages:
        raise ValueError(
            f"num_layers={num_layers} not divisible by pipeline stages {s_stages}"
        )
    layers_per_stage = num_layers // s_stages

    def fn(p_local, x_loc, mask_loc, rng_in):
        idx = lax.axis_index(axis_name)
        b_loc = x_loc.shape[0]
        if b_loc % m:
            raise ValueError(
                f"per-shard batch {b_loc} not divisible by "
                f"num_microbatches={m}"
            )
        xm = x_loc.reshape((m, b_loc // m) + x_loc.shape[1:])
        maskm = None
        if mask_loc is not None:
            maskm = mask_loc.reshape((m, b_loc // m) + mask_loc.shape[1:])
        layer0 = idx * layers_per_stage

        def body(buf, t):
            # Rotate: stage p's activation moves to stage p+1 (stage 0
            # receives S-1's garbage, overwritten by the injection below).
            buf = lax.ppermute(
                buf, axis_name, [(i, (i + 1) % s_stages) for i in range(s_stages)]
            )
            inject = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, m - 1), 0, keepdims=False
            )
            buf = jnp.where((idx == 0) & (t < m), inject, buf)
            # The microbatch currently in this stage is t - idx.
            mb_id = jnp.clip(t - idx, 0, m - 1)
            mb_mask = None
            if maskm is not None:
                mb_mask = lax.dynamic_index_in_dim(maskm, mb_id, 0,
                                                   keepdims=False)
            mb_rng = None
            if rng_in is not None:
                mb_rng = jax.random.fold_in(rng_in, mb_id * num_layers)
            buf = _stage_apply(layer, p_local, buf, mb_mask, mb_rng, layer0,
                               train=train)
            return buf, buf

        buf0 = jnp.zeros_like(xm[0])
        _, emitted = lax.scan(body, buf0, jnp.arange(m + s_stages - 1))
        # The last stage emits microbatch t-(S-1) at step t, so its slice
        # emitted[S-1:] is exactly [mb0..mbM-1]; other stages' slices are
        # pipeline garbage, dropped by the [-1] selection outside (the
        # stacked out-spec makes that a one-hop broadcast from the last
        # stage, not a ring-wide all-reduce of zeros).
        outs = emitted[s_stages - 1:].reshape(x_loc.shape)
        return outs[None]

    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

    data_axes = batch_spec(mesh)[0]  # the canonical batch-sharding axes
    x_spec = P(data_axes, *([None] * (x.ndim - 1)))
    stack_spec = jax.tree.map(
        lambda leaf: P(axis_name, *([None] * (leaf.ndim - 1))), stacked_params
    )
    mask_spec = None
    if mask is not None:
        mask_spec = P(data_axes, *([None] * (mask.ndim - 1)))
    rng_spec = None if rng is None else P()
    out_spec = P(axis_name, data_axes, *([None] * (x.ndim - 1)))
    mapped = coll.shard_map(
        fn,
        mesh=mesh,
        in_specs=(stack_spec, x_spec, mask_spec, rng_spec),
        out_specs=out_spec,
        check_vma=False,
    )
    return mapped(stacked_params, x, mask, rng)[-1]


class PipelinedBert:
    """BERT-for-MLM with the encoder stack pipelined over ``pipe``.

    Flax-compatible ``init``/``apply`` surface (duck-typed for
    train/step.py's StepBuilder) without being an nn.Module: the stacked
    layer params are built with a vmapped per-layer init and managed as a
    plain pytree under params["pipeline_layers"], which is what the
    sharding rules key on.
    """

    def __init__(self, *, vocab_size: int, hidden_size: int, num_layers: int,
                 num_heads: int, mlp_dim: int, max_seq_len: int,
                 dropout_rate: float, dtype: Any, mesh,
                 num_stages: int, num_microbatches: int,
                 attention_impl: str = "xla", fused_qkv: bool = False):
        if mesh is None:
            raise ValueError("PipelinedBert needs the physical mesh")
        if num_layers % num_stages:
            raise ValueError(
                f"num_layers={num_layers} must divide into "
                f"pipeline_stages={num_stages}"
            )
        if attention_impl == "ring":
            raise ValueError(
                "attention_impl='ring' nests a shard_map inside the pipeline "
                "shard_map — unsupported; use 'xla' or 'pallas' with PP"
            )
        from distributed_tensorflow_framework_tpu.models.bert import (
            BertEmbed,
            EncoderLayer,
            MLMHead,
        )

        self.num_layers = num_layers
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches or num_stages
        self.mesh = mesh
        self.embed = BertEmbed(vocab_size, hidden_size, max_seq_len,
                               dropout_rate, dtype)
        self.layer = EncoderLayer(num_heads, mlp_dim, dropout_rate,
                                  dtype=dtype, attention_impl=attention_impl,
                                  fused_qkv=fused_qkv)
        self.head = MLMHead(vocab_size, hidden_size, dtype)

    # ---------------------------------------------------- flax-like API --
    def init(self, rngs: dict, input_ids, attention_mask=None, *,
             train: bool = False) -> dict:
        del attention_mask, train
        params_rng = rngs["params"]
        k_embed, k_layers, k_head = jax.random.split(params_rng, 3)
        e_vars = self.embed.init({"params": k_embed}, input_ids, train=False)
        x, emb_table = self.embed.apply(e_vars, input_ids, train=False)

        keys = jax.random.split(k_layers, self.num_layers)
        stacked = jax.vmap(
            lambda k: self.layer.init({"params": k}, x, None,
                                      train=False)["params"]
        )(keys)

        h_vars = self.head.init({"params": k_head}, x, emb_table)
        return {"params": {
            "embed_block": e_vars["params"],
            STACK_KEY: stacked,
            "head": h_vars["params"],
        }}

    def apply(self, variables: dict, input_ids, attention_mask=None, *,
              train: bool = True, mutable=False, rngs: dict | None = None):
        p = variables["params"]
        embed_rngs = None
        rng = None
        if rngs is not None and train:
            rng = rngs.get("dropout")
            if rng is not None:
                embed_rngs = {"dropout": jax.random.fold_in(rng, 0x5A5A)}
        x, emb_table = self.embed.apply({"params": p["embed_block"]},
                                        input_ids, train=train,
                                        rngs=embed_rngs)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        x = pipeline_apply(
            self.layer, p[STACK_KEY], x, mask, rng,
            mesh=self.mesh, num_stages=self.num_stages,
            num_microbatches=self.num_microbatches, train=train,
        )
        logits = self.head.apply({"params": p["head"]}, x, emb_table)
        if mutable:
            return logits, {}
        return logits

    # Reference (non-pipelined) forward with the same params — used by the
    # numerics tests to pin the schedule's correctness.
    def apply_reference(self, variables: dict, input_ids,
                        attention_mask=None, *, train: bool = False):
        p = variables["params"]
        x, emb_table = self.embed.apply({"params": p["embed_block"]},
                                        input_ids, train=train)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(self.num_layers):
            layer_p = jax.tree.map(lambda leaf: leaf[i], p[STACK_KEY])
            x, _ = self.layer.apply({"params": layer_p}, x, mask, train=train)
        return self.head.apply({"params": p["head"]}, x, emb_table)
