"""Block-scaled int8 quantization for collective wire formats.

The EQuARX recipe (PAPERS.md, arxiv 2506.17615): split a tensor into
fixed-size blocks, carry one f32 scale per block (max-abs / 127), and ship
the payload as int8. The scale rides the wire next to its block — 4 bytes
per ``block_size`` elements, ~1.6% overhead at the default 256 — so a
quantized collective moves ~1.016 bytes/element against f32's 4.

These are pure trace-time functions; the collective wrappers in
``parallel/collectives.py`` own padding, the wire protocol and the
error-feedback residual. Contract here:

  * inputs are flat f32 arrays whose size divides ``block_size``
    (callers pad with zeros — a zero block quantizes to zeros exactly,
    so padding contributes no quantization error);
  * a zero block gets scale 1.0, not 0 (dequantize never divides or
    multiplies by zero into NaN territory);
  * round-to-nearest-even (``jnp.rint``) with clamp to ±127, so the
    worst-case per-element error is ``maxabs/254`` — the bound the
    single-step error test asserts (tests/test_compressed_allreduce.py).
"""

from __future__ import annotations

import jax.numpy as jnp

DEFAULT_BLOCK_SIZE = 256
# Wire-format overhead: one f32 scale per block.
SCALE_BYTES = 4


def quantize_blockwise(x: jnp.ndarray, block_size: int = DEFAULT_BLOCK_SIZE):
    """Flat f32 -> (int8 payload of x.shape, f32 scales of size/block).

    ``x`` must be 1-D with ``x.size % block_size == 0``.
    """
    if x.ndim != 1 or x.size % block_size:
        raise ValueError(
            f"quantize_blockwise wants a flat array padded to a multiple of "
            f"block_size={block_size}, got shape {x.shape}"
        )
    blocks = x.astype(jnp.float32).reshape(-1, block_size)
    maxabs = jnp.max(jnp.abs(blocks), axis=-1)
    scales = jnp.where(maxabs > 0, maxabs / 127.0, 1.0)
    q = jnp.clip(jnp.rint(blocks / scales[:, None]), -127, 127)
    return q.astype(jnp.int8).reshape(x.shape), scales


def dequantize_blockwise(q: jnp.ndarray, scales: jnp.ndarray,
                         block_size: int = DEFAULT_BLOCK_SIZE) -> jnp.ndarray:
    """Inverse of :func:`quantize_blockwise` (up to the rounding)."""
    blocks = q.astype(jnp.float32).reshape(-1, block_size)
    return (blocks * scales[:, None]).reshape(q.shape)


def quantization_error(x: jnp.ndarray,
                       block_size: int = DEFAULT_BLOCK_SIZE) -> jnp.ndarray:
    """``x - D(Q(x))`` — the quantity error feedback carries forward."""
    q, s = quantize_blockwise(x, block_size)
    return x.astype(jnp.float32) - dequantize_blockwise(q, s, block_size)
