"""Ring attention — sequence-parallel exact attention over the ``seq`` axis.

Long-context support (absent from the reference, which is conv-net DP only —
SURVEY.md §5 "Long-context" row — but first-class here): the sequence is
sharded over the ``seq`` mesh axis; each device holds its local Q/K/V shard
and the K/V shards rotate around the ring via ``ppermute`` while every
device accumulates its queries' attention over the full sequence with an
online (flash-style) softmax. Communication rides ICI neighbor links and
overlaps with the per-chunk attention compute. Chunks merge by logsumexp
reweighting; the per-chunk attention dispatches between the fused Pallas
flash kernel (ops/flash_attention.flash_attention_chunk — long chunks,
where it keeps the (S/n)² score block out of HBM entirely) and a plain
XLA chain (short chunks, where XLA's fusion wins) at the measured
FLASH_CHUNK_MIN crossover.

``ring_attention`` is the per-shard body (call inside shard_map);
``ring_attention_sharded`` wraps it for use from jit-level code (e.g. the
BERT module with ``attention_impl="ring"``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

# Module import (not by-value) so the env/monkeypatch-tunable dispatch
# constants (MAX_SEQ_VMEM) stay coherent between the two modules.
from distributed_tensorflow_framework_tpu.ops import flash_attention as _fa
from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.ops.flash_attention import (
    chunk_supported,
    flash_attention_chunk,
)

# Per-chunk implementation crossover. Re-derived on TPU v5 lite against
# the round-4 fat-tile/input-dtype kernels (scripts/bench_chunk_crossover,
# 2026-08-01 window, fwd+bwd median ms): XLA and flash TIE within noise
# below 2048 (chunk 512: 65.7 vs 66.7; 1024: 66.9 vs 67.0), flash wins at
# 2048 (70.7 vs 69.6) and 4096 (89.8 vs 84.1, +6.8%). 2048 stands as the
# measured crossover — the round-3 value survived the 2x kernel speedup
# because XLA's chain got proportionally cheaper at short chunks too.
# Those flash timings are TWO-PASS backward numbers — the matched
# regime: the round-5 whole-K fused takeover now ships default-off
# (ops/flash_attention.py FUSED_WHOLE_K_MIN parks above MAX_SEQ_VMEM
# until the wk2048/wk4096 chip A/B lands), so chunks in [2048,
# MAX_SEQ_VMEM] take the measured two-pass path unless the operator
# re-arms the knob, which would only widen flash's margin here.
# Module-level so tests can force either path.
FLASH_CHUNK_MIN = 2048


def _chunk_attention(q, k, v, bias, q_seg=None, kv_seg=None):
    """One K/V chunk → (chunk-normalized o (B,Sq,H,D) f32, lse (B,Sq,H,1)).

    ``q_seg``/``kv_seg`` (B,Sq)/(B,Sk) optional packed-sequence segment
    ids (attend only within equal ids). Dispatches on the static chunk
    length: Pallas flash kernel at/above FLASH_CHUNK_MIN (see crossover
    note above) — including chunks beyond MAX_SEQ_VMEM, which take the
    K-blocked streaming kernels (ops/flash_attention module docstring).
    Short or oddly-shaped small chunks take the plain-XLA chain, which
    handles any shape; that chain materializes a per-chunk
    (B,H,Sq,Sk) score block, so chunks above MAX_SEQ_VMEM that the
    kernel can't take (non-BLOCK_Q-multiple) fail loudly instead of
    silently allocating O(chunk²) HBM (VERDICT r3 weak #2).
    """
    c = q.shape[1]
    # Flash kernels take any supported chunk at/above the crossover AND
    # any chunk above the VMEM threshold (the latter matters when
    # MAX_SEQ_VMEM is tuned below FLASH_CHUNK_MIN, e.g. the
    # FLASH_MAX_SEQ_VMEM=0 force-streaming knob — without it those
    # chunks would fall through to the misleading raise below).
    if (c >= FLASH_CHUNK_MIN or c > _fa.MAX_SEQ_VMEM) and chunk_supported(c):
        o, lse = flash_attention_chunk(q, k, v, bias, q_seg, kv_seg)
        return o.astype(jnp.float32), lse
    if c > _fa.MAX_SEQ_VMEM:
        raise ValueError(
            f"ring chunk {c} exceeds MAX_SEQ_VMEM={_fa.MAX_SEQ_VMEM} but "
            f"is not a BLOCK_Q multiple, so the flash kernels can't take "
            f"it and the XLA fallback would materialize a {c}x{c} score "
            f"block per shard. Pick mesh.seq so seq/ring_shards is a "
            f"128-multiple."
        )
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    s = s + bias[:, None, None, :]
    if q_seg is not None:
        s = jnp.where(
            q_seg[:, None, :, None] == kv_seg[:, None, None, :],
            s, jnp.finfo(jnp.float32).min)
    m = jnp.max(s, axis=-1, keepdims=True)                   # (B,H,Sq,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)                   # (B,H,Sq,1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    o = pv / l.transpose(0, 2, 1, 3)
    lse = (m + jnp.log(l)).transpose(0, 2, 1, 3)             # (B,Sq,H,1)
    return o, lse


def _merge_chunks(o, lse, o_c, lse_c):
    """Logsumexp-reweighted online merge of two chunk-normalized partial
    attentions: o,o_c (B,Sq,H,D) f32, lse,lse_c (B,Sq,H,1)."""
    lse_new = jnp.logaddexp(lse, lse_c)
    o_new = o * jnp.exp(lse - lse_new) + o_c * jnp.exp(lse_c - lse_new)
    return o_new, lse_new


def ring_attention(q, k, v, bias, segment_ids=None, *, axis_name: str = "seq"):
    """Exact attention with K/V rotating around the ring. Per-shard code —
    must run inside shard_map with q,k,v sharded over ``axis_name`` on the
    sequence dim. Shapes per shard: (B, S/n, H, D); ``bias`` is the
    additive key-mask shard (B, S/n) and rotates with its K/V;
    ``segment_ids`` (B, S/n) optional packed-sequence ids — the K/V-side
    shard rotates with its chunk while the local shard masks queries, so
    packing works across ring shard boundaries."""
    n = coll.axis_size(axis_name)

    seg = segment_ids
    o0, lse0 = _chunk_attention(q, k, v, bias, seg, seg)

    def body(i, carry):
        o, lse, k_cur, v_cur, b_cur, s_cur = carry
        # Rotate K/V (and their mask/segment shards) to the next ring
        # position; the send overlaps with the local chunk's attention
        # compute below (XLA schedules the collective-permute concurrently
        # with the independent kernel call).
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        b_nxt = lax.ppermute(b_cur, axis_name, perm)
        s_nxt = (lax.ppermute(s_cur, axis_name, perm)
                 if s_cur is not None else None)
        o_c, lse_c = _chunk_attention(q, k_nxt, v_nxt, b_nxt, seg, s_nxt)
        o, lse = _merge_chunks(o, lse, o_c, lse_c)
        return o, lse, k_nxt, v_nxt, b_nxt, s_nxt

    # Static trip count → lowered as scan, so reverse-mode AD flows
    # through the merge (incl. the lse cotangent into the chunk kernel).
    o, _, _, _, _, _ = lax.fori_loop(
        0, n - 1, body, (o0, lse0, k, v, bias, seg))
    return o.astype(q.dtype)


def ring_attention_sharded(q, k, v, *, mesh, mask=None, segment_ids=None,
                           axis_name: str = "seq"):
    """jit-level wrapper: shard q,k,v over the seq axis and run the ring.

    Usable inside an outer jit (nested shard_map); batch stays sharded over
    the data axes, heads/features replicated across ``seq``. ``mask`` is the
    (B,1,1,S) bool key mask (as produced by the BERT module) or None;
    ``segment_ids`` (B, S) optional packed-sequence ids, sharded over the
    seq axis like the tokens they describe.
    """
    if mesh is None:
        raise ValueError("ring attention needs the physical mesh "
                         "(pass mesh= to the model)")
    b, s = q.shape[0], q.shape[1]
    if mask is not None:
        bias = jnp.where(mask[:, 0, 0, :], 0.0,
                         jnp.finfo(jnp.float32).min).astype(jnp.float32)
    else:
        bias = jnp.zeros((b, s), jnp.float32)
    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

    data_axes = batch_spec(mesh)[0]  # the canonical batch-sharding axes
    spec = P(data_axes, axis_name, None, None)
    bias_spec = P(data_axes, axis_name)
    if segment_ids is None:
        in_specs = (spec, spec, spec, bias_spec)
        args = (q, k, v, bias)
    else:
        in_specs = (spec, spec, spec, bias_spec, bias_spec)
        args = (q, k, v, bias, segment_ids)
    fn = coll.shard_map(
        functools.partial(ring_attention, axis_name=axis_name),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=spec,
        check_vma=False,
    )
    return fn(*args)
