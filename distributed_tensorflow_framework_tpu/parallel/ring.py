"""Ring attention — sequence-parallel exact attention over the ``seq`` axis.

Long-context support (absent from the reference, which is conv-net DP only —
SURVEY.md §5 "Long-context" row — but first-class here): the sequence is
sharded over the ``seq`` mesh axis; each device holds its local Q/K/V shard
and the K/V shards rotate around the ring via ``ppermute`` while every
device accumulates its queries' attention over the full sequence with an
online (flash-style) softmax. Communication rides ICI neighbor links and
overlaps with the per-chunk attention compute; peak memory per device is
O(S/n · S/n) scores instead of O(S²).

``ring_attention`` is the per-shard body (call inside shard_map);
``ring_attention_sharded`` wraps it for use from jit-level code (e.g. the
BERT module with ``attention_impl="ring"``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def _chunk_scores(q, k, v, bias, scale):
    """Unnormalized attention stats for one K/V chunk.

    q: (B, Sq, H, D); k,v: (B, Sk, H, D); bias: (B, Sk) additive mask →
    (max (B,H,Sq,1), exp-sum (B,H,Sq,1), weighted-v (B,Sq,H,D)).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    s = s + bias[:, None, None, :]
    m = jnp.max(s, axis=-1, keepdims=True)                   # (B,H,Sq,1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)                   # (B,H,Sq,1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return m, l, pv


def ring_attention(q, k, v, bias, *, axis_name: str = "seq"):
    """Exact attention with K/V rotating around the ring. Per-shard code —
    must run inside shard_map with q,k,v sharded over ``axis_name`` on the
    sequence dim. Shapes per shard: (B, S/n, H, D); ``bias`` is the
    additive key-mask shard (B, S/n) and rotates with its K/V."""
    n = lax.axis_size(axis_name)
    scale = 1.0 / (q.shape[-1] ** 0.5)

    m0, l0, pv0 = _chunk_scores(q, k, v, bias, scale)

    def body(i, carry):
        m, l, pv, k_cur, v_cur, b_cur = carry
        # Rotate K/V (and their mask shard) to the next ring position; the
        # send overlaps with the local chunk's attention compute below (XLA
        # schedules the collective-permute concurrently with the
        # independent einsum).
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        b_nxt = lax.ppermute(b_cur, axis_name, perm)
        m_c, l_c, pv_c = _chunk_scores(q, k_nxt, v_nxt, b_nxt, scale)
        # Online-softmax merge of the running stats with the new chunk.
        m_new = jnp.maximum(m, m_c)
        a = jnp.exp(m - m_new)
        b = jnp.exp(m_c - m_new)
        l_new = l * a + l_c * b
        # pv carries (B,Sq,H,D); scale factors are (B,H,Sq,1) → align axes.
        a_t = a.transpose(0, 2, 1, 3)  # (B,Sq,H,1)
        b_t = b.transpose(0, 2, 1, 3)
        pv_new = pv * a_t + pv_c * b_t
        return m_new, l_new, pv_new, k_nxt, v_nxt, b_nxt

    m, l, pv, _, _, _ = lax.fori_loop(0, n - 1, body, (m0, l0, pv0, k, v, bias))
    out = pv / l.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, *, mesh, mask=None, axis_name: str = "seq"):
    """jit-level wrapper: shard q,k,v over the seq axis and run the ring.

    Usable inside an outer jit (nested shard_map); batch stays sharded over
    the data axes, heads/features replicated across ``seq``. ``mask`` is the
    (B,1,1,S) bool key mask (as produced by the BERT module) or None.
    """
    if mesh is None:
        raise ValueError("ring attention needs the physical mesh "
                         "(pass mesh= to the model)")
    b, s = q.shape[0], q.shape[1]
    if mask is not None:
        bias = jnp.where(mask[:, 0, 0, :], 0.0,
                         jnp.finfo(jnp.float32).min).astype(jnp.float32)
    else:
        bias = jnp.zeros((b, s), jnp.float32)
    from distributed_tensorflow_framework_tpu.core.mesh import batch_spec

    data_axes = batch_spec(mesh)[0]  # the canonical batch-sharding axes
    spec = P(data_axes, axis_name, None, None)
    bias_spec = P(data_axes, axis_name)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec, bias_spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, bias)
