"""Static pipeline schedules — slot tables, bubbles, activation residency.

Everything here is plain Python over static ints: the schedule for a
(S stages, M microbatches, v virtual stages) triple is fully known at
trace time, so the executors in parallel/pipeline.py unroll/scan over
tables built here, the trainer logs the analytic bubble from here, and
the unit tests (tests/test_pipeline.py) pin warmup/steady/cooldown
structure without touching a device.

Three schedules (ModelConfig.pipeline_schedule):

``gpipe``
    Circular fill-drain: one forward pass of ``M + S - 1`` slots (slot t,
    stage s runs microbatch ``t - s``), backward mirrored by autodiff.
    Bubble fraction ``(S-1)/(M+S-1)`` per direction; every slot's
    residuals stay live until its mirrored backward slot → activation
    residency **O(M + S)** stage-activation sets per device.

``1f1b``
    Same forward pass; the backward is hand-built (pipeline.py) as a
    combined recompute+backward schedule: slot ``t`` runs a forward
    (re)compute of microbatch ``t - s`` on stage ``s`` AND the backward
    of microbatch ``t - 2(S-1) + s`` — one-forward-one-backward in the
    steady region, with ``S-1`` forward-only warmup slots and ``S-1``
    backward-only cooldown slots. Only the stage-INPUT boundary
    activation is carried between a microbatch's forward slot and its
    backward slot (a depth-``2S-1`` rolling store); per-layer residuals
    exist only transiently inside the backward slot's VJP. Residency
    **O(S)**, independent of M — the schedule that buys more
    microbatches at a fixed activation budget. Analytic bubble equals
    gpipe's (the win is memory, not slots).

``interleaved``
    v virtual stages per device, round-robin layer assignment (global
    chunk ``q = c*S + s`` lives on device ``s``, chunks cover the layer
    stack in order). Forward pass ``v*M + S - 1`` slots of 1/v-sized
    chunk work, backward mirrored by autodiff → bubble fraction
    ``(S-1)/(v*M + S-1)`` — strictly below gpipe's for v > 1 at equal
    (S, M). Requires ``M % S == 0`` (microbatches advance in groups of
    S) and ``num_layers % (S*v) == 0``.

Bubble convention: fraction of total schedule slots that are fill/drain
(idle on real hardware, masked garbage compute under SPMD lockstep) —
the same convention as the original gpipe ``pipe_bubble_frac`` metric
(3/11 = 0.2727 at S=4, M=8). The Megatron-style bubble/ideal ratio is
``(S-1)/(v*M)``; both shrink with v.
"""

from __future__ import annotations

from dataclasses import dataclass, field

SCHEDULES = ("gpipe", "1f1b", "interleaved")


def resolve_virtual(schedule: str, num_stages: int, num_microbatches: int,
                    virtual_stages: int, num_layers: int) -> int:
    """Validate the (schedule, S, M, v, L) tuple; return the resolved v.

    ``virtual_stages == 0`` means "default": 1 for gpipe/1f1b,
    ``num_layers // num_stages`` (one layer per chunk — the maximal
    bubble cut) for interleaved.
    """
    if schedule not in SCHEDULES:
        raise ValueError(
            f"model.pipeline_schedule must be one of {SCHEDULES}, "
            f"got {schedule!r}"
        )
    s, m, v = num_stages, num_microbatches, virtual_stages
    if s < 1 or m < 1:
        raise ValueError(f"need stages>=1 and microbatches>=1, got {s}, {m}")
    if schedule != "interleaved":
        if v not in (0, 1):
            raise ValueError(
                f"model.pipeline_virtual_stages={v} only applies to "
                f"pipeline_schedule='interleaved' (got {schedule!r})"
            )
        return 1
    if v == 0:
        v = max(num_layers // s, 1)
    if m % s:
        raise ValueError(
            f"interleaved schedule needs pipeline_microbatches ({m}) "
            f"divisible by pipeline_stages ({s}) — microbatches advance "
            f"through the virtual chunks in groups of S"
        )
    if num_layers % (s * v):
        raise ValueError(
            f"interleaved schedule needs num_layers ({num_layers}) "
            f"divisible by stages*virtual_stages ({s}*{v}) for the "
            f"round-robin chunk assignment"
        )
    return v


def num_slots(schedule: str, num_stages: int, num_microbatches: int,
              virtual_stages: int = 1) -> int:
    """Forward-pass slot count (the scan/unroll length per direction)."""
    s, m, v = num_stages, num_microbatches, virtual_stages
    if schedule == "interleaved":
        return v * m + s - 1
    return m + s - 1


def bubble_frac(schedule: str, num_stages: int, num_microbatches: int,
                virtual_stages: int = 1) -> float:
    """Analytic fill/drain fraction of the schedule (see module note)."""
    s, m, v = num_stages, num_microbatches, virtual_stages
    if schedule == "interleaved":
        return (s - 1) / (v * m + s - 1)
    # gpipe and 1f1b share the analytic bubble; 1f1b's win is residency.
    return (s - 1) / (m + s - 1)


def peak_inflight(schedule: str, num_stages: int, num_microbatches: int,
                  virtual_stages: int = 1) -> float:
    """Peak per-device activation residency, in stage-activation-set
    units (one unit = the saved forward state for one microbatch across
    one device's layers), worst stage.

    gpipe/interleaved: autodiff through the forward scan keeps every
    slot's residuals until the mirrored backward slot → all slots live
    at the turnaround (interleaved slots are 1/v-sized, hence /v).
    1f1b: a microbatch's state lives from its forward slot ``mb + s`` to
    its backward slot ``mb + 2(S-1) - s``; span ``2(S-1-s) + 1``, worst
    at stage 0 and capped by M → ``min(M, 2S-1)`` — O(S), not O(M).
    """
    s, m, v = num_stages, num_microbatches, virtual_stages
    if schedule == "1f1b":
        return float(min(m, 2 * s - 1))
    if schedule == "interleaved":
        return (v * m + s - 1) / v
    return float(m + s - 1)


@dataclass
class Slot:
    """One schedule slot: which microbatch each stage runs, per phase.

    ``fwd``/``bwd`` map stage → microbatch id (absent = stage idle in
    that phase). ``kind`` classifies the slot: "warmup" (forward-only),
    "steady" (both phases active somewhere), "cooldown" (backward-only).
    """

    t: int
    fwd: dict[int, int] = field(default_factory=dict)
    bwd: dict[int, int] = field(default_factory=dict)
    kind: str = "steady"


def slot_table(schedule: str, num_stages: int, num_microbatches: int,
               virtual_stages: int = 1) -> list[Slot]:
    """The full static schedule as a list of Slots.

    gpipe/interleaved tables are forward-pass only (autodiff mirrors
    them); the 1f1b table is the combined recompute+backward schedule
    its executor unrolls, with the warmup / steady / cooldown structure
    the ISSUE's unit tests pin.
    """
    s, m, v = num_stages, num_microbatches, virtual_stages
    slots: list[Slot] = []
    if schedule == "1f1b":
        for t in range(m + 2 * s - 2):
            slot = Slot(t=t)
            if t <= m + s - 2:  # forward (re)compute phase
                for st in range(s):
                    mb = t - st
                    if 0 <= mb < m:
                        slot.fwd[st] = mb
            if t >= s - 1:      # backward phase
                for st in range(s):
                    mb = t - 2 * (s - 1) + st
                    if 0 <= mb < m:
                        slot.bwd[st] = mb
            if not slot.bwd:
                slot.kind = "warmup"
            elif not slot.fwd:
                slot.kind = "cooldown"
            slots.append(slot)
        return slots
    for t in range(num_slots(schedule, s, m, v)):
        slot = Slot(t=t)
        for st in range(s):
            tp = t - st  # stage-local clock
            if schedule == "interleaved":
                if 0 <= tp < v * m:
                    g, r = divmod(tp, s * v)
                    c, j = divmod(r, s)
                    slot.fwd[st] = g * s + j  # chunk c of microbatch g*S+j
            else:
                if 0 <= tp < m:
                    slot.fwd[st] = tp
        if t < s - 1:
            slot.kind = "warmup"
        elif len(slot.fwd) < s:
            slot.kind = "cooldown"
        slots.append(slot)
    return slots
