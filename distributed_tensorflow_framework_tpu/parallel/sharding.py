"""Parameter and activation sharding rules.

Replaces ``tf.train.replica_device_setter`` (SURVEY.md §2 row 2): instead of
pinning variables to parameter-server processes, every parameter gets a
`PartitionSpec` over the canonical mesh axes:

  * **DP** (reference parity): all params replicated, batch sharded over
    ``data`` — XLA turns the grad mean into a cross-replica-sum over ICI,
    which is the SyncReplicasOptimizer+NCCL pipeline with zero user code.
  * **FSDP**: each param's largest divisible axis additionally sharded over
    ``fsdp`` (ZeRO-3-style; cf. SURVEY.md §7 hard part 5 / the
    cross-replica weight-update sharding paper in PAPERS.md).
  * **TP**: transformer kernels get megatron-style column/row splits over
    ``model`` via name-pattern rules.

Rules are name-pattern based so models don't need flax partitioning
metadata threaded through every module (they may still provide it; explicit
metadata wins).
"""

from __future__ import annotations

import re
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Megatron-style TP rules for the transformer models: column-parallel QKV and
# MLP-in (shard output features), row-parallel attn-out and MLP-out (shard
# input features). Patterns are matched against "/".join(param path).
TP_RULES: list[tuple[str, tuple[str | None, ...]]] = [
    # Fused projection (models/bert.py fused_qkv): kernel is (H, 3, H)
    # with q/k/v interleaved on the middle axis precisely so TP shards
    # the LAST axis — each model shard then holds its own q/k/v column
    # slice and the in-layer split is shard-local (no resharding). Rules
    # apply only at matching rank (_match_rules), so a flat (H, 3H) qkv
    # from an external model still takes the rank-2 rule below.
    (r".*qkv/kernel$", (None, None, "model")),
    (r".*(query|key|value|qkv)/kernel$", (None, "model")),
    (r".*attn_out/kernel$", ("model", None)),
    (r".*mlp_in/kernel$", (None, "model")),
    (r".*mlp_out/kernel$", ("model", None)),
    (r".*embed/embedding$", (None, "model")),
]

# MoE expert weights (models/moe.py): leading num_experts dim over the
# ``expert`` axis, hidden dims megatron-split over ``model`` (column-parallel
# wi, row-parallel wo). The gate stays replicated. Applied whenever the
# pattern matches — on an expert=1 mesh the axis is a no-op.
MOE_RULES: list[tuple[str, tuple[str | None, ...]]] = [
    (r".*moe/wi$", ("expert", None, "model")),
    (r".*moe/wo$", ("expert", "model", None)),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts)


def _match_rules(
    path: str,
    shape: tuple[int, ...],
    mesh: Mesh,
    rules: list[tuple[str, tuple[str | None, ...]]],
) -> P | None:
    for pattern, spec in rules:
        if re.match(pattern, path):
            if len(spec) != len(shape):
                # Rank-mismatched rule: keep looking (e.g. the rank-3
                # fused-qkv rule must not half-apply to a rank-2 kernel
                # via zip truncation — silent TP loss).
                continue
            # Drop axes that are absent/trivial in the mesh or don't divide
            # evenly (falls back to replication on that dim, not failure).
            fixed = []
            for dim, axis in zip(shape, spec):
                if (
                    axis is not None
                    and mesh.shape.get(axis, 1) > 1
                    and dim % mesh.shape[axis] == 0
                ):
                    fixed.append(axis)
                else:
                    fixed.append(None)
            return P(*fixed)
    return None


def _apply_tp(path: str, shape: tuple[int, ...], mesh: Mesh) -> P | None:
    if mesh.shape.get("model", 1) <= 1:
        return None
    return _match_rules(path, shape, mesh, TP_RULES)


def pick_fsdp_dim(shape: tuple[int, ...], fsdp: int,
                  taken: tuple = ()) -> int:
    """Dim index to shard over fsdp, or -1 if none qualifies.

    The LARGEST still-unsharded dim divisible by ``fsdp`` wins; among
    equal-size candidates the TRAILING dim wins — matching the TP rules'
    column/row convention (kernels shard their last dim first) and, more
    importantly, DETERMINISTIC: the old first-dim tie-break depended on
    scan order alone, so a square kernel's layout could flip between a
    spec computed here and one computed by a caller iterating
    differently. ``taken`` marks already-sharded dims (per-dim axis
    entries; None = free).
    """
    axes = tuple(taken) + (None,) * (len(shape) - len(tuple(taken)))
    best, best_size = -1, 0
    for i, (dim, axis) in enumerate(zip(shape, axes)):
        if axis is None and dim and dim % fsdp == 0 and dim >= best_size:
            best, best_size = i, dim
    return best


def _apply_fsdp(spec: P | None, shape: tuple[int, ...], mesh: Mesh) -> P | None:
    fsdp = mesh.shape.get("fsdp", 1)
    if fsdp <= 1:
        return spec
    dims = spec if spec is not None else (None,) * len(shape)
    dims = tuple(dims) + (None,) * (len(shape) - len(tuple(dims)))
    best = pick_fsdp_dim(shape, fsdp, dims)
    if best < 0:
        return spec
    new = list(dims)
    new[best] = "fsdp"
    return P(*new)


def infer_param_specs(
    params: Any,
    mesh: Mesh,
    *,
    fsdp: bool | None = None,
    tensor_parallel: bool | None = None,
) -> Any:
    """PartitionSpec pytree for a param pytree under the given mesh.

    Defaults: TP rules apply iff the mesh's ``model`` axis > 1; FSDP applies
    iff the ``fsdp`` axis > 1. Anything unmatched is replicated — the
    reference-parity DP layout.
    """
    use_tp = tensor_parallel if tensor_parallel is not None else mesh.shape.get("model", 1) > 1
    use_fsdp = fsdp if fsdp is not None else mesh.shape.get("fsdp", 1) > 1

    def rule(path, leaf) -> P:
        shape = tuple(np.shape(leaf))
        p = _path_str(path)
        # Pipelined layer stacks (parallel/pipeline.py STACK_KEY): leading
        # num_layers dim over ``pipe``, nothing else — the stage shard_map
        # owns these leaves, so FSDP/TP must not touch them. Substring
        # match so optimizer-state mirrors (mu/nu/...) get the same layout.
        if "pipeline_layers" in p and len(shape) >= 1:
            return P("pipe", *([None] * (len(shape) - 1)))
        # Expert weights next: their layout is fixed by the MoE dispatch
        # regardless of whether TP is on.
        spec: P | None = _match_rules(p, shape, mesh, MOE_RULES)
        if spec is None and use_tp:
            spec = _apply_tp(p, shape, mesh)
        if use_fsdp:
            spec = _apply_fsdp(spec, shape, mesh)
        if spec is None:
            spec = P()
        return spec

    return jax.tree_util.tree_map_with_path(rule, params)


def specs_to_shardings(specs: Any, mesh: Mesh) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """Place a host pytree onto the mesh with the given specs."""
    shardings = specs_to_shardings(specs, mesh)
    return jax.tree.map(jax.device_put, tree, shardings)


def tree_map_specs(fn: Callable[[P], P], specs: Any) -> Any:
    return jax.tree.map(fn, specs, is_leaf=lambda x: isinstance(x, P))


_WARNED_NO_THREAD_RESOURCES = False


def constrain_activation(x: jax.Array, *axes: Any) -> jax.Array:
    """Best-effort ``with_sharding_constraint`` for model-internal
    activations (e.g. the MoE (B, E, C, H) expert tensors, whose backward
    otherwise hits XLA SPMD "involuntary full rematerialization" — the
    partitioner can't see that the cotangents should stay expert-sharded).

    No-ops when there is no mesh context (plain CPU tests, ``init``,
    the shard_map twin — which never enters one) or when any named axis
    in the spec is absent from the context mesh, so callers can hint
    unconditionally. The jit step paths enter their mesh via
    ``with self.mesh:`` (train/step.py) to arm it.

    ``None`` in the spec means REPLICATED (with_sharding_constraint has
    no unconstrained marker for named specs) — only pin dims whose
    layout you know; a wrong ``None`` forces an all-gather.
    """
    try:  # private API (jax 0.9): best-effort must stay best-effort
        from jax._src.mesh import thread_resources
        m = thread_resources.env.physical_mesh
    except Exception:
        global _WARNED_NO_THREAD_RESOURCES
        if not _WARNED_NO_THREAD_RESOURCES:
            _WARNED_NO_THREAD_RESOURCES = True
            import logging

            logging.getLogger(__name__).warning(
                "jax._src.mesh.thread_resources unavailable on this jax "
                "version — activation sharding hints are disabled"
            )
        return x
    if m.empty:
        return x
    names = set(m.axis_names)
    for a in axes:
        for name in (a,) if isinstance(a, str) else tuple(a or ()):
            if name not in names:
                return x
    return jax.lax.with_sharding_constraint(x, P(*axes))
