"""ZeRO weight-update sharding with bucketed compute/comm overlap.

The "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" paper (PAPERS.md) observes that sync-DP wastes two resources:
every replica holds the FULL optimizer state, and every replica runs the
FULL weight update — both redundant, since the all-reduced gradient is
identical everywhere. The fix: reduce-SCATTER the gradients so replica
``i`` owns shard ``i`` of the flattened gradient, update only that shard
(1/n of the optimizer memory and update FLOPs), and all-gather the result.

This module is the explicit shard_map twin of that transform
(``optimizer.zero_sharding="shard_map"``; the passive jit-spec variant is
``"jit"``, the deprecated ``optimizer.shard_opt_state``). Layout:

  * Every param leaf is flattened, zero-padded to ``n·c`` elements
    (``c = ceil(size/n)``), and viewed as ``n`` rows of ``c`` — row ``i``
    is replica ``i``'s shard. Optimizer slots are created directly at the
    stacked ``(n, c)`` shape (``tx.init`` on the stacked tree), globally
    sharded ``P(("data","fsdp"))`` on the row dim, so per-device slot HBM
    is ~1/n of the replicated layout. Padding rows are inert: padded
    grads are exactly zero, so their momentum/variance never moves and
    their update is identically zero for every optax rule we ship.
  * Gradients are reduce-scattered in BUCKETS of consecutive leaves in
    REVERSE layer order (natural-sorted param path, deepest-in-backward
    first). TPU collectives execute in program order, so issuing bucket
    ``k``'s reduce-scatter before the (independent) remaining program
    lets XLA's latency-hiding scheduler overlap it with the backward of
    layers issued after it — every bucket except the last can hide
    behind compute. ``optimizer.zero_bucket_mb`` trades per-collective
    latency overhead against overlap granularity.
  * The all-gather ships the UPDATES, not the params: every replica
    applies the identical gathered update to its full f32 master params,
    so replicas cannot drift even under a lossy gather wire. Wire
    formats reuse ``parallel.collective_dtype`` (bf16 cast / int8
    block-scaled, parallel/quantization.py); the int8 reduce-scatter
    threads per-replica error feedback through
    ``TrainState.collective_residual`` exactly like the all-reduce path
    (compensate → quantize → carry ``c − D(Q(c))`` to the next step).
    The update all-gather has NO error feedback — gathered values have
    no next-step correction site — which is why it ships updates (lossy
    but replica-identical) rather than params.

Checkpoint/reshard integration: the stacked ``(n, c)`` slots round-trip
through orbax as ordinary arrays; a cross-mesh restore reads them at the
STORED row count and refolds host-side (ckpt/reshard.refold_zero_opt_state
— flatten, truncate the padding, re-pad for the new ``n``), mirroring the
error-feedback residual's fold.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import optax
from jax import lax

from distributed_tensorflow_framework_tpu.parallel import collectives as coll
from distributed_tensorflow_framework_tpu.parallel.quantization import (
    DEFAULT_BLOCK_SIZE,
    SCALE_BYTES,
    dequantize_blockwise,
    quantize_blockwise,
)

DATA_AXES = ("data", "fsdp")

# Tally kinds for the ZeRO collectives — kept distinct from the generic
# reduce_scatter/all_gather kinds so the telemetry rollup (KIND_ZERO_UPDATE)
# and the bench A/B can attribute wire bytes to this path specifically.
RS_KIND = "zero_reduce_scatter"
AG_KIND = "zero_all_gather"

# Order-of-magnitude per-link ICI bandwidth (v4/v5-class, one direction)
# used ONLY for the telemetry "hidden ms" estimate — an interpretation aid
# for the overlap fraction, not a measurement. Real numbers come from the
# bench/trace pipeline.
NOMINAL_ICI_BYTES_PER_S = 45e9


def natural_key(path: str) -> tuple:
    """Digit-aware sort key: ``layer_10`` sorts after ``layer_2``."""
    return tuple(
        (0, int(tok)) if tok.isdigit() else (1, tok)
        for tok in re.split(r"(\d+)", path)
        if tok
    )


@dataclasses.dataclass(frozen=True)
class LeafChunk:
    """Shard geometry for one param leaf."""

    index: int               # position in the param tree's flatten order
    path: str                # "/"-joined tree path (bucket ordering key)
    shape: tuple[int, ...]
    size: int                # true element count
    chunk: int               # per-replica elements: ceil(size / n)


@dataclasses.dataclass(frozen=True)
class ZeroPlan:
    """Static shard/bucket plan for one param tree on an ``n``-way mesh.

    ``leaf_chunks`` is in tree-flatten order (index-aligned with any
    params-shaped tree); ``buckets`` groups the same leaves in REVERSE
    layer order — the issue order of the bucketed reduce-scatter.
    """

    n: int
    bucket_bytes: int
    leaf_chunks: tuple[LeafChunk, ...]
    buckets: tuple[tuple[LeafChunk, ...], ...]

    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def shard_elements(self) -> int:
        """Per-replica elements across all leaves (incl. padding)."""
        return sum(lc.chunk for lc in self.leaf_chunks)


def build_plan(params: Any, n: int, bucket_mb: float) -> ZeroPlan:
    """Partition a param tree into per-replica shards and RS buckets.

    ``params`` may hold arrays or ShapeDtypeStructs — only paths and
    shapes are read, so the plan is identical between ``eval_shape`` and
    the live step (it must be: the opt-state specs derive from it).
    """
    if n < 1:
        raise ValueError(f"zero sharding needs n >= 1, got {n}")
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    chunks = []
    for index, (path, leaf) in enumerate(leaves):
        shape = tuple(int(d) for d in leaf.shape)
        size = int(math.prod(shape)) if shape else 1
        chunks.append(LeafChunk(
            index=index,
            path="/".join(
                str(getattr(p, "key", getattr(p, "name", p))) for p in path),
            shape=shape,
            size=size,
            chunk=-(-size // n),
        ))
    # Reverse layer order: backward produces the deepest layers' grads
    # first, so their bucket's reduce-scatter is issued first and overlaps
    # the rest of the backward.
    ordered = sorted(chunks, key=lambda lc: natural_key(lc.path),
                     reverse=True)
    bucket_bytes = max(1, int(bucket_mb * 2**20))
    buckets: list[tuple[LeafChunk, ...]] = []
    cur: list[LeafChunk] = []
    cur_bytes = 0
    for lc in ordered:
        cur.append(lc)
        cur_bytes += lc.size * 4  # f32 gradient bytes
        if cur_bytes >= bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(tuple(cur))
    return ZeroPlan(n=n, bucket_bytes=bucket_bytes,
                    leaf_chunks=tuple(chunks), buckets=tuple(buckets))


# ------------------------------------------------------- shard reshaping --
def _stack_rows(x: jax.Array, lc: LeafChunk, n: int) -> jax.Array:
    """Full leaf → ``(n, chunk)`` rows (flattened, zero-padded)."""
    flat = x.reshape(-1)
    pad = n * lc.chunk - flat.size
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, lc.chunk)


def stacked_shards(tree: Any, plan: ZeroPlan) -> Any:
    """Params-shaped tree → stacked ``(n, chunk)`` tree (global view).

    This is the tree ``tx.init`` runs on: the resulting slot leaves are
    born at the sharded-friendly stacked shape (scalars like optax step
    counts stay scalar), so no post-hoc slot rewriting is needed.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [_stack_rows(x, lc, plan.n)
           for x, lc in zip(leaves, plan.leaf_chunks)]
    return jax.tree_util.tree_unflatten(treedef, out)


def local_shards(tree: Any, plan: ZeroPlan, row: jax.Array) -> Any:
    """Per-replica ``(chunk,)`` views of a full (replicated) tree.

    ``row`` is this replica's linear index over the shard axes
    (collectives.linear_axis_index) — used inside shard_map to slice the
    param shard the optax update needs for weight decay.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = []
    for x, lc in zip(leaves, plan.leaf_chunks):
        flat = x.reshape(-1)
        pad = plan.n * lc.chunk - flat.size
        if pad:
            flat = jnp.pad(flat, (0, pad))
        out.append(lax.dynamic_slice(flat, (row * lc.chunk,), (lc.chunk,)))
    return jax.tree_util.tree_unflatten(treedef, out)


def squeeze_slots(opt_state: Any) -> Any:
    """Local shard_map view ``(1, chunk)`` → ``(chunk,)`` slot leaves.

    Scalar leaves (optax step counters, replicated) pass through. The
    stacked layout guarantees every non-scalar slot leaf is exactly 2-D.
    """
    return jax.tree.map(
        lambda x: x[0] if getattr(x, "ndim", 0) >= 2 else x, opt_state)


def unsqueeze_slots(opt_state: Any) -> Any:
    """Inverse of :func:`squeeze_slots`: ``(chunk,)`` → ``(1, chunk)``."""
    return jax.tree.map(
        lambda x: x[None] if getattr(x, "ndim", 0) >= 1 else x, opt_state)


# ------------------------------------------------- slot/param tree pairing --
def map_slots(fn, opt_state: Any, params: Any) -> Any:
    """Map ``fn(slot_leaf, param_leaf_or_None)`` over an optax state.

    Optax slot trees (mu/nu/trace/...) mirror the param tree, so a slot
    leaf's tree path ends with its param's path; non-mirroring leaves
    (step counters) match nothing and get ``param_leaf=None``. The
    longest-suffix match disambiguates params whose path is a suffix of
    another's.
    """
    p_by_key = {
        jax.tree_util.keystr(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }

    def lookup(slot_key: str):
        best = None
        for pk in p_by_key:
            if slot_key.endswith(pk) and (best is None or len(pk) > len(best)):
                best = pk
        return p_by_key[best] if best is not None else None

    leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    out = [fn(leaf, lookup(jax.tree_util.keystr(path)))
           for path, leaf in leaves]
    return jax.tree_util.tree_unflatten(treedef, out)


def stacked_rows(opt_state: Any, params: Any) -> int | None:
    """Detect the zero stacked slot layout; return its row count or None.

    Structural test used by the checkpoint reshard path (which sees only
    a restore template): the layout is "stacked over n" when every
    param-mirroring slot leaf has shape ``(n, ceil(size/n))`` and at
    least one such shape differs from its param's (otherwise the layouts
    are indistinguishable AND interchangeable).
    """
    n: int | None = None
    differs = False
    pairs: list[tuple[Any, Any]] = []
    map_slots(lambda s, p: pairs.append((s, p)), opt_state, params)
    for slot, p in pairs:
        if p is None or getattr(slot, "ndim", None) in (None, 0):
            continue
        if slot.ndim != 2:
            return None
        size = int(math.prod(p.shape)) if p.shape else 1
        rows = int(slot.shape[0])
        if slot.shape != (rows, -(-size // rows)):
            return None
        if n is None:
            n = rows
        elif rows != n:
            return None
        if tuple(slot.shape) != tuple(p.shape):
            differs = True
    return n if differs else None


# ------------------------------------------------- bucketed collectives --
def _reduce_scatter_bucket(mat: jax.Array, axes: tuple, *, wire,
                           block_size: int, paths: tuple[str, ...]):
    """Reduce-scatter ONE bucket: ``(n, C)`` rows → own summed ``(C,)``.

    Module-level (not a closure) so the dispatch-order test can spy the
    per-bucket issue sequence, mirroring tests/test_pipeline.py's
    schedule-dispatch spy. ``paths`` names the bucket's leaves — unused
    in compute, load-bearing for the spy and for debugging.

    Returns ``(own_row_sum, e1)`` where ``e1`` (int8 wire only, else
    None) is this replica's full quantization error ``c − D(Q(c))`` in
    the ``(n, C)`` layout — the error-feedback carry.
    """
    del paths
    n, c = mat.shape
    if wire == jnp.int8:
        rows = jax.vmap(lambda v: coll._pad_to(v, block_size))(mat)
        q, scales = jax.vmap(
            lambda v: quantize_blockwise(v, block_size))(rows)
        coll._record(RS_KIND, mat, wire_dtype=jnp.int8,
                     logical_dtype=jnp.float32,
                     overhead_bytes=scales.size * SCALE_BYTES)
        # Row p of every replica routes to replica p — the scatter phase.
        qx = lax.all_to_all(q, axes, split_axis=0, concat_axis=0,
                            tiled=False)
        sx = lax.all_to_all(scales, axes, split_axis=0, concat_axis=0,
                            tiled=False)
        partials = jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, block_size))(qx, sx)
        own = partials.sum(axis=0)[:c]
        e1 = (rows - jax.vmap(
            lambda qq, ss: dequantize_blockwise(qq, ss, block_size)
        )(q, scales))[:, :c]
        return own, e1
    flat = mat.reshape(-1)
    if wire is not None and wire != flat.dtype:
        # Narrow-float wire AND narrow adds (same contract as the
        # collectives.reduce_scatter bf16 path — document at call sites).
        coll._record(RS_KIND, flat, wire_dtype=wire)
        own = lax.psum_scatter(flat.astype(wire), axes,
                               scatter_dimension=0, tiled=True)
        return own.astype(jnp.float32), None
    coll._record(RS_KIND, flat)
    return lax.psum_scatter(flat, axes, scatter_dimension=0, tiled=True), None


def _all_gather_bucket(vec: jax.Array, axes: tuple, *, wire,
                       block_size: int, paths: tuple[str, ...]) -> jax.Array:
    """All-gather ONE bucket's ``(C,)`` shard → ``(n, C)`` rows.

    Module-level for the same spy-ability as the scatter twin. Lossy
    wire formats are replica-IDENTICAL (every replica dequantizes the
    same payload), so gathered updates cannot diverge the master params.
    """
    del paths
    full = coll.all_gather(vec, axes, axis=0, tiled=True,
                           wire_dtype=wire, block_size=block_size,
                           kind=AG_KIND)
    return full.reshape(-1, vec.shape[0])


def _axes_list(axes) -> tuple:
    return (axes,) if isinstance(axes, str) else tuple(axes)


def bucketed_reduce_scatter(
    plan: ZeroPlan,
    grads: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    wire_dtype: Any = None,
    block_size: int = DEFAULT_BLOCK_SIZE,
    residual: Any | None = None,
) -> tuple[Any, Any | None]:
    """Bucketed mean reduce-scatter of a gradient tree.

    Issues one :func:`_reduce_scatter_bucket` per plan bucket in reverse
    layer order — the program order that lets each bucket's collective
    overlap the backward of the layers issued after it. Returns
    ``(shard_grads, new_residual)``: ``shard_grads`` mirrors the param
    tree with per-replica ``(chunk,)`` f32 leaves holding this replica's
    slice of the MEAN gradient; ``new_residual`` (int8 wire with
    ``residual`` given, else None) mirrors it at full param shapes.

    Error feedback: ``residual`` holds this replica's last-step
    compression error at param shape; it is added to the gradients
    before quantization (compensation) and the new error
    ``c − D(Q(c))`` is returned. Summed over replicas that is exactly
    the signal the scattered mean missed — no requantization happens on
    the scatter side, so unlike the all-reduce there is no second error
    term.
    """
    axes = _axes_list(axis_names)
    n = plan.n
    wire = coll._canon_wire(wire_dtype)
    use_ef = wire == jnp.int8 and residual is not None
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    r_leaves = (jax.tree_util.tree_flatten(residual)[0]
                if use_ef else [None] * len(g_leaves))
    if len(g_leaves) != len(plan.leaf_chunks):
        raise ValueError(
            f"zero plan covers {len(plan.leaf_chunks)} leaves but the "
            f"gradient tree has {len(g_leaves)}")
    shard_out: list[Any] = [None] * len(g_leaves)
    res_out: list[Any] = [None] * len(g_leaves)
    for bucket in plan.buckets:
        mats = []
        for lc in bucket:
            g = g_leaves[lc.index].astype(jnp.float32)
            if use_ef:
                g = g + r_leaves[lc.index].astype(jnp.float32)
            mats.append(_stack_rows(g, lc, n))
        mat = jnp.concatenate(mats, axis=1) if len(mats) > 1 else mats[0]
        paths = tuple(lc.path for lc in bucket)
        own, e1 = _reduce_scatter_bucket(
            mat, axes, wire=wire, block_size=block_size, paths=paths)
        mean_own = own / n
        off = 0
        for lc in bucket:
            shard_out[lc.index] = mean_own[off:off + lc.chunk]
            if e1 is not None:
                res_out[lc.index] = (
                    e1[:, off:off + lc.chunk].reshape(-1)[: lc.size]
                    .reshape(lc.shape))
            off += lc.chunk
    shards = jax.tree_util.tree_unflatten(treedef, shard_out)
    if not use_ef:
        return shards, None
    return shards, jax.tree_util.tree_unflatten(treedef, res_out)


def bucketed_all_gather(
    plan: ZeroPlan,
    shards: Any,
    axis_names: Sequence[str] = DATA_AXES,
    *,
    wire_dtype: Any = None,
    block_size: int = DEFAULT_BLOCK_SIZE,
) -> Any:
    """Gather per-replica ``(chunk,)`` shards back to full param shapes.

    The gather runs over the same buckets as the scatter (one collective
    per bucket, program-ordered), shipping the UPDATE values — see the
    module docstring for why updates rather than params.
    """
    axes = _axes_list(axis_names)
    n = plan.n
    wire = coll._canon_wire(wire_dtype)
    s_leaves, treedef = jax.tree_util.tree_flatten(shards)
    out: list[Any] = [None] * len(s_leaves)
    for bucket in plan.buckets:
        vec = jnp.concatenate(
            [s_leaves[lc.index].astype(jnp.float32).reshape(-1)
             for lc in bucket])
        paths = tuple(lc.path for lc in bucket)
        rows = _all_gather_bucket(vec, axes, wire=wire,
                                  block_size=block_size, paths=paths)
        assert rows.shape[0] == n, (rows.shape, n)
        off = 0
        for lc in bucket:
            out[lc.index] = (rows[:, off:off + lc.chunk].reshape(-1)
                             [: lc.size].reshape(lc.shape))
            off += lc.chunk
    return jax.tree_util.tree_unflatten(treedef, out)


def fused_update_walk(
    plan: ZeroPlan,
    txs: Sequence[Any],
    grads: Any,
    params: Any,
    opt_buckets: Sequence[Any],
    axis_names: Sequence[str] = DATA_AXES,
    *,
    wire_dtype: Any = None,
    block_size: int = DEFAULT_BLOCK_SIZE,
    residual: Any | None = None,
    row: jax.Array,
) -> tuple[Any, tuple, Any | None, jax.Array]:
    """Fused donated optimizer update (precision.fused_update).

    The unfused ZeRO step is three whole-tree passes over HBM: every
    bucket's reduce-scatter, then ONE optax update re-reading every param
    shard, then every bucket's all-gather + a whole-tree apply_updates.
    This walk fuses them per bucket, in the same reverse layer order:

        RS(bucket k) → slice bucket k's param shards → tx_k.update →
        AG(bucket k's updates) → apply to bucket k's master params

    so each param leaf is read-modified-written once while its gradient
    is still hot, and bucket k+1's reduce-scatter can overlap bucket k's
    update math. Collective kinds/counts per bucket are IDENTICAL to
    bucketed_reduce_scatter + bucketed_all_gather (one RS + one AG each),
    so the jaxpr-collective-census balances unchanged; donation of the
    incoming state is asserted by the hlo-donation-survival pass.

    ``txs`` is one optax chain per bucket (per-bucket weight-decay mask
    subset — train/optimizers.make_optimizer ``decay_mask``); per-leaf
    update rules make the per-bucket split bitwise identical to the
    single whole-tree update (cross-leaf rules — lars, global grad clip —
    are rejected at StepBuilder level, same as unfused ZeRO).
    ``opt_buckets`` is the matching tuple of per-bucket optax states with
    stacked ``(n, chunk)`` slot leaves. Returns ``(new_params,
    new_opt_buckets, new_residual, shard_sq_sum)`` — the last is this
    replica's local sum of squared mean-grad shard elements (psum + sqrt
    gives the same grad_norm shard_global_norm logs).
    """
    axes = _axes_list(axis_names)
    n = plan.n
    wire = coll._canon_wire(wire_dtype)
    use_ef = wire == jnp.int8 and residual is not None
    g_leaves, treedef = jax.tree_util.tree_flatten(grads)
    p_leaves = jax.tree_util.tree_flatten(params)[0]
    r_leaves = (jax.tree_util.tree_flatten(residual)[0]
                if use_ef else [None] * len(g_leaves))
    if len(g_leaves) != len(plan.leaf_chunks):
        raise ValueError(
            f"zero plan covers {len(plan.leaf_chunks)} leaves but the "
            f"gradient tree has {len(g_leaves)}")
    if len(txs) != plan.num_buckets or len(opt_buckets) != plan.num_buckets:
        raise ValueError(
            f"fused walk needs one tx and one opt state per bucket "
            f"({plan.num_buckets}), got {len(txs)} txs / "
            f"{len(opt_buckets)} states")
    new_p: list[Any] = [None] * len(g_leaves)
    res_out: list[Any] = [None] * len(g_leaves)
    new_opt: list[Any] = []
    sq_sum = jnp.float32(0.0)
    for b, bucket in enumerate(plan.buckets):
        mats = []
        for lc in bucket:
            g = g_leaves[lc.index].astype(jnp.float32)
            if use_ef:
                g = g + r_leaves[lc.index].astype(jnp.float32)
            mats.append(_stack_rows(g, lc, n))
        mat = jnp.concatenate(mats, axis=1) if len(mats) > 1 else mats[0]
        paths = tuple(lc.path for lc in bucket)
        own, e1 = _reduce_scatter_bucket(
            mat, axes, wire=wire, block_size=block_size, paths=paths)
        mean_own = own / n
        shard_g: list[jax.Array] = []
        p_shards: list[jax.Array] = []
        off = 0
        for lc in bucket:
            sg = mean_own[off:off + lc.chunk]
            shard_g.append(sg)
            flat = p_leaves[lc.index].reshape(-1)
            pad = n * lc.chunk - flat.size
            if pad:
                flat = jnp.pad(flat, (0, pad))
            p_shards.append(
                lax.dynamic_slice(flat, (row * lc.chunk,), (lc.chunk,)))
            if e1 is not None:
                res_out[lc.index] = (
                    e1[:, off:off + lc.chunk].reshape(-1)[: lc.size]
                    .reshape(lc.shape))
            off += lc.chunk
        sq_sum = sq_sum + sum(
            jnp.sum(jnp.square(sg)) for sg in shard_g)
        with jax.named_scope("optimizer_update"):
            updates, opt_new = txs[b].update(
                tuple(shard_g), squeeze_slots(opt_buckets[b]),
                tuple(p_shards))
        new_opt.append(unsqueeze_slots(opt_new))
        vec = jnp.concatenate(
            [u.astype(jnp.float32).reshape(-1) for u in updates])
        rows = _all_gather_bucket(vec, axes, wire=wire,
                                  block_size=block_size, paths=paths)
        off = 0
        for lc in bucket:
            upd = (rows[:, off:off + lc.chunk].reshape(-1)[: lc.size]
                   .reshape(lc.shape))
            # optax.apply_updates semantics on the one leaf: the gathered
            # update is replica-identical, so the master params stay in
            # lockstep exactly as in the unfused path.
            new_p[lc.index] = optax.apply_updates(p_leaves[lc.index], upd)
            off += lc.chunk
    new_params = jax.tree_util.tree_unflatten(treedef, new_p)
    new_res = (jax.tree_util.tree_unflatten(treedef, res_out)
               if use_ef else None)
    return new_params, tuple(new_opt), new_res, sq_sum


def shard_global_norm(shards: Any,
                      axis_names: Sequence[str] = DATA_AXES) -> jax.Array:
    """Global L2 norm of a tree whose leaves are disjoint per-replica
    shards: sqrt of the psum of local squared sums (padding contributes
    exactly zero). Replaces ``collectives.global_norm`` for the zero
    path, where the full mean gradient never materializes."""
    local = sum(
        jnp.sum(jnp.square(x.astype(jnp.float32)))
        for x in jax.tree.leaves(shards))
    # coll.psum (not raw lax.psum) so the scalar rides the CollectiveTally
    # ledger like every other wire transfer in the step.
    return jnp.sqrt(coll.psum(local, _axes_list(axis_names)))


# ------------------------------------------------------------ telemetry --
def plan_summary(plan: ZeroPlan, *, wire_dtype: Any = None,
                 block_size: int = DEFAULT_BLOCK_SIZE) -> dict:
    """Static per-step wire estimate for the KIND_ZERO_UPDATE event.

    Analytic (from the plan, not a trace tally) so the Trainer can emit
    it at build time; follows the CollectiveTally byte convention
    (reduce-scatter: 1× input payload; all-gather: 1× output payload).
    ``overlap_frac_est`` is the structural bound — every reduce-scatter
    bucket except the LAST issued has backward compute left to hide
    behind — and ``hidden_ms_est`` converts the hideable bytes at a
    nominal ICI bandwidth (interpretation aid, not a measurement).
    """
    wire = coll._canon_wire(wire_dtype)
    itemsize = 4 if wire is None else jnp.dtype(wire).itemsize
    rs_bytes = ag_bytes = 0
    for bucket in plan.buckets:
        c = sum(lc.chunk for lc in bucket)
        payload = plan.n * c
        if wire == jnp.int8:
            padded = -(-c // block_size) * block_size
            scales = plan.n * (padded // block_size) * SCALE_BYTES
            rs_bytes += plan.n * padded + scales
            ag_bytes += plan.n * padded + scales
        else:
            rs_bytes += payload * itemsize
            ag_bytes += payload * itemsize
    b = plan.num_buckets
    overlap = (b - 1) / b if b else 0.0
    return {
        "buckets": b,
        "shards": plan.n,
        "shard_elements": plan.shard_elements(),
        "bucket_mb": round(plan.bucket_bytes / 2**20, 3),
        "wire": str(jnp.dtype(wire)) if wire is not None else "float32",
        "rs_wire_bytes": int(rs_bytes),
        "ag_wire_bytes": int(ag_bytes),
        "overlap_frac_est": round(overlap, 4),
        "hidden_ms_est": round(
            rs_bytes * overlap / NOMINAL_ICI_BYTES_PER_S * 1e3, 3),
    }
