"""Serving path: frozen artifacts + standing batched-inference engine.

The "millions of users" half of the north star (ROADMAP item 3). Three
layers, bottom-up:

  * serve/export.py — freeze a trained checkpoint (via the ckpt/manifest
    restore path, resharded onto the dp-only serving mesh under
    ``serve.allow_reshard``) into an integrity-manifested artifact with
    the model config and a param-tree digest recorded;
  * serve/engine.py — the standing engine: request queue, dynamic
    batching (max-batch-size / max-wait-ms admission), padding buckets
    bounding XLA recompiles, a jitted forward reusing
    parallel/sharding.py specs, and the KIND_SERVE_* SLO telemetry;
  * serve/decode.py — the autoregressive decode engine for mlm-task
    artifacts: paged KV cache (fixed page pool, bucketed page tables
    bounding recompiles), continuous batching (streams join/leave the
    in-flight batch every token), int8 KV pages, and live weight reload
    that drains in-flight streams;
  * serve/server.py — the stdlib-only HTTP front end (predict, generate
    streaming, healthz) with graceful SIGTERM drain mirroring the
    supervisor's preemption contract;
  * serve/fleet.py — the health-aware router over N replica engines:
    least-loaded routing, hedged retries, circuit-breaker eject/readmit,
    supervised restarts, load shedding, rolling live weight reloads,
    multi-tenant QoS admission, and cross-model multiplexing;
  * serve/autoscale.py — the pure control-plane policy the router's
    prober tick runs: hysteresis autoscaling over the fleet pressure
    signal plus per-tenant token-bucket quotas.

See docs/SERVING.md for the architecture and knob reference.
"""

from distributed_tensorflow_framework_tpu.serve.autoscale import (  # noqa: F401
    Autoscaler,
    FleetSnapshot,
    ScaleDecision,
    TenantQuotas,
    priority_of,
)
from distributed_tensorflow_framework_tpu.serve.fleet import (  # noqa: F401
    FleetDrainError,
    FleetError,
    FleetProberError,
    FleetRouter,
    ReplicaLaunchError,
)

from distributed_tensorflow_framework_tpu.serve.decode import (  # noqa: F401
    CacheFullError,
    DecodeClosedError,
    DecodeEngine,
    DecodeError,
    DecodeStream,
    StreamTooLongError,
    page_table_buckets,
    pages_for,
)
from distributed_tensorflow_framework_tpu.serve.engine import (  # noqa: F401
    EngineClosedError,
    InferenceEngine,
    OversizeRequestError,
    QueueFullError,
    ReloadError,
    SequenceTooLongError,
    ServeError,
    serving_mesh,
)
from distributed_tensorflow_framework_tpu.serve.export import (  # noqa: F401
    Artifact,
    artifact_content_digest,
    export_checkpoint,
    load_artifact,
    save_artifact,
)
