"""Fleet autoscaling policy + multi-tenant admission (docs/SERVING.md).

Two small, pure pieces the fleet router composes into its control loop:

  * :class:`Autoscaler` — the scaling POLICY. The router's prober tick
    hands it a :class:`FleetSnapshot` (queue depth + in-flight over
    admitted capacity, shed delta, boot/crash-loop state) and gets back
    at most one :class:`ScaleDecision` per cooldown window. The policy
    never touches processes: the ROUTER actuates, scale-up through the
    same supervised spawn path restarts use (so the crash-loop breaker
    gates both) and scale-down through the same drain path rolling
    reloads use. Keeping policy pure is what makes hysteresis unit-
    testable without HTTP or subprocesses.

  * :class:`TenantQuotas` — per-tenant token buckets for admission
    control. ``admit()`` is the only entry point and is thread-safe:
    concurrent requests racing one remaining token see exactly one
    winner. A breach returns the seconds until the next token so the
    router can answer 429 with an honest Retry-After.

Priority classes are fixed and ordered best-first: ``high`` (0),
``default`` (1), ``batch`` (2). A tenant header of ``class`` or
``class:anything`` maps to that class; unknown names get the configured
default class. The class number is the number of reserved queue slots
(per ``serve.tenant_priority_reserve``) the request must leave free on a
replica to claim it — which is what makes shedding strictly
priority-ordered under exact-capacity load.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time

__all__ = [
    "Autoscaler",
    "FleetSnapshot",
    "PRIORITY_CLASSES",
    "ScaleDecision",
    "TenantQuotas",
    "priority_of",
]

# Best-first priority order. The value doubles as the number of
# tenant_priority_reserve steps the class gives up in _claim_replica.
PRIORITY_CLASSES = {"high": 0, "default": 1, "batch": 2}


def priority_of(tenant: str | None, *, default_class: str = "default") -> int:
    """Priority of a tenant header value (lower = better).

    The class is the header value itself or its prefix before ``:``
    (``batch:nightly-eval`` is a batch-class tenant named
    ``batch:nightly-eval``); anything unrecognized gets the configured
    default class so a typo degrades to default service, never to a
    crash or to silent high-priority treatment.
    """
    name = (tenant or default_class).partition(":")[0]
    if name not in PRIORITY_CLASSES:
        name = default_class
    return PRIORITY_CLASSES.get(name, PRIORITY_CLASSES["default"])


@dataclasses.dataclass
class FleetSnapshot:
    """One observation of the fleet, taken under the router lock.

    ``alive`` counts replicas that could serve traffic now or soon:
    admitted + booting + restarting, but NOT retired (drained away by a
    scale-down) and NOT given up (crash-loop verdict). The max bound
    applies to ``alive`` so a replica being restarted mid-scale-event
    still occupies its slot — the autoscaler and the restart supervisor
    never race to fill the same hole.
    """

    admitted: int = 0
    alive: int = 0
    booting: int = 0      # spawned but never yet admitted
    draining: int = 0     # scale-down victims still finishing in-flight
    give_up: int = 0      # crash-loop breaker verdicts (supervision)
    load: float = 0.0     # sum of queue_depth + inflight + synthetic
    capacity: int = 1     # per-replica queue_capacity
    shed_delta: int = 0   # sheds since the previous decision

    def pressure(self) -> float:
        """Fleet utilization in [0, inf): load over admitted capacity."""
        if self.admitted <= 0 or self.capacity <= 0:
            return 0.0
        return self.load / float(self.admitted * self.capacity)


@dataclasses.dataclass
class ScaleDecision:
    action: str           # "up" | "down"
    reason: str
    pressure: float
    from_replicas: int    # alive before actuation
    to_replicas: int      # alive after actuation

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Autoscaler:
    """Hysteresis + cooldown + hard bounds around ``FleetSnapshot.pressure``.

    One decision per call, at most one call acted on per cooldown
    window; the router applies it (spawn one / drain one) and calls
    back next tick with a fresh snapshot. Growing one replica at a time
    through the supervised spawn path means a traffic spike produces a
    measured ramp, and a crash-looping artifact (give_up > 0) freezes
    scale-up entirely — more copies of a broken binary is not capacity.
    """

    def __init__(
        self,
        *,
        min_replicas: int,
        max_replicas: int,
        up_threshold: float,
        down_threshold: float,
        cooldown_s: float,
        now: float | None = None,
    ) -> None:
        if min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas={max_replicas} < min_replicas={min_replicas}"
            )
        if not (0.0 < down_threshold < up_threshold):
            raise ValueError(
                f"need 0 < down_threshold={down_threshold} < "
                f"up_threshold={up_threshold} for hysteresis"
            )
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_threshold = up_threshold
        self.down_threshold = down_threshold
        self.cooldown_s = max(0.0, cooldown_s)
        # Allow an immediate first decision: a fleet that boots into a
        # spike should not idle out a full cooldown before reacting.
        t = time.monotonic() if now is None else now
        self._last_action_t = t - self.cooldown_s
        self.last_pressure = 0.0

    def decide(
        self, snap: FleetSnapshot, now: float | None = None
    ) -> ScaleDecision | None:
        """Return the one action warranted by this snapshot, or None."""
        now = time.monotonic() if now is None else now
        pressure = snap.pressure()
        # A shed since the last look means demand already exceeded
        # capacity, whatever the instantaneous queue depths say — treat
        # it as at least up-threshold pressure.
        if snap.shed_delta > 0:
            pressure = max(pressure, self.up_threshold)
        self.last_pressure = pressure
        if snap.admitted <= 0:
            # Nothing healthy to measure: supervision owns this phase.
            return None
        if snap.booting > 0:
            # A spawn is still warming up; judging pressure now would
            # double-count the gap it was spawned to fill.
            return None
        if now - self._last_action_t < self.cooldown_s:
            return None
        if pressure >= self.up_threshold and snap.alive < self.max_replicas:
            if snap.give_up > 0:
                # Crash-loop verdict standing: scale-up would just feed
                # the breaker more corpses of the same artifact.
                return None
            self._last_action_t = now
            return ScaleDecision(
                action="up",
                reason=f"pressure {pressure:.3f} >= {self.up_threshold}",
                pressure=pressure,
                from_replicas=snap.alive,
                to_replicas=snap.alive + 1,
            )
        if (pressure <= self.down_threshold
                and snap.admitted > self.min_replicas
                and snap.alive > self.min_replicas
                and snap.draining == 0):
            self._last_action_t = now
            return ScaleDecision(
                action="down",
                reason=f"pressure {pressure:.3f} <= {self.down_threshold}",
                pressure=pressure,
                from_replicas=snap.alive,
                to_replicas=snap.alive - 1,
            )
        return None


@dataclasses.dataclass
class QuotaVerdict:
    ok: bool
    tenant: str
    retry_after_s: float = 0.0
    tokens_left: float = 0.0


class TenantQuotas:
    """Per-tenant token buckets (``serve.tenant_quota_rps`` / ``_burst``).

    Buckets refill continuously at ``rate_per_s`` up to ``burst`` and
    are created full on a tenant's first request. ``admit`` takes an
    explicit ``now`` for deterministic tests; production callers omit it
    and get the monotonic clock. rate_per_s <= 0 disables enforcement
    (every admit succeeds and no state is kept).
    """

    def __init__(self, rate_per_s: float, burst: int = 0) -> None:
        self.rate_per_s = float(rate_per_s)
        if burst <= 0:
            burst = max(1, math.ceil(self.rate_per_s))
        self.burst = int(burst)
        self._lock = threading.Lock()
        self._buckets: dict[str, list[float]] = {}  # tenant -> [tokens, t]

    @property
    def enabled(self) -> bool:
        return self.rate_per_s > 0

    def admit(self, tenant: str, now: float | None = None) -> QuotaVerdict:
        if not self.enabled:
            return QuotaVerdict(ok=True, tenant=tenant)
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = [float(self.burst), now]
                self._buckets[tenant] = bucket
            tokens, last = bucket
            # Refill across however many clock ticks elapsed; a stale
            # (or test-supplied non-monotonic) now never drains tokens.
            tokens = min(
                float(self.burst),
                tokens + max(0.0, now - last) * self.rate_per_s,
            )
            bucket[1] = max(last, now)
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                return QuotaVerdict(
                    ok=True, tenant=tenant, tokens_left=bucket[0]
                )
            bucket[0] = tokens
            return QuotaVerdict(
                ok=False,
                tenant=tenant,
                retry_after_s=(1.0 - tokens) / self.rate_per_s,
                tokens_left=tokens,
            )

    def snapshot(self) -> dict[str, float]:
        """Tenant -> tokens remaining (telemetry / healthz)."""
        with self._lock:
            return {t: b[0] for t, b in self._buckets.items()}
