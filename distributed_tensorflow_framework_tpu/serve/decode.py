"""Autoregressive decode engine: paged KV cache + continuous batching.

The single-shot engine (serve/engine.py) turns the trainer's step loop
inside out; this module does the same to the DECODE loop. Token-by-token
generation is a throughput problem before it is anything else: a static
batch idles the chip whenever streams finish at different lengths, so the
scheduler here rebuilds the in-flight batch EVERY token — finished slots
refill from the queue immediately (continuous batching) instead of at
batch boundaries (the ``decode.scheduler="static"`` A/B control arm).

Memory is the other half. Per-stream KV state lives in a paged,
block-allocated device pool (one ``(pages, slot, hidden)`` plane per layer
and tensor): a stream holds just the pages its current length needs, pages
recycle the moment a stream finishes, and under pressure the
newest-admitted stream is preempted back to the queue (its pages freed,
its progress kept — re-prefill resumes it without re-emitting a token).
``decode.kv_dtype="int8"`` stores pages through the EQuARX-style blockwise
codecs (parallel/quantization.py), halving... quartering bytes per stream
at a bounded per-token logit cost.

XLA discipline matches engine.py: page tables pad to a power-of-two page
ladder and row counts to the dp row ladder, so the compile budget is the
fixed grid |page buckets| x (|row ladder| + |prompt buckets|); each
bucket's first execution is telemetered (KIND_SERVE_RECOMPILE) because
past warmup an unexpected recompile IS the bug. Every step rides
KIND_DECODE_STEP (occupancy, per-token ms) and the pool rides
KIND_KV_CACHE (pages in use/free, evictions) — scripts/analyze_trace.py
rolls both up.
"""

from __future__ import annotations

import logging
import os
import queue as queue_mod
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from distributed_tensorflow_framework_tpu.core import telemetry
from distributed_tensorflow_framework_tpu.core.config import (
    DecodeConfig,
    ServeConfig,
)
from distributed_tensorflow_framework_tpu.models import decode_support_reason
from distributed_tensorflow_framework_tpu.models.bert import (
    bert_decode_head_params,
    bert_decode_layers,
    bert_decode_logits,
    causal_prefill_attention,
    paged_decode_attention,
)
from distributed_tensorflow_framework_tpu.parallel import sharding as shd
from distributed_tensorflow_framework_tpu.parallel.quantization import (
    DEFAULT_BLOCK_SIZE,
    dequantize_blockwise,
    quantize_blockwise,
)
from distributed_tensorflow_framework_tpu.serve.engine import (
    EngineClosedError,
    QueueFullError,
    ReloadError,
    ServeError,
    batch_buckets,
    pick_bucket,
    serving_mesh,
)
from distributed_tensorflow_framework_tpu.serve.export import (
    Artifact,
    load_artifact,
)

log = logging.getLogger(__name__)


class DecodeError(ServeError):
    """Base for autoregressive-decode request errors (server.py maps
    subclasses onto HTTP statuses; an unknown decode failure is a 500)."""


class CacheFullError(DecodeError):
    """The stream could never fit: prompt + max_new_tokens needs more KV
    pages than the pool owns (``decode.num_pages - 1`` allocatable; page 0
    is reserved scratch). Shorten the stream or grow the pool — transient
    pressure is absorbed by queueing and eviction, never by this error."""


class StreamTooLongError(DecodeError):
    """prompt + max_new_tokens exceeds ``decode.max_len`` (itself capped
    at model.max_seq_len — positions past it have no embedding row)."""


class DecodeClosedError(EngineClosedError):
    """Stream submitted after decode drain began, or still queued/active
    when the drain timeout expired."""


class DecodeSchedulerError(RuntimeError):
    """The decode scheduler thread died. Active and queued streams fail
    with the cause, and :meth:`DecodeEngine.drain` re-raises — a dead
    scheduler must not read as a healthy engine (the async-saver
    contract: background failures surface on the owning thread)."""

    def __init__(self, cause: BaseException):
        super().__init__(
            f"decode scheduler thread failed: "
            f"{type(cause).__name__}: {cause}")
        self.__cause__ = cause


# ------------------------------------------------------------ page math


def pages_for(tokens: int, page_size: int) -> int:
    """KV pages covering ``tokens`` positions (ceil; at least one)."""
    return max(1, -(-int(tokens) // int(page_size)))


def page_table_buckets(max_len: int, page_size: int,
                       explicit=None) -> list[int]:
    """Page-table width ladder: powers of two capped at a max-length
    stream's page count — the decode twin of engine.batch_buckets. Page
    tables pad to the next entry, so table width (and with it the jitted
    step's shape) comes from a fixed grid. An explicit ladder is extended
    to cover max_len: a max-length stream must always have a bucket."""
    cap = pages_for(max_len, page_size)
    if explicit:
        out = sorted(int(b) for b in explicit)
        if out[-1] < cap:
            out.append(cap)
        return out
    out, b = [], 1
    while b < cap:
        out.append(b)
        b *= 2
    out.append(cap)
    return out


def kv_block_size(hidden: int) -> int:
    """Block length for int8 KV pages: the quantization block must divide
    the per-token hidden vector so no scale straddles two tokens."""
    return DEFAULT_BLOCK_SIZE if hidden % DEFAULT_BLOCK_SIZE == 0 else hidden


def make_kv_pool(num_layers: int, num_pages: int, page_size: int,
                 hidden: int, kv_dtype: str) -> dict[str, jax.Array]:
    """Device KV pool pytree: one ``(pages, slot, hidden)`` plane per
    layer and tensor. int8 pools carry EQuARX-style blockwise scales
    alongside the payload (parallel/quantization.py); zero-init scales
    are 1.0 so an unwritten slot dequantizes to finite zeros."""
    shape = (num_layers, num_pages, page_size, hidden)
    if kv_dtype == "int8":
        block = kv_block_size(hidden)
        sshape = (num_layers, num_pages, page_size, hidden // block)
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.ones(sshape, jnp.float32),
                "v_scale": jnp.ones(sshape, jnp.float32)}
    return {"k": jnp.zeros(shape, jnp.float32),
            "v": jnp.zeros(shape, jnp.float32)}


def _quant_pages(x, block):
    """(..., H) f32 -> (int8 payload same shape, (..., H//block) scales)."""
    q, scales = quantize_blockwise(x.reshape(-1), block)
    return (q.reshape(x.shape),
            scales.reshape(x.shape[:-1] + (x.shape[-1] // block,)))


def _dequant_pages(q, scales, block):
    flat = dequantize_blockwise(q.reshape(-1), scales.reshape(-1), block)
    return flat.reshape(q.shape).astype(jnp.float32)


# ------------------------------------------------------- jitted forwards


def make_prefill_fn(num_heads: int, page_size: int, kv_dtype: str):
    """The jitted prefill: one causal forward over a single prompt (B=1)
    that writes every layer's K/V into the stream's pages and returns the
    next-token logits. Module-level builder (engine.make_forward
    discipline) so audits can lower the real path without an engine.
    Retraces per (prompt bucket, page bucket); the engine telemeters
    first use. Padded page-table entries point at scratch page 0, so
    prompt padding only ever writes garbage there."""

    def _prefill(params, pool, ids, length, page_table):
        # ids (1, S) int32; length (1,) int32; page_table (P,) int32.
        s = ids.shape[1]
        hidden = pool["k"].shape[-1]
        kv: list = []

        def attend(i, q, k, v):
            kv.append((k[0], v[0]))
            return causal_prefill_attention(q, k, v, length, num_heads)

        positions = jnp.arange(s, dtype=jnp.int32)[None, :]
        x = bert_decode_layers(params, ids, positions, attend)
        h_last = jnp.take(x[0], length[0] - 1, axis=0)
        logits = bert_decode_logits(params, h_last)
        # Page capacity vs prompt bucket: the real tokens (<= length)
        # always fit the allocated pages; rows past ``cap`` are prompt
        # padding whose K/V no position ever attends, so slice them off
        # (a short stream's page bucket is far below the prompt bucket).
        cap = page_table.shape[0] * page_size
        ks = jnp.stack([k for k, _ in kv])[:, :cap]
        vs = jnp.stack([v for _, v in kv])[:, :cap]
        if cap > s:
            pad = ((0, 0), (0, cap - s), (0, 0))
            ks = jnp.pad(ks, pad)
            vs = jnp.pad(vs, pad)
        ks = ks.reshape(len(kv), -1, page_size, hidden)
        vs = vs.reshape(len(kv), -1, page_size, hidden)
        if kv_dtype == "int8":
            block = kv_block_size(hidden)
            kq, kscale = _quant_pages(ks, block)
            vq, vscale = _quant_pages(vs, block)
            pool = dict(
                pool,
                k=pool["k"].at[:, page_table].set(kq),
                v=pool["v"].at[:, page_table].set(vq),
                k_scale=pool["k_scale"].at[:, page_table].set(kscale),
                v_scale=pool["v_scale"].at[:, page_table].set(vscale))
        else:
            pool = dict(pool,
                        k=pool["k"].at[:, page_table].set(ks),
                        v=pool["v"].at[:, page_table].set(vs))
        return logits, pool

    # Donate the pool: the caller always replaces its handle with the
    # returned pool, and without donation every call copies the entire
    # KV arena just to update a few pages.
    return jax.jit(_prefill, donate_argnums=(1,))


def make_decode_fn(num_heads: int, page_size: int, kv_dtype: str):
    """The jitted decode step: one token for every in-flight row — write
    the token's K/V through the page table, gather the row's pages, and
    attend with a live-position mask. Retraces per (row bucket, page
    bucket). Filler rows carry an all-zero page table (scratch page 0):
    their writes land on scratch, and real rows only ever gather scratch
    at masked positions, so padding is bitwise inert."""

    def _decode(params, pool, ids, positions, page_table):
        # ids/positions (R,) int32; page_table (R, P) int32.
        r = ids.shape[0]
        hidden = pool["k"].shape[-1]
        block = kv_block_size(hidden)
        slot = positions // page_size
        page_ids = jnp.take_along_axis(
            page_table, slot[:, None], axis=1)[:, 0]
        off = positions % page_size
        state = {"pool": pool}

        def attend(i, q, k, v):
            k1, v1 = k[:, 0, :], v[:, 0, :]
            p = state["pool"]
            if kv_dtype == "int8":
                kq, kscale = _quant_pages(k1, block)
                vq, vscale = _quant_pages(v1, block)
                p = dict(
                    p,
                    k=p["k"].at[i, page_ids, off].set(kq),
                    v=p["v"].at[i, page_ids, off].set(vq),
                    k_scale=p["k_scale"].at[i, page_ids, off].set(kscale),
                    v_scale=p["v_scale"].at[i, page_ids, off].set(vscale))
                kmat = _dequant_pages(p["k"][i][page_table],
                                      p["k_scale"][i][page_table], block)
                vmat = _dequant_pages(p["v"][i][page_table],
                                      p["v_scale"][i][page_table], block)
            else:
                p = dict(p,
                         k=p["k"].at[i, page_ids, off].set(k1),
                         v=p["v"].at[i, page_ids, off].set(v1))
                kmat = p["k"][i][page_table]
                vmat = p["v"][i][page_table]
            state["pool"] = p
            ctx = paged_decode_attention(
                q[:, 0, :],
                kmat.reshape(r, -1, hidden),
                vmat.reshape(r, -1, hidden),
                positions, num_heads)
            return ctx[:, None, :]

        x = bert_decode_layers(params, ids[:, None], positions[:, None],
                               attend)
        logits = bert_decode_logits(params, x[:, 0, :])
        return logits, state["pool"]

    # Pool donation, as in make_prefill_fn: in-place arena update.
    return jax.jit(_decode, donate_argnums=(1,))


# ----------------------------------------------------------- page pool


class PagePool:
    """Host-side allocator over the device pool's page ids. Page 0 is
    reserved scratch (filler rows and page-table padding point at it), so
    ``num_pages - 1`` pages are allocatable. Alloc is all-or-nothing:
    a partial grant would deadlock two streams each holding half of what
    the other needs."""

    def __init__(self, num_pages: int):
        self.num_pages = int(num_pages)
        self.capacity = self.num_pages - 1
        self._lock = threading.Lock()
        self._free: deque[int] = deque(range(1, self.num_pages))

    def alloc(self, n: int = 1) -> list[int] | None:
        """n page ids, or None if fewer than n are free."""
        with self._lock:
            if len(self._free) < n:
                return None
            return [self._free.popleft() for _ in range(n)]

    def free(self, pages) -> None:
        with self._lock:
            self._free.extend(pages)

    def available(self) -> int:
        with self._lock:
            return len(self._free)


# -------------------------------------------------------------- stream


class DecodeStream:
    """One autoregressive stream: the handle :meth:`DecodeEngine.submit`
    returns. Token events arrive on a Queue (the server's NDJSON writer
    and tests iterate :meth:`events`); :attr:`future` resolves to the
    completion summary. All mutation happens on the scheduler thread;
    clients only ever read through the queue/future."""

    def __init__(self, prompt: list[int], max_new: int,
                 eos_id: int | None, return_logits: bool):
        self.prompt = list(prompt)
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.return_logits = bool(return_logits)
        # prompt + generated so far; an evicted stream re-prefills over
        # exactly this list, so no token is ever produced twice.
        self.tokens: list[int] = list(self.prompt)
        self.generated: list[int] = []
        self.pages: list[int] = []
        self.slot = -1
        self.admissions = 0  # 1 + times an eviction re-admitted it
        self.t_submit = time.monotonic()
        self.t_admit = 0.0
        self.t_first: float | None = None
        self.future: Future = Future()
        self._events: queue_mod.Queue = queue_mod.Queue()
        # Tokens staged by the scheduler but not yet handed to the
        # consumer queue (decode.stream_interval batching). Only the
        # scheduler thread touches it.
        self._buf: list[dict[str, Any]] = []

    # -- scheduler side ---------------------------------------------

    def emit_token(self, token: int, logits=None) -> None:
        idx = len(self.generated)
        self.generated.append(int(token))
        self.tokens.append(int(token))
        if self.t_first is None:
            self.t_first = time.monotonic()
        payload: dict[str, Any] = {"token": int(token), "index": idx}
        if logits is not None:
            payload["logits"] = logits
        self._buf.append(payload)

    def flush_events(self) -> None:
        """Hand buffered tokens to the consumer as ONE queue item: one
        wakeup per burst instead of per token. The engine calls this on
        a stream's first token, every ``stream_interval`` steps, and at
        finish/failure, so nothing is ever stranded in the buffer."""
        if self._buf:
            batch, self._buf = self._buf, []
            self._events.put(("batch", batch))

    def finish(self, reason: str) -> None:
        summary = {
            "tokens": list(self.generated),
            "prompt_len": len(self.prompt),
            "finish": reason,
            "admissions": self.admissions,
            "ttft_ms": ((self.t_first - self.t_submit) * 1e3
                        if self.t_first is not None else None),
        }
        self.flush_events()
        self._events.put(("done", summary))
        self.future.set_result(summary)

    def fail(self, exc: BaseException) -> None:
        self.flush_events()
        self._events.put(("error", exc))
        if not self.future.done():
            self.future.set_exception(exc)

    # -- client side ------------------------------------------------

    def events(self, timeout: float | None = None):
        """Yield ("token", payload) events, ending with ("done",
        summary); an engine-side failure re-raises here."""
        while True:
            kind, payload = self._events.get(timeout=timeout)
            if kind == "error":
                raise payload
            if kind == "batch":
                for item in payload:
                    yield "token", item
                continue
            yield kind, payload
            if kind == "done":
                return

    def pending(self) -> int:
        """Events already emitted but not yet consumed (approximate —
        the scheduler appends concurrently). Consumers forwarding events
        over a socket use this to batch flushes: syscall once per burst,
        not once per token, without ever sitting on the newest event."""
        return self._events.qsize()

    def result(self, timeout: float | None = None) -> dict[str, Any]:
        return self.future.result(timeout)


# -------------------------------------------------------------- engine


class DecodeEngine:
    """Continuous-batching decode engine over a loaded
    :class:`~serve.export.Artifact` (mlm task, dense bert family).

    Thread layout: callers enqueue streams in :meth:`submit`; ONE
    scheduler thread owns admission, paging, eviction, the jitted
    prefill/decode calls and reload swaps, so device order is trivially
    serial and per-stream state needs no fine-grained locking.
    """

    def __init__(self, artifact: Artifact, decode_cfg: DecodeConfig,
                 serve_cfg: ServeConfig, *, mesh=None,
                 telemetry_writer=None):
        if artifact.task != "mlm":
            raise DecodeError(
                f"decode serves the mlm task, not {artifact.task!r}")
        reason = decode_support_reason(artifact.model_config)
        if reason:
            raise DecodeError(f"decode unsupported: {reason}")
        self.artifact = artifact
        self.cfg = decode_cfg
        self.serve_cfg = serve_cfg
        self.mesh = mesh if mesh is not None else serving_mesh(serve_cfg.data)
        self._tw = telemetry_writer
        mc = artifact.model_config
        self.hidden = int(mc.hidden_size)
        self.num_heads = int(mc.num_heads)
        self.num_layers = int(mc.num_layers)
        self.max_len = int(decode_cfg.max_len or mc.max_seq_len)
        self.page_size = int(decode_cfg.page_size)
        self.kv_dtype = decode_cfg.kv_dtype
        self.dp = int(np.prod(
            [self.mesh.shape[a] for a in ("data", "fsdp", "expert")]))
        self.row_buckets = batch_buckets(decode_cfg.max_streams, self.dp)
        self.max_rows = self.row_buckets[-1]
        self.page_buckets = page_table_buckets(
            self.max_len, self.page_size, decode_cfg.page_buckets)
        self.prompt_buckets = ([int(b) for b in decode_cfg.prompt_buckets]
                               or [self.max_len])
        if self.prompt_buckets[-1] < self.max_len:
            # An evicted stream re-prefills over prompt + generated, so
            # the prompt ladder must reach max_len.
            self.prompt_buckets.append(self.max_len)
        self.pool = PagePool(decode_cfg.num_pages)
        self._params = self._place_params(artifact.params)
        self._pool = jax.device_put(
            make_kv_pool(self.num_layers, decode_cfg.num_pages,
                         self.page_size, self.hidden, self.kv_dtype),
            NamedSharding(self.mesh, PartitionSpec()))
        self._prefill = make_prefill_fn(
            self.num_heads, self.page_size, self.kv_dtype)
        self._decode = make_decode_fn(
            self.num_heads, self.page_size, self.kv_dtype)
        self._compiled: set[tuple] = set()

        self._cond = threading.Condition()
        self._queue: deque[DecodeStream] = deque()
        self._slots: list[DecodeStream | None] = [None] * self.max_rows
        self._state = "running"  # running | draining | closed
        self._pending_reload: tuple | None = None
        self._reloads = 0
        self._replica_label = os.environ.get("DTF_REPLICA_ID", "engine")
        self._t_start = time.monotonic()
        self._streams = 0
        self._streams_done = 0
        self._tokens = 0
        self._steps = 0
        self._step_ms = 0.0
        self._prefills = 0
        self._prefill_ms = 0.0
        self._occupancy = 0
        self._evictions = 0
        self._last_kv = 0.0
        self._scheduler_error: DecodeSchedulerError | None = None
        self._scheduler = threading.Thread(
            target=self._schedule_loop, name="dtf-decode-scheduler",
            daemon=True)
        self._scheduler.start()
        log.info(
            "decode engine up: step=%d scheduler=%s kv=%s pages=%dx%d "
            "rows=%s page_buckets=%s prompt_buckets=%s max_len=%d",
            artifact.step, decode_cfg.scheduler, self.kv_dtype,
            decode_cfg.num_pages, self.page_size, self.row_buckets,
            self.page_buckets, self.prompt_buckets, self.max_len)

    # ------------------------------------------------------ public API

    def submit(self, prompt, *, max_new_tokens: int | None = None,
               eos_id: int | None = None,
               return_logits: bool = False) -> DecodeStream:
        """Validate + enqueue one stream; tokens arrive on the returned
        stream's event queue as the scheduler produces them."""
        toks = [int(t) for t in (prompt or [])]
        if not toks:
            raise DecodeError("empty prompt — decode needs >= 1 token")
        max_new = int(max_new_tokens or self.cfg.max_new_tokens)
        if max_new < 1:
            raise DecodeError("max_new_tokens must be >= 1")
        if len(toks) + max_new > self.max_len:
            raise StreamTooLongError(
                f"prompt ({len(toks)}) + max_new_tokens ({max_new}) "
                f"exceeds decode.max_len={self.max_len} — truncate the "
                f"prompt or raise the knob")
        need = pages_for(len(toks) + max_new - 1, self.page_size)
        if need > self.pool.capacity:
            raise CacheFullError(
                f"stream needs {need} KV pages but the pool has "
                f"{self.pool.capacity} allocatable (decode.num_pages="
                f"{self.cfg.num_pages}, page 0 reserved scratch) — "
                f"shorten the stream or grow decode.num_pages")
        stream = DecodeStream(toks, max_new, eos_id, return_logits)
        with self._cond:
            if self._state != "running":
                raise DecodeClosedError(
                    f"decode engine is {self._state} — not accepting "
                    f"streams")
            if len(self._queue) >= self.serve_cfg.queue_capacity:
                raise QueueFullError(
                    f"decode queue at capacity "
                    f"({self.serve_cfg.queue_capacity}) — retry with "
                    f"backoff")
            err = self._scheduler_error
            if err is not None:
                raise err
            self._queue.append(stream)
            self._streams += 1
            self._cond.notify_all()
        return stream

    def generate(self, prompt, *, max_new_tokens: int | None = None,
                 eos_id: int | None = None, return_logits: bool = False,
                 timeout: float | None = None) -> dict[str, Any]:
        """Synchronous :meth:`submit` — the completion summary."""
        return self.submit(
            prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
            return_logits=return_logits).result(timeout)

    def request_reload(self, artifact_dir: str) -> Future:
        """Stage a live weight swap. The scheduler stops admitting, lets
        every in-flight stream run to completion on the OLD weights
        (drain, never kill), swaps in one locked assignment, then resumes
        admission — queued streams decode on the new weights. Validation
        and host->device placement happen here on the calling thread, so
        a bad artifact raises :class:`~serve.engine.ReloadError` without
        the scheduler ever seeing it."""
        try:
            art = load_artifact(artifact_dir)
        except (ValueError, OSError) as e:
            raise ReloadError(
                f"decode reload rejected, still serving step "
                f"{self.artifact.step}: {e}") from e
        if art.task != "mlm":
            raise ReloadError(
                f"decode reload rejected: artifact task {art.task!r} != "
                f"serving task 'mlm'")
        if art.model_config != self.artifact.model_config:
            raise ReloadError(
                "decode reload rejected: model config differs from the "
                "serving artifact — a fleet swaps weights, not "
                "architectures")
        params = self._place_params(art.params)
        fut: Future = Future()
        with self._cond:
            if self._state != "running":
                raise DecodeClosedError(
                    f"decode engine is {self._state} — not accepting "
                    f"reloads")
            if self._pending_reload is not None:
                raise ReloadError(
                    "decode reload rejected: another reload is already "
                    "staged")
            self._pending_reload = (art, params, fut, time.monotonic())
            self._cond.notify_all()
        return fut

    def reload(self, artifact_dir: str,
               timeout: float | None = 60.0) -> dict[str, Any]:
        """Synchronous :meth:`request_reload` (server.py POST /reload)."""
        return self.request_reload(artifact_dir).result(timeout)

    def stats(self) -> dict[str, Any]:
        """Point-in-time decode counters for /healthz."""
        with self._cond:
            waiting = len(self._queue)
            active = sum(1 for s in self._slots if s is not None)
            snap = dict(
                state=self._state, streams=self._streams,
                streams_done=self._streams_done, tokens=self._tokens,
                steps=self._steps, step_ms_total=self._step_ms,
                prefills=self._prefills,
                prefill_ms_total=self._prefill_ms,
                evictions=self._evictions, reloads=self._reloads,
                occupancy_rows=self._occupancy)
        free = self.pool.available()
        snap.update({
            "streams_active": active,
            "streams_waiting": waiting,
            "scheduler": self.cfg.scheduler,
            "kv_dtype": self.kv_dtype,
            "tokens_per_sec": self._tokens / max(
                time.monotonic() - self._t_start, 1e-9),
            "avg_occupancy": (snap["occupancy_rows"]
                              / max(1, snap["steps"]) / self.max_rows),
            "pages": {"total": self.pool.num_pages,
                      "allocatable": self.pool.capacity,
                      "free": free, "used": self.pool.capacity - free,
                      "page_size": self.page_size},
            "row_buckets": self.row_buckets,
            "page_buckets": self.page_buckets,
            "prompt_buckets": self.prompt_buckets,
            "max_len": self.max_len,
            "compiled_buckets": sorted(str(k) for k in self._compiled),
        })
        return snap

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admission, finish every queued and in-flight stream, stop
        the scheduler. Returns True when everything completed within
        ``timeout``; leftovers fail with DecodeClosedError rather than
        hanging their clients."""
        with self._cond:
            if self._state == "closed":
                return True
            self._state = "draining"
            self._cond.notify_all()
        self._scheduler.join(timeout)
        drained = not self._scheduler.is_alive()
        with self._cond:
            self._state = "closed"
            leftovers = list(self._queue)
            self._queue.clear()
            leftovers += [s for s in self._slots if s is not None]
            self._slots = [None] * self.max_rows
            pending, self._pending_reload = self._pending_reload, None
            err, self._scheduler_error = self._scheduler_error, None
            self._cond.notify_all()
        for s in leftovers:
            s.fail(DecodeClosedError(
                "decode drain timed out before this stream finished"))
        if pending is not None:
            pending[2].set_exception(DecodeClosedError(
                "decode engine drained before the staged reload applied"))
        self._emit_kv(event="drain")
        log.info("decode engine drained: %d streams, %d tokens, "
                 "%d evictions, %d undrained",
                 self._streams_done, self._tokens, self._evictions,
                 len(leftovers))
        if err is not None:
            raise err
        return drained and not leftovers

    # ------------------------------------------------------- scheduler

    def _place_params(self, raw_params) -> Any:
        """Serving layout for a param pytree: derive the pre-transposed
        head projection (bert_decode_head_params), then shard onto the
        mesh. Used at construction AND on every live reload so the two
        paths can never diverge in layout."""
        prepared = bert_decode_head_params(raw_params)
        specs = shd.infer_param_specs(prepared, self.mesh)
        return shd.shard_pytree(prepared, specs, self.mesh)

    def _active_locked(self) -> bool:
        return any(s is not None for s in self._slots)

    def _schedule_loop(self) -> None:
        try:
            while self._tick():
                pass
        except BaseException as e:  # funnel: surface on drain()/submit()
            log.error("decode scheduler thread failed", exc_info=True)
            err = DecodeSchedulerError(e)
            with self._cond:
                self._scheduler_error = err
                victims = [s for s in self._slots if s is not None]
                victims += list(self._queue)
                self._queue.clear()
                self._slots = [None] * self.max_rows
            for s in victims:
                s.fail(err)

    def _tick(self) -> bool:
        with self._cond:
            while (not self._queue and not self._active_locked()
                   and self._pending_reload is None):
                if self._state != "running":
                    return False
                self._cond.wait(0.05)
        self._maybe_apply_reload()
        self._admit()
        active = [s for s in self._slots if s is not None]
        if active:
            self._step(active)
        self._maybe_emit_kv()
        return True

    def _admit(self) -> None:
        with self._cond:
            if (self.cfg.scheduler == "static" and self._active_locked()):
                return  # static A/B arm: join at batch boundary only
        while True:
            with self._cond:
                if self._pending_reload is not None:
                    return  # reload staged: drain actives before swap
                if not self._queue:
                    return
                free_slots = [i for i, s in enumerate(self._slots)
                              if s is None]
                if not free_slots:
                    return
                stream = self._queue[0]
                need = pages_for(len(stream.tokens), self.page_size)
                # One page of headroom per active stream keeps admission
                # from starving rows that will cross a page boundary on
                # the very next token (eviction thrash).
                headroom = sum(1 for s in self._slots if s is not None)
                if self.pool.available() < need + headroom:
                    return
                pages = self.pool.alloc(need)
                if pages is None:
                    return
                self._queue.popleft()
                slot = free_slots[0]
                stream.pages = pages
                stream.slot = slot
                stream.admissions += 1
                stream.t_admit = time.monotonic()
                self._slots[slot] = stream
            self._prefill_stream(stream)

    def _prefill_stream(self, stream: DecodeStream) -> None:
        n = len(stream.tokens)
        seq_bucket = pick_bucket(n, self.prompt_buckets)
        page_bucket = pick_bucket(len(stream.pages), self.page_buckets)
        ids = np.zeros((1, seq_bucket), np.int32)
        ids[0, :n] = stream.tokens
        table = np.zeros((page_bucket,), np.int32)
        table[:len(stream.pages)] = stream.pages
        key = ("prefill", seq_bucket, page_bucket)
        first = key not in self._compiled
        t0 = time.monotonic()
        logits, pool = self._prefill(
            self._params, self._pool, ids, np.asarray([n], np.int32),
            table)
        logits = np.asarray(jax.block_until_ready(logits))
        self._pool = pool
        ms = (time.monotonic() - t0) * 1e3
        with self._cond:
            self._prefills += 1
            self._prefill_ms += ms
        if first:
            self._note_compiled(key, ms)
        self._finish_token(stream, logits)

    def _step(self, active: list[DecodeStream]) -> None:
        # Grow each row's page list to cover the position it writes this
        # step; under pressure, preempt the newest-admitted other stream.
        for s in list(active):
            if s not in active:
                continue  # evicted earlier in this very loop
            if s.slot < 0:
                active.remove(s)
                continue
            need = pages_for(len(s.tokens), self.page_size)
            while len(s.pages) < need:
                got = self.pool.alloc(1)
                if got is not None:
                    s.pages.extend(got)
                    continue
                victim = self._evict_for(s)
                if victim is None:
                    # Unreachable: submit-time capacity check guarantees
                    # a solo page-holder always fits. Fail loud, not hang.
                    raise RuntimeError(
                        "KV pool exhausted with no evictable stream")
                if victim in active:
                    active.remove(victim)
        if not active:
            return
        rows = active
        r_bucket = pick_bucket(len(rows), self.row_buckets)
        p_bucket = max(pick_bucket(len(s.pages), self.page_buckets)
                       for s in rows)
        ids = np.zeros((r_bucket,), np.int32)
        positions = np.zeros((r_bucket,), np.int32)
        table = np.zeros((r_bucket, p_bucket), np.int32)
        for r, s in enumerate(rows):
            ids[r] = s.tokens[-1]
            positions[r] = len(s.tokens) - 1
            table[r, :len(s.pages)] = s.pages
        key = ("decode", r_bucket, p_bucket)
        first = key not in self._compiled
        t0 = time.monotonic()
        logits, pool = self._decode(
            self._params, self._pool, ids, positions, table)
        logits = np.asarray(jax.block_until_ready(logits))
        self._pool = pool
        ms = (time.monotonic() - t0) * 1e3
        if first:
            self._note_compiled(key, ms)
        with self._cond:
            self._steps += 1
            self._step_ms += ms
            self._occupancy += len(rows)
        if self._tw:
            self._tw.emit(
                telemetry.KIND_DECODE_STEP,
                metrics={"rows": len(rows), "padded_rows": r_bucket,
                         "step_ms": ms,
                         "per_token_ms": ms / len(rows),
                         "occupancy": len(rows) / self.max_rows})
        for r, s in enumerate(rows):
            self._finish_token(s, logits[r])

    def _finish_token(self, stream: DecodeStream, logits_row) -> None:
        token = int(np.argmax(logits_row))
        pages: list[int] = []
        with self._cond:
            stream.emit_token(
                token,
                logits=(np.asarray(logits_row, np.float32)
                        if stream.return_logits else None))
            # First token flushes immediately (TTFT); after that the
            # buffer drains every stream_interval tokens, i.e. every
            # stream_interval steps, since a stream lands at most one
            # token per step. finish()/fail() flush the remainder.
            if (len(stream.generated) == 1
                    or len(stream._buf) >= self.cfg.stream_interval):
                stream.flush_events()
            self._tokens += 1
            hit_eos = (stream.eos_id is not None
                       and token == stream.eos_id)
            done = hit_eos or len(stream.generated) >= stream.max_new
            if done:
                if stream.slot >= 0:
                    self._slots[stream.slot] = None
                stream.slot = -1
                pages, stream.pages = stream.pages, []
                self._streams_done += 1
        if done:
            self.pool.free(pages)
            stream.finish("eos" if hit_eos else "length")
            with self._cond:
                self._cond.notify_all()

    def _evict_for(self, needy: DecodeStream) -> DecodeStream | None:
        """Preempt the newest-admitted OTHER stream: free its pages and
        requeue it at the FRONT — it re-prefills over prompt + everything
        generated so far, so no token is re-emitted and its next token
        simply continues the stream. Newest-first preserves progress for
        the oldest stream, which by the submit-time capacity check can
        always finish solo."""
        with self._cond:
            candidates = [s for s in self._slots
                          if s is not None and s is not needy]
            if not candidates:
                return None
            victim = max(candidates, key=lambda s: s.t_admit)
            self._slots[victim.slot] = None
            victim.slot = -1
            pages, victim.pages = victim.pages, []
            self._queue.appendleft(victim)
            self._evictions += 1
        self.pool.free(pages)
        self._emit_kv(event="evict")
        return victim

    def _maybe_apply_reload(self) -> None:
        with self._cond:
            if self._pending_reload is None or self._active_locked():
                return  # actives finish on the old weights first
            pending, self._pending_reload = self._pending_reload, None
        art, params, fut, t0 = pending
        old = self.artifact
        with self._cond:
            self.artifact = art
            self._params = params
            self._reloads += 1
        reload_ms = (time.monotonic() - t0) * 1e3
        if self._tw:
            self._tw.emit(
                telemetry.KIND_SERVE_RELOAD,
                metrics={"reload_ms": reload_ms},
                replica=self._replica_label, ok=True, engine="decode",
                from_digest=old.version_digest,
                to_digest=art.version_digest,
                from_step=old.step, to_step=art.step)
        log.info("decode live reload: step %d -> %d (%.0f ms, drained)",
                 old.step, art.step, reload_ms)
        fut.set_result({
            "from_step": old.step, "to_step": art.step,
            "from_digest": old.version_digest,
            "to_digest": art.version_digest,
            "reload_ms": reload_ms,
        })

    # ------------------------------------------------------- telemetry

    def _note_compiled(self, key: tuple, ms: float) -> None:
        self._compiled.add(key)
        kind, a, b = key
        label = (f"prefill:seq{a}xpages{b}" if kind == "prefill"
                 else f"decode:rows{a}xpages{b}")
        if self._tw:
            self._tw.emit(telemetry.KIND_SERVE_RECOMPILE,
                          metrics={"compile_ms": ms}, bucket=label)
        log.info("decode compiled bucket %s in %.0f ms", label, ms)

    def _maybe_emit_kv(self) -> None:
        now = time.monotonic()
        if now - self._last_kv < self.serve_cfg.report_interval_s:
            return
        self._last_kv = now
        self._emit_kv()

    def _emit_kv(self, event: str = "sample") -> None:
        if not self._tw:
            return
        with self._cond:
            waiting = len(self._queue)
            active = sum(1 for s in self._slots if s is not None)
            evictions = self._evictions
        free = self.pool.available()
        self._tw.emit(
            telemetry.KIND_KV_CACHE,
            metrics={"pages_used": self.pool.capacity - free,
                     "pages_free": free,
                     "streams_active": active,
                     "streams_waiting": waiting,
                     "evictions": evictions},
            event=event)
